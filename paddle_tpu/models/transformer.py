"""Transformer encoder-decoder + BERT encoder (reference workloads:
Transformer-base WMT en-de in tests/unittests/dist_transformer.py;
BERT-base in inference/tests/api/analyzer_bert_tester.cc).

Pre-norm residual blocks over the fused attention layer; positional info via
learned embeddings (BERT) / sinusoid table (translation model). All shapes
static; padding is expressed through additive attention bias computed from
the input mask — the segment-ids/packing path replaces Fluid LoD.
"""

from __future__ import annotations

import numpy as np

from .. import initializer as init_mod
from .. import layers
from ..layers import attention as attn_layers
from ..layers import tensor as tl


def _ffn(x, d_inner, d_model, dropout_rate, is_test, name=None, act="relu"):
    h = layers.fc(x, size=d_inner, num_flatten_dims=2, act=act, name=name and name + "_fc1")
    if dropout_rate:
        h = layers.dropout(h, dropout_rate, is_test=is_test,
                           dropout_implementation="upscale_in_train")
    return layers.fc(h, size=d_model, num_flatten_dims=2, name=name and name + "_fc2")


def _pre_norm(x):
    return layers.layer_norm(x, begin_norm_axis=2)


def _residual(x, y, dropout_rate, is_test):
    if dropout_rate:
        y = layers.dropout(y, dropout_rate, is_test=is_test,
                           dropout_implementation="upscale_in_train")
    return layers.elementwise_add(x, y)


def encoder_layer(x, attn_bias, n_head, d_key, d_value, d_model, d_inner,
                  dropout_rate=0.1, is_test=False, name=None, seg_ids=None,
                  ffn_act="relu", inner_dropout=None, post_norm=False,
                  attn_dropout=None, causal=False):
    """One encoder block.

    ``post_norm=False`` is the pre-norm arrangement of the translation
    Transformer (dist_transformer.py); ``post_norm=True`` is the original
    BERT arrangement (LN after each residual add). ``inner_dropout`` is the
    relu_dropout INSIDE the FFN — present in the translation model, absent
    in BERT (whose FFN is gelu with dropout only on sublayer outputs); an
    extraneous inner dropout also forces XLA to rematerialize a threefry
    chain inside both fc dw-grad fusions (~0.8 ms/layer/step measured,
    benchmarks/diag_adam_fusion.py). Defaults preserve the translation
    model; BERT passes gelu/0/True.
    """
    if inner_dropout is None:
        inner_dropout = dropout_rate
    if attn_dropout is None:
        # dropout on the attention probabilities. Since r5 the vendored
        # flash kernels implement dropout IN-KERNEL (ops/pallas_kernels/
        # flash_attention.py _dropout_keep_tile), so long sequences keep the
        # flash path either way; pass 0 to follow the modern long-context
        # recipes that drop attention-probs dropout entirely.
        attn_dropout = dropout_rate
    att = attn_layers.multi_head_attention(
        x if post_norm else _pre_norm(x), None, None, attn_bias, d_key,
        d_value, d_model, n_head,
        dropout_rate=attn_dropout, causal=causal, is_test=is_test, name=name,
        segment_ids_q=seg_ids, segment_ids_kv=seg_ids)
    x = _residual(x, att, dropout_rate, is_test)
    if post_norm:
        x = _pre_norm(x)
    ff = _ffn(x if post_norm else _pre_norm(x), d_inner, d_model,
              inner_dropout, is_test, name=name, act=ffn_act)
    x = _residual(x, ff, dropout_rate, is_test)
    if post_norm:
        x = _pre_norm(x)
    return x


def decoder_layer(x, enc_out, self_bias, cross_bias, n_head, d_key, d_value,
                  d_model, d_inner, dropout_rate=0.1, is_test=False, name=None,
                  trg_seg=None, src_seg=None):
    att = attn_layers.multi_head_attention(
        _pre_norm(x), None, None, self_bias, d_key, d_value, d_model, n_head,
        dropout_rate=dropout_rate, causal=True, is_test=is_test,
        name=name and name + "_self", segment_ids_q=trg_seg, segment_ids_kv=trg_seg)
    x = _residual(x, att, dropout_rate, is_test)
    cross = attn_layers.multi_head_attention(
        _pre_norm(x), enc_out, enc_out, cross_bias, d_key, d_value, d_model,
        n_head, dropout_rate=dropout_rate, is_test=is_test,
        name=name and name + "_cross", segment_ids_q=trg_seg, segment_ids_kv=src_seg)
    x = _residual(x, cross, dropout_rate, is_test)
    ff = _ffn(_pre_norm(x), d_inner, d_model, dropout_rate, is_test, name=name)
    return _residual(x, ff, dropout_rate, is_test)


def _position_encoding_table(max_len, d_model):
    pos = np.arange(max_len)[:, None]
    dim = np.arange(d_model)[None, :]
    angle = pos / np.power(10000, 2 * (dim // 2) / d_model)
    table = np.zeros((max_len, d_model), dtype="float32")
    table[:, 0::2] = np.sin(angle[:, 0::2])
    table[:, 1::2] = np.cos(angle[:, 1::2])
    return table


def _padding_bias_from_mask(mask, n_head):
    """mask: [batch, seq] 1.0 for real tokens → additive bias [b, h, 1→q, k]."""
    neg = tl.scale(mask, scale=1e9, bias=-1e9)  # 0→-1e9, 1→0
    bias = layers.unsqueeze(neg, axes=[1, 2])  # [b,1,1,k]
    return layers.expand(bias, [1, n_head, 1, 1])


def embed_inputs(ids, vocab_size, d_model, max_len, name, pos_ids=None,
                 dropout_rate=0.1, is_test=False, scale_embedding=True):
    emb = layers.embedding(ids, size=[vocab_size, d_model],
                           param_attr=layers.ParamAttr(
                               name=name + "_emb",
                               initializer=init_mod.Normal(0.0, d_model ** -0.5)))
    if scale_embedding:
        emb = tl.scale(emb, scale=d_model ** 0.5)
    pos_table = _position_encoding_table(max_len, d_model)
    if pos_ids is None:
        seq_len = ids.shape[1]
        pos = tl.assign(pos_table[:seq_len])
        out = layers.elementwise_add(emb, pos, axis=1)
    else:
        pos_param = layers.ParamAttr(name=name + "_pos_emb",
                                     initializer=init_mod.NumpyArrayInitializer(pos_table))
        pos_emb = layers.embedding(pos_ids, size=[max_len, d_model], param_attr=pos_param)
        out = layers.elementwise_add(emb, pos_emb)
    if dropout_rate:
        out = layers.dropout(out, dropout_rate, is_test=is_test,
                             dropout_implementation="upscale_in_train")
    return out


def transformer(
    src_ids,
    trg_ids,
    trg_labels,
    src_mask,
    trg_mask,
    src_vocab_size,
    trg_vocab_size,
    max_length=256,
    n_layer=6,
    n_head=8,
    d_model=512,
    d_inner=2048,
    dropout_rate=0.1,
    label_smooth_eps=0.1,
    is_test=False,
    weight_sharing=False,
):
    """Transformer-base seq2seq with teacher forcing (training graph).

    src_ids/trg_ids: [batch, seq] int64; trg_labels: [batch, seq, 1] int64
    (next-token targets); masks: [batch, seq] float 1.0 on real tokens.
    """
    d_key = d_value = d_model // n_head

    enc_in = embed_inputs(src_ids, src_vocab_size, d_model, max_length, "src",
                          dropout_rate=dropout_rate, is_test=is_test)
    src_seg = tl.cast(src_mask, "int32")
    trg_seg = tl.cast(trg_mask, "int32")
    x = enc_in
    for i in range(n_layer):
        x = encoder_layer(x, None, n_head, d_key, d_value, d_model, d_inner,
                          dropout_rate, is_test, name="enc_%d" % i, seg_ids=src_seg)
    enc_out = _pre_norm(x)

    dec_in = embed_inputs(trg_ids, trg_vocab_size, d_model, max_length, "trg",
                          dropout_rate=dropout_rate, is_test=is_test)
    y = dec_in
    for i in range(n_layer):
        y = decoder_layer(y, enc_out, None, None, n_head, d_key,
                          d_value, d_model, d_inner, dropout_rate, is_test,
                          name="dec_%d" % i, trg_seg=trg_seg, src_seg=src_seg)
    dec_out = _pre_norm(y)

    logits = layers.fc(dec_out, size=trg_vocab_size, num_flatten_dims=2,
                       name="predict")
    # label smoothing fused into the single log_softmax pass — the [B, S, V]
    # logits array is the HBM-bandwidth hot spot, traverse it once.
    per_tok = layers.softmax_with_cross_entropy(
        logits, trg_labels,
        label_smoothing=(label_smooth_eps or 0.0) if not is_test else 0.0)
    # mask out padding positions; normalize by token count
    masked = layers.elementwise_mul(per_tok, layers.unsqueeze(trg_mask, axes=[2]))
    token_count = layers.reduce_sum(trg_mask)
    loss = layers.elementwise_div(layers.reduce_sum(masked), token_count)
    return logits, loss


def transformer_base(src_ids, trg_ids, trg_labels, src_mask, trg_mask,
                     src_vocab_size=30000, trg_vocab_size=30000, **kw):
    return transformer(src_ids, trg_ids, trg_labels, src_mask, trg_mask,
                       src_vocab_size, trg_vocab_size,
                       n_layer=6, n_head=8, d_model=512, d_inner=2048, **kw)


# -- BERT ---------------------------------------------------------------------


def bert_encoder(
    input_ids,
    pos_ids,
    sent_ids,
    input_mask,
    vocab_size=30522,
    max_position=512,
    type_vocab_size=2,
    n_layer=12,
    n_head=12,
    d_model=768,
    d_inner=3072,
    dropout_rate=0.1,
    is_test=False,
):
    """BERT-base encoder producing sequence + pooled outputs."""
    emb = layers.embedding(input_ids, size=[vocab_size, d_model],
                           param_attr=layers.ParamAttr(
                               name="word_embedding",
                               initializer=init_mod.Normal(0.0, 0.02)))
    pos_emb = layers.embedding(pos_ids, size=[max_position, d_model],
                               param_attr=layers.ParamAttr(
                                   name="pos_embedding",
                                   initializer=init_mod.Normal(0.0, 0.02)))
    sent_emb = layers.embedding(sent_ids, size=[type_vocab_size, d_model],
                                param_attr=layers.ParamAttr(
                                    name="sent_embedding",
                                    initializer=init_mod.Normal(0.0, 0.02)))
    emb = layers.elementwise_add(layers.elementwise_add(emb, pos_emb), sent_emb)
    emb = layers.layer_norm(emb, begin_norm_axis=2)
    if dropout_rate:
        emb = layers.dropout(emb, dropout_rate, is_test=is_test,
                             dropout_implementation="upscale_in_train")

    seg = tl.cast(input_mask, "int32")
    d_key = d_value = d_model // n_head
    x = emb
    for i in range(n_layer):
        # BERT arrangement: post-norm blocks, gelu FFN, no relu_dropout
        x = encoder_layer(x, None, n_head, d_key, d_value, d_model, d_inner,
                          dropout_rate, is_test, name="bert_l%d" % i,
                          seg_ids=seg, inner_dropout=0, post_norm=True,
                          # tanh-approx gelu: the erf form rematerializes as
                          # a 135-instruction polynomial inside both fc
                          # dw-grad fusions (~0.25 ms/layer/step more than
                          # the 18-instruction tanh form on the VPU)
                          ffn_act={"type": "gelu", "approximate": True})
    seq_out = x
    first_tok = layers.slice(seq_out, axes=[1], starts=[0], ends=[1])
    pooled = layers.fc(layers.squeeze(first_tok, axes=[1]), size=d_model,
                       act="tanh", name="pooled_fc")
    return seq_out, pooled


def bert_pretrain(
    input_ids, pos_ids, sent_ids, input_mask, mask_positions, mask_labels,
    nsp_labels, vocab_size=30522, d_model=768, **kw
):
    """Masked-LM + next-sentence-prediction pretraining loss.

    mask_positions: [batch, n_mask] int64 flat positions into [b*s];
    mask_labels: [batch*n_mask, 1]; nsp_labels: [batch, 1].
    """
    seq_out, pooled = bert_encoder(input_ids, pos_ids, sent_ids, input_mask,
                                   vocab_size=vocab_size, d_model=d_model, **kw)
    flat = layers.reshape(seq_out, [-1, d_model])
    picked = layers.gather(flat, layers.reshape(mask_positions, [-1, 1]))
    mlm_h = layers.fc(picked, size=d_model,
                      act={"type": "gelu", "approximate": True},
                      name="mlm_transform")
    mlm_h = layers.layer_norm(mlm_h, begin_norm_axis=1)
    mlm_logits = layers.fc(mlm_h, size=vocab_size, name="mlm_out")
    mlm_loss = layers.mean(layers.softmax_with_cross_entropy(mlm_logits, mask_labels))
    nsp_logits = layers.fc(pooled, size=2, name="nsp_out")
    nsp_loss = layers.mean(layers.softmax_with_cross_entropy(nsp_logits, nsp_labels))
    total = layers.elementwise_add(mlm_loss, nsp_loss)
    return total, mlm_loss, nsp_loss


def causal_lm(token_ids, labels, vocab_size=32000, max_length=2048,
              n_layer=12, n_head=16, d_model=1024, d_inner=4096,
              dropout_rate=0.1, is_test=False):
    """Decoder-only causal LM over the encoder blocks (pre-norm, gelu FFN,
    causal attention). Attention-probs dropout is 0 (the modern
    long-context recipe; the r5 in-kernel dropout path supports it at ~7%
    step cost if wanted via encoder_layer's attn_dropout) and the Pallas
    flash kernel carries the attention FLOPs at
    S >= FLAGS_flash_attention_min_seq — the long-context training
    configuration (residual/embedding dropout stay on). Returns
    (logits, mean token cross-entropy loss)."""
    x = embed_inputs(token_ids, vocab_size, d_model, max_length, "lm",
                     dropout_rate=dropout_rate, is_test=is_test)
    d_key = d_value = d_model // n_head
    for i in range(n_layer):
        x = encoder_layer(x, None, n_head, d_key, d_value, d_model, d_inner,
                          dropout_rate, is_test, name="lm_l%d" % i,
                          ffn_act={"type": "gelu", "approximate": True},
                          inner_dropout=0, attn_dropout=0, causal=True)
    x = _pre_norm(x)
    logits = layers.fc(x, size=vocab_size, num_flatten_dims=2, name="lm_head")
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, labels))
    return logits, loss
