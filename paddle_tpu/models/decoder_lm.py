"""Decoder-only transformer LM as pure JAX functions — the serving workload.

The model the serving stack (paddle_tpu.serving) drives: a pre-LN GPT-style
decoder with tied input/output embeddings, written as pure functions over a
params pytree so the engine can AOT-compile one prefill per prompt bucket
and one incremental decode step whose KV cache stays on device (the
static-graph models in this package build Programs; a Program-authored
decoder plugs into the same engine once ROADMAP item 6's ``to_static``
extraction lands, via the ``prefill_forward``/``decode_forward`` contract).

The decode loop is cache-layout-blind: it threads an opaque cache pytree
through ``cache_ops`` (serving.kv_cache.PagedKVCache or ContiguousKVCache),
writing each new position's K/V before attending over the gathered context
with ``ops.attention_ops.decode_attention``.
"""

from __future__ import annotations

import math
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import attention_ops

__all__ = ["DecoderConfig", "DecoderLM", "init_params", "prefill_forward",
           "decode_forward", "verify_forward", "reference_decode"]


class DecoderConfig:
    """Static decoder hyperparameters (closed over at trace time)."""

    def __init__(self, vocab_size: int = 256, n_layer: int = 2,
                 d_model: int = 64, n_head: int = 4, max_seq: int = 128,
                 ffn_mult: int = 4, dtype="float32"):
        if d_model % n_head != 0:
            raise ValueError("d_model must divide by n_head")
        self.vocab_size = int(vocab_size)
        self.n_layer = int(n_layer)
        self.d_model = int(d_model)
        self.n_head = int(n_head)
        self.d_head = self.d_model // self.n_head
        self.max_seq = int(max_seq)
        self.ffn_mult = int(ffn_mult)
        self.dtype = jnp.dtype(dtype)
        self.sm_scale = 1.0 / math.sqrt(self.d_head)

    def __repr__(self):
        return ("DecoderConfig(V=%d, L=%d, d=%d, H=%d, S=%d, %s)"
                % (self.vocab_size, self.n_layer, self.d_model, self.n_head,
                   self.max_seq, self.dtype))


def init_params(cfg: DecoderConfig, seed: int = 0) -> Dict:
    key = jax.random.PRNGKey(seed)
    d, f = cfg.d_model, cfg.d_model * cfg.ffn_mult

    def nrm(k, shape, scale=0.02):
        return (scale * jax.random.normal(k, shape)).astype(cfg.dtype)

    keys = jax.random.split(key, 2 + 6 * cfg.n_layer)
    params = {
        "tok_emb": nrm(keys[0], (cfg.vocab_size, d)),
        "pos_emb": nrm(keys[1], (cfg.max_seq, d)),
        "lnf_g": jnp.ones((d,), cfg.dtype),
        "lnf_b": jnp.zeros((d,), cfg.dtype),
        "layers": [],
    }
    for i in range(cfg.n_layer):
        k = keys[2 + 6 * i: 8 + 6 * i]
        params["layers"].append({
            "ln1_g": jnp.ones((d,), cfg.dtype),
            "ln1_b": jnp.zeros((d,), cfg.dtype),
            "wq": nrm(k[0], (d, d)),
            "wk": nrm(k[1], (d, d)),
            "wv": nrm(k[2], (d, d)),
            "wo": nrm(k[3], (d, d)),
            "ln2_g": jnp.ones((d,), cfg.dtype),
            "ln2_b": jnp.zeros((d,), cfg.dtype),
            "w1": nrm(k[4], (d, f)),
            "b1": jnp.zeros((f,), cfg.dtype),
            "w2": nrm(k[5], (f, d)),
            "b2": jnp.zeros((d,), cfg.dtype),
        })
    return params


def _ln(x, g, b, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * g + b


def _ffn(x, lp):
    return jax.nn.gelu(x @ lp["w1"] + lp["b1"]) @ lp["w2"] + lp["b2"]


def prefill_forward(params: Dict, cfg: DecoderConfig, tokens, lengths
                    ) -> Tuple[jnp.ndarray, List[Tuple[jnp.ndarray, jnp.ndarray]]]:
    """Full causal forward over (bucket-padded) prompts.

    ``tokens`` [B,S] int32, ``lengths`` [B]. Returns (logits [B,S,V], kvs)
    where ``kvs`` is one (k, v) pair [B,S,H,D] per layer for the caller to
    write into its cache layout. Padding positions are masked out of valid
    queries' attention via segment ids; their own rows are garbage the
    caller must ignore (read logits at ``lengths-1``, write KV < length).
    """
    b, s = tokens.shape
    x = params["tok_emb"][tokens] + params["pos_emb"][:s][None]
    valid = (jnp.arange(s)[None, :] < lengths[:, None]).astype(jnp.int32)
    kvs = []
    for lp in params["layers"]:
        h = _ln(x, lp["ln1_g"], lp["ln1_b"])
        q = (h @ lp["wq"]).reshape(b, s, cfg.n_head, cfg.d_head)
        k = (h @ lp["wk"]).reshape(b, s, cfg.n_head, cfg.d_head)
        v = (h @ lp["wv"]).reshape(b, s, cfg.n_head, cfg.d_head)
        kvs.append((k, v))
        o = attention_ops.sdpa(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3),
            segment_ids_q=valid, segment_ids_kv=valid,
            causal=True, sm_scale=cfg.sm_scale)
        x = x + o.transpose(0, 2, 1, 3).reshape(b, s, cfg.d_model) @ lp["wo"]
        x = x + _ffn(_ln(x, lp["ln2_g"], lp["ln2_b"]), lp)
    x = _ln(x, params["lnf_g"], params["lnf_b"])
    return x @ params["tok_emb"].T, kvs


def decode_forward(params: Dict, cfg: DecoderConfig, cache, cache_ops,
                   tokens, pos, active):
    """One incremental decode position for every batch slot.

    ``tokens``/``pos``/``active`` are [B]; the token at ``pos[b]`` has its
    K/V written into the cache (inactive slots dropped inside the scatter)
    BEFORE attention over the context masked to ``pos+1`` valid positions —
    dispatched through ``cache_ops.decode_attention``, so the layout owns
    the gather-vs-fused-Pallas-kernel choice and this loop stays
    layout-blind. Returns (logits [B,V], cache') — the cache pytree threads
    functionally so the engine's fused scan carries it on device.
    """
    b = tokens.shape[0]
    pos_c = jnp.clip(pos, 0, cfg.max_seq - 1)
    x = params["tok_emb"][tokens] + params["pos_emb"][pos_c]
    for i, lp in enumerate(params["layers"]):
        h = _ln(x, lp["ln1_g"], lp["ln1_b"])
        q = (h @ lp["wq"]).reshape(b, cfg.n_head, cfg.d_head)
        k = (h @ lp["wk"]).reshape(b, cfg.n_head, cfg.d_head)
        v = (h @ lp["wv"]).reshape(b, cfg.n_head, cfg.d_head)
        cache = cache_ops.write_token(cache, i, k, v, pos, active)
        o = cache_ops.decode_attention(cache, i, q, pos + 1,
                                       sm_scale=cfg.sm_scale)
        x = x + o.reshape(b, cfg.d_model) @ lp["wo"]
        x = x + _ffn(_ln(x, lp["ln2_g"], lp["ln2_b"]), lp)
    x = _ln(x, params["lnf_g"], params["lnf_b"])
    return x @ params["tok_emb"].T, cache


def verify_forward(params: Dict, cfg: DecoderConfig, cache, cache_ops,
                   tokens, pos, active, write_mask):
    """Speculative verify window: ``decode_forward`` over W consecutive
    positions per slot in ONE forward.

    ``tokens`` [B,W] is each slot's window — position 0 its pending next
    token, positions 1..W-1 the drafter's proposals; window position ``j``
    sits at logical position ``pos[b] + j``. All W positions' K/V are
    written BEFORE attention (the same write-then-attend order as decode),
    gated per position by ``write_mask`` [B,W] — the engine masks writes
    that would run past the slot's reservation (``gen + j >= max_new`` or
    ``pos + j >= max_ctx``), because those positions' page-table entries
    are unreserved and an unguarded scatter would land on another slot's
    page. Attention dispatches through ``cache_ops.decode_verify`` (ragged
    per-row lengths ``pos + 1 + j`` give in-window causality), so the
    layout again owns the gather-vs-fused-kernel choice. Returns (logits
    [B,W,V], cache'). With W=1 and write_mask=active this is
    ``decode_forward`` on the same math.
    """
    b, w = tokens.shape
    posw = pos[:, None] + jnp.arange(w)[None, :]
    pos_c = jnp.clip(posw, 0, cfg.max_seq - 1)
    x = params["tok_emb"][tokens] + params["pos_emb"][pos_c]
    for i, lp in enumerate(params["layers"]):
        h = _ln(x, lp["ln1_g"], lp["ln1_b"])
        q = (h @ lp["wq"]).reshape(b, w, cfg.n_head, cfg.d_head)
        k = (h @ lp["wk"]).reshape(b, w, cfg.n_head, cfg.d_head)
        v = (h @ lp["wv"]).reshape(b, w, cfg.n_head, cfg.d_head)
        for jj in range(w):
            cache = cache_ops.write_token(cache, i, k[:, jj], v[:, jj],
                                          posw[:, jj], write_mask[:, jj])
        o = cache_ops.decode_verify(cache, i, q, pos + 1,
                                    sm_scale=cfg.sm_scale)
        x = x + o.reshape(b, w, cfg.d_model) @ lp["wo"]
        x = x + _ffn(_ln(x, lp["ln2_g"], lp["ln2_b"]), lp)
    x = _ln(x, params["lnf_g"], params["lnf_b"])
    return x @ params["tok_emb"].T, cache


class DecoderLM:
    """The serving contract (serving.engine.ServingEngine's ``model``):
    bundles a config + params pytree with the two step functions."""

    def __init__(self, cfg: DecoderConfig, params: Dict = None, seed: int = 0):
        self.cfg = cfg
        self.params = params if params is not None else init_params(cfg, seed)

    def prefill(self, params, tokens, lengths):
        return prefill_forward(params, self.cfg, tokens, lengths)

    def decode(self, params, cache, cache_ops, tokens, pos, active):
        return decode_forward(params, self.cfg, cache, cache_ops,
                              tokens, pos, active)

    def verify(self, params, cache, cache_ops, tokens, pos, active,
               write_mask):
        return verify_forward(params, self.cfg, cache, cache_ops,
                              tokens, pos, active, write_mask)


def reference_decode(params: Dict, cfg: DecoderConfig, prompt,
                     max_new_tokens: int):
    """O(S²) no-cache greedy reference: recompute the FULL causal forward
    for every generated token. The yardstick the incremental paged/
    contiguous decode paths are parity-tested against (ragged-vs-padded
    logit parity at mixed lengths). Returns (tokens list, logits list)."""
    seq = [int(t) for t in prompt]
    out_tokens, out_logits = [], []
    for _ in range(max_new_tokens):
        toks = jnp.asarray(np.asarray(seq, np.int32)[None])
        lengths = jnp.asarray([len(seq)], jnp.int32)
        logits, _ = prefill_forward(params, cfg, toks, lengths)
        last = np.asarray(logits[0, len(seq) - 1])
        nxt = int(np.argmax(last))
        out_tokens.append(nxt)
        out_logits.append(last)
        seq.append(nxt)
        if len(seq) >= cfg.max_seq:
            break
    return out_tokens, out_logits
