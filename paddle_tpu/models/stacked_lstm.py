"""Stacked dynamic LSTM sentiment model (reference: the book's
stacked_lstm_net, python/paddle/fluid/tests/book/test_understand_sentiment.py
— embedding → N stacked fc+dynamic_lstm layers with alternating direction →
max pools → fc softmax. The similarly-named
benchmark/fluid/models/stacked_dynamic_lstm.py is, despite its name, a
single hand-rolled DynamicRNN LSTM with 'last' pooling — covered by the
DynamicRNN tests)."""

from __future__ import annotations

from .. import layers
from ..layers import rnn as rnn_layers
from ..layers import sequence as seq_layers
from ..layers import tensor as tensor_layers


def stacked_lstm_net(words, length, label, dict_dim: int, emb_dim: int = 512,
                     hid_dim: int = 512, stacked_num: int = 3,
                     class_num: int = 2):
    """words [B, T] int64 + length [B], label [B, 1] → (avg_loss, acc).

    Padded+Length replaces the reference's LoD input; the lstm stack
    alternates direction per layer like the reference."""
    if hid_dim % 4 != 0:
        raise ValueError("hid_dim is the Fluid 4H projection size and must be "
                         "divisible by 4, got %d" % hid_dim)
    emb = layers.embedding(words, size=[dict_dim, emb_dim])
    # Fluid contract: dynamic_lstm's ``size`` is 4·hidden and its input is
    # the 4H x-projection (same convention as the reference benchmark model)
    fc1 = layers.fc(emb, size=hid_dim, num_flatten_dims=2)
    lstm1, _ = rnn_layers.dynamic_lstm(fc1, size=hid_dim, length=length)

    inputs = [fc1, lstm1]
    for i in range(2, stacked_num + 1):
        fc = layers.fc(tensor_layers.concat(inputs, axis=2), size=hid_dim,
                       num_flatten_dims=2)
        lstm, _ = rnn_layers.dynamic_lstm(fc, size=hid_dim, length=length,
                                          is_reverse=(i % 2) == 0)
        inputs = [fc, lstm]

    fc_last = seq_layers.sequence_pool(inputs[0], "max", length=length)
    lstm_last = seq_layers.sequence_pool(inputs[1], "max", length=length)
    pred = layers.fc(tensor_layers.concat([fc_last, lstm_last], axis=1),
                     size=class_num, act="softmax")
    loss = layers.mean(layers.cross_entropy(pred, label))
    acc = layers.accuracy(pred, label)
    return loss, acc
