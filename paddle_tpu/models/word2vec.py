"""word2vec N-gram language model (reference:
python/paddle/fluid/tests/book/test_word2vec.py __network__): four context
words share one embedding table ('shared_w'), concat → sigmoid fc → softmax
fc → cross_entropy against the next word.
"""

from __future__ import annotations

from .. import layers

__all__ = ["word2vec_ngram"]


def word2vec_ngram(first, second, third, forth, next_word, dict_size,
                   embed_size=32, hidden_size=256, is_sparse=False):
    """Each word input: [batch, 1] int64. Returns (avg_cost, predict_word)."""
    embeds = []
    for w in (first, second, third, forth):
        embeds.append(layers.embedding(
            w, size=[dict_size, embed_size], dtype="float32",
            is_sparse=is_sparse,
            param_attr=layers.ParamAttr(name="shared_w")))
    concat = layers.concat([layers.reshape(e, [0, embed_size]) for e in embeds],
                           axis=1)
    hidden = layers.fc(concat, size=hidden_size, act="sigmoid")
    predict_word = layers.fc(hidden, size=dict_size, act="softmax")
    cost = layers.cross_entropy(predict_word, next_word)
    return layers.mean(cost), predict_word
