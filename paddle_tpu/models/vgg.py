"""VGG-16 (reference: benchmark/fluid/models/vgg.py — conv groups with BN +
dropout, two FC heads). Built on fluid.nets.img_conv_group like the
reference."""

from __future__ import annotations

from .. import layers, nets


def vgg16(img, label, class_num: int = 1000):
    """img [N, 3, H, W], label [N, 1] int64 → (avg_loss, logits)."""

    def group(x, num, groups):
        return nets.img_conv_group(
            x, conv_num_filter=[num] * groups, pool_size=2, pool_stride=2,
            conv_filter_size=3, conv_act="relu", conv_with_batchnorm=True,
            conv_batchnorm_drop_rate=0.0)

    c = group(img, 64, 2)
    c = group(c, 128, 2)
    c = group(c, 256, 3)
    c = group(c, 512, 3)
    c = group(c, 512, 3)

    d = layers.dropout(c, dropout_prob=0.5)
    fc1 = layers.fc(d, size=512, act=None)
    bn = layers.batch_norm(fc1, act="relu")
    d2 = layers.dropout(bn, dropout_prob=0.5)
    fc2 = layers.fc(d2, size=512, act=None)
    logits = layers.fc(fc2, size=class_num)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    return loss, logits
