"""Label semantic roles: the book's deep bidirectional LSTM + linear-chain
CRF tagger (reference: python/paddle/fluid/tests/book/
test_label_semantic_roles.py db_lstm + crf head).

8 feature streams (word, 5 context windows, predicate, region mark) embed,
sum through fcs into a stacked alternating-direction LSTM chain; the CRF
trains on the summed emission and ``crf_decoding`` reuses the same 'crfw'
transition parameter at inference.
"""

from __future__ import annotations

from .. import layers

__all__ = ["db_lstm", "srl_train_net", "srl_decode"]


def db_lstm(word, predicate, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, mark,
            length=None, word_dict_len=100, pred_dict_len=50, mark_dict_len=2,
            label_dict_len=10, word_dim=16, mark_dim=5, hidden_dim=32,
            depth=4):
    """Inputs: [batch, T] int64 token streams. Returns emission [B, T, L]."""
    pred_emb = layers.embedding(
        predicate, size=[pred_dict_len, word_dim], dtype="float32",
        param_attr=layers.ParamAttr(name="vemb"))
    mark_emb = layers.embedding(mark, size=[mark_dict_len, mark_dim],
                                dtype="float32")
    word_input = [word, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2]
    emb_layers = [
        layers.embedding(x, size=[word_dict_len, word_dim], dtype="float32",
                         param_attr=layers.ParamAttr(name="word_emb",
                                                     trainable=True))
        for x in word_input
    ]
    emb_layers += [pred_emb, mark_emb]

    hidden_0 = layers.sums([
        layers.fc(emb, size=hidden_dim * 4, num_flatten_dims=2)
        for emb in emb_layers
    ])
    lstm_0, _ = layers.dynamic_lstm(
        hidden_0, size=hidden_dim * 4, length=length,
        candidate_activation="relu", gate_activation="sigmoid",
        cell_activation="sigmoid")

    input_tmp = [hidden_0, lstm_0]
    for i in range(1, depth):
        mix_hidden = layers.sums([
            layers.fc(input_tmp[0], size=hidden_dim * 4, num_flatten_dims=2),
            layers.fc(input_tmp[1], size=hidden_dim * 4, num_flatten_dims=2),
        ])
        lstm, _ = layers.dynamic_lstm(
            mix_hidden, size=hidden_dim * 4, length=length,
            candidate_activation="relu", gate_activation="sigmoid",
            cell_activation="sigmoid", is_reverse=(i % 2) == 1)
        input_tmp = [mix_hidden, lstm]

    feature_out = layers.sums([
        layers.fc(input_tmp[0], size=label_dict_len, num_flatten_dims=2, act="tanh"),
        layers.fc(input_tmp[1], size=label_dict_len, num_flatten_dims=2, act="tanh"),
    ])
    return feature_out


def srl_train_net(feature_out, target, length=None):
    """CRF training head: returns avg negative log-likelihood cost."""
    crf_cost = layers.linear_chain_crf(
        feature_out, target,
        param_attr=layers.ParamAttr(name="crfw"), length=length)
    return layers.mean(crf_cost)


def srl_decode(feature_out, length=None):
    """Viterbi decode with the trained 'crfw' transitions (inference)."""
    return layers.crf_decoding(
        feature_out, param_attr=layers.ParamAttr(name="crfw"), length=length)
