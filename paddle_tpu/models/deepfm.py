"""DeepFM CTR model (reference workload: tests/unittests/dist_ctr.py +
dist_fleet_ctr-style DeepFM; sparse path via lookup_table/SelectedRows).

Sparse features are dense int id tensors here (one slot per column); the
embedding grads are XLA scatter-adds instead of SelectedRows rows, and the
distributed story is a sharded embedding table over the mesh
(paddle_tpu/parallel) instead of a parameter server.
"""

from __future__ import annotations

from .. import initializer as init_mod
from .. import layers
from ..layers import tensor as tl


def deepfm(
    sparse_ids,
    dense_feat,
    label,
    sparse_feature_dim=int(1e5),
    embedding_size=10,
    num_fields=26,
    layer_sizes=(400, 400, 400),
    is_test=False,
    is_sparse=True,
    sharding_axis=None,
):
    """sparse_ids: [batch, num_fields] int64 (global hashed ids);
    dense_feat: [batch, dense_dim] float32; label: [batch, 1] int64.
    Returns (predict_probs, avg_loss, auc_var).

    ``sharding_axis`` (e.g. ``"model"``) row-shards both embedding tables
    (and their Adam moments) over that mesh axis via
    ``parallel.sharded_embedding`` — the V=1e8 capacity path: ~V/n rows per
    device, table initialized shard-by-shard, optimizer updates shard-local
    rows-only. Run the program through ``CompiledProgram.with_mesh`` with a
    mesh carrying the axis.
    """
    init = layers.ParamAttr(
        name="sparse_emb",
        initializer=init_mod.TruncatedNormal(0.0, 1.0 / (embedding_size ** 0.5)),
    )
    w1_attr = layers.ParamAttr(
        name="sparse_w1",
        initializer=init_mod.TruncatedNormal(0.0, 1e-4))
    # [b, f, e] factor embeddings + [b, f, 1] first-order weights
    # is_sparse=True: SelectedRows-equivalent rows-only gradients + lazy
    # optimizer updates (reference dist_ctr.py uses is_sparse=True too) —
    # the step cost must stay independent of sparse_feature_dim
    if sharding_axis:
        from .. import parallel

        emb = parallel.sharded_embedding(
            sparse_ids, size=[sparse_feature_dim, embedding_size],
            mesh_axis=sharding_axis, param_attr=init, is_sparse=is_sparse)
        w1 = parallel.sharded_embedding(
            sparse_ids, size=[sparse_feature_dim, 1],
            mesh_axis=sharding_axis, param_attr=w1_attr, is_sparse=is_sparse)
    else:
        emb = layers.embedding(sparse_ids,
                               size=[sparse_feature_dim, embedding_size],
                               param_attr=init, is_sparse=is_sparse)
        w1 = layers.embedding(sparse_ids, size=[sparse_feature_dim, 1],
                              param_attr=w1_attr, is_sparse=is_sparse)

    # FM first order
    first_order = layers.reduce_sum(w1, dim=1)  # [b, 1]

    # FM second order: 0.5 * ((sum e)^2 - sum e^2)
    sum_emb = layers.reduce_sum(emb, dim=1)  # [b, e]
    sum_sq = layers.square(sum_emb)
    sq_emb = layers.square(emb)
    sq_sum = layers.reduce_sum(sq_emb, dim=1)
    second_order = tl.scale(
        layers.reduce_sum(layers.elementwise_sub(sum_sq, sq_sum), dim=1, keep_dim=True),
        scale=0.5,
    )  # [b, 1]

    # Deep part
    deep = layers.reshape(emb, [-1, num_fields * embedding_size])
    if dense_feat is not None:
        deep = tl.concat([deep, dense_feat], axis=1)
    for i, size in enumerate(layer_sizes):
        deep = layers.fc(deep, size=size, act="relu",
                         param_attr=layers.ParamAttr(
                             initializer=init_mod.Normal(0.0, 1.0 / (size ** 0.5))),
                         name="deep_fc_%d" % i)
    deep_out = layers.fc(deep, size=1, name="deep_out")

    logit = layers.elementwise_add(
        layers.elementwise_add(first_order, second_order), deep_out)
    # two-class softmax head (reference ctr models fetch class probs for AUC)
    two_logits = tl.concat([tl.zeros_like(logit), logit], axis=1)
    predict = layers.softmax(two_logits)
    loss = layers.mean(layers.softmax_with_cross_entropy(two_logits, label))
    auc_var, _ = layers.auc(predict, label)
    return predict, loss, auc_var
