"""Model zoo mirroring the reference benchmark configs
(reference: benchmark/fluid/models/ — mnist, resnet, machine_translation;
plus BERT and DeepFM from BASELINE.json's five workloads) and the book-test
models (reference: python/paddle/fluid/tests/book/ — word2vec,
label_semantic_roles, recommender_system)."""

from . import (bert, decoder_lm, deepfm, machine_translation, mnist,  # noqa: F401
               recommender, resnet, se_resnext, semantic_roles, stacked_lstm,
               transformer, vgg, word2vec)
