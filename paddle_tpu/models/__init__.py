"""Model zoo mirroring the reference benchmark configs
(reference: benchmark/fluid/models/ — mnist, resnet, machine_translation;
plus BERT and DeepFM from BASELINE.json's five workloads)."""

from . import deepfm, machine_translation, mnist, resnet, se_resnext, stacked_lstm, transformer, vgg  # noqa: F401
