"""MNIST models (reference: benchmark/fluid/models/mnist.py and
tests/book/test_recognize_digits.py)."""

from __future__ import annotations

from .. import layers


def mlp(img, label, hidden_sizes=(200, 200), class_num=10):
    """The book MLP: two tanh-free relu hidden layers + softmax head."""
    h = img
    for size in hidden_sizes:
        h = layers.fc(h, size=size, act="relu")
    logits = layers.fc(h, size=class_num)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(layers.softmax(logits), label)
    return logits, loss, acc


def lenet5(img, label, class_num=10):
    """conv-pool-conv-pool-fc (reference mnist.py cnn_model)."""
    conv1 = layers.conv2d(img, num_filters=20, filter_size=5, act="relu")
    pool1 = layers.pool2d(conv1, pool_size=2, pool_stride=2)
    conv2 = layers.conv2d(pool1, num_filters=50, filter_size=5, act="relu")
    pool2 = layers.pool2d(conv2, pool_size=2, pool_stride=2)
    logits = layers.fc(pool2, size=class_num)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(layers.softmax(logits), label)
    return logits, loss, acc
