"""Recommender system: the book's two-tower movielens model (reference:
python/paddle/fluid/tests/book/test_recommender_system.py): user features
(id/gender/age/job) and movie features (id/categories/title) embed into two
200-d towers; scaled cosine similarity regresses the 1-5 rating with
square_error_cost.
"""

from __future__ import annotations

from .. import layers

__all__ = ["usr_combined_features", "mov_combined_features", "inference_program"]


def usr_combined_features(uid, gender_id, age_id, job_id, usr_dict_size=100,
                          gender_dict_size=2, age_dict_size=7,
                          job_dict_size=21, is_sparse=False):
    usr_emb = layers.embedding(uid, size=[usr_dict_size, 32], dtype="float32",
                               param_attr=layers.ParamAttr(name="user_table"),
                               is_sparse=is_sparse)
    usr_fc = layers.fc(layers.reshape(usr_emb, [0, 32]), size=32)
    g_emb = layers.embedding(gender_id, size=[gender_dict_size, 16],
                             dtype="float32",
                             param_attr=layers.ParamAttr(name="gender_table"),
                             is_sparse=is_sparse)
    g_fc = layers.fc(layers.reshape(g_emb, [0, 16]), size=16)
    a_emb = layers.embedding(age_id, size=[age_dict_size, 16], dtype="float32",
                             param_attr=layers.ParamAttr(name="age_table"),
                             is_sparse=is_sparse)
    a_fc = layers.fc(layers.reshape(a_emb, [0, 16]), size=16)
    j_emb = layers.embedding(job_id, size=[job_dict_size, 16], dtype="float32",
                             param_attr=layers.ParamAttr(name="job_table"),
                             is_sparse=is_sparse)
    j_fc = layers.fc(layers.reshape(j_emb, [0, 16]), size=16)
    concat = layers.concat([usr_fc, g_fc, a_fc, j_fc], axis=1)
    return layers.fc(concat, size=200, act="tanh")


def mov_combined_features(mov_id, category_ids, title_ids, mov_dict_size=200,
                          category_dict_size=18, title_dict_size=500,
                          is_sparse=False):
    """category_ids/title_ids: [batch, T] int64 padded multi-hot sequences
    (the padded+Length replacement for the reference's LoD inputs)."""
    mov_emb = layers.embedding(mov_id, size=[mov_dict_size, 32],
                               dtype="float32",
                               param_attr=layers.ParamAttr(name="movie_table"),
                               is_sparse=is_sparse)
    mov_fc = layers.fc(layers.reshape(mov_emb, [0, 32]), size=32)
    cat_emb = layers.embedding(category_ids, size=[category_dict_size, 32],
                               dtype="float32", is_sparse=is_sparse)
    cat_hidden = layers.sequence_pool(cat_emb, pool_type="sum")
    title_emb = layers.embedding(title_ids, size=[title_dict_size, 32],
                                 dtype="float32", is_sparse=is_sparse)
    title_hidden = layers.sequence_pool(title_emb, pool_type="sum")
    concat = layers.concat([mov_fc, cat_hidden, title_hidden], axis=1)
    return layers.fc(concat, size=200, act="tanh")


def inference_program(usr_features, mov_features, rating):
    """Scaled cosine similarity → square error vs the [batch,1] rating."""
    sim = layers.cos_sim(usr_features, mov_features)
    scale_infer = layers.scale(sim, scale=5.0)
    cost = layers.square_error_cost(scale_infer, rating)
    return scale_infer, layers.mean(cost)
