"""ResNet family (reference: benchmark/fluid/models/resnet.py and
benchmark/fluid/models/se_resnext.py).

Built from the framework's conv2d/batch_norm/pool2d layers; everything
compiles into one XLA program where conv+BN+relu fuse — the reference needs
the conv_bn_fuse IR pass (framework/ir/conv_bn_fuse_pass.cc) to get the same
effect at inference only.
"""

from __future__ import annotations

from .. import layers

_DEPTH_CFG = {
    18: ("basic", [2, 2, 2, 2]),
    34: ("basic", [3, 4, 6, 3]),
    50: ("bottleneck", [3, 4, 6, 3]),
    101: ("bottleneck", [3, 4, 23, 3]),
    152: ("bottleneck", [3, 8, 36, 3]),
}


def conv_bn_layer(input, num_filters, filter_size, stride=1, groups=1, act=None,
                  data_format="NCHW"):
    conv = layers.conv2d(
        input,
        num_filters=num_filters,
        filter_size=filter_size,
        stride=stride,
        padding=(filter_size - 1) // 2,
        groups=groups,
        bias_attr=False,
        data_format=data_format,
    )
    return layers.batch_norm(conv, act=act, data_layout=data_format)


def _shortcut(input, ch_out, stride, data_format="NCHW"):
    ch_in = input.shape[-1] if data_format == "NHWC" else input.shape[1]
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride, data_format=data_format)
    return input


def basic_block(input, num_filters, stride, data_format="NCHW"):
    conv0 = conv_bn_layer(input, num_filters, 3, stride, act="relu",
                          data_format=data_format)
    conv1 = conv_bn_layer(conv0, num_filters, 3, 1, data_format=data_format)
    short = _shortcut(input, num_filters, stride, data_format)
    return layers.elementwise_add(short, conv1, act="relu")


def bottleneck_block(input, num_filters, stride, data_format="NCHW"):
    conv0 = conv_bn_layer(input, num_filters, 1, act="relu",
                          data_format=data_format)
    conv1 = conv_bn_layer(conv0, num_filters, 3, stride, act="relu",
                          data_format=data_format)
    conv2 = conv_bn_layer(conv1, num_filters * 4, 1, data_format=data_format)
    short = _shortcut(input, num_filters * 4, stride, data_format)
    return layers.elementwise_add(short, conv2, act="relu")


def resnet(img, label, depth=50, class_num=1000, dataset="imagenet",
           data_format="NCHW"):
    """reference: resnet.py resnet_imagenet/resnet_cifar10.

    data_format="NHWC" transposes the (NCHW) input once and runs the whole
    network channels-last — the TPU-native layout (channels land on the
    128-lane minor dim; measured ~4% faster than NCHW on v5e).
    """
    block_kind, counts = _DEPTH_CFG[depth]
    block_fn = bottleneck_block if block_kind == "bottleneck" else basic_block

    if data_format == "NHWC" and img.shape[1] in (1, 3, 4):
        img = layers.transpose(img, [0, 2, 3, 1])

    if dataset == "imagenet":
        conv = conv_bn_layer(img, 64, 7, stride=2, act="relu",
                             data_format=data_format)
        conv = layers.pool2d(conv, pool_size=3, pool_stride=2, pool_padding=1,
                             data_format=data_format)
    else:  # cifar10: 3x3 stem, no maxpool
        conv = conv_bn_layer(img, 64, 3, stride=1, act="relu",
                             data_format=data_format)

    for stage, count in enumerate(counts):
        num_filters = 64 * (2 ** stage)
        for i in range(count):
            stride = 2 if i == 0 and stage > 0 else 1
            conv = block_fn(conv, num_filters, stride, data_format=data_format)

    pool = layers.pool2d(conv, pool_type="avg", global_pooling=True,
                         data_format=data_format)
    logits = layers.fc(pool, size=class_num)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(layers.softmax(logits), label)
    return logits, loss, acc


def resnet50(img, label, class_num=1000, data_format="NCHW"):
    return resnet(img, label, depth=50, class_num=class_num,
                  data_format=data_format)


def resnet_cifar10(img, label, depth=18, class_num=10):
    return resnet(img, label, depth=depth, class_num=class_num, dataset="cifar10")
