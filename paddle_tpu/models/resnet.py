"""ResNet family (reference: benchmark/fluid/models/resnet.py and
benchmark/fluid/models/se_resnext.py).

Built from the framework's conv2d/batch_norm/pool2d layers; everything
compiles into one XLA program where conv+BN+relu fuse — the reference needs
the conv_bn_fuse IR pass (framework/ir/conv_bn_fuse_pass.cc) to get the same
effect at inference only.
"""

from __future__ import annotations

from .. import layers

_DEPTH_CFG = {
    18: ("basic", [2, 2, 2, 2]),
    34: ("basic", [3, 4, 6, 3]),
    50: ("bottleneck", [3, 4, 6, 3]),
    101: ("bottleneck", [3, 4, 23, 3]),
    152: ("bottleneck", [3, 8, 36, 3]),
}


def conv_bn_layer(input, num_filters, filter_size, stride=1, groups=1, act=None):
    conv = layers.conv2d(
        input,
        num_filters=num_filters,
        filter_size=filter_size,
        stride=stride,
        padding=(filter_size - 1) // 2,
        groups=groups,
        bias_attr=False,
    )
    return layers.batch_norm(conv, act=act)


def _shortcut(input, ch_out, stride):
    ch_in = input.shape[1]
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride)
    return input


def basic_block(input, num_filters, stride):
    conv0 = conv_bn_layer(input, num_filters, 3, stride, act="relu")
    conv1 = conv_bn_layer(conv0, num_filters, 3, 1)
    short = _shortcut(input, num_filters, stride)
    return layers.elementwise_add(short, conv1, act="relu")


def bottleneck_block(input, num_filters, stride):
    conv0 = conv_bn_layer(input, num_filters, 1, act="relu")
    conv1 = conv_bn_layer(conv0, num_filters, 3, stride, act="relu")
    conv2 = conv_bn_layer(conv1, num_filters * 4, 1)
    short = _shortcut(input, num_filters * 4, stride)
    return layers.elementwise_add(short, conv2, act="relu")


def resnet(img, label, depth=50, class_num=1000, dataset="imagenet"):
    """reference: resnet.py resnet_imagenet/resnet_cifar10."""
    block_kind, counts = _DEPTH_CFG[depth]
    block_fn = bottleneck_block if block_kind == "bottleneck" else basic_block

    if dataset == "imagenet":
        conv = conv_bn_layer(img, 64, 7, stride=2, act="relu")
        conv = layers.pool2d(conv, pool_size=3, pool_stride=2, pool_padding=1)
    else:  # cifar10: 3x3 stem, no maxpool
        conv = conv_bn_layer(img, 64, 3, stride=1, act="relu")

    for stage, count in enumerate(counts):
        num_filters = 64 * (2 ** stage)
        for i in range(count):
            stride = 2 if i == 0 and stage > 0 else 1
            conv = block_fn(conv, num_filters, stride)

    pool = layers.pool2d(conv, pool_type="avg", global_pooling=True)
    logits = layers.fc(pool, size=class_num)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(layers.softmax(logits), label)
    return logits, loss, acc


def resnet50(img, label, class_num=1000):
    return resnet(img, label, depth=50, class_num=class_num)


def resnet_cifar10(img, label, depth=18, class_num=10):
    return resnet(img, label, depth=depth, class_num=class_num, dataset="cifar10")
