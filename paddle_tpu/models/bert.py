"""Named BERT configs over the encoder in models/transformer.py.

Reference workload: BERT-base in
inference/tests/api/analyzer_bert_tester.cc and the BASELINE.json bert
entry. ``bert_base``/``bert_large`` pin the canonical hyperparameters;
``bert_tiny`` is the test-scale config used by the pretrain convergence
test.
"""

from __future__ import annotations

from .transformer import bert_encoder, bert_pretrain

__all__ = ["BERT_BASE_CONFIG", "BERT_LARGE_CONFIG", "bert_base", "bert_large",
           "bert_tiny", "bert_pretrain"]

BERT_BASE_CONFIG = dict(vocab_size=30522, max_position=512, type_vocab_size=2,
                        n_layer=12, n_head=12, d_model=768, d_inner=3072)
BERT_LARGE_CONFIG = dict(vocab_size=30522, max_position=512, type_vocab_size=2,
                         n_layer=24, n_head=16, d_model=1024, d_inner=4096)
BERT_TINY_CONFIG = dict(vocab_size=64, max_position=32, type_vocab_size=2,
                        n_layer=2, n_head=2, d_model=32, d_inner=64)


def bert_base(input_ids, pos_ids, sent_ids, input_mask, **overrides):
    cfg = dict(BERT_BASE_CONFIG, **overrides)
    return bert_encoder(input_ids, pos_ids, sent_ids, input_mask, **cfg)


def bert_large(input_ids, pos_ids, sent_ids, input_mask, **overrides):
    cfg = dict(BERT_LARGE_CONFIG, **overrides)
    return bert_encoder(input_ids, pos_ids, sent_ids, input_mask, **cfg)


def bert_tiny(input_ids, pos_ids, sent_ids, input_mask, **overrides):
    cfg = dict(BERT_TINY_CONFIG, **overrides)
    return bert_encoder(input_ids, pos_ids, sent_ids, input_mask, **cfg)
