"""Persistent XLA compile cache (``PADDLE_TPU_COMPILE_CACHE=<dir>``).

The TVM argument (PAPERS.md) applied to this stack: the traced step is an
ahead-of-time compilation artifact, yet by default every process restart
re-pays the full XLA compile — minutes for the big train steps. JAX ships a
persistent on-disk compilation cache; this module wires it up at import
when ``PADDLE_TPU_COMPILE_CACHE`` names a directory, with the cache
thresholds zeroed so *every* executable is cached (JAX's defaults skip
fast-compiling programs, which would make CPU tests and small models look
like the cache doesn't work).

Observability: a ``compile_cache/hit`` / ``compile_cache/miss`` counter
pair in :mod:`paddle_tpu.monitor`, fed by JAX's own monitoring events — so
a bench JSON ``metrics`` section from a warm process shows the hits
directly. Pair with ``tools/warmup.py`` (AOT ``lower().compile()`` of a
named model) to prime the cache before the real job.
"""

from __future__ import annotations

import os
from typing import Optional

from .monitor import metrics as _mx

__all__ = ["setup_compile_cache", "compile_cache_dir", "is_configured"]

# Registered at import so the counters exist (value 0) even when the cache
# is off — tools/dump_metrics --selftest asserts their presence.
_m_hit = _mx.counter("compile_cache/hit",
                     help="XLA executables loaded from the persistent "
                          "compile cache (PADDLE_TPU_COMPILE_CACHE)")
_m_miss = _mx.counter("compile_cache/miss",
                      help="XLA compiles that went to the compiler and were "
                           "written to the persistent cache")

_configured = False

_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_MISS_EVENT = "/jax/compilation_cache/cache_misses"


def compile_cache_dir() -> Optional[str]:
    """The configured cache directory, or None when the env var is unset."""
    return os.environ.get("PADDLE_TPU_COMPILE_CACHE") or None


def is_configured() -> bool:
    return _configured


def _on_event(event: str, **kwargs) -> None:
    if event == _HIT_EVENT:
        _m_hit.inc()
    elif event == _MISS_EVENT:
        _m_miss.inc()


def setup_compile_cache(path: Optional[str] = None) -> bool:
    """Point JAX's persistent compilation cache at ``path`` (default: the
    ``PADDLE_TPU_COMPILE_CACHE`` env var) and hook the hit/miss counters.

    Idempotent; returns True when the cache is (now) configured. Called at
    ``paddle_tpu`` import, so setting the env var is all a job needs — but
    it can also be called explicitly before any compile to enable the cache
    programmatically.
    """
    global _configured
    if _configured:
        return True
    path = path or compile_cache_dir()
    if not path:
        return False
    import jax

    jax.config.update("jax_compilation_cache_dir", os.path.abspath(path))
    # Cache EVERYTHING: the default min-size/min-compile-time thresholds
    # exist to keep the cache small, but they also make warm-start silently
    # not happen for small models — the worst failure mode for a knob whose
    # whole point is predictable restart latency.
    for knob, val in (("jax_persistent_cache_min_entry_size_bytes", 0),
                      ("jax_persistent_cache_min_compile_time_secs", 0)):
        try:
            jax.config.update(knob, val)
        except AttributeError:  # older jax without the knob
            pass
    try:
        from jax._src import monitoring as _jmon

        # register once; _configured guards re-registration
        _jmon.register_event_listener(_on_event)
    except Exception:
        # counters stay at 0 but the on-disk cache still works
        pass
    _configured = True
    return True
