"""Imperative (dygraph) engine: eager op dispatch + define-by-run autograd.

The reference's imperative mode routes every appended op through a C++
Tracer that executes it immediately and records grad-op nodes for a later
backward walk (reference: paddle/fluid/imperative/tracer.cc:102,
imperative/layer.h:113 VarBase / :285 OpBase, python/paddle/fluid/framework.py
``_in_imperative_mode``). JAX is already eager outside ``jit``, so the
TPU-native design needs no second execution engine: ``dispatch`` runs the
*same registered op impls* the static Executor traces (core/registry.py),
eagerly, and records a lightweight autodiff Node per call. ``backward`` walks
the recorded DAG once, computing each node's input cotangents with
``jax.vjp`` — the replayed-grad-program structure of the reference, but
derived from the op's own JAX definition instead of hand-written grad ops.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import unique_name
from ..core.dtypes import convert_dtype, to_jnp_dtype
from ..core.registry import OpContext, get_op_impl

__all__ = ["VarBase", "Tracer", "dispatch", "trace_fn", "EagerBlock", "current_tracer"]

_TRACER_STACK: List["Tracer"] = []


def current_tracer() -> Optional["Tracer"]:
    return _TRACER_STACK[-1] if _TRACER_STACK else None


def _is_float(x) -> bool:
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


class VarBase:
    """Eager variable: a jax array + autograd metadata.

    The analog of the reference's ``imperative::VarBase``
    (imperative/layer.h:113): holds the value, the accumulated gradient, the
    producing autodiff node, and the ``stop_gradient`` flag.
    """

    def __init__(self, value, name: Optional[str] = None, stop_gradient: bool = False,
                 persistable: bool = False, trainable: bool = True,
                 is_parameter: bool = False):
        self.value = jnp.asarray(value)
        self.name = name or unique_name.generate("tmp_var")
        self.stop_gradient = stop_gradient
        self.persistable = persistable
        self.trainable = trainable
        self.is_parameter = is_parameter  # trainable-weight flag (vs BN stats etc.)
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self._grad = None
        self._node: Optional[Node] = None

    # -- reference VarBase surface -------------------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self.value.shape)

    @property
    def dtype(self) -> str:
        return convert_dtype(str(self.value.dtype))

    def numpy(self) -> np.ndarray:
        return np.asarray(self.value)

    _numpy = numpy  # reference 1.x spelling: var._numpy()

    def gradient(self) -> Optional[np.ndarray]:
        return None if self._grad is None else np.asarray(self._grad)

    _gradient = gradient

    def backward(self):
        backward(self)

    _backward = backward

    def clear_gradient(self):
        self._grad = None

    def detach(self) -> "VarBase":
        return VarBase(self.value, name=self.name + ".detach", stop_gradient=True)

    def astype(self, dtype) -> "VarBase":
        return trace_fn(lambda x: x.astype(to_jnp_dtype(convert_dtype(dtype))), self)

    def __repr__(self):
        return "VarBase(name=%s, shape=%s, dtype=%s)" % (self.name, self.shape, self.dtype)

    def __len__(self):
        return int(self.value.shape[0])

    # -- math sugar (taped) ---------------------------------------------------
    def _binop(self, other, fn):
        other = other if isinstance(other, VarBase) else jnp.asarray(other)
        return trace_fn(fn, self, other)

    def __add__(self, o):
        return self._binop(o, lambda a, b: a + b)

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, lambda a, b: a - b)

    def __rsub__(self, o):
        return self._binop(o, lambda a, b: b - a)

    def __mul__(self, o):
        return self._binop(o, lambda a, b: a * b)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, lambda a, b: a / b)

    def __rtruediv__(self, o):
        return self._binop(o, lambda a, b: b / a)

    def __pow__(self, o):
        return self._binop(o, lambda a, b: a ** b)

    def __matmul__(self, o):
        return self._binop(o, lambda a, b: a @ b)

    def __neg__(self):
        return trace_fn(lambda a: -a, self)

    def __getitem__(self, idx):
        return trace_fn(lambda a: a[idx], self)


class Node:
    """One recorded eager op: enough to replay it under ``jax.vjp``.

    Input arrays are saved at record time (reference OpBase keeps its input
    VarBase holders alive the same way) because ``.value`` of a VarBase may be
    overwritten later (e.g. in-place optimizer updates).
    """

    __slots__ = ("fn", "in_vars", "in_arrays", "out_vars")

    def __init__(self, fn, in_vars, in_arrays, out_vars):
        self.fn = fn                  # fn(*in_arrays) -> tuple(out arrays)
        self.in_vars = in_vars        # List[Optional[VarBase]], parallel to in_arrays
        self.in_arrays = in_arrays
        self.out_vars = out_vars      # Tuple[Optional[VarBase]]


def _record(fn, in_vars, in_arrays, out_arrays) -> Tuple[VarBase, ...]:
    """Wrap eager outputs in VarBases and, if any input needs grad, link a Node."""
    out_vars = tuple(
        None if a is None else VarBase(a, stop_gradient=True) for a in out_arrays
    )
    needs_grad = any(
        v is not None and not v.stop_gradient and _is_float(a)
        for v, a in zip(in_vars, in_arrays)
    )
    if needs_grad:
        node = Node(fn, list(in_vars), list(in_arrays), out_vars)
        for ov in out_vars:
            if ov is not None and _is_float(ov.value):
                ov.stop_gradient = False
                ov._node = node
    return out_vars


def trace_fn(fn, *inputs, **kwargs):
    """Apply a pure jnp function to VarBase/array inputs, eagerly, on the tape.

    The dygraph PyLayer primitive: any JAX-traceable function becomes a
    differentiable eager op.
    """
    in_vars = [x if isinstance(x, VarBase) else None for x in inputs]
    in_arrays = [x.value if isinstance(x, VarBase) else jnp.asarray(x) for x in inputs]
    f = (lambda *a: fn(*a, **kwargs)) if kwargs else fn
    out = f(*in_arrays)
    multi = isinstance(out, (tuple, list))
    outs = tuple(out) if multi else (out,)
    fn_tuple = (lambda *a: tuple(f(*a))) if multi else (lambda *a: (f(*a),))
    out_vars = _record(fn_tuple, in_vars, in_arrays, outs)
    return out_vars if multi else out_vars[0]


class _FakeOp:
    """Minimal symbolic-op shim so registered impls run outside a Program."""

    __slots__ = ("type", "inputs", "outputs", "attrs", "block")

    def __init__(self, type_, inputs, outputs, attrs):
        self.type = type_
        self.inputs = inputs
        self.outputs = outputs
        self.attrs = attrs
        self.block = None


class _EagerTrace:
    """TraceContext stand-in for eager op execution (rng + test mode)."""

    def __init__(self, rng_key, is_test: bool):
        self.base_rng = rng_key
        self.is_test = is_test
        self.current_op_idx = 0
        self.mesh = None
        self.program = None

    def op_rng(self, ctx: OpContext):
        seed = ctx.attr("seed", 0)
        key = jax.random.PRNGKey(seed) if seed else self.base_rng
        return jax.random.fold_in(key, self.current_op_idx)


def _flatten_slots(d: Optional[Dict[str, Any]], prefix: str):
    """slot→(value|list) dict → (op slot-name map, [(name, value)] pairs)."""
    slot_names: Dict[str, List[str]] = {}
    flat: List[Tuple[str, Any]] = []
    for slot, val in (d or {}).items():
        if val is None:
            continue
        vals = list(val) if isinstance(val, (list, tuple)) else [val]
        names = []
        for i, v in enumerate(vals):
            n = "__%s_%s_%d" % (prefix, slot, i)
            names.append(n)
            flat.append((n, v))
        slot_names[slot] = names
    return slot_names, flat


def dispatch(type_: str, inputs: Dict[str, Any], attrs: Optional[Dict[str, Any]] = None,
             out_slots: Sequence[str] = ("Out",), is_test: Optional[bool] = None):
    """Run a registered op eagerly with autograd.

    ``inputs`` maps slot → VarBase | array | list thereof (None skipped);
    returns one VarBase per out slot (single value if one slot). This is the
    imperative twin of the static tracer's op step — same registry, same
    semantics, so every op in paddle_tpu/ops/ works in dygraph.
    """
    tracer = current_tracer()
    op_inputs, flat = _flatten_slots(inputs, "in")
    flat_names = [n for n, _ in flat]
    flat_vals = [v for _, v in flat]
    op_outputs = {s: ["__out_%s" % s] for s in out_slots}
    op = _FakeOp(type_, op_inputs, op_outputs, dict(attrs or {}))
    impl = get_op_impl(type_)
    if is_test is None:
        is_test = not (tracer.training if tracer else True)
    rng_key = tracer.next_rng() if tracer else jax.random.PRNGKey(0)

    in_vars = [v if isinstance(v, VarBase) else None for v in flat_vals]
    in_arrays = [v.value if isinstance(v, VarBase) else jnp.asarray(v) for v in flat_vals]

    def fn_core(*arrays):
        env = dict(zip(flat_names, arrays))
        impl(OpContext(op, env, _EagerTrace(rng_key, is_test)))
        return tuple(env.get("__out_%s" % s) for s in out_slots)

    outs = fn_core(*in_arrays)
    out_vars = _record(fn_core, in_vars, in_arrays, outs)
    return out_vars if len(out_slots) > 1 else out_vars[0]


def backward(loss: VarBase):
    """Reverse pass from ``loss`` over the recorded DAG.

    Reverse post-order DFS over producer links gives a topological order in
    which every consumer is processed before the node that produced its
    inputs, so each node runs its ``jax.vjp`` exactly once with complete
    output cotangents (the reference's sorted grad-op replay,
    imperative/layer.cc ApplyGrad).
    """
    if loss._node is None and loss.stop_gradient:
        raise RuntimeError(
            "backward() on a variable with no recorded graph — did every "
            "input have stop_gradient=True?")
    order: List[Node] = []
    seen = set()
    stack: List[Tuple[Node, bool]] = [(loss._node, False)] if loss._node else []
    while stack:
        node, done = stack.pop()
        if done:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for v in node.in_vars:
            if v is not None and v._node is not None and id(v._node) not in seen:
                stack.append((v._node, False))
    # A repeated backward must not compound stale intermediate cotangents:
    # clear every non-leaf grad in the subgraph, then re-seed. Leaves
    # (parameters / user vars not produced by a node) keep accumulating,
    # matching the reference's VarBase grad accumulation semantics.
    for node in order:
        for ov in node.out_vars:
            if ov is not None:
                ov._grad = None
    loss._grad = jnp.ones_like(loss.value)
    for node in reversed(order):
        _node_backward(node)


def _node_backward(node: Node):
    diff_pos = [
        i for i, (v, a) in enumerate(zip(node.in_vars, node.in_arrays))
        if v is not None and _is_float(a)
    ]
    out_pos = [
        j for j, ov in enumerate(node.out_vars)
        if ov is not None and _is_float(ov.value)
    ]
    if not diff_pos or not out_pos:
        return
    cts = []
    any_ct = False
    for j in out_pos:
        g = node.out_vars[j]._grad
        if g is None:
            cts.append(jnp.zeros_like(node.out_vars[j].value))
        else:
            cts.append(g)
            any_ct = True
    if not any_ct:
        return

    def f_diff(*diff_arrays):
        full = list(node.in_arrays)
        for p, a in zip(diff_pos, diff_arrays):
            full[p] = a
        outs = node.fn(*full)
        return tuple(outs[j] for j in out_pos)

    primals = tuple(node.in_arrays[p] for p in diff_pos)
    _, vjp_fn = jax.vjp(f_diff, *primals)
    in_cts = vjp_fn(tuple(cts))
    for p, ct in zip(diff_pos, in_cts):
        v = node.in_vars[p]
        if v.stop_gradient:
            continue
        v._grad = ct if v._grad is None else v._grad + ct


class EagerBlock:
    """Block stand-in whose ``append_op`` executes immediately, in place.

    Used where static code appends state-mutating ops — parameter
    initializers and optimizer update ops. Inputs/outputs may be VarBase or
    any object with a ``.value`` array (optimizer accumulator slots); outputs
    are written back in place with no autograd (these ops are leaves).
    """

    def append_op(self, type_, inputs=None, outputs=None, attrs=None):
        op_inputs, in_flat = _flatten_slots(inputs, "in")
        env = {n: getattr(v, "value", v) for n, v in in_flat}
        op_outputs, out_flat = _flatten_slots(outputs, "out")
        out_objs = dict(out_flat)
        op = _FakeOp(type_, op_inputs, op_outputs, dict(attrs or {}))
        tracer = current_tracer()
        rng = tracer.next_rng() if tracer else jax.random.PRNGKey(0)
        get_op_impl(type_)(OpContext(op, env, _EagerTrace(rng, is_test=False)))
        for n, obj in out_objs.items():
            if n in env:
                obj.value = env[n]
        return op


class Tracer:
    """Per-guard state: parameter registry, RNG stream, train/eval mode."""

    def __init__(self, seed: int = 0):
        self._params: Dict[str, VarBase] = {}
        self._key = jax.random.PRNGKey(seed or 0)
        self._counter = 0
        self.training = True

    def next_rng(self):
        self._counter += 1
        return jax.random.fold_in(self._key, self._counter)

    def register_parameter(self, p: VarBase):
        self._params[p.name] = p

    def parameters(self) -> List[VarBase]:
        return list(self._params.values())

    def train_mode(self):
        self.training = True

    def eval_mode(self):
        self.training = False
