"""Dygraph layers (reference: python/paddle/fluid/imperative/nn.py —
Conv2D:28, Pool2D:134, FC:193, BatchNorm:266, Embedding:388).

Each forward dispatches the same registered ops the static graph uses
(tracer.dispatch), so numerics match static mode exactly. BatchNorm's
running-stat update writes the eager state variables in place, which is the
dygraph twin of the static op's MeanOut/VarianceOut in-place outputs.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .. import initializer as init_mod
from ..core.dtypes import convert_dtype, to_jnp_dtype
from ..layers.layer_helper import ParamAttr
from .layers import Layer
from .tracer import VarBase, dispatch, trace_fn

__all__ = ["Conv2D", "Pool2D", "FC", "BatchNorm", "Embedding"]


def _act(out: VarBase, act: Optional[str]) -> VarBase:
    if act is None:
        return out
    return dispatch(act, {"X": out})


class Conv2D(Layer):
    """reference: imperative/nn.py:28."""

    def __init__(self, name_scope, num_channels, num_filters, filter_size,
                 stride=1, padding=0, dilation=1, groups=None, param_attr=None,
                 bias_attr=None, use_cudnn=False, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        groups = groups or 1
        if isinstance(filter_size, int):
            filter_size = [filter_size, filter_size]
        self._stride = [stride, stride] if isinstance(stride, int) else list(stride)
        self._padding = [padding, padding] if isinstance(padding, int) else list(padding)
        self._dilation = [dilation, dilation] if isinstance(dilation, int) else list(dilation)
        self._groups = groups
        self._act = act
        filter_shape = [num_filters, num_channels // groups] + list(filter_size)
        std = (2.0 / (filter_shape[1] * filter_shape[2] * filter_shape[3])) ** 0.5
        self.weight = self.create_parameter(
            attr=param_attr, shape=filter_shape, dtype=dtype,
            default_initializer=init_mod.Normal(0.0, std))
        self.bias = (None if bias_attr is False else self.create_parameter(
            attr=bias_attr, shape=[num_filters], dtype=dtype, is_bias=True))

    def forward(self, input):
        out = dispatch("conv2d", {"Input": input, "Filter": self.weight},
                       attrs={"strides": self._stride, "paddings": self._padding,
                              "dilations": self._dilation, "groups": self._groups},
                       out_slots=("Output",))
        if self.bias is not None:
            out = dispatch("elementwise_add", {"X": out, "Y": self.bias},
                           attrs={"axis": 1})
        return _act(out, self._act)


class Pool2D(Layer):
    """reference: imperative/nn.py:134."""

    def __init__(self, name_scope, pool_size=-1, pool_type="max", pool_stride=1,
                 pool_padding=0, global_pooling=False, use_cudnn=False,
                 ceil_mode=False, exclusive=True, dtype="float32"):
        super().__init__(name_scope, dtype)
        if pool_type not in ("max", "avg"):
            raise ValueError("pool_type must be 'max' or 'avg', got %r" % pool_type)
        self._attrs = {
            "pooling_type": pool_type,
            "ksize": [pool_size, pool_size] if isinstance(pool_size, int) else list(pool_size),
            "strides": [pool_stride, pool_stride] if isinstance(pool_stride, int) else list(pool_stride),
            "paddings": [pool_padding, pool_padding] if isinstance(pool_padding, int) else list(pool_padding),
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
        }

    def forward(self, input):
        return dispatch("pool2d", {"X": input}, attrs=dict(self._attrs))


class FC(Layer):
    """reference: imperative/nn.py:193 — lazily sized on first input."""

    def __init__(self, name_scope, size, param_attr=None, bias_attr=None,
                 num_flatten_dims=1, dtype="float32", act=None):
        super().__init__(name_scope, dtype)
        self._size = size
        self._num_flatten_dims = num_flatten_dims
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._act = act

    def _build_once(self, input):
        in_dim = 1
        for d in input.shape[self._num_flatten_dims:]:
            in_dim *= int(d)
        self.weight = self.create_parameter(
            attr=self._param_attr, shape=[in_dim, self._size], dtype=self._dtype)
        self.bias = (None if self._bias_attr is False else self.create_parameter(
            attr=self._bias_attr, shape=[self._size], dtype=self._dtype, is_bias=True))

    def forward(self, input):
        out = dispatch("mul", {"X": input, "Y": self.weight},
                       attrs={"x_num_col_dims": self._num_flatten_dims,
                              "y_num_col_dims": 1})
        if self.bias is not None:
            out = dispatch("elementwise_add", {"X": out, "Y": self.bias},
                           attrs={"axis": out.value.ndim - 1})
        return _act(out, self._act)


class BatchNorm(Layer):
    """reference: imperative/nn.py:266. Running stats are eager state vars
    updated in place each training forward."""

    def __init__(self, name_scope, num_channels, act=None, is_test=False,
                 momentum=0.9, epsilon=1e-5, param_attr=None, bias_attr=None,
                 dtype="float32", data_layout="NCHW", use_global_stats=False,
                 moving_mean_name=None, moving_variance_name=None):
        super().__init__(name_scope, dtype)
        self._momentum = momentum
        self._epsilon = epsilon
        self._act = act
        self._is_test = is_test
        self._layout = data_layout
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            attr=param_attr, shape=[num_channels], dtype=dtype,
            default_initializer=init_mod.Constant(1.0))
        self.bias = self.create_parameter(
            attr=bias_attr, shape=[num_channels], dtype=dtype, is_bias=True)
        self._mean = self.create_variable(
            name=moving_mean_name, persistable=True, dtype=dtype, shape=[num_channels])
        self._variance = self.create_variable(
            name=moving_variance_name, persistable=True, dtype=dtype, shape=[num_channels])
        self._variance.value = jnp.ones((num_channels,), to_jnp_dtype(convert_dtype(dtype)))

    def forward(self, input):
        from . import tracer as tracer_mod

        t = tracer_mod.current_tracer()
        # constructor is_test=True pins inference; otherwise follow the
        # tracer's train/eval mode (Layer.eval()) like the static trace does
        is_test = True if self._is_test else (t is not None and not t.training)
        y, mean_out, var_out = dispatch(
            "batch_norm",
            {"X": input, "Scale": self.weight, "Bias": self.bias,
             "Mean": self._mean, "Variance": self._variance},
            attrs={"momentum": self._momentum, "epsilon": self._epsilon,
                   "data_layout": self._layout, "is_test": is_test,
                   "use_global_stats": self._use_global_stats},
            out_slots=("Y", "MeanOut", "VarianceOut"))
        if not is_test:
            self._mean.value = mean_out.value
            self._variance.value = var_out.value
        return _act(y, self._act)


class Embedding(Layer):
    """reference: imperative/nn.py:388."""

    def __init__(self, name_scope, size, is_sparse=False, is_distributed=False,
                 padding_idx=None, param_attr=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._size = size
        # normalize to a non-negative row index (the op impl only masks >= 0)
        self._padding_idx = (-1 if padding_idx is None
                             else padding_idx if padding_idx >= 0
                             else size[0] + padding_idx)
        self.weight = self.create_parameter(
            attr=param_attr, shape=list(size), dtype=dtype,
            default_initializer=init_mod.Xavier())
        if padding_idx is not None:
            self.weight.value = self.weight.value.at[self._padding_idx].set(0.0)

    def forward(self, input):
        return dispatch("lookup_table_v2", {"W": self.weight, "Ids": input},
                        attrs={"padding_idx": self._padding_idx})
