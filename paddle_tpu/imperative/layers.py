"""Dygraph Layer base classes (reference: python/paddle/fluid/imperative/layers.py:28,216).

``Layer`` owns eagerly-initialized parameters (VarBase) and composes via
attribute assignment, mirroring the reference's Layer/sublayer/parameter
registries. ``PyLayer`` wraps a user-defined forward (and optional custom
backward) as a taped eager op.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp

from .. import initializer as init_mod
from ..core import unique_name
from ..core.dtypes import convert_dtype, to_jnp_dtype
from ..layers.layer_helper import ParamAttr
from . import tracer as tracer_mod
from .tracer import EagerBlock, VarBase, trace_fn

__all__ = ["Layer", "PyLayer"]


class Layer:
    """reference: imperative/layers.py:28 (class Layer(core.Layer))."""

    def __init__(self, name_scope: str, dtype: str = "float32"):
        self._full_name = unique_name.generate(name_scope)
        self._dtype = convert_dtype(dtype)
        self._parameters: Dict[str, VarBase] = {}
        self._sub_layers: Dict[str, "Layer"] = {}
        self._built = False

    def full_name(self) -> str:
        """reference: imperative/layers.py:49."""
        return self._full_name

    # -- parameter/variable creation -----------------------------------------
    def create_parameter(self, attr=None, shape=None, dtype=None, is_bias: bool = False,
                         default_initializer=None) -> VarBase:
        """Eagerly create + initialize a parameter (reference:
        imperative/layers.py:58 → layer_object_helper.py create_parameter).
        The initializer's init op runs immediately through EagerBlock instead
        of being appended to a startup program."""
        attr = ParamAttr.to_attr(attr)
        dtype = convert_dtype(dtype or self._dtype)
        name = attr.name or unique_name.generate(
            "%s.%s" % (self._full_name, "b" if is_bias else "w"))
        initializer = attr.initializer or default_initializer
        if initializer is None:
            initializer = init_mod.Constant(0.0) if is_bias else init_mod.Xavier()
        p = VarBase(jnp.zeros(tuple(shape), to_jnp_dtype(dtype)), name=name,
                    persistable=True, trainable=attr.trainable, is_parameter=True)
        p.stop_gradient = not attr.trainable
        p.optimize_attr = {"learning_rate": attr.learning_rate}
        p.regularizer = attr.regularizer
        initializer(p, EagerBlock())
        t = tracer_mod.current_tracer()
        if t is not None:
            t.register_parameter(p)
        return p

    def create_variable(self, name: Optional[str] = None, persistable: bool = False,
                        dtype: Optional[str] = None, shape=None) -> VarBase:
        """Non-trainable eager state (e.g. BN running stats); reference:
        imperative/layers.py:79."""
        dtype = convert_dtype(dtype or self._dtype)
        v = VarBase(jnp.zeros(tuple(shape or []), to_jnp_dtype(dtype)),
                    name=name or unique_name.generate("%s.var" % self._full_name),
                    stop_gradient=True, persistable=persistable, trainable=False)
        return v

    # -- registries -----------------------------------------------------------
    def parameters(self, include_sublayers: bool = True) -> List[VarBase]:
        ret = list(self._parameters.values())
        if include_sublayers:
            for l in self._sub_layers.values():
                ret.extend(l.parameters(include_sublayers=True))
        return ret

    def sublayers(self, include_sublayers: bool = True) -> List["Layer"]:
        ret = list(self._sub_layers.values())
        if include_sublayers:
            for l in self._sub_layers.values():
                ret.extend(l.sublayers(include_sublayers=True))
        return ret

    def state_dict(self) -> Dict[str, VarBase]:
        """All persistable state by parameter name (for save/load)."""
        out = {p.name: p for p in self.parameters()}
        for l in [self] + self.sublayers():
            for v in vars(l).values():
                if isinstance(v, VarBase) and v.persistable:
                    out[v.name] = v
        return out

    def clear_gradients(self):
        """reference: imperative/layers.py:134."""
        for p in self.parameters():
            p.clear_gradient()

    def set_state(self, state_dict, strict: bool = True):
        """Load arrays produced by ``base.save_dygraph`` into this Layer's
        parameters/state by name; shapes/dtypes must match."""
        own = self.state_dict()
        missing = [k for k in own if k not in state_dict]
        unexpected = [k for k in state_dict if k not in own]
        if strict and (missing or unexpected):
            raise KeyError("state mismatch: missing=%s unexpected=%s"
                           % (missing, unexpected))
        for k, arr in state_dict.items():
            if k not in own:
                continue
            arr = jnp.asarray(arr)
            if tuple(arr.shape) != own[k].shape:
                raise ValueError(
                    "state %r has shape %s but parameter expects %s"
                    % (k, tuple(arr.shape), own[k].shape))
            own[k].value = arr.astype(own[k].value.dtype)

    # reference-compat alias
    load_dict = set_state

    def train(self):
        t = tracer_mod.current_tracer()
        if t:
            t.train_mode()

    def eval(self):
        t = tracer_mod.current_tracer()
        if t:
            t.eval_mode()

    # -- call protocol --------------------------------------------------------
    def _build_once(self, *args):
        pass

    def __call__(self, *inputs):
        if not self._built:
            self._build_once(*inputs)
            self._built = True
        return self.forward(*inputs)

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *inputs):
        raise ValueError("Layer shouldn't implement backward")

    # -- explicit registration -------------------------------------------------
    def add_sublayer(self, name: str, sublayer: "Layer") -> "Layer":
        assert isinstance(sublayer, Layer)
        self._sub_layers[name] = sublayer
        return sublayer

    def add_parameter(self, name: str, parameter: VarBase) -> VarBase:
        assert isinstance(parameter, VarBase)
        parameter.is_parameter = True
        self._parameters[name] = parameter
        return parameter

    # -- attribute magic (reference: imperative/layers.py:185-214) ------------
    def __getattr__(self, name):
        if "_parameters" in self.__dict__ and name in self.__dict__["_parameters"]:
            return self.__dict__["_parameters"][name]
        if "_sub_layers" in self.__dict__ and name in self.__dict__["_sub_layers"]:
            return self.__dict__["_sub_layers"][name]
        raise AttributeError(name)

    def __setattr__(self, name, value):
        if isinstance(value, VarBase) and getattr(value, "is_parameter", False):
            # only true parameters — persistable state like BN running stats
            # stays a plain attribute (it must not appear in parameters())
            self.__dict__.get("_parameters", {}).pop(name, None)
            if "_parameters" in self.__dict__:
                self._parameters[name] = value
                return
        elif isinstance(value, Layer):
            if "_sub_layers" in self.__dict__:
                self._sub_layers[name] = value
                return
        object.__setattr__(self, name, value)

    def __delattr__(self, name):
        if name in self.__dict__.get("_parameters", {}):
            del self._parameters[name]
        elif name in self.__dict__.get("_sub_layers", {}):
            del self._sub_layers[name]
        else:
            object.__delattr__(self, name)


class PyLayer:
    """User-defined eager op (reference: imperative/layers.py:216).

    Subclass with static ``forward(*arrays)``; autograd comes from jax.vjp
    over it (a custom ``backward`` is unnecessary under JAX but accepted for
    API parity and ignored with a clear error if it disagrees in arity).
    """

    def __init__(self):
        pass

    @staticmethod
    def forward(*inputs):
        raise NotImplementedError

    @staticmethod
    def backward(*douts):
        raise NotImplementedError

    @classmethod
    def __call__(cls, *inputs):
        return cls.apply(*inputs)

    @classmethod
    def apply(cls, *inputs):
        return trace_fn(cls.forward, *inputs)
