"""Imperative mode entry points (reference: python/paddle/fluid/imperative/base.py:29,47).

``guard()`` activates a Tracer; inside it, ops run eagerly with autograd
(see tracer.py) and ``to_variable`` lifts numpy arrays onto the device.
"""

from __future__ import annotations

import contextlib

import numpy as np

from ..core import unique_name
from . import tracer as tracer_mod
from .tracer import Tracer, VarBase

__all__ = ["enabled", "guard", "to_variable", "save_dygraph", "load_dygraph"]


def enabled() -> bool:
    """reference: framework._in_imperative_mode()."""
    return tracer_mod.current_tracer() is not None


@contextlib.contextmanager
def guard(place=None, seed: int = 0):
    """Enter imperative mode (reference: imperative/base.py:29).

    ``place`` is accepted for API parity; XLA owns device placement.
    """
    t = Tracer(seed=seed)
    tracer_mod._TRACER_STACK.append(t)
    try:
        with unique_name.guard():
            yield t
    finally:
        tracer_mod._TRACER_STACK.pop()


def to_variable(value, block=None, name=None) -> VarBase:
    """Lift a numpy array (or VarBase, passthrough) to an eager variable
    (reference: imperative/base.py:47)."""
    if isinstance(value, VarBase):
        return value
    if not enabled():
        raise RuntimeError("to_variable could only be called in imperative mode "
                           "(inside paddle_tpu.imperative.guard())")
    value = np.asarray(value)
    return VarBase(value, name=name)


def save_dygraph(state_or_layer, model_path: str):
    """Save a Layer's (or dict of VarBase) state to ``model_path``
    (reference: the dygraph save_persistables / later save_dygraph API)."""
    from .layers import Layer  # local: layers imports this module's guard

    state = state_or_layer.state_dict() if isinstance(state_or_layer, Layer) \
        else dict(state_or_layer)
    arrays = {name: np.asarray(v.value if hasattr(v, "value") else v)
              for name, v in state.items()}
    np.savez(model_path + ".npz", **arrays)


def load_dygraph(model_path: str):
    """→ {name: np.ndarray}; pair with ``Layer.set_state``."""
    with np.load(model_path + ".npz") as data:
        return {k: data[k] for k in data.files}
