"""Eager functional ops for dygraph code.

Thin wrappers over ``tracer.dispatch`` onto the registered op impls — the
same kernels static programs trace, run eagerly with autograd. The reference
reuses its ``fluid.layers.*`` functions in imperative mode via the tracer
hook (python/paddle/fluid/framework.py _in_imperative_mode branches); here
the explicit functional namespace keeps the static layer builders (which do
shape inference on symbolic Variables) separate from eager execution.
"""

from __future__ import annotations

from .tracer import VarBase, dispatch, trace_fn

__all__ = [
    "relu", "sigmoid", "tanh", "softmax", "mean", "reduce_sum", "reshape",
    "cross_entropy", "softmax_with_cross_entropy", "dropout", "concat",
    "matmul", "log_softmax", "square", "sqrt", "exp", "log", "accuracy",
]


def _unary(op):
    def f(x):
        return dispatch(op, {"X": x})

    f.__name__ = op
    return f


relu = _unary("relu")
sigmoid = _unary("sigmoid")
tanh = _unary("tanh")
square = _unary("square")
sqrt = _unary("sqrt")
exp = _unary("exp")
log = _unary("log")


def softmax(x, axis=-1):
    return dispatch("softmax", {"X": x}, attrs={"axis": axis})


def log_softmax(x, axis=-1):
    return dispatch("log_softmax", {"X": x}, attrs={"axis": axis})


def mean(x):
    return dispatch("mean", {"X": x})


def reduce_sum(x, dim=None, keep_dim=False):
    return dispatch("reduce_sum", {"X": x},
                    attrs={"dim": dim, "keep_dim": keep_dim,
                           "reduce_all": dim is None})


def reshape(x, shape):
    return dispatch("reshape2", {"X": x}, attrs={"shape": list(shape)})


def concat(xs, axis=0):
    return dispatch("concat", {"X": list(xs)}, attrs={"axis": axis})


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0):
    return dispatch("matmul", {"X": x, "Y": y},
                    attrs={"transpose_X": transpose_x, "transpose_Y": transpose_y,
                           "alpha": alpha})


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    return dispatch("cross_entropy", {"X": input, "Label": label},
                    attrs={"soft_label": soft_label, "ignore_index": ignore_index})


def softmax_with_cross_entropy(logits, label, soft_label=False):
    loss, _ = dispatch("softmax_with_cross_entropy",
                       {"Logits": logits, "Label": label},
                       attrs={"soft_label": soft_label},
                       out_slots=("Loss", "Softmax"))
    return loss


def dropout(x, dropout_prob=0.5, is_test=None, seed=0):
    return dispatch("dropout", {"X": x},
                    attrs={"dropout_prob": dropout_prob, "seed": seed},
                    is_test=is_test)


def accuracy(input, label, k=1):
    import jax.numpy as jnp

    def acc(logits, lab):
        pred = jnp.argmax(logits, axis=-1)
        return jnp.mean((pred == lab.reshape(pred.shape)).astype(jnp.float32))

    return trace_fn(acc, input, label)
