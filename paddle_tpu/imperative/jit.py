"""Dygraph → compiled execution: the ``imperative.jit`` escape hatch.

The reference's dygraph runs one kernel per op from Python (tracer.cc); ours
interprets the same registered ops eagerly, which costs 10-100x on small
models (README). ``jit(layer)`` closes that gap the TPU-native way: the
Layer's ``forward`` is traced ONCE through ``jax.jit`` — every dispatch()
call executes on tracers instead of concrete arrays — and every later call
runs the single fused XLA executable. This is the dygraph twin of
``to_static`` (the reference grew @declarative/ProgramTranslator for the
same reason, in later versions than the one mirrored here).

Parameters are passed as jit ARGUMENTS (not baked constants), so optimizer
updates to ``layer.parameters()`` take effect without retracing; a reshape
of the inputs triggers exactly one recompile per new shape, like the static
executor's program cache.

Scope: forward/inference. The compiled call returns ``stop_gradient``
VarBases — the eager tape cannot see through an XLA executable. For full
training-step compilation use the static Program path (that IS the
framework's training story); this helper exists so dygraph-style code stops
paying the per-op interpretation tax where it hurts most (eval loops,
generation, metrics).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp

from .layers import Layer
from .tracer import VarBase

__all__ = ["jit"]


def jit(target: Any) -> Callable:
    """Compile a dygraph ``Layer`` (or a function over VarBase/arrays).

    >>> mlp = MyMLP("mlp")
    >>> fast = imperative.jit(mlp)
    >>> y = fast(x)          # first call traces+compiles, later calls fused
    """
    if isinstance(target, Layer):
        fwd = target.forward

        def params():
            return target.parameters()
    else:
        fwd = target

        def params():
            return []

    def run(param_vals, input_vals):
        ps = params()
        olds = [p.value for p in ps]
        for p, v in zip(ps, param_vals):
            p.value = v
        try:
            ins = [VarBase(v, stop_gradient=True) for v in input_vals]
            out = fwd(*ins)
        finally:
            for p, v in zip(ps, olds):
                p.value = v
        return jax.tree_util.tree_map(
            lambda o: o.value if isinstance(o, VarBase) else o, out,
            is_leaf=lambda o: isinstance(o, VarBase))

    compiled = jax.jit(run)  # jit's own cache handles new input shapes

    def wrapper(*inputs):
        if isinstance(target, Layer) and not target._built:
            # lazily-built layers (FC etc.) create params on first forward;
            # run one eager call so the parameter list is final before the
            # trace captures it
            target(*inputs)
        input_vals = [x.value if isinstance(x, VarBase) else jnp.asarray(x)
                      for x in inputs]
        param_vals = [p.value for p in params()]
        out = compiled(param_vals, input_vals)
        return jax.tree_util.tree_map(
            lambda v: VarBase(v, stop_gradient=True), out)

    wrapper._jit_fn = compiled
    return wrapper
