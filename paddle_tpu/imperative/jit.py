"""Dygraph → compiled execution: the ``imperative.jit`` escape hatch.

The reference's dygraph runs one kernel per op from Python (tracer.cc); ours
interprets the same registered ops eagerly, which costs 10-100x on small
models (README). ``jit(layer)`` closes that gap the TPU-native way: the
Layer's ``forward`` is traced ONCE through ``jax.jit`` — every dispatch()
call executes on tracers instead of concrete arrays — and every later call
runs the single fused XLA executable. This is the dygraph twin of
``to_static`` (the reference grew @declarative/ProgramTranslator for the
same reason, in later versions than the one mirrored here).

Parameters are passed as jit ARGUMENTS (not baked constants), so optimizer
updates to ``layer.parameters()`` take effect without retracing; a reshape
of the inputs triggers exactly one recompile per new shape, like the static
executor's program cache.

``jit`` compiles forward/inference (its outputs are ``stop_gradient`` —
the eager tape cannot see through an XLA executable). ``jit_train``
compiles a FULL train step — forward, backward, optimizer update — into
one executable with donated parameter/accumulator buffers: inside the
trace the backward comes from ``jax.value_and_grad`` over the traced
forward (the tape is bypassed) and the update reuses the optimizer's own
eager update math on traced arrays, so every optimizer subclass works
unchanged. This is the dygraph twin of the static Executor's fused train
step (reference: the ProgramTranslator/@declarative direction of later
versions).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp

from .layers import Layer
from .tracer import VarBase

__all__ = ["jit", "jit_train"]


def jit(target: Any) -> Callable:
    """Compile a dygraph ``Layer`` (or a function over VarBase/arrays).

    >>> mlp = MyMLP("mlp")
    >>> fast = imperative.jit(mlp)
    >>> y = fast(x)          # first call traces+compiles, later calls fused
    """
    if isinstance(target, Layer):
        fwd = target.forward

        def params():
            return target.parameters()
    else:
        fwd = target

        def params():
            return []

    def run(param_vals, input_vals):
        ps = params()
        olds = [p.value for p in ps]
        for p, v in zip(ps, param_vals):
            p.value = v
        try:
            ins = [VarBase(v, stop_gradient=True) for v in input_vals]
            out = fwd(*ins)
        finally:
            for p, v in zip(ps, olds):
                p.value = v
        return jax.tree_util.tree_map(
            lambda o: o.value if isinstance(o, VarBase) else o, out,
            is_leaf=lambda o: isinstance(o, VarBase))

    compiled = jax.jit(run)  # jit's own cache handles new input shapes

    def wrapper(*inputs):
        if isinstance(target, Layer) and not target._built:
            # lazily-built layers (FC etc.) create params on first forward;
            # run one eager call so the parameter list is final before the
            # trace captures it
            target(*inputs)
        input_vals = [x.value if isinstance(x, VarBase) else jnp.asarray(x)
                      for x in inputs]
        param_vals = [p.value for p in params()]
        out = compiled(param_vals, input_vals)
        return jax.tree_util.tree_map(
            lambda v: VarBase(v, stop_gradient=True), out)

    wrapper._jit_fn = compiled
    return wrapper


def _unique_slots(optimizer):
    """Deterministic list of the optimizer's UNIQUE eager accumulator slots
    (shared slots — e.g. Adam's one beta-pow pair — appear once)."""
    slots, seen = [], set()
    for name in sorted(optimizer._accumulators):
        per_param = optimizer._accumulators[name]
        for pname in sorted(per_param):
            s = per_param[pname]
            if id(s) not in seen:
                seen.add(id(s))
                slots.append(s)
    return slots


def jit_train(loss_fn: Callable, layer: Layer, optimizer) -> Callable:
    """Compile a dygraph train step to ONE donated-buffer XLA executable.

    ``loss_fn(*inputs) -> scalar-loss VarBase`` is dygraph code over
    ``layer`` (any registered ops). Returns ``step(*inputs) -> loss
    VarBase``; each call runs forward+backward+update fused, updating
    ``layer.parameters()`` and the optimizer's accumulators in place.

    >>> step = imperative.jit_train(
    ...     lambda img, lbl: F.mean(F.softmax_with_cross_entropy(mlp(img), lbl)),
    ...     mlp, fluid.optimizer.Adam(1e-3))
    >>> for img, lbl in batches:
    ...     loss = step(img, lbl)

    The FIRST call runs one ordinary eager step (it materializes lazily-
    built parameters and the optimizer's accumulators, whose set must be
    final before the trace); subsequent calls are compiled. Per-step
    dropout keys derive from a traced step counter, so masks differ per
    step without retracing. Mixing ``step()`` with a manual
    ``loss._backward()`` over the same parameters in the same iteration is
    a HARD ERROR: the compiled step computes its own gradients inside the
    trace and would silently ignore the eager tape's accumulated ``_grad``
    (or double-count it into the warmup step). ``clear_gradient()`` the
    parameters first if the manual pass was intentional.
    """
    from .tracer import current_tracer

    state: Dict[str, Any] = {"compiled": None, "step": 0}

    def _params():
        ps = [p for p in layer.parameters() if p.trainable]
        return sorted(ps, key=lambda p: p.name)

    def _buffers():
        """Non-trainable carried state: frozen parameters plus persistable
        VarBases mutated by forward (e.g. BatchNorm running stats). They
        ride the trace as inputs and (via has_aux) outputs — without this,
        a buffer assigned inside the traced forward would be left holding a
        leaked tracer and its updates silently dropped."""
        out = {id(p): p for p in layer.parameters() if not p.trainable}
        for lyr in [layer] + layer.sublayers():
            for v in vars(lyr).values():
                if isinstance(v, VarBase) and v.persistable:
                    out.setdefault(id(v), v)
        return sorted(out.values(), key=lambda b: b.name)

    def _eager_step(*inputs):
        ins = [x if isinstance(x, VarBase) else VarBase(jnp.asarray(x), stop_gradient=True)
               for x in inputs]
        loss = loss_fn(*ins)
        loss._backward()
        optimizer._imperative_minimize(loss, parameter_list=_params())
        for p in _params():
            p.clear_gradient()
        return VarBase(loss.value, stop_gradient=True)

    def _build():
        ps = _params()
        bufs = _buffers()
        slots = _unique_slots(optimizer)
        tracer = current_tracer()

        def run(param_vals, buf_vals, acc_vals, step_idx, input_vals):
            old_p = [p.value for p in ps]
            old_g = [p._grad for p in ps]
            old_b = [b.value for b in bufs]
            old_a = [s.value for s in slots]
            old_key, old_ctr = tracer._key, tracer._counter
            try:
                # per-step RNG: fold the traced step index into the guard's
                # seed key so dropout masks vary per call without retracing
                tracer._key = jax.random.fold_in(old_key, step_idx)
                tracer._counter = 0
                for s, v in zip(slots, acc_vals):
                    s.value = v

                def pure(pvals):
                    for p, v in zip(ps, pvals):
                        p.value = v
                    for b, v in zip(bufs, buf_vals):
                        b.value = v
                    ins = [VarBase(v, stop_gradient=True) for v in input_vals]
                    out = loss_fn(*ins)
                    # buffers mutated by forward (BN stats) become aux
                    # OUTPUTS — the only legal way their in-trace values
                    # may escape value_and_grad
                    return (jnp.sum(out.value.astype(jnp.float32)),
                            [b.value for b in bufs])

                (loss, new_b), grads = jax.value_and_grad(
                    pure, has_aux=True)(param_vals)
                for p, v, g in zip(ps, param_vals, grads):
                    p.value = v
                    p._grad = g
                optimizer._imperative_minimize(None, parameter_list=ps)
                new_p = [p.value for p in ps]
                new_a = [s.value for s in slots]
                return loss, new_p, new_b, new_a
            finally:
                for p, v, g in zip(ps, old_p, old_g):
                    p.value = v
                    p._grad = g
                for b, v in zip(bufs, old_b):
                    b.value = v
                for s, v in zip(slots, old_a):
                    s.value = v
                tracer._key, tracer._counter = old_key, old_ctr

        return ps, bufs, slots, jax.jit(run, donate_argnums=(0, 1, 2))

    def step(*inputs):
        # Same-tape mixing guard: a manual backward() since the last step
        # left gradients the compiled step would silently ignore (or, on
        # the eager warmup step, double-count into). The compiled path owns
        # the whole forward/backward/update — refuse loudly.
        stale = [p.name for p in _params() if p._grad is not None]
        if stale:
            raise RuntimeError(
                "imperative.jit_train: parameter(s) %s carry gradients from "
                "a manual backward() on the same tape; jit_train's compiled "
                "step computes its own gradients and would silently ignore "
                "them. Run either the compiled step OR manual "
                "backward()+minimize() per iteration — or clear_gradient() "
                "first if the manual pass was intentional." % stale)
        if state["compiled"] is None:
            if not layer._built or not optimizer._accumulators:
                # warmup: one true eager step finalizes params + slots
                out = _eager_step(*inputs)
                state["step"] += 1
                state["compiled"] = _build()
                return out
            state["compiled"] = _build()
        ps, bufs, slots, compiled = state["compiled"]
        input_vals = [x.value if isinstance(x, VarBase) else jnp.asarray(x)
                      for x in inputs]
        loss, new_p, new_b, new_a = compiled(
            [p.value for p in ps], [b.value for b in bufs],
            [s.value for s in slots], jnp.uint32(state["step"]), input_vals)
        state["step"] += 1
        for p, v in zip(ps, new_p):
            p.value = v
        for b, v in zip(bufs, new_b):
            b.value = v
        for s, v in zip(slots, new_a):
            s.value = v
        return VarBase(loss, stop_gradient=True)

    step._jit_state = state
    return step
