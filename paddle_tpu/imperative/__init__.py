"""Imperative (dygraph) mode — eager execution with define-by-run autograd.

Reference side stack: paddle/fluid/imperative/ (layer.h, tracer.cc) +
python/paddle/fluid/imperative/ (base.py, layers.py, nn.py). TPU-native
design notes in tracer.py.
"""

from . import functional  # noqa: F401
from .base import enabled, guard, load_dygraph, save_dygraph, to_variable  # noqa: F401
from .jit import jit, jit_train  # noqa: F401
from .layers import Layer, PyLayer  # noqa: F401
from .nn import FC, BatchNorm, Conv2D, Embedding, Pool2D  # noqa: F401
from .tracer import EagerBlock, Tracer, VarBase, current_tracer, dispatch, trace_fn  # noqa: F401

F = functional

__all__ = [
    "enabled", "guard", "to_variable", "save_dygraph", "load_dygraph", "Layer", "PyLayer",
    "FC", "BatchNorm", "Conv2D", "Embedding", "Pool2D",
    "VarBase", "Tracer", "current_tracer", "dispatch", "trace_fn", "F",
    "functional", "EagerBlock", "jit", "jit_train",
]
