"""Dead-op / dead-var elimination.

Reference: the eager-deletion + graph pruning machinery Fluid spreads over
``framework/prune.cc`` and ``ir/graph_helper.cc``; TVM's graph-level DCE
(PAPERS.md) is the closer model — remove whole ops the fetch targets can
never observe, *before* tracing, so the jaxpr and the XLA program shrink.

Liveness is seeded from the fetch targets plus everything with
externally-visible semantics: persistable writes (param/optimizer-state
updates, streaming-metric accumulators like ``auc``), the loss var (the
Executor differentiates it even when unfetched), the grad-norm probe and
the LR var. Walking the op list in reverse, an op stays when it writes a
live or persistable var or is opaque (side effects / sub-blocks); its reads
then become live. Everything else — e.g. an ``accuracy`` branch in an eval
program that only fetches the loss, or train-only tail ops in a
``clone(for_test=True)`` graph — is dropped, and vars nothing references
anymore are pruned from the symbol table.
"""

from __future__ import annotations

from ..core.pass_framework import Pass, register_pass
from . import analysis as A

__all__ = ["DeadCodeEliminationPass"]


@register_pass("dead_code_elimination")
class DeadCodeEliminationPass(Pass):
    """attrs: ``fetch_names`` (tuple, may be empty), ``protected`` (set).

    With no fetch info (a build-time application before fetches are known)
    every leaf output — an output no other op consumes — is treated as a
    potential fetch, which makes the pass conservative instead of wrong.
    Reports ``ops_removed`` / ``vars_removed`` attrs for the pipeline.
    """

    def apply_impl(self, program):
        block = program.global_block
        fetch_names = self.attr("fetch_names")
        protected = set(self.attr("protected") or ())
        protected |= A.protected_names(program, fetch_names or ())

        live = set(protected)
        # sub-block ops (while/cond/RNN bodies) read outer vars straight out
        # of the trace env without listing them on the owning op — every
        # name any non-global block touches is live to the global walk
        for blk in program.blocks:
            if blk is block:
                continue
            for op in blk.ops:
                live.update(op.input_arg_names)
        if fetch_names is None:
            # fetch set unknown: any leaf output may be observed later
            uses = A.use_counts(program)
            for op in block.ops:
                for n in op.output_arg_names:
                    if not uses.get(n):
                        live.add(n)

        known = A.all_var_names(program)
        doomed = set()
        for op in reversed(block.ops):
            keep = (A.is_opaque(op)
                    or any(n in live for n in op.output_arg_names))
            if not keep:
                for n in op.output_arg_names:
                    v = block._find_var_recursive(n)
                    if v is not None and v.persistable:
                        keep = True
                        break
            if keep:
                live.update(op.input_arg_names)
                if A.has_sub_block(op):
                    live.update(A.attr_referenced_names(op, known))
            else:
                doomed.add(id(op))

        removed = A.remove_ops_by_id(block, doomed)
        pruned = A.prune_dead_vars(program, extra_keep=live) if removed else 0
        self.set_attr("ops_removed", removed)
        self.set_attr("vars_removed", pruned)
        return program
