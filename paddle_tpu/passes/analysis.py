"""Shared graph analysis for the default trace-time optimizer passes.

The reference's ir passes each re-derive graph facts from the ``ir::Graph``
node links (``framework/ir/graph_helper.cc``); here the Program IS the IR
(op list + var table, ``core/framework.py``), so the facts every pass needs
— who consumes a var, who defines it, which ops may draw RNG or carry side
effects — live in one module instead of being re-scanned per pass with
O(n^2) loops (the bug the old ``conv_bn_fuse_pass.consumers()`` had).

RNG stability contract
----------------------
Stochastic ops derive their PRNG key from the op's *position* in the block
(``TraceContext.op_rng``). An optimizer that deletes a dead op ahead of a
``dropout`` would silently shift every later key — losses would differ from
the unoptimized program for no semantic reason. Before any pass mutates a
program, :func:`stamp_rng_slots` freezes each stochastic op's original
position into a ``__rng_slot__`` attr (and the original key-table size into
``Program._rng_table_n``); ``op_rng`` honors the stamp, so op deletion and
motion never perturb the RNG stream and optimized losses stay bit-identical
to ``PADDLE_TPU_OPT_LEVEL=0``.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Set

__all__ = [
    "RNG_OPS", "SIDE_EFFECT_OPS", "MARKER_OPS", "CSE_PURE_OPS", "FOLDABLE_OPS",
    "has_sub_block", "is_opaque", "use_counts", "producer_map",
    "attr_referenced_names", "stamp_rng_slots", "stamp_op_slots",
    "protected_names", "remove_ops_by_id", "prune_dead_vars",
]

# Ops that draw from the per-step PRNG (directly or via ctx.rng()). Their
# position-derived key is frozen by stamp_rng_slots before the first rewrite.
RNG_OPS = frozenset({
    "dropout", "scaled_dot_product_attention",
    "uniform_random", "uniform_random_batch_size_like",
    "gaussian_random", "gaussian_random_batch_size_like",
    "truncated_gaussian_random", "randint",
    "sampling_id", "random_crop", "shuffle_channel",
    "nce", "sample_logits", "lstm",
    "rpn_target_assign", "generate_proposal_labels", "generate_mask_labels",
})

# Structural markers the Executor itself interprets — never remove, never CSE.
MARKER_OPS = frozenset({"backward_marker", "feed", "fetch"})

# Ops whose effect is not captured by their output list (host I/O, state the
# liveness walk can't see). Conservative: kept live, inputs kept live.
SIDE_EFFECT_OPS = frozenset({
    "print", "py_func", "save", "load", "read",
    "while", "conditional_block", "recurrent", "assert",
})

# Attr keys that reference sub-blocks; ops carrying one are opaque to the
# optimizer (their body may read anything — treat every referenced name live).
_BLOCK_ATTR_KEYS = ("sub_block", "true_block", "false_block")

# Pure, deterministic, single-assignment-friendly ops safe to deduplicate.
# Whitelist, not blacklist: an op type not listed is simply never CSE'd.
CSE_PURE_OPS = frozenset({
    "elementwise_add", "elementwise_sub", "elementwise_mul", "elementwise_div",
    "elementwise_pow", "elementwise_max", "elementwise_min", "elementwise_mod",
    "elementwise_floordiv",
    "scale", "cast", "clip", "sign", "mean", "sum",
    "mul", "matmul", "softmax", "log_softmax",
    "relu", "relu6", "sigmoid", "tanh", "gelu", "elu", "leaky_relu",
    "exp", "log", "sqrt", "rsqrt", "square", "abs", "pow", "floor", "ceil",
    "reduce_sum", "reduce_mean", "reduce_max", "reduce_min", "reduce_prod",
    "reshape", "reshape2", "transpose", "transpose2",
    "squeeze", "squeeze2", "unsqueeze", "unsqueeze2", "flatten", "flatten2",
    "concat", "stack", "split", "slice", "strided_slice",
    "gather", "gather_nd", "one_hot", "expand", "expand_as", "tile",
    "fill_constant", "fill_zeros_like", "assign", "assign_value", "shape",
    "arg_max", "arg_min", "top_k", "lookup_table",
    "equal", "not_equal", "less_than", "less_equal",
    "greater_than", "greater_equal",
    "logical_and", "logical_or", "logical_not", "logical_xor",
    "layer_norm", "cross_entropy", "softmax_with_cross_entropy",
    "pad", "pad2d", "where", "cos", "sin",
})

# Ops the constant folder may host-evaluate when every input is a known
# compile-time constant. Strictly deterministic, attr/shape-static subset.
FOLDABLE_OPS = frozenset({
    "scale", "cast", "sign", "clip",
    "elementwise_add", "elementwise_sub", "elementwise_mul", "elementwise_div",
    "elementwise_pow", "elementwise_max", "elementwise_min",
    "exp", "log", "sqrt", "rsqrt", "square", "abs", "floor", "ceil",
    "reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
    "reshape", "reshape2", "transpose", "transpose2",
    "squeeze", "squeeze2", "unsqueeze", "unsqueeze2", "flatten", "flatten2",
    "concat", "stack", "one_hot", "expand", "tile",
    "fill_zeros_like", "assign", "range", "linspace", "mean", "sum",
})

# Constant *sources*: ops with no data inputs whose output is fully
# determined by attrs.
CONST_SOURCE_OPS = frozenset({"fill_constant", "assign_value"})


def has_sub_block(op) -> bool:
    return any(k in op.attrs for k in _BLOCK_ATTR_KEYS)


def is_opaque(op) -> bool:
    """True when the optimizer must neither remove nor rewrite this op."""
    return (op.type in MARKER_OPS or op.type in SIDE_EFFECT_OPS
            or has_sub_block(op))


def attr_referenced_names(op, known: Set[str]) -> List[str]:
    """Var names an opaque op references through attrs (control-flow ops
    carry (outer, inner) name pairs in attrs like ``carry_vars`` /
    ``step_inputs`` rather than input slots). Conservative: every attr
    string (or string inside a list/tuple of strings/pairs) that names a
    known var counts as a reference."""
    refs = []
    for v in op.attrs.values():
        if isinstance(v, str):
            if v in known:
                refs.append(v)
        elif isinstance(v, (list, tuple)):
            for item in v:
                if isinstance(item, str):
                    if item in known:
                        refs.append(item)
                elif isinstance(item, (list, tuple)):
                    for s in item:
                        if isinstance(s, str) and s in known:
                            refs.append(s)
    return refs


def use_counts(program) -> Dict[str, int]:
    """name -> number of reading references across ALL blocks (input slots
    plus attr refs of opaque ops). One linear scan; passes that mutate the
    program maintain their copy incrementally or rebuild."""
    known = all_var_names(program)
    counts: Dict[str, int] = {}
    for blk in program.blocks:
        for op in blk.ops:
            for n in op.input_arg_names:
                counts[n] = counts.get(n, 0) + 1
            if has_sub_block(op):
                for n in attr_referenced_names(op, known):
                    counts[n] = counts.get(n, 0) + 1
    return counts


def all_var_names(program) -> Set[str]:
    names: Set[str] = set()
    for blk in program.blocks:
        names.update(blk.vars)
    return names


def producer_map(block) -> Dict[str, object]:
    """name -> LAST op in the block writing it (matching trace-time
    semantics, where later writes shadow earlier ones in the env)."""
    prod: Dict[str, object] = {}
    for op in block.ops:
        for n in op.output_arg_names:
            prod[n] = op
    return prod


def stamp_op_slots(program) -> None:
    """Freeze every op's original position into ``__op_slot__`` — the
    device-side attribution identity: ``jax.named_scope`` labels, the
    numerics watchdog and ``tools/profile_report`` all report
    ``<slot>:<type>``, so op deletion/motion by the passes never shifts
    a reported op identity away from the SOURCE program's numbering.
    Idempotent (already-stamped ops keep their slot); ops inserted by
    later rewrites carry no stamp and fall back to their position.
    CSE ignores ``__*__`` framework attrs when value-numbering, so the
    stamp can never block a merge (cse.py ``_attr_key``)."""
    for i, op in enumerate(program.global_block.ops):
        if "__op_slot__" not in op.attrs:
            op.attrs["__op_slot__"] = i


def stamp_rng_slots(program) -> None:
    """Freeze every stochastic op's positional PRNG identity (see module
    docstring). Idempotent: already-stamped ops and an already-recorded
    table size are left alone, so re-optimizing an optimized program (or
    composing user passes after the default pipeline) never re-derives."""
    block = program.global_block
    if not hasattr(program, "_rng_table_n"):
        # mirror TraceContext.op_rng's pre-optimization table size: the key
        # table is built with jax.random.split(key, n) and split keys DEPEND
        # on n, so the optimized program must keep the original n even after
        # ops are deleted.
        program._rng_table_n = len(block.ops) + 8
    for i, op in enumerate(block.ops):
        if op.type in RNG_OPS and "__rng_slot__" not in op.attrs:
            op.attrs["__rng_slot__"] = i


def protected_names(program, fetch_names: Iterable[str] = ()) -> Set[str]:
    """Vars no pass may eliminate or alias away: fetch targets, the loss
    (the Executor differentiates it), the grad-norm probe, the LR var, and
    every gradient name the backward info wires up out-of-band."""
    from ..monitor import GRAD_NORM_VAR

    prot = set(fetch_names or ())
    bw = getattr(program, "_backward_info", None)
    if bw:
        if bw.get("loss"):
            prot.add(bw["loss"])
        if bw.get("loss_grad"):
            prot.add(bw["loss_grad"])
        for p, g in (bw.get("param_to_grad") or {}).items():
            prot.add(p)
            prot.add(g)
    if getattr(program, "_lr_var_name", None):
        prot.add(program._lr_var_name)
    if GRAD_NORM_VAR in program.global_block.vars:
        prot.add(GRAD_NORM_VAR)
    return prot


def remove_ops_by_id(block, doomed_ids: Set[int]) -> int:
    """Drop every op whose id() is in ``doomed_ids``; returns count."""
    kept = [op for op in block.ops if id(op) not in doomed_ids]
    removed = len(block.ops) - len(kept)
    if removed:
        block.ops[:] = kept
        block.program._version += 1
    return removed


def prune_dead_vars(program, extra_keep: Optional[Set[str]] = None) -> int:
    """Delete block vars nothing references anymore: not persistable, not
    feed data, not produced/consumed by any remaining op in any block, not
    attr-referenced, not protected. Returns the number pruned."""
    keep = set(extra_keep or ())
    known = all_var_names(program)
    referenced: Set[str] = set()
    for blk in program.blocks:
        for op in blk.ops:
            referenced.update(op.input_arg_names)
            referenced.update(op.output_arg_names)
            if has_sub_block(op):
                referenced.update(attr_referenced_names(op, known))
    pruned = 0
    for blk in program.blocks:
        for name in list(blk.vars):
            v = blk.vars[name]
            if (name in referenced or name in keep or v.persistable
                    or getattr(v, "is_data", False)):
                continue
            del blk.vars[name]
            pruned += 1
    if pruned:
        program._version += 1
    return pruned
