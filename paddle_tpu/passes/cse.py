"""Common-subexpression elimination over the Program op list.

Two ops compute the same value when they have the same type, the same
*value-numbered* inputs (not just the same names — a var redefined between
the two occurrences gets a fresh value number, so in-place reassignment
patterns can never be merged wrongly) and equal attrs. Only ops in the
``CSE_PURE_OPS`` whitelist participate: stochastic ops (dropout & friends)
each own a distinct PRNG slot, and side-effecting or sub-block ops are
opaque. The duplicate op is deleted and later readers of its outputs are
redirected to the first occurrence's outputs.

Typical wins in ported Fluid scripts: repeated mask/bias construction
(every attention layer re-building the same ``scale``/``expand`` chain from
the same input mask), duplicate ``fill_constant``\\ s, repeated
``reshape2``/``transpose2`` of a shared activation.
"""

from __future__ import annotations

from ..core.pass_framework import Pass, register_pass
from . import analysis as A

__all__ = ["CommonSubexpressionEliminationPass"]


def _attr_key(attrs):
    items = []
    for k in sorted(attrs):
        if k.startswith("__") and k.endswith("__"):
            # framework-private stamps (__op_slot__, __rng_slot__) carry
            # per-op IDENTITY, not semantics — keying on them would make
            # every stamped op unique and defeat CSE entirely
            continue
        v = attrs[k]
        try:
            hash(v)
        except TypeError:
            v = repr(v)
        items.append((k, v))
    return tuple(items)


@register_pass("common_subexpression_elimination")
class CommonSubexpressionEliminationPass(Pass):
    """attrs: ``protected`` — vars whose defining op must survive (fetch
    targets etc.); ``fetch_names`` — None when fetches are unknown (leaf
    outputs are then protected, like DCE's conservative mode: merging away
    a leaf would make its name unfetchable at run time).
    Reports ``ops_removed``."""

    def apply_impl(self, program):
        block = program.global_block
        protected = set(self.attr("protected") or ())
        protected |= A.protected_names(program)
        if self.attr("fetch_names") is None:
            uses0 = A.use_counts(program)
            for op in block.ops:
                for n in op.output_arg_names:
                    if not uses0.get(n):
                        protected.add(n)

        value_num = {}   # var name -> value number of its current definition
        next_vn = [0]

        def vn_of(name):
            if name not in value_num:
                # external def (feed, state, startup-initialized param):
                # stable for the whole block scan
                value_num[name] = ("ext", name)
            return value_num[name]

        # names read by other blocks or through opaque attrs: the aliasing
        # rewrite below can't reach those readers, so their defining ops
        # must never be merged away
        known = A.all_var_names(program)
        outer_refs = set()
        for blk in program.blocks:
            for op in blk.ops:
                if blk is not block:
                    outer_refs.update(op.input_arg_names)
                if A.has_sub_block(op):
                    outer_refs.update(A.attr_referenced_names(op, known))

        exprs = {}    # expr key -> (first op, its outputs' value numbers)
        alias = {}    # replaced var name -> canonical var name
        doomed = set()
        removed = 0
        for op in block.ops:
            # redirect reads through aliases established by earlier merges
            if alias:
                for slot, names in op.inputs.items():
                    if any(n in alias for n in names):
                        op.inputs[slot] = [alias.get(n, n) for n in names]

            eligible = (
                op.type in A.CSE_PURE_OPS
                and not A.has_sub_block(op)
                and op.output_arg_names
                and not any(n in protected for n in op.output_arg_names)
                and not any(n in outer_refs for n in op.output_arg_names)
                and not any(
                    (lambda v: v is not None and v.persistable)(
                        block._find_var_recursive(n))
                    for n in op.output_arg_names)
                # in-place op (an output aliasing an input) — don't touch
                and not (set(op.output_arg_names) & set(op.input_arg_names)))

            key = None
            if eligible:
                key = (
                    op.type,
                    tuple((slot, tuple(vn_of(n) for n in names))
                          for slot, names in sorted(op.inputs.items())),
                    tuple(sorted(op.outputs)),  # same output arity/slots
                    _attr_key(op.attrs),
                )
                hit = exprs.get(key)
                if hit is not None:
                    first, first_vns = hit
                    # the first occurrence's outputs must still hold their
                    # original values (no redefinition in between)
                    if all(value_num.get(n) == vn
                           for n, vn in zip(first.output_arg_names,
                                            first_vns)):
                        for slot, names in op.outputs.items():
                            for mine, theirs in zip(names,
                                                    first.outputs[slot]):
                                if mine != theirs:
                                    alias[mine] = theirs
                        doomed.add(id(op))
                        removed += 1
                        continue

            # assign new value numbers to this op's definitions — and kill
            # any alias whose replaced name this op redefines (later readers
            # must see the NEW definition, not the stale first occurrence)
            for n in op.output_arg_names:
                value_num[n] = next_vn[0]
                next_vn[0] += 1
                alias.pop(n, None)
            if key is not None:
                exprs[key] = (op, tuple(value_num[n]
                                        for n in op.output_arg_names))

        if removed:
            A.remove_ops_by_id(block, doomed)
            # opaque sub-block ops may reference aliased names through attrs;
            # those references were left untouched, so keep the aliased vars
            # only if still referenced — prune handles it
            A.prune_dead_vars(program, extra_keep=protected)
        self.set_attr("ops_removed", removed)
        return program
