"""Default trace-time optimization pipeline + the Executor's entry point.

``PADDLE_TPU_OPT_LEVEL`` (default 1) gates everything:

* ``0`` — no default passes; programs trace exactly as built.
* ``1`` — constant folding, CSE, fused-kernel pattern rewrites
  (softmax+cross_entropy, unfused attention -> flash), conv+bn weight
  folding (inference programs), then dead-op/dead-var elimination.
* ``2`` — level 1 applied to a fixpoint (a second round picks up chains
  the first round's rewrites exposed).

Individual passes can be switched off with ``PADDLE_TPU_PASS_<NAME>=0``
(e.g. ``PADDLE_TPU_PASS_COMMON_SUBEXPRESSION_ELIMINATION=0``).

The Executor calls :func:`maybe_optimize` in ``_run_impl`` / ``run_steps``
/ ``prepare`` *before* plan resolution, so the optimized program is the one
the dispatch-plan cache and the persistent compile cache key on. Results
are memoized on the source Program keyed by (version, fetch set, opt
level) with the deriving scope held by weakref — a cache-hit run never
re-enters a pass, and the source program itself is NEVER mutated (passes
run on a clone).
"""

from __future__ import annotations

import contextlib
import os
import time
import weakref
from typing import Iterable, Optional

from ..core.pass_framework import PassBuilder, get_pass
from ..monitor import metrics as _mx
from . import analysis as A

__all__ = [
    "DEFAULT_PASS_NAMES", "opt_level", "pass_enabled", "default_pipeline",
    "optimize_program", "maybe_optimize", "pass_gate_overrides",
]

# Order matters: folding exposes CSE opportunities, both feed the pattern
# matchers cleaner graphs, and DCE last sweeps every leftover intermediate.
DEFAULT_PASS_NAMES = (
    "constant_folding",
    "common_subexpression_elimination",
    "softmax_xent_fuse_pass",
    "flash_attention_rewrite",
    "conv_bn_fuse_pass",
    "dead_code_elimination",
)

# Passes that DELETE the defining op of a value that is still computed
# (folded chains, merged duplicates, rewritten compositions). They may only
# run when the fetch set is KNOWN — at build time any named intermediate
# could still be fetched later, and removing its def would turn a formerly
# working `fetch_list=[name]` into a KeyError. conv_bn + DCE are fetch-safe
# in conservative mode (DCE keeps everything transitively feeding a leaf).
_NEEDS_FETCH_INFO = frozenset({
    "constant_folding",
    "common_subexpression_elimination",
    "softmax_xent_fuse_pass",
    "flash_attention_rewrite",
})

_m_runs = _mx.counter("passes/pipeline/runs",
                      help="default-pipeline applications (one per program "
                           "version x fetch-set, never per step)")
_m_time = _mx.histogram("passes/pipeline/time_ms",
                        help="wall time of one full default-pipeline run")
_m_before = _mx.gauge("passes/pipeline/op_count_before",
                      help="global-block op count entering the last run")
_m_after = _mx.gauge("passes/pipeline/op_count_after",
                     help="global-block op count leaving the last run")


def opt_level() -> int:
    """Current ``PADDLE_TPU_OPT_LEVEL`` (read per call so tests and REPLs
    can flip it without restarting), clamped to 0..2."""
    raw = os.environ.get("PADDLE_TPU_OPT_LEVEL", "1").strip()
    try:
        lvl = int(raw)
    except ValueError:
        lvl = 1
    return max(0, min(2, lvl))


def pass_enabled(name: str) -> bool:
    raw = os.environ.get("PADDLE_TPU_PASS_" + name.upper(), "1")
    return raw.strip().lower() not in ("0", "false", "no", "off")


@contextlib.contextmanager
def pass_gate_overrides(disabled: Iterable[str]):
    """Temporarily force ``PADDLE_TPU_PASS_<NAME>=0`` for each name in
    ``disabled`` (restoring prior values on exit). This is the knob the
    autotuner's ``pass_gates`` tunable (paddle_tpu.tune) measures candidate
    gate sets through: :func:`maybe_optimize` keys its memo on the active
    gate set, so flipping gates here yields a freshly optimized clone
    instead of a stale cache hit."""
    saved = {}
    try:
        for name in disabled:
            key = "PADDLE_TPU_PASS_" + str(name).upper()
            saved[key] = os.environ.get(key)
            os.environ[key] = "0"
        yield
    finally:
        for key, prev in saved.items():
            if prev is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = prev


def default_pipeline(scope=None, fetch_names: Optional[Iterable[str]] = None,
                     protected: Optional[set] = None,
                     level: Optional[int] = None) -> PassBuilder:
    """The default PassBuilder for ``level`` (current env level when None).
    ``conv_bn_fuse_pass`` joins only when a scope is available (it folds
    parameter *values*)."""
    lvl = opt_level() if level is None else level
    builder = PassBuilder()
    if lvl <= 0:
        return builder
    for name in DEFAULT_PASS_NAMES:
        if not pass_enabled(name):
            continue
        if fetch_names is None and name in _NEEDS_FETCH_INFO:
            continue  # def-removing passes wait for real fetch targets
        if name == "conv_bn_fuse_pass":
            if scope is None:
                continue
            from .. import transpiler  # noqa: F401 — registers the pass
        p = get_pass(name)
        if scope is not None:
            p.set_attr("scope", scope)
        if fetch_names is not None:
            p.set_attr("fetch_names", tuple(fetch_names))
        if protected:
            p.set_attr("protected", set(protected))
        builder.append_pass(p)
    return builder


def _mirror_pass_metrics(builder: PassBuilder) -> None:
    if not _mx._enabled:
        return
    for p in builder.all_passes():
        name = p.name or type(p).__name__
        removed = p.attr("ops_removed")
        if removed:
            _mx.counter("passes/%s/ops_removed" % name).inc(removed)
        rewrites = p.attr("rewrites_matched")
        if rewrites:
            _mx.counter("passes/%s/rewrites_matched" % name).inc(rewrites)
        fused = p.attr("fused_count")
        if fused:
            _mx.counter("passes/%s/rewrites_matched" % name).inc(fused)


def optimize_program(program, fetch_names: Optional[Iterable[str]] = None,
                     scope=None, level: Optional[int] = None):
    """Clone ``program``, stamp RNG slots, run the default pipeline, return
    the optimized clone (the source is left untouched). A second
    application to the result is a no-op by construction (stamps and
    rewrites are idempotent)."""
    lvl = opt_level() if level is None else level
    if lvl <= 0 or not program.global_block.ops:
        return program

    t0 = time.perf_counter()
    work = program.clone()
    # clone() drops framework-private attrs — carry the RNG contract over
    work._rng_table_n = getattr(
        program, "_rng_table_n", len(program.global_block.ops) + 8)
    A.stamp_rng_slots(work)
    # freeze per-op attribution identity (named scopes, numerics watchdog)
    # BEFORE any pass deletes/moves ops — same contract as the RNG slots
    A.stamp_op_slots(work)

    protected = A.protected_names(work, fetch_names or ())
    builder = default_pipeline(scope=scope, fetch_names=fetch_names,
                               protected=protected, level=lvl)
    n_before = len(work.global_block.ops)
    rounds = 2 if lvl >= 2 else 1
    for _ in range(rounds):
        work = builder.apply_all(work)
        _mirror_pass_metrics(builder)
    if _mx._enabled:
        _m_runs.inc()
        _m_before.set(n_before)
        _m_after.set(len(work.global_block.ops))
        _m_time.observe((time.perf_counter() - t0) * 1e3)
    return work


def maybe_optimize(program, fetch_names=None, scope=None):
    """Memoized :func:`optimize_program` — the Executor's per-run entry.

    The cache lives ON the source program (version-keyed, like the
    dispatch-plan table) so it dies with it and a version bump invalidates
    it; re-running a pass on a cache hit is a bug this function exists to
    prevent. The scope is part of the identity (conv+bn folding reads
    VALUES from it) — held by weakref, so a dead scope's entry can never be
    served to an unrelated new scope that reused its id, and dead entries
    are pruned as they are seen."""
    lvl = opt_level()
    if lvl <= 0:
        return program
    if getattr(program, "_opt_product", False):
        return program  # already a pipeline output; never re-optimize
    # flipping a PADDLE_TPU_PASS_* gate mid-process must not be masked by a
    # memo hit — the active gate set is part of the identity
    gates = tuple(n for n in DEFAULT_PASS_NAMES if not pass_enabled(n))
    key = (tuple(fetch_names or ()), lvl, gates)
    entry = getattr(program, "_opt_cache", None)
    if entry is None or entry[0] != program._version:
        entry = (program._version, {})
        program._opt_cache = entry
    cache = entry[1]
    hit = cache.get(key)
    if hit is not None:
        scope_ref, cached = hit
        live = scope_ref() if scope_ref is not None else None
        if ((scope_ref is None and scope is None) or live is scope) \
                and _fold_sources_fresh(cached, scope):
            return cached
        del cache[key]  # dead/foreign scope or value-stale fold
    opt = optimize_program(program, fetch_names=fetch_names, scope=scope,
                           level=lvl)
    if opt is not program:
        opt._opt_product = True
    cache[key] = (weakref.ref(scope) if scope is not None else None, opt)
    return opt


def _fold_sources_fresh(cached, scope):
    """Value-folding passes (conv+bn) bake SCOPE VALUES into the optimized
    clone; the clone records which objects it read (``_fold_sources``). A
    checkpoint load — or a train step updating the weights — replaces those
    scope entries with new objects, which this identity check catches, so
    the memo never serves a fold derived from superseded values (even for
    clones like ``clone(for_test=True)`` programs that a version bump on
    the train program cannot reach)."""
    sources = getattr(cached, "_fold_sources", None)
    if not sources:
        return True
    if scope is None:
        return False
    return all(scope.find_var(name) is obj for name, obj in sources.items())
