"""paddle_tpu.passes — the default trace-time Program optimizer.

Fluid's L4 layer is an IR pass framework (``framework/ir/``) that rewrites
the program before execution; this package is its TPU-native counterpart
over the Program IR (``core/framework.py``), run AUTOMATICALLY by the
Executor at trace/prepare time (gated by ``PADDLE_TPU_OPT_LEVEL=0|1|2``,
default 1):

* :mod:`~paddle_tpu.passes.dce` — dead-op/dead-var elimination, liveness
  seeded from fetch targets + persistables (eval programs shed train-only
  ops).
* :mod:`~paddle_tpu.passes.constant_fold` — host-evaluates ops whose
  inputs are all compile-time constants (``fill_constant -> scale ->
  elementwise_*`` chains collapse to one constant).
* :mod:`~paddle_tpu.passes.cse` — common-subexpression elimination keyed
  on (op type, value-numbered inputs, attrs).
* :mod:`~paddle_tpu.passes.fuse_patterns` — rewrites XLA cannot do:
  ``softmax``+``cross_entropy`` -> the fused loss op, and the unfused
  QKV-matmul/scale/softmax/matmul attention composition -> the
  flash-attention op.
* ``conv_bn_fuse_pass`` (``transpiler/fuse_passes.py``) joins the default
  pipeline for inference programs.

A smaller program means faster tracing, smaller jaxprs, faster XLA
compiles, better dispatch-plan / persistent-compile-cache hit rates, and
more programs landing on the hand-tuned Pallas kernels. Each pass reports
``passes/<name>/ops_removed`` / ``rewrites_matched`` counters and a
``passes/<name>/time_ms`` histogram via :mod:`paddle_tpu.monitor`;
inspect a program's before/after with ``python -m tools.dump_program``.
"""

from __future__ import annotations

# importing the modules registers the passes
from . import analysis, constant_fold, cse, dce, fuse_patterns  # noqa: F401
from .pipeline import (  # noqa: F401
    DEFAULT_PASS_NAMES, default_pipeline, maybe_optimize, opt_level,
    optimize_program, pass_enabled,
)

__all__ = [
    "DEFAULT_PASS_NAMES", "default_pipeline", "maybe_optimize", "opt_level",
    "optimize_program", "pass_enabled",
]
