"""Fused-kernel pattern rewrites — the rewrites XLA cannot do for us.

XLA fuses elementwise chains, but it cannot (a) swap a numerically-naive
composition for a numerically-superior fused op (``softmax`` followed by
``cross_entropy`` -> ``softmax_with_cross_entropy``, whose log-softmax /
custom-vjp formulation avoids the exp-then-log round trip and the f32
log-prob residuals), nor (b) recognize an O(S^2)-materializing attention
composition and route it onto the vendored Pallas flash-attention kernel
(``ops/pallas_kernels/flash_attention.py``). Both rewrites run in the
default pipeline, so Fluid-style scripts written against primitives hit the
fused TPU paths without opting in (the Ragged-Paged-Attention thesis from
PAPERS.md: push attention onto hand-tuned kernels whenever the pattern
allows).

Matched attention shape (the classic dist_transformer composition)::

    scores = matmul(Q, K, transpose_y=True[, alpha])   # [B,H,Sq,Sk]
    scores = scale(scores, s)                          # optional
    scores = elementwise_add(scores, bias)             # optional
    probs  = softmax(scores, axis=-1)
    probs  = dropout(probs, upscale_in_train)          # optional
    out    = matmul(probs, V)

Every intermediate must have exactly one consumer and not be fetched;
Q/K/V must be rank-4. Causal compositions (masking via tril constants)
are NOT matched — use the fused layer for causal attention.
"""

from __future__ import annotations

from ..core.framework import Operator
from ..core.pass_framework import Pass, register_pass
from . import analysis as A

__all__ = ["SoftmaxXentFusePass", "FlashAttentionRewritePass"]


def _single_consumer(name, uses, protected):
    return uses.get(name, 0) == 1 and name not in protected


@register_pass("softmax_xent_fuse_pass")
class SoftmaxXentFusePass(Pass):
    """``softmax`` + ``cross_entropy`` -> ``softmax_with_cross_entropy``.

    The softmax op is removed only when the loss op was its sole consumer;
    if its probabilities are observed elsewhere (fetched predictions), the
    loss is still fused on the logits and the softmax op stays. Reports
    ``rewrites_matched``.
    """

    def apply_impl(self, program):
        block = program.global_block
        protected = set(self.attr("protected") or ())
        protected |= A.protected_names(program)
        uses = A.use_counts(program)
        prod = A.producer_map(block)

        matched = 0
        i = 0
        while i < len(block.ops):
            xent = block.ops[i]
            if xent.type != "cross_entropy" or A.is_opaque(xent):
                i += 1
                continue
            probs_name = (xent.input("X") or [None])[0]
            label_name = (xent.input("Label") or [None])[0]
            sm = prod.get(probs_name)
            if (sm is None or sm.type != "softmax"
                    or probs_name is None or label_name is None):
                i += 1
                continue
            axis = sm.attr("axis", -1)
            x_var = block._find_var_recursive(sm.input("X")[0])
            ndim = len(x_var.shape) if (x_var is not None
                                        and x_var.shape is not None) else None
            if axis != -1 and (ndim is None or axis != ndim - 1):
                i += 1
                continue

            fused = Operator(
                block, "softmax_with_cross_entropy",
                inputs={"Logits": sm.input("X"), "Label": [label_name]},
                outputs={"Loss": xent.output("Y")},
                attrs={"soft_label": xent.attr("soft_label", False),
                       "ignore_index": xent.attr("ignore_index", -100)})
            block.ops[i] = fused
            # when the loss was the probabilities' only observer the softmax
            # op goes too; otherwise (fetched predictions) it stays and
            # keeps defining the var
            if _single_consumer(probs_name, uses, protected):
                block.ops.remove(sm)
                i -= 1  # the list shifted left past the removed softmax
            program._version += 1
            matched += 1
            # producer/use maps shifted; rebuild (rewrites are rare)
            uses = A.use_counts(program)
            prod = A.producer_map(block)
            i += 1

        if matched:
            A.prune_dead_vars(program, extra_keep=protected)
        self.set_attr("rewrites_matched", matched)
        return program


def _rank4(block, name):
    v = block._find_var_recursive(name)
    return (v is not None and v.shape is not None and len(v.shape) == 4)


@register_pass("flash_attention_rewrite")
class FlashAttentionRewritePass(Pass):
    """Unfused QKV attention composition -> ``scaled_dot_product_attention``
    (the fused layer's op: Pallas flash kernel on TPU when shapes allow,
    composed einsum elsewhere — but with O(S) residuals instead of the
    matmul-materialized [B,H,S,S] probs when flash is hit).

    Reports ``rewrites_matched``. A consumed ``dropout``'s PRNG slot is
    transplanted onto the fused op so repeated optimizations of the same
    source program stay deterministic.
    """

    def apply_impl(self, program):
        block = program.global_block
        protected = set(self.attr("protected") or ())
        protected |= A.protected_names(program)

        matched = 0
        changed = True
        while changed:
            changed = False
            uses = A.use_counts(program)
            prod = A.producer_map(block)
            for sm in list(block.ops):
                if sm.type != "softmax":
                    continue
                plan = self._match(block, sm, uses, prod, protected)
                if plan is None:
                    continue
                self._rewrite(block, plan)
                program._version += 1
                matched += 1
                changed = True
                break  # maps are stale; rescan

        if matched:
            A.prune_dead_vars(program, extra_keep=protected)
        self.set_attr("rewrites_matched", matched)
        return program

    # -- matching -------------------------------------------------------------
    def _match(self, block, sm, uses, prod, protected):
        if sm.attr("axis", -1) not in (-1, 3):
            return None
        probs_name = sm.output("Out")[0]

        # ---- upstream: [matmul -> scale? -> add-bias?] ----
        cur = sm.input("X")[0]
        sm_scale = 1.0
        bias = None
        removable = [sm]
        add = prod.get(cur)
        if add is not None and add.type == "elementwise_add" \
                and add.attr("axis", -1) in (-1,):
            y = (add.input("Y") or [None])[0]
            if y is not None and _rank4(block, y):
                if not _single_consumer(cur, uses, protected):
                    return None
                bias = y
                removable.append(add)
                cur = add.input("X")[0]
        sc = prod.get(cur)
        if sc is not None and sc.type == "scale":
            if float(sc.attr("bias", 0.0)) == 0.0:
                if not _single_consumer(cur, uses, protected):
                    return None
                sm_scale *= float(sc.attr("scale", 1.0))
                removable.append(sc)
                cur = sc.input("X")[0]
        mm1 = prod.get(cur)
        if (mm1 is None or mm1.type != "matmul"
                or mm1.attr("transpose_X", False)
                or not mm1.attr("transpose_Y", False)
                or not _single_consumer(cur, uses, protected)):
            return None
        sm_scale *= float(mm1.attr("alpha", 1.0))
        q_name, k_name = mm1.input("X")[0], mm1.input("Y")[0]
        if not (_rank4(block, q_name) and _rank4(block, k_name)):
            return None
        removable.append(mm1)

        # ---- downstream: [dropout?] -> matmul(probs, V) ----
        dropout_rate = 0.0
        is_test_attr = None
        rng_slot = None
        cur_out = probs_name
        nxt = self._sole_consumer(block, cur_out, uses, protected)
        drop = None
        if nxt is not None and nxt.type == "dropout":
            if nxt.attr("dropout_implementation") != "upscale_in_train":
                return None
            mask = nxt.output("Mask")
            if mask and uses.get(mask[0], 0):
                return None
            if mask and mask[0] in protected:
                return None
            drop = nxt
            dropout_rate = float(nxt.attr("dropout_prob", 0.0))
            is_test_attr = nxt.attr("is_test")
            rng_slot = nxt.attr("__rng_slot__")
            cur_out = nxt.output("Out")[0]
            nxt = self._sole_consumer(block, cur_out, uses, protected)
        if (nxt is None or nxt.type != "matmul"
                or nxt.attr("transpose_X", False)
                or nxt.attr("transpose_Y", False)
                or float(nxt.attr("alpha", 1.0)) != 1.0
                or (nxt.input("X") or [None])[0] != cur_out):
            return None
        v_name = nxt.input("Y")[0]
        if not _rank4(block, v_name):
            return None
        if drop is not None:
            removable.append(drop)
        mm2 = nxt

        return {
            "q": q_name, "k": k_name, "v": v_name, "bias": bias,
            "sm_scale": sm_scale, "dropout_rate": dropout_rate,
            "is_test": is_test_attr, "rng_slot": rng_slot,
            "out": mm2.output("Out")[0],
            "removable": removable, "mm2": mm2,
        }

    @staticmethod
    def _sole_consumer(block, name, uses, protected):
        if not _single_consumer(name, uses, protected):
            return None
        for op in block.ops:
            if any(name in ns for ns in op.inputs.values()):
                return op
        return None

    # -- rewriting ------------------------------------------------------------
    def _rewrite(self, block, plan):
        inputs = {"Q": [plan["q"]], "K": [plan["k"]], "V": [plan["v"]]}
        if plan["bias"] is not None:
            inputs["Bias"] = [plan["bias"]]
        attrs = {"causal": False, "sm_scale": float(plan["sm_scale"]),
                 "dropout_rate": float(plan["dropout_rate"])}
        if plan["is_test"] is not None:
            attrs["is_test"] = plan["is_test"]
        if plan["rng_slot"] is not None:
            attrs["__rng_slot__"] = plan["rng_slot"]
        fused = Operator(block, "scaled_dot_product_attention",
                         inputs=inputs,
                         outputs={"Out": [plan["out"]]}, attrs=attrs)
        idx = block.ops.index(plan["mm2"])
        block.ops[idx] = fused
        doomed = {id(op) for op in plan["removable"]}
        block.ops[:] = [op for op in block.ops if id(op) not in doomed]
        out_var = block._find_var_recursive(plan["out"])
        if out_var is not None:
            out_var.op = fused
