"""Trace-time constant folding.

Reference: ``ir/constant_folding_pass`` territory, reimagined for the
trace-and-jit executor: XLA folds constants *after* paying trace + lowering
for them, so folding chains like ``fill_constant -> scale ->
elementwise_add`` at the Program level removes traced ops (smaller jaxpr,
faster trace, better persistent-compile-cache reuse across shape variants)
rather than device work.

Mechanics: scan the block in order carrying a name -> ndarray environment of
known constants. ``fill_constant`` / ``assign_value`` seed it; any op in the
FOLDABLE whitelist whose inputs are all known is host-evaluated through its
*registered impl* (exactly the code the tracer would run, so folded values
can't diverge from unfolded execution). A folded value still needed by a
surviving op is re-materialized as a single ``fill_constant`` (uniform) or
``assign_value`` op; everything else vanishes. Outputs larger than
``max_elements`` (default 65536) are never folded — attrs are host memory.
"""

from __future__ import annotations

import numpy as np

from ..core.pass_framework import Pass, register_pass
from ..core.registry import OpContext, get_op_impl, has_op
from . import analysis as A

__all__ = ["ConstantFoldingPass"]

_MAX_ELEMENTS = 65536


class _FoldTrace:
    """Minimal TraceContext stand-in for host-evaluating pure ops."""

    def __init__(self, program):
        self.program = program
        self.is_test = True
        self.current_op_idx = 0
        self.mesh = None

    def op_rng(self, ctx):  # pragma: no cover - FOLDABLE ops never draw RNG
        raise RuntimeError("constant folder evaluated an RNG-consuming op")


def _try_eval(op, const_env, program):
    """Evaluate ``op`` over numpy constants via its registered impl.
    Returns {out_name: ndarray} or None when evaluation is unsafe."""
    env = {}
    for n in op.input_arg_names:
        env[n] = const_env[n]
    impl = get_op_impl(op.type)
    try:
        impl(OpContext(op, env, _FoldTrace(program)))
    except Exception:
        return None
    outs = {}
    for n in op.output_arg_names:
        if n not in env:
            return None  # optional output the impl didn't write
        arr = np.asarray(env[n])
        if arr.size > _MAX_ELEMENTS:
            return None
        outs[n] = arr
    return outs


def _np_dtype_name(arr) -> str:
    from ..core.dtypes import convert_dtype

    try:
        return convert_dtype(arr.dtype)
    except Exception:
        return str(arr.dtype)


def _materialize(block, index, name, arr):
    """Insert one constant op producing ``name`` = ``arr`` at ``index``."""
    dtype = _np_dtype_name(arr)
    if arr.size and (arr == arr.ravel()[0]).all():
        return block.insert_op(
            index, "fill_constant", outputs={"Out": [name]},
            attrs={"shape": list(arr.shape), "dtype": dtype,
                   "value": arr.ravel()[0].item()})
    return block.insert_op(
        index, "assign_value", outputs={"Out": [name]},
        attrs={"shape": list(arr.shape), "dtype": dtype,
               "values": arr.ravel().tolist()})


@register_pass("constant_folding")
class ConstantFoldingPass(Pass):
    """attrs: ``protected`` (names that must keep their defining op as an
    explicit constant rather than disappear), ``fetch_names`` (None when
    fetches are unknown — build-time application — in which case every leaf
    output may be observed later and is kept, mirroring DCE's conservative
    mode). Reports ``ops_removed``."""

    def apply_impl(self, program):
        block = program.global_block
        protected = set(self.attr("protected") or ())
        protected |= A.protected_names(program)
        if self.attr("fetch_names") is None:
            # fetch set unknown: a chain's leaf may be fetched at run time —
            # treat every output nothing in-program reads as protected
            uses = A.use_counts(program)
            for op in block.ops:
                for n in op.output_arg_names:
                    if not uses.get(n):
                        protected.add(n)

        # names any sub-block op writes (loop carries mutate outer vars):
        # their global defs must never be treated as constants
        mutated_elsewhere = set()
        for blk in program.blocks:
            if blk is not block:
                for op in blk.ops:
                    mutated_elsewhere.update(op.output_arg_names)

        const_env = {}        # name -> ndarray (current definition)
        folded_ops = {}       # id(op) -> op, ops whose outputs are all known
        folded_producer = {}  # name -> id(op) of the folded op defining it
        for op in block.ops:
            if op.type in A.MARKER_OPS:
                continue
            # a persistable write is externally visible (the Executor flows
            # it back to the scope) — such ops may SEED the constant env but
            # must never be deleted (e.g. startup fill_constant initializers)
            writes_persistable = any(
                (lambda v: v is not None and v.persistable)(
                    block._find_var_recursive(n))
                for n in op.output_arg_names)
            foldable = False
            if op.type in A.CONST_SOURCE_OPS and not op.input_arg_names:
                foldable = True
            elif (op.type in A.FOLDABLE_OPS and has_op(op.type)
                    and op.input_arg_names
                    and all(n in const_env for n in op.input_arg_names)):
                foldable = True
            if foldable and not any(n in mutated_elsewhere
                                    for n in op.output_arg_names):
                outs = _try_eval(op, const_env, program)
                if outs is not None:
                    const_env.update(outs)
                    if not writes_persistable:
                        folded_ops[id(op)] = op
                        for n in outs:
                            folded_producer[n] = id(op)
                    continue
            # not folded: this op's writes shadow any earlier constant defs —
            # and any folded op defining a now-redefined name must SURVIVE
            # (its materialization slot would otherwise be lost)
            for n in op.output_arg_names:
                const_env.pop(n, None)
                pid = folded_producer.pop(n, None)
                if pid is not None:
                    folded_ops.pop(pid, None)

        if not folded_ops:
            self.set_attr("ops_removed", 0)
            return program

        # A folded var is still NEEDED when a surviving op reads it, an
        # opaque op references it, or it is protected (fetch target etc.).
        known = A.all_var_names(program)
        needed = set(protected)
        for blk in program.blocks:
            for op in blk.ops:
                if blk is block and id(op) in folded_ops:
                    continue
                needed.update(op.input_arg_names)
                if A.has_sub_block(op):
                    needed.update(A.attr_referenced_names(op, known))

        before = len(block.ops)
        new_ops = []
        for op in block.ops:
            if id(op) not in folded_ops:
                new_ops.append(op)
                continue
            for n in op.output_arg_names:
                if n in needed and n in const_env:
                    # splice the constant where the producer stood, keeping
                    # def-before-use order for surviving consumers
                    new_ops.append(_ConstPlaceholder(n, const_env[n]))
        block.ops[:] = [o for o in new_ops
                        if not isinstance(o, _ConstPlaceholder)]
        # materialize placeholders via insert_op (runs shape inference and
        # wires var.op) at their recorded positions, front to back
        for pos, ph in [(i, o) for i, o in enumerate(new_ops)
                        if isinstance(o, _ConstPlaceholder)]:
            _materialize(block, pos, ph.name, ph.value)
        program._version += 1
        removed = before - len(block.ops)
        A.prune_dead_vars(program, extra_keep=needed | set(const_env))
        self.set_attr("ops_removed", removed)
        return program


class _ConstPlaceholder:
    __slots__ = ("name", "value")

    def __init__(self, name, value):
        self.name = name
        self.value = value
