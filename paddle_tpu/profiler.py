"""Profiler (reference: python/paddle/fluid/profiler.py:39-221 +
platform/profiler.cc / device_tracer.cc over CUPTI).

The TPU-native stack: ``jax.profiler`` captures both host events and device
(TPU) timelines into a trace viewable in TensorBoard/Perfetto — the role the
reference splits between RecordEvent, CUPTI DeviceTracer, profiler.proto and
tools/timeline.py. The context-manager UX is kept identical.

For always-on, TensorBoard-free observability see
:mod:`paddle_tpu.monitor`: a metrics registry (counters/gauges/histograms
pre-wired through the Executor and readers) and a host-span tracer whose
Chrome-trace export loads directly in ``chrome://tracing``.
``record_event`` below feeds BOTH layers — the jax.profiler device trace
and the monitor host-span timeline — so one annotation shows up wherever
you are looking.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Optional

import jax

__all__ = ["profiler", "start_profiler", "stop_profiler", "cuda_profiler",
           "npu_profiler", "record_event"]

_active_dir: Optional[str] = None


def start_profiler(state: str = "All", tracer_option=None, log_dir: Optional[str] = None):
    """reference: profiler.py:125. state/tracer_option accepted for parity."""
    global _active_dir
    _active_dir = log_dir or os.environ.get("PADDLE_TPU_PROFILE_DIR", "/tmp/paddle_tpu_profile")
    jax.profiler.start_trace(_active_dir)


def stop_profiler(sorted_key=None, profile_path: Optional[str] = None):
    """reference: profiler.py:165. The trace lands in the log dir for
    TensorBoard/Perfetto instead of a text table."""
    global _active_dir
    jax.profiler.stop_trace()
    d, _active_dir = _active_dir, None
    return d


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key=None, profile_path: Optional[str] = None,
             tracer_option=None, log_dir: Optional[str] = None):
    """reference: profiler.py:221 context manager."""
    start_profiler(state, tracer_option, log_dir or profile_path)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


# GPU-era aliases kept for API parity; both map to the same TPU trace.
cuda_profiler = profiler
npu_profiler = profiler


@contextlib.contextmanager
def record_event(name: str):
    """RAII scope marker (reference: platform/profiler.h:41 RecordEvent) —
    shows up as a named range in the jax.profiler device trace AND, when
    host tracing is active (``PADDLE_TPU_TRACE_FILE`` /
    ``monitor.tracer.start_tracing()``), as a host span in the Chrome-trace
    export."""
    from .monitor import tracer as _tr

    with _tr.span(name, cat="user", device=True):
        yield


class StepProfiler:
    """Step-time statistics table (reference: profiler.py:221's sorted text
    table — per-OP rows don't exist under XLA fusion, so the rows here are
    named step scopes: wall time min/avg/max/total + calls, plus a pointer
    at the full device trace for kernel-level drill-down).

        prof = StepProfiler()
        for batch in data:
            with prof.step("train"):
                exe.run(...)
        print(prof.summary())
    """

    def __init__(self):
        self._records = {}

    @contextlib.contextmanager
    def step(self, name: str = "step"):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._records.setdefault(name, []).append(time.perf_counter() - t0)

    def reset(self):
        self._records.clear()

    def summary(self, sorted_key: str = "total") -> str:
        keys = {"total": lambda r: -sum(r[1]), "max": lambda r: -max(r[1]),
                "min": lambda r: -min(r[1]), "calls": lambda r: -len(r[1]),
                "ave": lambda r: -sum(r[1]) / len(r[1])}
        if sorted_key not in keys:
            raise ValueError("sorted_key must be one of %s, got %r"
                             % (sorted(keys), sorted_key))
        rows = sorted(self._records.items(), key=keys[sorted_key])
        lines = ["%-24s %8s %12s %12s %12s %12s %12s %12s %12s" % (
            "Event", "Calls", "Total(ms)", "Min(ms)", "Max(ms)", "Ave(ms)",
            "P50(ms)", "P95(ms)", "P99(ms)")]
        from .monitor.metrics import sorted_percentile

        for name, ts in rows:
            st = sorted(ts)
            lines.append(
                "%-24s %8d %12.3f %12.3f %12.3f %12.3f %12.3f %12.3f %12.3f" % (
                    name, len(ts), sum(ts) * 1e3, min(ts) * 1e3, max(ts) * 1e3,
                    sum(ts) / len(ts) * 1e3, sorted_percentile(st, 50) * 1e3,
                    sorted_percentile(st, 95) * 1e3,
                    sorted_percentile(st, 99) * 1e3))
        lines.append("(kernel-level drill-down: run under profiler()/"
                     "start_profiler and open the trace dir in TensorBoard)")
        return "\n".join(lines)


__all__ += ["StepProfiler"]

# Module-level default profiler: scripts that just want step timings can use
# ``default_step_profiler().step(...)`` without threading an instance around,
# and reset_profiler() has real state to clear (reference semantics).
_default_step_profiler = StepProfiler()


def default_step_profiler() -> StepProfiler:
    return _default_step_profiler


def reset_profiler():
    """Clear collected profile data (reference: profiler.py reset_profiler):
    resets the module-level default StepProfiler. jax.profiler device traces
    are per start/stop window and need no clearing."""
    _default_step_profiler.reset()


__all__ += ["reset_profiler", "default_step_profiler"]
