"""DataFeedDesc — training-data format descriptor (reference:
python/paddle/fluid/data_feed_desc.py + framework/data_feed.proto).

Parses the reference's proto-text format (name / batch_size /
multi_slot_desc { slots { ... } }) with a small text parser instead of a
protobuf dependency — the on-disk files are byte-compatible.
"""

from __future__ import annotations

import re
from typing import Dict, List

__all__ = ["DataFeedDesc"]

_SLOT_RE = re.compile(
    r"slots\s*\{([^}]*)\}", re.S)
_FIELD_RE = re.compile(r"(\w+)\s*:\s*(\"[^\"]*\"|\S+)")


class _Slot:
    def __init__(self, name="", type="uint64", is_dense=False, is_used=True,
                 dense_dim=1):
        self.name = name
        self.type = type
        self.is_dense = is_dense
        self.is_used = is_used
        self.dense_dim = dense_dim

    def __repr__(self):
        return ("slots {\n    name: \"%s\"\n    type: \"%s\"\n    is_dense: %s\n"
                "    is_used: %s\n  }" % (self.name, self.type,
                                          str(self.is_dense).lower(),
                                          str(self.is_used).lower()))


def _parse_value(raw: str):
    raw = raw.strip()
    if raw.startswith('"'):
        return raw.strip('"')
    if raw in ("true", "false"):
        return raw == "true"
    try:
        return int(raw)
    except ValueError:
        return raw


class DataFeedDesc:
    """reference: data_feed_desc.py:21 — same proto-text file format."""

    def __init__(self, proto_file: str):
        with open(proto_file) as f:
            text = f.read()
        self.name = "MultiSlotDataFeed"
        self.batch_size = 1
        for m in _FIELD_RE.finditer(re.sub(_SLOT_RE, "", text)):
            key, val = m.group(1), _parse_value(m.group(2))
            if key == "name":
                self.name = val
            elif key == "batch_size":
                self.batch_size = int(val)
        self.slots: List[_Slot] = []
        for m in _SLOT_RE.finditer(text):
            fields = {k: _parse_value(v) for k, v in _FIELD_RE.findall(m.group(1))}
            self.slots.append(_Slot(**{k: v for k, v in fields.items()
                                       if k in ("name", "type", "is_dense",
                                                "is_used", "dense_dim")}))
        self._index: Dict[str, int] = {s.name: i for i, s in enumerate(self.slots)}

    # -- reference mutators ----------------------------------------------------
    def set_batch_size(self, batch_size: int):
        self.batch_size = int(batch_size)

    def set_dense_slots(self, dense_slots_name):
        for n in dense_slots_name:
            self.slots[self._index[n]].is_dense = True

    def set_use_slots(self, use_slots_name):
        for s in self.slots:
            s.is_used = False
        for n in use_slots_name:
            self.slots[self._index[n]].is_used = True

    def desc(self) -> str:
        lines = ["name: \"%s\"" % self.name, "batch_size: %d" % self.batch_size,
                 "multi_slot_desc {"]
        lines += ["  " + repr(s) for s in self.slots]
        lines.append("}")
        return "\n".join(lines)
