"""Per-op test harness (reference: tests/unittests/op_test.py:133 — the
workhorse behind the reference's 334 per-op test files).

Same contract, TPU-native mechanics:
- ``check_output``: run the registered op impl on concrete inputs, compare
  against expected numpy outputs (the reference runs the real kernel on every
  available place; here the impl IS the single XLA-lowered definition).
- ``check_grad``: compare ``jax.grad`` of sum(output) against central finite
  differences (the reference compares its hand-written grad op against
  finite differences — here autodiff replaces the grad op, and the check
  validates the forward impl is differentiable and smooth).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..core.framework import Operator, Program
from ..core.registry import OpContext, get_op_impl

__all__ = ["run_op", "check_output", "check_grad", "OpTest"]

InputSpec = Union[np.ndarray, List[Tuple[str, np.ndarray]]]


def _canon_inputs(inputs: Dict[str, InputSpec]):
    """Normalize {slot: array | [(name, array), ...]} → (slot_map, env)."""
    slot_map: Dict[str, List[str]] = {}
    env: Dict[str, Any] = {}
    from ..core.sparse import SparseGrad

    def as_value(v):
        if isinstance(v, SparseGrad):  # sparse-optimizer variants under test
            return SparseGrad(ids=jnp.asarray(v.ids), rows=jnp.asarray(v.rows))
        return jnp.asarray(v)

    for slot, spec in (inputs or {}).items():
        if isinstance(spec, list) and spec and isinstance(spec[0], tuple):
            names = []
            for name, arr in spec:
                env[name] = as_value(arr)
                names.append(name)
            slot_map[slot] = names
        else:
            name = "%s@in" % slot
            env[name] = as_value(spec)
            slot_map[slot] = [name]
    return slot_map, env


class _Trace:
    def __init__(self, is_test=False, seed=0):
        self.is_test = is_test
        self.base_rng = jax.random.PRNGKey(seed)
        self.current_op_idx = 0
        self.mesh = None
        self.program = None

    def op_rng(self, ctx):
        seed = ctx.attr("seed", 0)
        key = jax.random.PRNGKey(seed) if seed else self.base_rng
        return jax.random.fold_in(key, self.current_op_idx)


def run_op(
    op_type: str,
    inputs: Dict[str, InputSpec],
    output_slots: Sequence[str],
    attrs: Optional[Dict[str, Any]] = None,
    is_test: bool = False,
    seed: int = 0,
) -> Dict[str, Any]:
    """Execute one registered op; returns {output_slot: value}."""
    slot_map, env = _canon_inputs(inputs)
    out_map = {slot: ["%s@out" % slot] for slot in output_slots}
    prog = Program()
    op = Operator(prog.global_block, op_type, attrs=attrs)
    op.inputs = slot_map
    op.outputs = out_map
    impl = get_op_impl(op_type)
    impl(OpContext(op, env, _Trace(is_test, seed)))
    return {slot: env.get(out_map[slot][0]) for slot in output_slots}


def check_output(
    op_type: str,
    inputs: Dict[str, InputSpec],
    expected: Dict[str, np.ndarray],
    attrs: Optional[Dict[str, Any]] = None,
    atol: float = 1e-5,
    rtol: float = 1e-5,
    is_test: bool = False,
):
    got = run_op(op_type, inputs, list(expected), attrs, is_test=is_test)
    for slot, want in expected.items():
        np.testing.assert_allclose(
            np.asarray(got[slot]), want, atol=atol, rtol=rtol,
            err_msg="op %r output slot %r mismatch" % (op_type, slot))


def check_grad(
    op_type: str,
    inputs: Dict[str, InputSpec],
    inputs_to_check: Sequence[str],
    output_slot: str,
    attrs: Optional[Dict[str, Any]] = None,
    max_relative_error: float = 5e-3,
    delta: float = 1e-3,
    seed: int = 0,
):
    """Autodiff-vs-finite-difference check (reference: op_test.py:418)."""
    slot_map, env0 = _canon_inputs(inputs)
    out_map = {output_slot: ["%s@out" % output_slot]}
    prog = Program()
    op = Operator(prog.global_block, op_type, attrs=attrs)
    op.inputs = slot_map
    op.outputs = out_map

    check_names = []
    for slot in inputs_to_check:
        check_names.extend(slot_map[slot])

    def f(check_env):
        env = dict(env0)
        env.update(check_env)
        get_op_impl(op_type)(OpContext(op, env, _Trace(False, seed)))
        return jnp.sum(env[out_map[output_slot][0]].astype(jnp.float32))

    check_env0 = {n: env0[n] for n in check_names}
    analytic = jax.grad(f)(check_env0)

    for name in check_names:
        base = env0[name]
        flat = jnp.asarray(base, jnp.float32).reshape(-1)
        n = flat.size
        eye = jnp.eye(n, dtype=jnp.float32) * delta

        def g(x_flat, _name=name, _shape=base.shape, _dtype=base.dtype):
            ce = dict(check_env0)
            ce[_name] = x_flat.reshape(_shape).astype(_dtype)
            return f(ce)

        # all 2n perturbed evaluations batched through ONE jitted vmap —
        # wide-op grad checks stay practical (VERDICT weak #6)
        batched = jax.jit(jax.vmap(g))
        plus = batched(flat[None, :] + eye)
        minus = batched(flat[None, :] - eye)
        num = (np.asarray(plus, np.float64) - np.asarray(minus, np.float64)) / (2 * delta)
        num = num.reshape(np.asarray(base).shape)
        a = np.asarray(analytic[name], dtype=np.float64)
        abs_err = np.abs(a - num)
        denom = np.maximum(np.maximum(np.abs(a), np.abs(num)), 1.0)
        rel = (abs_err / denom).max()
        assert rel <= max_relative_error, (
            "op %r grad wrt %r: max relative error %.3e > %.3e\nanalytic=%s\nnumeric=%s"
            % (op_type, name, rel, max_relative_error, a, num))


class OpTest:
    """Class-style harness for familiarity with the reference's OpTest.

    Subclass sets ``op_type``, ``inputs``, ``attrs``, ``outputs`` in setup and
    calls ``self.check_output()`` / ``self.check_grad([...], 'Out')``.
    """

    op_type: str = ""
    inputs: Dict[str, InputSpec] = {}
    attrs: Dict[str, Any] = {}
    outputs: Dict[str, np.ndarray] = {}

    def check_output(self, atol=1e-5, rtol=1e-5, is_test=False):
        check_output(self.op_type, self.inputs, self.outputs, self.attrs,
                     atol=atol, rtol=rtol, is_test=is_test)

    def check_grad(self, inputs_to_check, output_slot="Out",
                   max_relative_error=5e-3, delta=1e-3):
        check_grad(self.op_type, self.inputs, inputs_to_check, output_slot,
                   self.attrs, max_relative_error, delta)
