from .op_test import OpTest, check_grad, check_output, run_op  # noqa: F401
