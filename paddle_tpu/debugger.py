"""Program inspection tools (reference: python/paddle/fluid/debugger.py,
graphviz.py, net_drawer.py)."""

from __future__ import annotations

from .core.framework import Program

__all__ = ["pprint_program_codes", "draw_block_graphviz"]


def pprint_program_codes(program: Program) -> str:
    """Pretty program listing (reference: debugger.py pprint_program_codes)."""
    lines = []
    for blk in program.blocks:
        lines.append("// block %d (parent %d)" % (blk.idx, blk.parent_idx))
        for v in blk.vars.values():
            mods = []
            if v.persistable:
                mods.append("persistable")
            if v.is_data:
                mods.append("data")
            lines.append("  var %s : %s%s %s" % (
                v.name, v.dtype, list(v.shape) if v.shape is not None else "?",
                " ".join(mods)))
        for op in blk.ops:
            outs = ", ".join("%s=%s" % (k, v) for k, v in op.outputs.items())
            ins = ", ".join("%s=%s" % (k, v) for k, v in op.inputs.items())
            lines.append("  {%s} = %s(%s) [%s]" % (
                outs, op.type, ins,
                ", ".join("%s=%r" % kv for kv in sorted(op.attrs.items()))))
    text = "\n".join(lines)
    print(text)
    return text


def draw_block_graphviz(block, output_path: str = "program.dot", highlights=None):
    """DOT dump of a block's dataflow (reference: graph_viz_pass.cc /
    debugger.draw_block_graphviz)."""
    highlights = set(highlights or [])
    lines = ["digraph G {", "  rankdir=TB;"]
    for v in block.vars.values():
        style = ' style=filled fillcolor="#ffd2d2"' if v.name in highlights else ""
        lines.append('  "%s" [shape=oval%s];' % (v.name, style))
    for i, op in enumerate(block.ops):
        op_id = "op_%d_%s" % (i, op.type)
        lines.append('  "%s" [shape=box label="%s"];' % (op_id, op.type))
        for name in op.input_arg_names:
            lines.append('  "%s" -> "%s";' % (name, op_id))
        for name in op.output_arg_names:
            lines.append('  "%s" -> "%s";' % (op_id, name))
    lines.append("}")
    with open(output_path, "w") as f:
        f.write("\n".join(lines))
    return output_path
