"""paddle_tpu.serving — continuous batching, paged KV-cache, decode driver.

The million-user inference surface (ROADMAP item 1): where
``inference.predictor`` runs one fully-padded request at a time, this
package multiplexes a request stream onto a device-resident autoregressive
decode loop —

* :class:`~.scheduler.Scheduler`: bounded FIFO queue → fixed batch slots,
  with continuous (in-flight) admission each decode step,
* :class:`~.page_pool.PagePool` + :class:`~.kv_cache.PagedKVCache`: fixed
  HBM pages and per-request page tables, so ragged sequence lengths pay
  for pages, not padding (kernel blueprint: "Ragged Paged Attention",
  PAPERS.md; XLA-gather path in ``ops.attention_ops.decode_attention``),
* :class:`~.engine.ServingEngine`: AOT-compiled (``executor.aot_compile``)
  per-bucket prefill + fused decode steps with all serving state on device,
* ``serving/*`` monitor counters + latency histograms, flight-recorder
  capture of the in-flight batch on decode failure.

Quick start::

    from paddle_tpu import serving
    from paddle_tpu.models import decoder_lm

    model = decoder_lm.DecoderLM(decoder_lm.DecoderConfig(max_seq=128))
    eng = serving.ServingEngine(model, serving.ServingConfig(
        slots=8, page_size=16, max_seq=128))
    reqs = [eng.submit([1, 2, 3], max_new_tokens=16) for _ in range(32)]
    eng.run()                 # drains queue+slots, continuous batching
    print(reqs[0].tokens_out, reqs[0].latency_s)
    eng.close()               # releases the continuous-telemetry exporter

Benchmarks: ``python bench.py --serve`` (ragged continuous batching vs the
padded static baseline), ``python -m tools.serve_bench --selftest``.
"""

from . import trace  # noqa: F401
from .engine import ServingConfig, ServingEngine  # noqa: F401
from .kv_cache import (  # noqa: F401
    ContiguousKVCache, Int8PagedKVCache, PagedKVCache)
from .page_pool import PagePool, PagePoolExhausted  # noqa: F401
from .request import (  # noqa: F401
    FAILED, FINISHED, QUEUED, REJECTED, RUNNING, TIMEOUT, BackpressureError,
    DrainingError, Request)
from .scheduler import Scheduler  # noqa: F401

__all__ = [
    "ServingConfig", "ServingEngine",
    "PagedKVCache", "Int8PagedKVCache", "ContiguousKVCache",
    "PagePool", "PagePoolExhausted",
    "Scheduler", "Request", "BackpressureError", "DrainingError",
    "QUEUED", "RUNNING", "FINISHED", "TIMEOUT", "FAILED", "REJECTED",
    "trace",
]
