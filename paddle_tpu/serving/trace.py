"""Per-request lifecycle tracing: serving requests → Perfetto timeline.

Every :class:`~.request.Request` carries a ``trace_id``; when the host
tracer is active (``monitor.tracer.start_tracing()`` or
``PADDLE_TPU_TRACE_FILE``) the engine emits the request's lifecycle into
the SAME span stream the rest of the stack traces to, on virtual tracks:

* ``serving queue`` — ``submitted`` instants, the ``queued`` wait span
  (submission → admission; submission → timeout for requests that die in
  the queue), and terminal instants for never-admitted requests;
* ``serving slot <k>`` — one track per batch slot: the request's
  lifetime span (``req <trace_id>``, admission → retirement), its
  ``prefill(b=<bucket>)`` span, every ``decode`` chunk span it rode
  (``decode_fuse`` steps per span; pages held + fused step count in
  args), and the terminal instant (``retired`` / ``FAILED`` /
  ``TIMEOUT``).

Because spans nest by time containment per track, opening the Chrome
trace in Perfetto reconstructs the continuous-batching schedule visually:
slot occupancy, admission holes, prefill/decode interleave, and which
requests shared each fused dispatch. The flight recorder links crash
dumps to this timeline by carrying ``trace_id`` in the in-flight batch
spec.

Everything here guards on ``tracer.active()`` — an untraced engine pays
one bool read per call site.

:func:`validate_request_spans` is the invariant checker serve_bench's
selftest (and tests) run over a drained stream: every terminal request
must have a COMPLETE, WELL-NESTED span set — no orphan ``queued``
without a terminal instant, no partially-overlapping spans on a track.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from ..monitor import tracer as _tr

__all__ = [
    "QUEUE_TRACK", "slot_track",
    "on_submitted", "on_admitted", "on_prefill", "on_decode_chunk",
    "on_terminal",
    "request_spans", "validate_request_spans", "slot_assignments_from_spans",
    "assert_well_nested",
]

QUEUE_TRACK = "serving queue"
CAT = "serving"


def slot_track(slot: int) -> str:
    return "serving slot %d" % slot


def _us(t_s: float) -> int:
    return int(t_s * 1e6)


def _targs(req, **kw) -> dict:
    """Common span args: trace_id always; the fleet attempt number when
    this request is a fleet dispatch (attempt >= 1) — the key the merged
    cross-process timeline joins attempt-1/attempt-2 replays on."""
    args = {"trace_id": req.trace_id}
    attempt = getattr(req, "attempt", 0)
    if attempt:
        args["attempt"] = attempt
    args.update(kw)
    return args


def on_submitted(req) -> None:
    if not _tr.active():
        return
    _tr.record_instant(
        "submitted", _us(req.submitted_t), cat=CAT, track=QUEUE_TRACK,
        args=_targs(req, prompt_len=req.prompt_len,
                    max_new_tokens=req.max_new_tokens))


def on_admitted(req, slot: int) -> None:
    """Close the queue-wait span (submission → admission)."""
    if not _tr.active():
        return
    _tr.record_span(
        "queued", _us(req.submitted_t),
        _us(req.admitted_t) - _us(req.submitted_t), cat=CAT,
        track=QUEUE_TRACK,
        args=_targs(req, slot=slot, phase="queue", cause="engine"))


def on_prefill(req, slot: int, bucket: int, t0_s: float, t1_s: float,
               cause: str = "local") -> None:
    """``cause`` is the phase-ledger attribution: ``local`` for a cold
    prefill, ``resume`` when the prompt resumed from cached/shipped
    prefix pages (the remote-prefill consumption path)."""
    if not _tr.active():
        return
    _tr.record_span(
        "prefill(b=%d)" % bucket, _us(t0_s), _us(t1_s) - _us(t0_s), cat=CAT,
        track=slot_track(slot),
        args=_targs(req, bucket=bucket, prompt_len=req.prompt_len,
                    phase="prefill", cause=cause))


def on_decode_chunk(reqs_by_slot: Sequence, fuse: int, t0_s: float,
                    t1_s: float, spec: Optional[dict] = None) -> None:
    """One fused decode dispatch: a ``decode`` span on EVERY occupied
    slot's track (same wall window — that is the point: Perfetto shows
    which requests shared the dispatch). ``reqs_by_slot[k]`` is the
    request in slot k or None. A speculative verify dispatch passes
    ``spec`` (``serving.speculative.verify_window_args``): the span is
    tagged phase ``verify`` and carries the accepted-k attribution the
    phase ledger accumulates per request."""
    if not _tr.active():
        return
    ts, dur = _us(t0_s), _us(t1_s) - _us(t0_s)
    extra = dict(spec, phase="verify") if spec else {"phase": "decode"}
    for slot, req in enumerate(reqs_by_slot):
        if req is None:
            continue
        _tr.record_span(
            "decode", ts, dur, cat=CAT, track=slot_track(slot),
            args=_targs(req, steps=fuse, pages_held=len(req.pages),
                        generated=len(req.tokens_out), **extra))


def on_terminal(req, state: str, slot: Optional[int]) -> None:
    """Retirement from a slot (emits the request-lifetime span + the
    terminal instant on the slot track) or from the queue (``slot=None``:
    the queue-wait span never closed at admission — close it here — plus
    the terminal instant on the queue track)."""
    if not _tr.active():
        return
    label = {"finished": "retired", "failed": "FAILED",
             "timeout": "TIMEOUT"}.get(state, state)
    args = _targs(req, state=state, tokens_out=len(req.tokens_out))
    # the engine-measured readouts ride the terminal instant so the phase
    # ledger can check its decomposition against them (no new clocks —
    # these are the same request timestamps the histograms observe)
    if req.first_token_t is not None:
        args["ttft_ms"] = round((req.first_token_t - req.submitted_t) * 1e3,
                                3)
    if req.finished_t is not None:
        args["latency_ms"] = round((req.finished_t - req.submitted_t) * 1e3,
                                   3)
    if slot is not None:
        track = slot_track(slot)
        _tr.record_span(
            "req %s" % req.trace_id, _us(req.admitted_t),
            _us(req.finished_t) - _us(req.admitted_t), cat=CAT, track=track,
            args=dict(args, prompt_len=req.prompt_len))
    else:
        track = QUEUE_TRACK
        _tr.record_span(
            "queued", _us(req.submitted_t),
            _us(req.finished_t) - _us(req.submitted_t), cat=CAT, track=track,
            args=_targs(req, slot=None, phase="queue", cause="shed"))
    _tr.record_instant(label, _us(req.finished_t), cat=CAT, track=track,
                       args=args)


# -- read-back / validation ---------------------------------------------------

def request_spans(spans: Sequence[dict]) -> Dict[str, List[dict]]:
    """Group serving-cat spans by ``args.trace_id``."""
    out: Dict[str, List[dict]] = {}
    for s in spans:
        if s.get("cat") != CAT:
            continue
        tid = (s.get("args") or {}).get("trace_id")
        if tid:
            out.setdefault(tid, []).append(s)
    return out


_TERMINALS = {"retired": "finished", "FAILED": "failed", "TIMEOUT": "timeout"}


def validate_request_spans(spans: Sequence[dict], requests: Sequence
                           ) -> Dict[str, dict]:
    """Assert every terminal request has a complete, well-nested span set.

    Per terminal request: a ``submitted`` instant, a ``queued`` span, the
    matching terminal instant; admitted requests additionally need the
    lifetime ``req <id>`` span and a ``prefill`` span, and the lifetime
    span must CONTAIN every prefill/decode span of the request. Per
    track: spans must be disjoint or nested, never partially overlapping.
    Returns {trace_id: digest} for further assertions."""
    by_req = request_spans(spans)
    digests: Dict[str, dict] = {}
    for req in requests:
        if req.state not in ("finished", "failed", "timeout"):
            continue
        mine = by_req.get(req.trace_id, [])
        names = [s["name"] for s in mine]
        assert "submitted" in names, \
            "request %s: no submitted instant (spans: %s)" % (
                req.trace_id, names)
        assert "queued" in names, \
            "request %s: no queued span" % req.trace_id
        terminals = [s for s in mine if s["name"] in _TERMINALS]
        assert terminals, ("request %s: queued-without-terminal orphan "
                           "(state=%s, spans=%s)"
                           % (req.trace_id, req.state, names))
        assert len(terminals) == 1, \
            "request %s: %d terminal instants" % (req.trace_id,
                                                  len(terminals))
        term = terminals[0]
        assert _TERMINALS[term["name"]] == req.state, \
            "request %s: terminal %r but state %r" % (
                req.trace_id, term["name"], req.state)
        was_admitted = req.admitted_t is not None
        queued_args = next((s.get("args") or {} for s in mine
                            if s["name"] == "queued"), {})
        digest = {"state": req.state, "admitted": was_admitted,
                  "decode_chunks": sum(1 for n in names if n == "decode"),
                  "slot": queued_args.get("slot"), "track": None}
        if was_admitted:
            life = [s for s in mine if s["name"].startswith("req ")]
            assert len(life) == 1, \
                "request %s: %d lifetime spans" % (req.trace_id, len(life))
            life = life[0]
            assert any(n.startswith("prefill(") for n in names), \
                "request %s admitted but has no prefill span" % req.trace_id
            lo = life["ts_us"]
            hi = lo + life["dur_us"]
            for s in mine:
                if s["name"].startswith("prefill(") or s["name"] == "decode":
                    assert lo <= s["ts_us"] and \
                        s["ts_us"] + s["dur_us"] <= hi, (
                            "request %s: %s span [%d,%d] escapes lifetime "
                            "[%d,%d]" % (req.trace_id, s["name"], s["ts_us"],
                                         s["ts_us"] + s["dur_us"], lo, hi))
            digest["track"] = life["tid"]
        digests[req.trace_id] = digest
    assert_well_nested(spans)
    return digests


def assert_well_nested(spans: Sequence[dict], cat: str = CAT,
                       exempt: Sequence[str] = ("queued",)) -> None:
    """Per (pid, tid) track: any two ``cat`` spans are disjoint or one
    contains the other — the property that makes the Chrome viewer's
    stacking (and a human's read of the schedule) unambiguous. Span names
    in ``exempt`` are skipped: request lifelines of concurrent requests
    (``queued`` waits, fleet ``attempt`` windows) legitimately overlap
    partially — they are independent lifelines, not a call stack. The
    fleet validator (tools/fleet_trace.py) reuses this core per merged
    worker process, which is why the category is a parameter."""
    tracks: Dict[tuple, List[tuple]] = {}
    exempt = set(exempt)
    for s in spans:
        if s.get("cat") != cat or not s.get("dur_us"):
            continue
        if s["name"] in exempt:
            continue
        tracks.setdefault((s.get("pid"), s.get("tid")), []).append(
            (s["ts_us"], s["ts_us"] + s["dur_us"], s["name"]))
    for key, ivs in tracks.items():
        ivs.sort()
        stack: List[tuple] = []
        for lo, hi, name in ivs:
            while stack and stack[-1][1] <= lo:
                stack.pop()
            if stack:
                assert hi <= stack[-1][1], (
                    "track %s: span %r [%d,%d] partially overlaps %r "
                    "[%d,%d]" % (key, name, lo, hi, stack[-1][2],
                                 stack[-1][0], stack[-1][1]))
            stack.append((lo, hi, name))


def slot_assignments_from_spans(spans: Sequence[dict]) -> Dict[int, List[str]]:
    """{tid: [trace ids in start order]} from lifetime spans — the
    schedule reconstruction serve_bench cross-checks against the
    ``serving/*`` counters (sum of assignments == requests admitted)."""
    out: Dict[int, List[tuple]] = {}
    for s in spans:
        if s.get("cat") != CAT or not s["name"].startswith("req "):
            continue
        out.setdefault(s["tid"], []).append(
            (s["ts_us"], (s.get("args") or {}).get("trace_id")))
    return {tid: [t for _, t in sorted(v)] for tid, v in out.items()}
