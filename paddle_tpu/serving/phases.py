"""Per-request phase ledger: latency decomposition from the span stream.

The serving trace (serving/trace.py) and the fleet trace (fleet/trace.py)
already record everything needed to answer "where did this request's
latency go?" — this module just reads it back. No new hot-path clocks:
the emitters only TAG their existing spans with ``phase`` + ``cause``
args, and the ledger is derived entirely from a (merged) span stream.

Phase taxonomy — every microsecond of a request's life lands in one of:

* ``queue``     — waiting to run: the router's dispatch queue (cause
  ``router``, first attempt) and the engine's admission queue (cause
  ``engine``); a drain shedding queued work closes with cause ``shed``.
* ``admission`` — the scheduler gap between engine admission and the
  prefill dispatch actually starting (slot arming, page reservation).
* ``prefill``   — the prefill dispatch; ``cause`` distinguishes a cold
  local prefill (``local``) from a prefix-cache resume (``resume``) —
  the resume path is also how a remote-prefill replica's shipped pages
  are consumed, so a disaggregated decode replica shows ``resume``.
* ``ship``      — KV-page migration windows (export → binary ship →
  ingest) attributed to the requests the migration served; ``cause`` is
  the migration purpose (``disagg``/``remote_hit``/``rebalance``/...).
* ``decode``    — plain fused decode dispatches the request rode.
* ``verify``    — speculative draft-verify windows (a decode dispatch
  through the verify executable); args carry the accepted-k attribution
  (``proposed``/``accepted``) the ledger accumulates per request.
* ``retry``     — requeue gaps: a replica died or rejected, the request
  sat re-queued until its next dispatch (fleet queued span, attempt>=2).
* ``tail``      — the drain/timeout tail: time between the last dispatch
  touching the request and its terminal instant.

:func:`ledgers_from_spans` builds one :class:`RequestLedger` per
``trace_id``;  :meth:`RequestLedger.ttft_decomposition` explains the
engine-measured ``serving/ttft_ms`` as queue + admission + prefill
(+ pre-first-token ship), which ``tools/fleet_autopsy.py --selftest``
asserts sums to the measured value within tolerance. The fleet-scope
join (per-replica attribution, breach verdicts) lives in
``fleet/autopsy.py`` on top of this module.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

__all__ = [
    "QUEUE", "ADMISSION", "PREFILL", "SHIP", "DECODE", "VERIFY", "RETRY",
    "TAIL", "PHASES",
    "PhaseInterval", "RequestLedger", "ledgers_from_spans",
]

QUEUE = "queue"
ADMISSION = "admission"
PREFILL = "prefill"
SHIP = "ship"
DECODE = "decode"
VERIFY = "verify"
RETRY = "retry"
TAIL = "tail"

PHASES = (QUEUE, ADMISSION, PREFILL, SHIP, DECODE, VERIFY, RETRY, TAIL)

_SERVING_TERMINALS = {"retired": "finished", "FAILED": "failed",
                      "TIMEOUT": "timeout", "rejected": "rejected"}
_FLEET_TERMINALS = ("finished", "failed", "timeout", "rejected")


class PhaseInterval:
    """One attributed slice of a request's life: [t0_us, t1_us) spent in
    ``phase``, with the emitter's ``cause`` tag, the replica it ran on
    (None when unattributable), the fleet attempt it belongs to, and the
    span stream it came from (``src``: "serving" or "fleet")."""

    __slots__ = ("phase", "t0_us", "t1_us", "cause", "replica", "attempt",
                 "src", "args")

    def __init__(self, phase: str, t0_us: int, t1_us: int,
                 cause: Optional[str] = None, replica: Optional[int] = None,
                 attempt: Optional[int] = None, src: str = "serving",
                 args: Optional[dict] = None):
        self.phase = phase
        self.t0_us = int(t0_us)
        self.t1_us = max(int(t1_us), int(t0_us))
        self.cause = cause
        self.replica = replica
        self.attempt = attempt
        self.src = src
        self.args = args or {}

    @property
    def ms(self) -> float:
        return (self.t1_us - self.t0_us) / 1e3

    def to_doc(self) -> dict:
        return {"phase": self.phase, "t0_us": self.t0_us,
                "t1_us": self.t1_us, "ms": round(self.ms, 3),
                "cause": self.cause, "replica": self.replica,
                "attempt": self.attempt, "src": self.src}

    def __repr__(self):
        return ("PhaseInterval(%s, %.3fms, cause=%s, replica=%s, attempt=%s)"
                % (self.phase, self.ms, self.cause, self.replica,
                   self.attempt))


class RequestLedger:
    """Every attributed interval of one request, plus the request-level
    facts joined from its instants: terminal state, the engine-measured
    TTFT/latency the terminal instant carries, and which replicas served
    it. Intervals are sorted by start time — the waterfall order."""

    def __init__(self, trace_id: str):
        self.trace_id = trace_id
        self.state: Optional[str] = None
        self.intervals: List[PhaseInterval] = []
        self.submitted_us: Optional[int] = None
        self.terminal_us: Optional[int] = None
        self.measured_ttft_ms: Optional[float] = None
        self.measured_latency_ms: Optional[float] = None
        self.attempts: int = 0
        self.spec_proposed: int = 0
        self.spec_accepted: int = 0

    def add(self, iv: PhaseInterval) -> None:
        self.intervals.append(iv)

    @property
    def replicas(self) -> List[int]:
        return sorted({iv.replica for iv in self.intervals
                       if iv.replica is not None})

    def phase_ms(self) -> Dict[str, float]:
        """Total milliseconds per phase (all attempts, all replicas)."""
        out = {p: 0.0 for p in PHASES}
        for iv in self.intervals:
            out[iv.phase] = out.get(iv.phase, 0.0) + iv.ms
        return out

    def e2e_ms(self) -> Optional[float]:
        if self.submitted_us is not None and self.terminal_us is not None:
            return (self.terminal_us - self.submitted_us) / 1e3
        if self.measured_latency_ms is not None:
            return self.measured_latency_ms
        return None

    def ttft_decomposition(self) -> dict:
        """Explain the engine-measured ``serving/ttft_ms`` of the FINAL
        attempt as engine queue + admission + prefill (the engine clock
        starts at engine submission, so router queue / retry gaps / ship
        windows are reported alongside, not inside, ``explained_ms``)."""
        serving = [iv for iv in self.intervals if iv.src == "serving"]
        final = max((iv.attempt or 0) for iv in serving) if serving else 0
        mine = [iv for iv in serving if (iv.attempt or 0) == final]

        def tot(phase):
            return sum(iv.ms for iv in mine if iv.phase == phase)

        prefill_end = max((iv.t1_us for iv in mine if iv.phase == PREFILL),
                          default=None)
        ship = sum(iv.ms for iv in self.intervals if iv.phase == SHIP
                   and (prefill_end is None or iv.t1_us <= prefill_end))
        out = {
            "queue_ms": round(tot(QUEUE), 3),
            "admission_ms": round(tot(ADMISSION), 3),
            "prefill_ms": round(tot(PREFILL), 3),
            "ship_ms": round(ship, 3),
            "router_queue_ms": round(
                sum(iv.ms for iv in self.intervals
                    if iv.src == "fleet" and iv.phase in (QUEUE, RETRY)), 3),
            "attempt": final,
        }
        out["explained_ms"] = round(
            out["queue_ms"] + out["admission_ms"] + out["prefill_ms"], 3)
        out["measured_ttft_ms"] = self.measured_ttft_ms
        return out

    def to_doc(self) -> dict:
        doc = {"trace_id": self.trace_id, "state": self.state,
               "attempts": self.attempts, "replicas": self.replicas,
               "phase_ms": {k: round(v, 3)
                            for k, v in self.phase_ms().items() if v > 0},
               "e2e_ms": (round(self.e2e_ms(), 3)
                          if self.e2e_ms() is not None else None),
               "ttft": self.ttft_decomposition(),
               "intervals": [iv.to_doc() for iv in self.intervals]}
        if self.spec_proposed:
            doc["speculation"] = {"proposed": self.spec_proposed,
                                  "accepted": self.spec_accepted}
        return doc


def _num(v) -> Optional[float]:
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


def _build(trace_id: str, mine: Sequence[dict],
           pid_to_replica: Dict[int, int]) -> RequestLedger:
    led = RequestLedger(trace_id)
    lifetimes: List[dict] = []
    for s in sorted(mine, key=lambda s: int(s.get("ts_us", 0))):
        args = s.get("args") or {}
        name = str(s.get("name", ""))
        cat = s.get("cat")
        t0 = int(s.get("ts_us", 0))
        dur = int(s.get("dur_us", 0) or 0)
        attempt = args.get("attempt")
        attempt = int(attempt) if attempt is not None else None
        if cat == "serving":
            replica = pid_to_replica.get(s.get("pid"))
            if not dur:
                if name == "submitted":
                    continue  # engine submission: the fleet root wins
                state = _SERVING_TERMINALS.get(name)
                if state is not None:
                    led.state = led.state or state
                    if led.terminal_us is None:
                        led.terminal_us = t0
                    t = _num(args.get("ttft_ms"))
                    if t is not None:
                        led.measured_ttft_ms = t
                    t = _num(args.get("latency_ms"))
                    if t is not None:
                        led.measured_latency_ms = t
                continue
            if name == "queued":
                led.add(PhaseInterval(
                    QUEUE, t0, t0 + dur, cause=args.get("cause", "engine"),
                    replica=replica, attempt=attempt, src="serving"))
            elif name.startswith("prefill("):
                led.add(PhaseInterval(
                    PREFILL, t0, t0 + dur, cause=args.get("cause", "local"),
                    replica=replica, attempt=attempt, src="serving"))
            elif name == "decode":
                phase = VERIFY if args.get("phase") == VERIFY else DECODE
                if phase == VERIFY:
                    led.spec_proposed += int(args.get("proposed", 0) or 0)
                    led.spec_accepted += int(args.get("accepted", 0) or 0)
                led.add(PhaseInterval(
                    phase, t0, t0 + dur, cause=args.get("cause"),
                    replica=replica, attempt=attempt, src="serving",
                    args=args))
            elif name.startswith("req "):
                lifetimes.append(s)
        elif cat == "fleet":
            if not dur:
                if name == "submitted":
                    led.submitted_us = (t0 if led.submitted_us is None
                                        else min(led.submitted_us, t0))
                elif name in _FLEET_TERMINALS:
                    led.state = name  # the router's view is authoritative
                    led.terminal_us = t0
                    led.attempts = int(args.get("attempts",
                                                led.attempts) or 0)
                continue
            if name == "queued":
                phase = args.get("phase") or (
                    RETRY if (attempt or 1) >= 2 else QUEUE)
                led.add(PhaseInterval(
                    phase if phase in (QUEUE, RETRY) else QUEUE,
                    t0, t0 + dur,
                    cause=args.get("cause",
                                   "requeue" if phase == RETRY else "router"),
                    replica=args.get("replica"), attempt=attempt,
                    src="fleet"))
    # admission gap: engine queued-span end (admission) -> prefill start,
    # per attempt — the scheduler/page-reservation slice of TTFT
    for pf in [iv for iv in led.intervals if iv.phase == PREFILL]:
        q = [iv for iv in led.intervals
             if iv.phase == QUEUE and iv.src == "serving"
             and (iv.attempt or 0) == (pf.attempt or 0)
             and iv.t1_us <= pf.t0_us]
        if q:
            adm_t0 = max(iv.t1_us for iv in q)
            if pf.t0_us > adm_t0:
                led.add(PhaseInterval(
                    ADMISSION, adm_t0, pf.t0_us, cause="scheduler",
                    replica=pf.replica, attempt=pf.attempt, src="serving"))
    # tail: lifetime end past the last dispatch that touched the request
    # (a drain or deadline retiring it without a closing dispatch)
    for life in lifetimes:
        lo = int(life.get("ts_us", 0))
        hi = lo + int(life.get("dur_us", 0) or 0)
        last = max((iv.t1_us for iv in led.intervals
                    if iv.phase in (PREFILL, DECODE, VERIFY)
                    and lo <= iv.t0_us and iv.t1_us <= hi), default=lo)
        if hi > last:
            args = life.get("args") or {}
            led.add(PhaseInterval(
                TAIL, last, hi, cause=args.get("state", led.state),
                replica=pid_to_replica.get(life.get("pid")),
                attempt=args.get("attempt"), src="serving"))
    led.intervals.sort(key=lambda iv: (iv.t0_us, iv.t1_us))
    return led


def ledgers_from_spans(spans: Sequence[dict],
                       pid_to_replica: Optional[Dict[int, int]] = None
                       ) -> Dict[str, RequestLedger]:
    """One :class:`RequestLedger` per ``args.trace_id`` in ``spans``.

    Works on a single-engine serving stream (serve_bench traces) and on a
    merged fleet stream (``fleet.trace.load_fragments`` output — pass the
    manifest-derived ``pid_to_replica`` so engine-side intervals carry
    replica attribution). Migration (``ship``) windows are joined in from
    ``migrate *`` lifecycle spans via their ``trace_ids`` args."""
    p2r = dict(pid_to_replica or {})
    by_id: Dict[str, List[dict]] = {}
    ships: List[dict] = []
    for s in spans:
        args = s.get("args") or {}
        if (str(s.get("name", "")).startswith("migrate")
                and args.get("trace_ids") and s.get("dur_us")):
            ships.append(s)
        tid = args.get("trace_id")
        if tid:
            by_id.setdefault(tid, []).append(s)
    out = {tid: _build(tid, mine, p2r) for tid, mine in by_id.items()}
    for s in ships:
        args = s.get("args") or {}
        t0 = int(s.get("ts_us", 0))
        t1 = t0 + int(s.get("dur_us", 0) or 0)
        for tid in args.get("trace_ids") or []:
            led = out.get(tid)
            if led is not None:
                led.add(PhaseInterval(
                    SHIP, t0, t1, cause=args.get("cause", "migration"),
                    replica=args.get("dst"), src="fleet", args=args))
                led.intervals.sort(key=lambda iv: (iv.t0_us, iv.t1_us))
    return out
