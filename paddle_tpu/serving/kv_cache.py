"""KV-cache layouts for the decode driver: paged block-pool vs contiguous.

Two layouts behind ONE functional interface (`init_state` / `write_token` /
`write_prompt` / `context` / `decode_attention`), so the model's decode
loop is layout-blind and the two paths are bit-comparable:

* :class:`PagedKVCache` — the "Ragged Paged Attention" layout (PAPERS.md):
  KV rows live in a flat page pool ``[n_layer, num_pages*page_size, H, D]``
  and each slot owns an ordered page table ``[slots, pages_per_slot]``.
  Ragged sequence lengths cost only their pages; ``context`` gathers a
  slot's pages back into logical order (the XLA-gather path), and
  ``decode_attention`` dispatches between that gather and the fused
  ragged paged-attention Pallas kernel
  (ops/pallas_kernels/paged_attention.py) per
  ``FLAGS_paged_attention_kernel``.
* :class:`ContiguousKVCache` — the dense reference ``[n_layer, slots,
  max_ctx, H, D]`` every slot pays ``max_ctx`` for. The parity yardstick
  (tests/test_serving.py asserts bit-identical tokens/logits) and the
  padded-baseline cache.

Both write paths scatter with ``mode="drop"`` on out-of-bounds destination
rows, so inactive slots / padding positions are dropped INSIDE the compiled
step — no host-side branching, and unwritten rows stay zero in both
layouts, which is what makes the gathered contexts bit-identical.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax.numpy as jnp
import numpy as np

__all__ = ["PagedKVCache", "Int8PagedKVCache", "ContiguousKVCache"]

Cache = Dict[str, jnp.ndarray]


def _dtype_by_name(name: str) -> np.dtype:
    """Resolve a dtype by its ``.name`` — including the ml_dtypes extended
    set (bfloat16 etc.) that ``np.dtype(str)`` does not know."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


class _KVCacheBase:
    """Shared geometry: ``max_ctx`` context positions per slot, over
    ``n_layer`` layers of ``n_head`` heads of ``d_head`` lanes."""

    layout = "base"

    def __init__(self, n_layer: int, n_head: int, d_head: int, slots: int,
                 max_ctx: int, dtype=jnp.float32):
        self.n_layer = int(n_layer)
        self.n_head = int(n_head)
        self.d_head = int(d_head)
        self.slots = int(slots)
        self.max_ctx = int(max_ctx)
        self.dtype = jnp.dtype(dtype)

    def cache_bytes(self, state: Cache) -> int:
        return int(state["k"].nbytes + state["v"].nbytes)

    # -- page migration ------------------------------------------------------
    # Only paged layouts can ship pages; the dense layout refuses with a
    # typed error (there IS no page — a contiguous slot's KV is not an
    # addressable unit of state), which callers surface as "migration
    # unsupported" rather than a crash.
    def export_pages(self, state: Cache, pages):
        raise ValueError("layout %r has no pages to export" % self.layout)

    def import_pages(self, state: Cache, pages, meta: dict, blobs):
        raise ValueError("layout %r has no pages to import" % self.layout)


class PagedKVCache(_KVCacheBase):
    layout = "paged"

    def __init__(self, n_layer: int, n_head: int, d_head: int, slots: int,
                 max_ctx: int, page_size: int, num_pages: int,
                 dtype=jnp.float32):
        super().__init__(n_layer, n_head, d_head, slots, max_ctx, dtype)
        if max_ctx % page_size != 0:
            raise ValueError("max_ctx=%d must be a multiple of page_size=%d"
                             % (max_ctx, page_size))
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.pages_per_slot = self.max_ctx // self.page_size
        self.num_rows = self.num_pages * self.page_size  # flat KV rows

    def init_state(self) -> Cache:
        shp = (self.n_layer, self.num_rows, self.n_head, self.d_head)
        return {
            "k": jnp.zeros(shp, self.dtype),
            "v": jnp.zeros(shp, self.dtype),
            # page table: slot -> ordered page ids; rows beyond a slot's
            # reservation are whatever the allocator last left (reads are
            # masked by length, writes by the drop scatter)
            "pt": jnp.zeros((self.slots, self.pages_per_slot), jnp.int32),
        }

    # -- decode (one token per slot) -----------------------------------------
    def write_token(self, state: Cache, layer: int, k_new, v_new, pos,
                    active) -> Cache:
        """k_new/v_new [B,H,D] written at logical position ``pos[b]`` of
        slot b; inactive slots dropped via an OOB destination row."""
        ps = self.page_size
        pt = state["pt"]
        b_idx = jnp.arange(pt.shape[0])
        page = pt[b_idx, pos // ps]
        dest = page * ps + pos % ps
        dest = jnp.where(active, dest, self.num_rows)
        return {
            **state,
            "k": state["k"].at[layer, dest].set(k_new, mode="drop"),
            "v": state["v"].at[layer, dest].set(v_new, mode="drop"),
        }

    def context(self, state: Cache, layer: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Gather every slot's pages back into logical order:
        ``[slots, max_ctx, H, D]`` — the XLA-gather paged-attention path."""
        ps = self.page_size
        pt = state["pt"]
        rows = (pt * ps)[:, :, None] + jnp.arange(ps)[None, None, :]
        rows = rows.reshape(pt.shape[0], self.max_ctx)
        return state["k"][layer][rows], state["v"][layer][rows]

    def decode_attention(self, state: Cache, layer: int, q, ctx_len,
                         sm_scale: float = 1.0) -> jnp.ndarray:
        """One decode-attention step [B,H,D] over this layer's ragged
        contexts. With ``FLAGS_paged_attention_kernel`` armed (see
        ops.attention_ops.paged_kernel_mode) the Pallas kernel reads K/V
        pages straight from the pool via the device-resident page table —
        the ``[B, max_ctx, H, D]`` gather never materializes; otherwise the
        XLA gather + ops.attention_ops.decode_attention fallback runs.
        Both mask positions >= ctx_len with the SAME neg_inf constant, so
        the paths agree to float round-off (tier-1 parity tests pin it)."""
        from ..ops import attention_ops

        mode = attention_ops.paged_kernel_mode()
        if mode is not None:
            from ..ops.pallas_kernels import paged_attention as _pa

            if _pa.paged_attention_supported(self.dtype):
                return _pa.paged_decode_attention(
                    q, state["k"][layer], state["v"][layer], state["pt"],
                    ctx_len, page_size=self.page_size, sm_scale=sm_scale,
                    interpret=(mode == "interpret"))
        ctx_k, ctx_v = self.context(state, layer)
        return attention_ops.decode_attention(q, ctx_k, ctx_v, ctx_len,
                                              sm_scale=sm_scale)

    def decode_verify(self, state: Cache, layer: int, q, ctx_len,
                      sm_scale: float = 1.0) -> jnp.ndarray:
        """Speculative verify-window attention [B,W,H,D] over this layer's
        ragged contexts (window position j = logical position ctx_len-1+j;
        the caller wrote all W positions' K/V first). Rides the SAME ragged
        Pallas kernel as ``decode_attention`` by flattening the window into
        B*W pseudo-slots — each window row replays its slot's page table
        with length ctx_len+j, which is exactly the per-slot raggedness the
        kernel already handles; no kernel change, one dispatch. The XLA
        gather + ops.attention_ops.verify_attention path stays the parity
        reference (one ``context`` gather serves all W rows)."""
        from ..ops import attention_ops

        b, w = q.shape[0], q.shape[1]
        mode = attention_ops.paged_kernel_mode()
        if mode is not None:
            from ..ops.pallas_kernels import paged_attention as _pa

            if _pa.paged_attention_supported(self.dtype):
                lens = ctx_len[:, None] + jnp.arange(w)[None, :]
                lens = jnp.clip(lens.reshape(b * w), 0, self.max_ctx)
                out = _pa.paged_decode_attention(
                    q.reshape(b * w, self.n_head, self.d_head),
                    state["k"][layer], state["v"][layer],
                    jnp.repeat(state["pt"], w, axis=0), lens,
                    page_size=self.page_size, sm_scale=sm_scale,
                    interpret=(mode == "interpret"))
                return out.reshape(b, w, self.n_head, self.d_head)
        ctx_k, ctx_v = self.context(state, layer)
        return attention_ops.verify_attention(q, ctx_k, ctx_v, ctx_len,
                                              sm_scale=sm_scale)

    # -- prefill (one sequence) ----------------------------------------------
    def prompt_dest(self, pages) -> np.ndarray:
        """Host-side: the ``dest`` operand for ``write_prompt`` — a full
        page-table row (reserved pages first, rest parked on page 0;
        unused entries are never read or written)."""
        row = np.zeros(self.pages_per_slot, np.int32)
        row[:len(pages)] = np.asarray(pages, np.int32)
        return row

    def write_prompt(self, state: Cache, layer: int, k_new, v_new, dest,
                     length) -> Cache:
        """k_new/v_new [S,H,D] for ONE sequence; ``dest`` is its page-table
        row [pages_per_slot]; positions >= length are dropped."""
        ps = self.page_size
        s = k_new.shape[0]
        j = jnp.arange(s)
        flat = dest[j // ps] * ps + j % ps
        flat = jnp.where(j < length, flat, self.num_rows)
        return {
            **state,
            "k": state["k"].at[layer, flat].set(k_new, mode="drop"),
            "v": state["v"].at[layer, flat].set(v_new, mode="drop"),
        }

    # -- page migration ------------------------------------------------------
    def _page_rows(self, pages) -> np.ndarray:
        p = np.asarray(pages, np.int64)
        return (p[:, None] * self.page_size
                + np.arange(self.page_size)[None, :]).reshape(-1)

    def page_meta(self) -> dict:
        """Geometry a page payload must match to be importable here —
        embedded in every export, checked on every import."""
        return {"layout": self.layout, "n_layer": self.n_layer,
                "n_head": self.n_head, "d_head": self.d_head,
                "page_size": self.page_size,
                "kv_dtype": jnp.dtype(self._storage_dtype()).name}

    def _storage_dtype(self):
        return self.dtype

    def _check_meta(self, meta: dict, n_blobs: int, blobs) -> None:
        want = self.page_meta()
        got = {k: meta.get(k) for k in want}
        if got != want:
            raise ValueError("page payload geometry mismatch: %r != %r"
                             % (got, want))
        if len(blobs) != n_blobs:
            raise ValueError("page payload has %d blobs, expected %d"
                             % (len(blobs), n_blobs))

    def export_pages(self, state: Cache, pages):
        """Serialize ``pages`` (pool page ids) to ``(meta, blobs)``: raw
        C-order bytes of the K rows then the V rows, ``[n_layer,
        n_pages*page_size, H, D]`` each — bit-exact, no float formatting."""
        rows = self._page_rows(pages)
        k = np.ascontiguousarray(np.asarray(state["k"][:, rows]))
        v = np.ascontiguousarray(np.asarray(state["v"][:, rows]))
        meta = self.page_meta()
        meta["n_pages"] = len(pages)
        return meta, [k.tobytes(), v.tobytes()]

    def import_pages(self, state: Cache, pages, meta: dict, blobs) -> Cache:
        """Write an exported payload into ``pages`` of THIS pool; raises
        ``ValueError`` (typed, caller frees its reservation) on any
        geometry/dtype/size mismatch. Row bytes land verbatim, so an
        export of the same pages round-trips bit-identical."""
        self._check_meta(meta, 2, blobs)
        n = int(meta.get("n_pages", -1))
        if n != len(pages):
            raise ValueError("page payload has %d pages, caller reserved %d"
                             % (n, len(pages)))
        rows = self._page_rows(pages)
        dt = _dtype_by_name(meta["kv_dtype"])
        shp = (self.n_layer, len(rows), self.n_head, self.d_head)
        want = int(np.prod(shp)) * dt.itemsize
        if len(blobs[0]) != want or len(blobs[1]) != want:
            raise ValueError("page payload blob bytes %d/%d != %d"
                             % (len(blobs[0]), len(blobs[1]), want))
        k = np.frombuffer(blobs[0], dtype=dt).reshape(shp)
        v = np.frombuffer(blobs[1], dtype=dt).reshape(shp)
        return {
            **state,
            "k": state["k"].at[:, rows].set(jnp.asarray(k)),
            "v": state["v"].at[:, rows].set(jnp.asarray(v)),
        }


class Int8PagedKVCache(PagedKVCache):
    """Paged layout with int8 KV pages: each pool row stores symmetric
    int8 quantized K/V, dequantized through per-page fp32 scale arrays
    (``"ks"``/``"vs"``, ``[n_layer, num_pages]`` — the scale rides the page
    metadata, so a page is self-describing wherever its id travels).

    The scales are FIXED at construction from a calibrated amax
    (``monitor.numerics.kv_scale``) — a write never rescales a page, which
    is exactly why this layout is gated behind calibration: without a
    trustworthy amax the fixed grid would silently clip. ``self.dtype``
    stays the COMPUTE dtype (`context` returns it), so the model's decode
    loop and the attention ops stay layout-blind; only the pool storage and
    ``cache_bytes`` see int8 — half the page bytes of bf16, a quarter of
    fp32, which under the PagePool's unchanged reservation math doubles
    (resp. quadruples) the page capacity of the same byte budget
    (tools/serve_bench.py asserts the capacity and decode-parity claims).

    ``decode_attention`` always takes the gather path: the ragged Pallas
    kernel reads raw pool rows and has no dequant stage, so the kernel
    dispatch is bypassed rather than fed garbage — both decode paths
    (fused decode scan and prefill-side attention) dequantize through
    ``context``.
    """

    layout = "paged-int8"

    def __init__(self, n_layer: int, n_head: int, d_head: int, slots: int,
                 max_ctx: int, page_size: int, num_pages: int,
                 k_scale: float, v_scale: float, dtype=jnp.float32):
        super().__init__(n_layer, n_head, d_head, slots, max_ctx,
                         page_size, num_pages, dtype)
        if not (float(k_scale) > 0.0 and float(v_scale) > 0.0):
            raise ValueError(
                "Int8PagedKVCache needs calibrated positive scales, got "
                "k_scale=%r v_scale=%r — run a calibration pass "
                "(PADDLE_TPU_NUMERICS=2 / numerics.record_kv_calibration) "
                "first" % (k_scale, v_scale))
        self.k_scale = float(k_scale)
        self.v_scale = float(v_scale)

    def init_state(self) -> Cache:
        shp = (self.n_layer, self.num_rows, self.n_head, self.d_head)
        return {
            "k": jnp.zeros(shp, jnp.int8),
            "v": jnp.zeros(shp, jnp.int8),
            "pt": jnp.zeros((self.slots, self.pages_per_slot), jnp.int32),
            "ks": jnp.full((self.n_layer, self.num_pages), self.k_scale,
                           jnp.float32),
            "vs": jnp.full((self.n_layer, self.num_pages), self.v_scale,
                           jnp.float32),
        }

    def _quant(self, x, scale: float):
        return jnp.clip(jnp.round(x.astype(jnp.float32) / scale),
                        -127, 127).astype(jnp.int8)

    def write_token(self, state: Cache, layer: int, k_new, v_new, pos,
                    active) -> Cache:
        return super().write_token(state, layer,
                                   self._quant(k_new, self.k_scale),
                                   self._quant(v_new, self.v_scale),
                                   pos, active)

    def write_prompt(self, state: Cache, layer: int, k_new, v_new, dest,
                     length) -> Cache:
        return super().write_prompt(state, layer,
                                    self._quant(k_new, self.k_scale),
                                    self._quant(v_new, self.v_scale),
                                    dest, length)

    def context(self, state: Cache, layer: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
        ps = self.page_size
        pt = state["pt"]
        rows = (pt * ps)[:, :, None] + jnp.arange(ps)[None, None, :]
        rows = rows.reshape(pt.shape[0], self.max_ctx)
        pages = rows // ps  # page id per logical position [slots, max_ctx]
        ks = state["ks"][layer][pages][:, :, None, None].astype(self.dtype)
        vs = state["vs"][layer][pages][:, :, None, None].astype(self.dtype)
        return (state["k"][layer][rows].astype(self.dtype) * ks,
                state["v"][layer][rows].astype(self.dtype) * vs)

    def decode_attention(self, state: Cache, layer: int, q, ctx_len,
                         sm_scale: float = 1.0) -> jnp.ndarray:
        from ..ops import attention_ops

        ctx_k, ctx_v = self.context(state, layer)
        return attention_ops.decode_attention(q, ctx_k, ctx_v, ctx_len,
                                              sm_scale=sm_scale)

    def decode_verify(self, state: Cache, layer: int, q, ctx_len,
                      sm_scale: float = 1.0) -> jnp.ndarray:
        """Gather-only, like ``decode_attention``: the ragged kernel has no
        dequant stage, so int8 pools always dequantize through ``context``."""
        from ..ops import attention_ops

        ctx_k, ctx_v = self.context(state, layer)
        return attention_ops.verify_attention(q, ctx_k, ctx_v, ctx_len,
                                              sm_scale=sm_scale)

    def cache_bytes(self, state: Cache) -> int:
        return int(state["k"].nbytes + state["v"].nbytes
                   + state["ks"].nbytes + state["vs"].nbytes)

    # -- page migration ------------------------------------------------------
    def _storage_dtype(self):
        return jnp.int8

    def export_pages(self, state: Cache, pages):
        """int8 pages travel WITH their per-page fp32 scale columns
        (``ks``/``vs`` ``[n_layer]`` per page) — the payload is
        self-describing, so the importer dequantizes exactly as the
        exporter would even if its own constructor scales differ."""
        meta, blobs = super().export_pages(state, pages)
        p = np.asarray(pages, np.int64)
        ks = np.ascontiguousarray(np.asarray(state["ks"][:, p], np.float32))
        vs = np.ascontiguousarray(np.asarray(state["vs"][:, p], np.float32))
        return meta, blobs + [ks.tobytes(), vs.tobytes()]

    def import_pages(self, state: Cache, pages, meta: dict, blobs) -> Cache:
        self._check_meta(meta, 4, blobs)
        sshp = (self.n_layer, len(pages))
        want = int(np.prod(sshp)) * 4
        if len(blobs[2]) != want or len(blobs[3]) != want:
            raise ValueError("page payload scale bytes %d/%d != %d"
                             % (len(blobs[2]), len(blobs[3]), want))
        state = super().import_pages(state, pages, meta, blobs[:2])
        p = np.asarray(pages, np.int64)
        ks = np.frombuffer(blobs[2], dtype=np.float32).reshape(sshp)
        vs = np.frombuffer(blobs[3], dtype=np.float32).reshape(sshp)
        return {
            **state,
            "ks": state["ks"].at[:, p].set(jnp.asarray(ks)),
            "vs": state["vs"].at[:, p].set(jnp.asarray(vs)),
        }


class ContiguousKVCache(_KVCacheBase):
    layout = "contiguous"

    def init_state(self) -> Cache:
        shp = (self.n_layer, self.slots, self.max_ctx, self.n_head, self.d_head)
        return {"k": jnp.zeros(shp, self.dtype),
                "v": jnp.zeros(shp, self.dtype)}

    def write_token(self, state: Cache, layer: int, k_new, v_new, pos,
                    active) -> Cache:
        b_idx = jnp.arange(pos.shape[0])
        pos_c = jnp.where(active, pos, self.max_ctx)  # OOB -> dropped
        return {
            **state,
            "k": state["k"].at[layer, b_idx, pos_c].set(k_new, mode="drop"),
            "v": state["v"].at[layer, b_idx, pos_c].set(v_new, mode="drop"),
        }

    def context(self, state: Cache, layer: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
        return state["k"][layer], state["v"][layer]

    def decode_attention(self, state: Cache, layer: int, q, ctx_len,
                         sm_scale: float = 1.0) -> jnp.ndarray:
        """Dense layout has no gather to fuse away — always the XLA path
        (the parity yardstick the paged kernel is measured against)."""
        from ..ops import attention_ops

        ctx_k, ctx_v = self.context(state, layer)
        return attention_ops.decode_attention(q, ctx_k, ctx_v, ctx_len,
                                              sm_scale=sm_scale)

    def decode_verify(self, state: Cache, layer: int, q, ctx_len,
                      sm_scale: float = 1.0) -> jnp.ndarray:
        from ..ops import attention_ops

        ctx_k, ctx_v = self.context(state, layer)
        return attention_ops.verify_attention(q, ctx_k, ctx_v, ctx_len,
                                              sm_scale=sm_scale)

    def prompt_dest(self, slot: int) -> np.int32:
        return np.int32(slot)

    def write_prompt(self, state: Cache, layer: int, k_new, v_new, dest,
                     length) -> Cache:
        s = k_new.shape[0]
        j = jnp.arange(s)
        pos_c = jnp.where(j < length, j, self.max_ctx)
        return {
            **state,
            "k": state["k"].at[layer, dest, pos_c].set(k_new, mode="drop"),
            "v": state["v"].at[layer, dest, pos_c].set(v_new, mode="drop"),
        }
