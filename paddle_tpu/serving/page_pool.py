"""Fixed-size KV-cache page allocator (the vLLM/"Ragged Paged Attention"
block pool, host side).

HBM for the KV cache is carved into ``num_pages`` pages of ``page_size``
token positions each (every page spans all layers/heads — the device
arrays carry those axes). The pool hands out page INDICES; the device-side
arrays never move. Allocation is all-or-nothing: a request either gets its
full reservation or a :class:`PagePoolExhausted` (a
:class:`~.request.BackpressureError`) and the scheduler keeps it queued —
exhaustion degrades to queueing, never to a crash or a mid-decode OOM.

The engine reserves a request's WORST-CASE need (prompt + max_new_tokens)
at admission, so a running request can never hit exhaustion mid-decode —
the same preallocation posture as watermark-based vLLM scheduling, chosen
here over on-demand growth because it keeps the decode step free of
allocation control flow.
"""

from __future__ import annotations

from typing import List

from . import metrics as _sm
from .request import BackpressureError

__all__ = ["PagePool", "PagePoolExhausted"]


class PagePoolExhausted(BackpressureError):
    """Not enough free pages for the requested reservation."""


class PagePool:
    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 1 or page_size < 1:
            raise ValueError("num_pages and page_size must be >= 1")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        # LIFO free list: recently-freed (cache-warm) pages are reused first
        self._free: List[int] = list(range(self.num_pages - 1, -1, -1))
        self._free_set = set(self._free)
        self._update_gauges()

    # -- accounting -----------------------------------------------------------
    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_used(self) -> int:
        return self.num_pages - len(self._free)

    @property
    def utilization(self) -> float:
        return self.num_used / self.num_pages

    def pages_needed(self, total_tokens: int) -> int:
        """Pages covering ``total_tokens`` cache positions."""
        return -(-int(total_tokens) // self.page_size)

    def _update_gauges(self):
        _sm.PAGES_IN_USE.set(self.num_used)
        _sm.PAGE_POOL_UTILIZATION.set(self.utilization)

    # -- alloc/free -----------------------------------------------------------
    def alloc(self, n: int) -> List[int]:
        """Reserve ``n`` pages atomically; raises :class:`PagePoolExhausted`
        (leaving the pool untouched) when fewer than ``n`` are free."""
        n = int(n)
        if n < 0:
            raise ValueError("cannot allocate %d pages" % n)
        from ..reliability import faults as _faults

        spec = _faults.fire("page_pool.alloc")
        if spec is not None and spec.kind == "exhausted":
            # chaos drill: behave exactly like a real exhaustion — the
            # caller's backpressure path must absorb it
            raise PagePoolExhausted(
                "page pool exhausted (injected): need %d pages of %d — "
                "request stays queued until pages retire"
                % (n, self.num_pages))
        if n > len(self._free):
            raise PagePoolExhausted(
                "page pool exhausted: need %d pages, %d free of %d "
                "(page_size=%d) — request stays queued until pages retire"
                % (n, len(self._free), self.num_pages, self.page_size))
        pages = [self._free.pop() for _ in range(n)]
        self._free_set.difference_update(pages)
        self._update_gauges()
        return pages

    def free(self, pages: List[int]) -> None:
        for p in pages:
            p = int(p)
            if not 0 <= p < self.num_pages:
                raise ValueError("freeing page %d outside pool of %d"
                                 % (p, self.num_pages))
            if p in self._free_set:
                raise ValueError("double free of page %d" % p)
            self._free.append(p)
            self._free_set.add(p)
        self._update_gauges()
