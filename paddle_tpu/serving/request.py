"""Request objects and the serving backpressure error hierarchy.

A :class:`Request` is the unit the multiplexer schedules: it carries the
prompt, the generation budget, the lifecycle timestamps the latency
histograms are computed from, and — while running — its slot and reserved
KV pages. The reference's analog is one AsyncExecutor DataFeed work item
(SURVEY L4); here the item is an autoregressive generation, not a
training minibatch.
"""

from __future__ import annotations

import itertools
import time
from typing import List, Optional, Sequence

__all__ = ["Request", "BackpressureError", "DrainingError",
           "QUEUED", "RUNNING", "FINISHED", "REJECTED",
           "TIMEOUT", "FAILED"]

QUEUED = "queued"
RUNNING = "running"
FINISHED = "finished"
REJECTED = "rejected"
TIMEOUT = "timeout"    # deadline expired before completion (typed retirement)
FAILED = "failed"      # in-flight batch lost to a decode failure

_ids = itertools.count()


class BackpressureError(RuntimeError):
    """The serving stack cannot take more work RIGHT NOW (bounded queue
    full, or — via the :class:`~.page_pool.PagePoolExhausted` subclass — no
    KV pages left). Deliberately a distinct type: callers shed or retry;
    it never signals a crash."""


class DrainingError(BackpressureError):
    """The engine is draining (graceful shutdown: SIGTERM, rollout) — it
    stopped admitting and will finish in-flight work then close. Unlike
    queue backpressure, retrying THIS engine is pointless; the caller
    re-routes to a peer."""


class Request:
    """One generation request.

    ``prompt`` is a sequence of int token ids; ``max_new_tokens`` bounds
    generation (the prefill's first sampled token counts toward it).
    ``deadline_s`` (optional) is a wall-clock budget from submission: a
    request past its deadline is retired with state :data:`TIMEOUT` so it
    stops pinning a slot and KV pages. ``error`` carries the failure text
    when a decode failure retires the request as :data:`FAILED`.

    Sampling (device-side, inside the fused decode scan):
    ``temperature=0`` (the default) is EXACTLY the greedy argmax path —
    bit-identical tokens, not merely close; ``temperature>0`` samples from
    the temperature-scaled distribution, restricted to the ``top_k``
    highest logits when ``top_k>0`` (0 = no restriction). ``seed`` names
    the request's private RNG stream (derived from the request id when
    None, so two requests never share one by accident); the stream is
    keyed by absolute context position, which makes replays reproducible
    across ``decode_fuse`` widths and slot re-admissions.

    ``speculation`` overrides the engine's speculative-decoding default
    for this request: ``None`` inherit, ``0`` off, a positive int the
    draft k, ``"auto"`` the tune-table k (serving.speculative). A pure
    scheduling knob — the emitted stream is bit-identical either way, so
    replays (fleet requeues) need not pin it.
    """

    __slots__ = ("id", "prompt", "max_new_tokens", "state", "slot", "pages",
                 "tokens_out", "submitted_t", "admitted_t", "first_token_t",
                 "finished_t", "deadline_s", "error", "trace_id", "attempt",
                 "temperature", "top_k", "seed", "speculation")

    def __init__(self, prompt: Sequence[int], max_new_tokens: int,
                 deadline_s: Optional[float] = None,
                 temperature: float = 0.0, top_k: int = 0,
                 seed: Optional[int] = None,
                 trace_id: Optional[str] = None, attempt: int = 0,
                 speculation=None):
        if len(prompt) == 0:
            raise ValueError("Request needs a non-empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if deadline_s is not None and deadline_s < 0:
            raise ValueError("deadline_s must be >= 0")
        if temperature < 0:
            raise ValueError("temperature must be >= 0 (0 = greedy)")
        if top_k < 0:
            raise ValueError("top_k must be >= 0 (0 = unrestricted)")
        self.id = next(_ids)
        # The per-request trace identity: spans in the serving timeline and
        # flight-recorder batch specs carry it, so a crash dump links back
        # to the exact request lifelines in the Perfetto trace. A fleet
        # router overrides it with the FLEET trace id (stable across
        # requeues) so one cross-process timeline joins every attempt;
        # ``attempt`` (1-based, 0 = not a fleet replay) rides span args.
        self.trace_id = trace_id if trace_id else "req-%d" % self.id
        self.attempt = int(attempt)
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.state = QUEUED
        self.slot: Optional[int] = None
        self.pages: List[int] = []
        self.tokens_out: List[int] = []
        self.submitted_t = time.perf_counter()
        self.admitted_t: Optional[float] = None
        self.first_token_t: Optional[float] = None
        self.finished_t: Optional[float] = None
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        self.error: Optional[str] = None
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        # id-derived default: distinct per request, stable for replay when
        # the caller pins one explicitly
        self.seed = int(self.id if seed is None else seed) & 0x7FFFFFFF
        from .speculative import parse_speculation

        self.speculation = parse_speculation(speculation)

    @property
    def prompt_len(self) -> int:
        return len(self.prompt)

    @property
    def latency_s(self) -> Optional[float]:
        if self.finished_t is None:
            return None
        return self.finished_t - self.submitted_t

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.submitted_t

    def expired(self, now: Optional[float] = None) -> bool:
        """True once the wall clock passed this request's deadline (always
        False without one)."""
        if self.deadline_s is None:
            return False
        if now is None:
            now = time.perf_counter()
        return now - self.submitted_t >= self.deadline_s

    def __repr__(self):
        return ("Request(id=%d, state=%s, prompt_len=%d, out=%d/%d, slot=%s)"
                % (self.id, self.state, len(self.prompt),
                   len(self.tokens_out), self.max_new_tokens, self.slot))
