"""Request objects and the serving backpressure error hierarchy.

A :class:`Request` is the unit the multiplexer schedules: it carries the
prompt, the generation budget, the lifecycle timestamps the latency
histograms are computed from, and — while running — its slot and reserved
KV pages. The reference's analog is one AsyncExecutor DataFeed work item
(SURVEY L4); here the item is an autoregressive generation, not a
training minibatch.
"""

from __future__ import annotations

import itertools
import time
from typing import List, Optional, Sequence

__all__ = ["Request", "BackpressureError",
           "QUEUED", "RUNNING", "FINISHED", "REJECTED"]

QUEUED = "queued"
RUNNING = "running"
FINISHED = "finished"
REJECTED = "rejected"

_ids = itertools.count()


class BackpressureError(RuntimeError):
    """The serving stack cannot take more work RIGHT NOW (bounded queue
    full, or — via the :class:`~.page_pool.PagePoolExhausted` subclass — no
    KV pages left). Deliberately a distinct type: callers shed or retry;
    it never signals a crash."""


class Request:
    """One generation request.

    ``prompt`` is a sequence of int token ids; ``max_new_tokens`` bounds
    generation (the prefill's first sampled token counts toward it).
    """

    __slots__ = ("id", "prompt", "max_new_tokens", "state", "slot", "pages",
                 "tokens_out", "submitted_t", "admitted_t", "first_token_t",
                 "finished_t")

    def __init__(self, prompt: Sequence[int], max_new_tokens: int):
        if len(prompt) == 0:
            raise ValueError("Request needs a non-empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.id = next(_ids)
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.state = QUEUED
        self.slot: Optional[int] = None
        self.pages: List[int] = []
        self.tokens_out: List[int] = []
        self.submitted_t = time.perf_counter()
        self.admitted_t: Optional[float] = None
        self.first_token_t: Optional[float] = None
        self.finished_t: Optional[float] = None

    @property
    def prompt_len(self) -> int:
        return len(self.prompt)

    @property
    def latency_s(self) -> Optional[float]:
        if self.finished_t is None:
            return None
        return self.finished_t - self.submitted_t

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.submitted_t

    def __repr__(self):
        return ("Request(id=%d, state=%s, prompt_len=%d, out=%d/%d, slot=%s)"
                % (self.id, self.state, len(self.prompt),
                   len(self.tokens_out), self.max_new_tokens, self.slot))
