"""Speculative decoding: zero-weight drafters + the accept/reject math.

The draft-verify fast path through the continuous batcher (ROADMAP item 1's
last serving-speed piece): a host-side :class:`Drafter` proposes up to ``k``
continuation tokens per scheduler tick, the target model verifies the whole
window in ONE fused dispatch (``ServingEngine._get_verify_exe`` →
``models.decoder_lm.verify_forward`` → ``kv_cache.*.decode_verify``), and
acceptance rolls ``ctx_len`` forward only over the verified prefix. "Ragged
Paged Attention" (PAPERS.md) motivates the verify window riding the PR-12
paged kernel: per-slot ragged lengths already make a k-token window just
``k`` more pseudo-slots of the same page layout.

Correctness contract (the engine's hard invariant):

* The verify executable samples the TARGET's own token at every window
  position with the (seed, absolute-position)-keyed RNG
  (``engine._sample_tokens``) and accepts draft token ``d_j`` iff it equals
  that target draw ``t_j``.  For a DETERMINISTIC drafter (q is a point mass
  at ``d_j``) this IS exact speculative sampling: the accept probability is
  ``P(t_j = d_j) = p(d_j) = min(1, p(d_j)/q(d_j))``, and on rejection the
  emitted token ``t_j | t_j != d_j`` is distributed as the normalized
  residual ``max(0, p - q)`` — the Leviathan et al. accept/reject rule,
  specialized to q = delta.  Because every draw is a pure function of
  (seed, position), the emitted stream is BIT-identical to plain decode —
  greedy (temperature=0) by the argmax path, sampled by RNG-keying — which
  is strictly stronger than the distributional guarantee the rule promises.
* :func:`residual_sample` is the GENERAL accept/reject kernel (host-side
  reference) a future model-based drafter with a non-degenerate proposal
  distribution plugs into; tests/test_speculative.py asserts its output
  distribution matches the target statistically.

The shipped drafter is :class:`NGramDrafter` — prompt-lookup decoding: match
the trailing n-gram of (prompt + generated) against its own history and
propose the continuation that followed last time.  Zero weights, zero
device work, and it wins exactly on the repetitive traffic the PR-14
prefix-cached fleet implies (and on the loops tiny greedy models collapse
into).  Draft-k is one more measured tunable (TVM, PAPERS.md): resolve it
through the tune table with ``speculation="auto"``
(``tune.resolve_speculation_k``, sweep via ``tools/autotune.py --kernel
speculation_k``).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

__all__ = ["Drafter", "NGramDrafter", "make_drafter", "residual_sample",
           "SPEC_K_CAP", "parse_speculation", "verify_window_args"]

# Bound on per-request draft k: the verify executable's window width is
# k+1, and each distinct width compiles once — the cap keeps a hostile
# per-request knob from compiling unbounded executables.
SPEC_K_CAP = 8


class Drafter:
    """Proposes up to ``k`` continuation tokens for one request.

    ``propose`` sees the request's full token history (prompt + generated,
    host-side ints) and returns 0..k proposed next tokens.  A drafter is
    DETERMINISTIC by contract (``kind`` names it in provenance): the
    engine's equality-accept verify implements exact speculative sampling
    only for point-mass proposals — a future stochastic/model drafter must
    also return its per-token proposal probabilities and route through
    :func:`residual_sample` instead.
    """

    kind = "base"

    def propose(self, history: Sequence[int], k: int) -> List[int]:
        raise NotImplementedError


class NGramDrafter(Drafter):
    """Prompt-lookup decoding: the zero-weight n-gram drafter.

    Finds the most recent PRIOR occurrence of the trailing ``n``-gram of
    ``history`` (longest ``n`` first, ``max_n`` down to ``min_n``) and
    proposes the tokens that followed it, capped at ``k``.  No match →
    empty draft → the slot degrades to a plain one-token step inside the
    same verify dispatch.
    """

    kind = "ngram"

    def __init__(self, max_n: int = 3, min_n: int = 1):
        if not (1 <= min_n <= max_n):
            raise ValueError("need 1 <= min_n <= max_n, got min_n=%d "
                             "max_n=%d" % (min_n, max_n))
        self.max_n = int(max_n)
        self.min_n = int(min_n)

    def propose(self, history: Sequence[int], k: int) -> List[int]:
        h = list(history)
        n_hist = len(h)
        if k <= 0 or n_hist < self.min_n + 1:
            return []
        for n in range(min(self.max_n, n_hist - 1), self.min_n - 1, -1):
            suffix = h[-n:]
            # rightmost prior occurrence: recent context predicts best
            for start in range(n_hist - n - 1, -1, -1):
                if h[start:start + n] == suffix:
                    cont = h[start + n:start + n + k]
                    if cont:
                        return [int(t) for t in cont]
        return []


def make_drafter(kind: str, **kw) -> Drafter:
    """Drafter factory keyed by ``ServingConfig.spec_drafter`` — "ngram"
    today; a small-model drafter registers here when it lands."""
    if kind == "ngram":
        return NGramDrafter(**kw)
    raise ValueError("unknown drafter kind %r (have: 'ngram')" % (kind,))


def parse_speculation(value) -> Optional[object]:
    """Normalize a speculation knob (config, env var, or wire field) to
    ``0`` (off), a positive int draft-k (capped at :data:`SPEC_K_CAP`), or
    the string ``"auto"`` (resolve through the tune table).  ``None`` stays
    ``None`` (= inherit the engine default)."""
    if value is None:
        return None
    if isinstance(value, str):
        v = value.strip().lower()
        if v in ("", "0", "off", "none", "false", "no"):
            return 0
        if v == "auto":
            return "auto"
        value = int(v)
    k = int(value)
    if k < 0:
        raise ValueError("speculation must be >= 0, 'auto' or None, got %r"
                         % (value,))
    return min(k, SPEC_K_CAP)


def verify_window_args(window: int, proposed: int, accepted: int) -> dict:
    """Span-arg payload tagging a verify dispatch for the phase ledger
    (serving/phases.py): the window width (k+1 model positions), how many
    draft tokens rode it and how many the target accepted.  Keeping the
    attribution vocabulary here — next to the accept/reject math it
    describes — means the engine, the trace reader and the autopsy plane
    agree on one schema."""
    return {"verify": True, "window": int(window),
            "proposed": int(proposed), "accepted": int(accepted)}


def residual_sample(p: np.ndarray, q: np.ndarray, draft_token: int,
                    u_accept: float, u_residual: float) -> tuple:
    """One general accept/reject speculative-sampling step (host reference).

    ``p`` is the target distribution, ``q`` the drafter's proposal
    distribution over the same vocab, ``draft_token`` the drafter's draw,
    ``u_accept``/``u_residual`` uniform [0,1) variates.  Accept with
    probability ``min(1, p[d]/q[d])``; on rejection draw from the
    normalized residual ``max(0, p - q)``.  Returns ``(token, accepted)``.
    Marginally the emitted token is distributed EXACTLY as ``p`` — the
    Leviathan et al. guarantee tests/test_speculative.py checks
    statistically.  The engine's compiled verify path never calls this: its
    drafters are deterministic, where equality-accept against the
    position-keyed target draw is this same rule with q = delta.
    """
    p = np.asarray(p, np.float64)
    q = np.asarray(q, np.float64)
    d = int(draft_token)
    qd = q[d]
    accept = qd > 0.0 and u_accept < min(1.0, p[d] / qd)
    if accept:
        return d, True
    resid = np.maximum(p - q, 0.0)
    z = resid.sum()
    if z <= 0.0:
        # p <= q everywhere except where they agree: p == q, accept was
        # certain — numerically degenerate; fall back to the target draw
        resid, z = p, p.sum()
    resid = resid / z
    token = int(np.searchsorted(np.cumsum(resid), u_residual, side="right"))
    return min(token, len(p) - 1), False
