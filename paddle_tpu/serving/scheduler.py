"""Continuous (in-flight) batching scheduler: requests ↔ fixed batch slots.

The request multiplexer of the serving stack (the AsyncExecutor/DataFeed
ingestion role from the reference, SURVEY L4, re-shaped for autoregressive
decode): a bounded FIFO queue feeds ``n_slots`` fixed batch-bucket slots.
Each decode step the engine retires finished slots and admits queued
requests into the holes, so new requests join the running batch mid-flight
instead of waiting for it to drain. ``continuous=False`` degrades to the
classic static-batch policy — admit only when EVERY slot is free, drain the
whole wave — which is exactly the padded baseline ``bench.py --serve``
compares against.

Pure host-side bookkeeping (no device state) so its invariants are testable
under churn without compiling anything; the engine owns pages and device
arrays.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, List, Optional

from . import metrics as _sm
from .request import (FAILED, FINISHED, QUEUED, REJECTED, RUNNING, TIMEOUT,
                      BackpressureError, Request)

__all__ = ["Scheduler"]


class Scheduler:
    def __init__(self, n_slots: int, max_queue: int = 1024,
                 continuous: bool = True):
        if n_slots < 1:
            raise ValueError("need at least one slot")
        self.n_slots = int(n_slots)
        self.max_queue = int(max_queue)
        self.continuous = bool(continuous)
        self._queue: Deque[Request] = deque()
        self._slots: List[Optional[Request]] = [None] * self.n_slots

    # -- introspection --------------------------------------------------------
    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def occupancy(self) -> int:
        return sum(1 for r in self._slots if r is not None)

    def running(self) -> List[Request]:
        return [r for r in self._slots if r is not None]

    def slot_request(self, slot: int) -> Optional[Request]:
        return self._slots[slot]

    def idle(self) -> bool:
        return not self._queue and self.occupancy == 0

    # -- queue side -----------------------------------------------------------
    def submit(self, req: Request) -> Request:
        """Enqueue; raises :class:`BackpressureError` when the bounded queue
        is full (the caller sheds load — nothing was accepted)."""
        if len(self._queue) >= self.max_queue:
            _sm.REQUESTS_REJECTED.inc()
            raise BackpressureError(
                "serving queue full (%d requests); retry later"
                % self.max_queue)
        if req.state != QUEUED:
            raise ValueError("cannot submit request in state %r" % req.state)
        self._queue.append(req)
        _sm.REQUESTS_SUBMITTED.inc()
        _sm.QUEUE_DEPTH.set(len(self._queue))
        return req

    def peek(self) -> Optional[Request]:
        return self._queue[0] if self._queue else None

    def peek_n(self, n: int) -> List[Request]:
        """The first ``n`` queued requests (fewer if the queue is shorter) —
        the static wave policy sizes its padding bucket from these."""
        return [self._queue[i] for i in range(min(n, len(self._queue)))]

    # -- slot side ------------------------------------------------------------
    def admissible_slots(self) -> List[int]:
        """Slots the policy allows filling now: any free slot when
        continuous, and only a fully-drained batch otherwise."""
        free = [i for i, r in enumerate(self._slots) if r is None]
        if not self.continuous and len(free) != self.n_slots:
            return []
        return free

    def admit(self, slot: int) -> Request:
        """Move the queue head into ``slot`` (caller has already secured
        pages). FIFO by construction — admission order is submission order."""
        if self._slots[slot] is not None:
            raise ValueError("slot %d already occupied by %r"
                             % (slot, self._slots[slot]))
        if not self._queue:
            raise ValueError("admit() with an empty queue")
        req = self._queue.popleft()
        req.state = RUNNING
        req.slot = slot
        self._slots[slot] = req
        _sm.REQUESTS_ADMITTED.inc()
        _sm.QUEUE_DEPTH.set(len(self._queue))
        _sm.SLOT_OCCUPANCY.set(self.occupancy)
        return req

    def requeue_head_blocked(self) -> None:
        """Admission blocked on resources (pages): the head STAYS at the
        head — FIFO order survives backpressure, later smaller requests do
        not starve an early big one ... they wait behind it."""
        _sm.ADMISSION_BLOCKED.inc()

    def retire(self, slot: int, state: str = FINISHED) -> Request:
        """Vacate ``slot``; ``state`` is the request's terminal state —
        FINISHED (default), TIMEOUT (deadline) or FAILED (batch lost to a
        decode failure). Every path counts as a retirement (the slot was
        reclaimed); the engine keeps the per-cause counters."""
        req = self._slots[slot]
        if req is None:
            raise ValueError("retire() on empty slot %d" % slot)
        if state not in (FINISHED, TIMEOUT, FAILED):
            raise ValueError("invalid terminal state %r" % state)
        self._slots[slot] = None
        req.state = state
        req.slot = None
        _sm.REQUESTS_RETIRED.inc()
        _sm.SLOT_OCCUPANCY.set(self.occupancy)
        return req

    def drain_queue(self) -> List[Request]:
        """Graceful-drain shutdown of the QUEUE side: every queued request
        leaves with terminal state REJECTED (it never held a slot or
        pages; the caller re-routes it to a peer engine). Running slots
        are the engine's to finish — that is the point of draining."""
        out = list(self._queue)
        self._queue.clear()
        for r in out:
            r.state = REJECTED
        if out:
            _sm.DRAIN_REJECTED.inc(len(out))
            _sm.QUEUE_DEPTH.set(0)
        return out

    def drop_expired(self, now: float) -> List[Request]:
        """Remove queued requests whose deadline passed (they never got a
        slot); returns them, terminal state set to TIMEOUT. Running
        requests' deadlines are the engine's to enforce — it owns their
        pages and device state."""
        expired = [r for r in self._queue if r.expired(now)]
        if expired:
            keep = [r for r in self._queue if not r.expired(now)]
            self._queue.clear()
            self._queue.extend(keep)
            for r in expired:
                r.state = TIMEOUT
            _sm.QUEUE_DEPTH.set(len(self._queue))
        return expired
