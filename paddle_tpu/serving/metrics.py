"""serving/* instruments: the monitor-registry face of the serving stack.

One module owns every ``serving/*`` name so the scheduler, page pool and
decode driver never race a get-or-create, and tools (``tools/serve_bench``,
``tools/dump_metrics --selftest``) can assert the full set exists by
importing this module alone. Same hot-path contract as the executor
instruments: module-level handles, a single disabled-branch per call.
"""

from __future__ import annotations

from ..monitor import metrics as _mx

__all__ = [
    "REQUESTS_SUBMITTED", "REQUESTS_ADMITTED", "REQUESTS_RETIRED",
    "REQUESTS_REJECTED", "QUEUE_DEPTH", "SLOT_OCCUPANCY",
    "PAGES_IN_USE", "PAGE_POOL_UTILIZATION", "ADMISSION_BLOCKED",
    "PREFILL_COUNT", "DECODE_STEPS", "DECODE_DISPATCHES",
    "TOKENS_GENERATED", "TOKENS_PER_SEC",
    "REQUEST_LATENCY_MS", "TTFT_MS", "DECODE_STEP_MS", "PREFILL_MS",
    "FAULTS", "RETRIES", "TIMEOUTS", "REQUESTS_FAILED",
    "DRAINS", "DRAINED_REQUESTS", "DRAIN_REJECTED",
    "SPEC_PROPOSED", "SPEC_ACCEPTED", "SPEC_REJECTED", "SPEC_DRAFTS",
    "SPEC_VERIFY_DISPATCHES", "SPEC_ACCEPT_RATE",
]

REQUESTS_SUBMITTED = _mx.counter(
    "serving/requests_submitted", help="requests accepted into the queue")
REQUESTS_ADMITTED = _mx.counter(
    "serving/requests_admitted", help="requests admitted into a batch slot")
REQUESTS_RETIRED = _mx.counter(
    "serving/requests_retired", help="requests finished and retired")
REQUESTS_REJECTED = _mx.counter(
    "serving/requests_rejected",
    help="submissions rejected with BackpressureError (queue full)")
QUEUE_DEPTH = _mx.gauge(
    "serving/queue_depth", help="requests waiting for a slot")
SLOT_OCCUPANCY = _mx.gauge(
    "serving/slot_occupancy", help="batch slots currently running a request")
PAGES_IN_USE = _mx.gauge(
    "serving/page_pool_pages_in_use", help="KV-cache pages currently allocated")
PAGE_POOL_UTILIZATION = _mx.gauge(
    "serving/page_pool_utilization", help="pages_in_use / num_pages, 0..1")
ADMISSION_BLOCKED = _mx.counter(
    "serving/admission_blocked_on_pages",
    help="admission attempts deferred because the page pool could not "
         "cover the request's worst-case page need (backpressure, not crash)")
PREFILL_COUNT = _mx.counter(
    "serving/prefills", help="compiled prefill invocations")
DECODE_STEPS = _mx.counter(
    "serving/decode_steps", help="decode steps executed (all slots at once)")
DECODE_DISPATCHES = _mx.counter(
    "serving/decode_dispatches",
    help="decode dispatches issued (each fuses >=1 decode steps)")
TOKENS_GENERATED = _mx.counter(
    "serving/tokens_generated", help="tokens emitted to finished+running requests")
TOKENS_PER_SEC = _mx.gauge(
    "serving/tokens_per_sec",
    help="sustained generation rate over the last engine.run() drive")
REQUEST_LATENCY_MS = _mx.histogram(
    "serving/request_latency_ms",
    help="submit -> finish wall time per retired request")
TTFT_MS = _mx.histogram(
    "serving/ttft_ms", help="submit -> first token wall time per request")
DECODE_STEP_MS = _mx.histogram(
    "serving/decode_step_ms",
    help="host wall time of one decode dispatch / fused steps")
PREFILL_MS = _mx.histogram(
    "serving/prefill_ms", help="host wall time of one compiled prefill call")
FAULTS = _mx.counter(
    "serving/faults",
    help="decode dispatch failures absorbed by the recovery path (the "
         "in-flight batch was failed, the engine kept serving)")
RETRIES = _mx.counter(
    "serving/retries",
    help="decode dispatches retried after a transient-classified failure")
TIMEOUTS = _mx.counter(
    "serving/timeouts",
    help="requests retired with TIMEOUT status at their deadline (queued "
         "or running; slots and pages reclaimed)")
REQUESTS_FAILED = _mx.counter(
    "serving/requests_failed",
    help="requests retired as FAILED when their in-flight batch was lost "
         "to a decode failure")
DRAINS = _mx.counter(
    "serving/drains",
    help="graceful drains performed (stop admitting, finish in-flight, "
         "close) — SIGTERM/rollout shutdowns, not crashes")
DRAINED_REQUESTS = _mx.counter(
    "serving/drained_requests",
    help="in-flight requests that FINISHED during a graceful drain")
DRAIN_REJECTED = _mx.counter(
    "serving/drain_rejected",
    help="requests rejected because the engine was draining (typed "
         "DrainingError at submit, plus queued requests shed at drain "
         "start)")
SPEC_PROPOSED = _mx.counter(
    "serving/spec_proposed_tokens",
    help="draft tokens proposed to speculative verify dispatches")
SPEC_ACCEPTED = _mx.counter(
    "serving/spec_accepted_tokens",
    help="draft tokens accepted by the target model (each one is a decode "
         "step the engine did not have to dispatch)")
SPEC_REJECTED = _mx.counter(
    "serving/spec_rejected_tokens",
    help="draft tokens rejected (or cut by eos/budget) and rolled back — "
         "their KV rows sit beyond ctx_len until overwritten")
SPEC_DRAFTS = _mx.counter(
    "serving/spec_drafts",
    help="non-empty per-slot drafts submitted to verify dispatches")
SPEC_VERIFY_DISPATCHES = _mx.counter(
    "serving/spec_verify_dispatches",
    help="decode dispatches that took the speculative verify-window path")
SPEC_ACCEPT_RATE = _mx.histogram(
    "serving/spec_accept_rate",
    help="per-dispatch accepted/proposed draft-token ratio, 0..1")
