"""Autoregressive decode driver: continuous batching over a paged KV-cache.

The device-resident serving loop the ROADMAP's item-1 gap called for. One
:class:`ServingEngine` owns:

* a :class:`~.scheduler.Scheduler` (bounded queue → fixed batch slots,
  continuous in-flight admission or the static wave-drain baseline),
* a :class:`~.page_pool.PagePool` + :class:`~.kv_cache.PagedKVCache` (or
  the :class:`~.kv_cache.ContiguousKVCache` reference layout),
* AOT-compiled step functions built through ``executor.aot_compile`` —
  ONE prefill executable per prompt bucket (power-of-two padded, so a
  ragged prompt stream compiles O(log max_seq) programs, the same
  bounded-specialization idea as the Predictor's batch buckets) and ONE
  decode executable per fuse length whose state (KV pages, page tables,
  slot occupancy, lengths) never leaves the device between steps — the
  serving twin of ``Executor.run_steps``'s stack-and-scan fusion, with
  retirement/admission decisions surfacing only at chunk boundaries.

Observability rides PR 1/5's monitor: ``serving/*`` counters + latency
histograms (serving.metrics), and the crash flight recorder captures the
in-flight batch spec on any decode failure (``PADDLE_TPU_FLIGHT_DIR``).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..executor import _safe_flight_dump, aot_compile
from ..monitor import device as _dev, slo as _slo, telemetry as _telemetry
from ..reliability import faults as _faults
from . import metrics as _sm
from . import speculative as _speculative
from . import trace as _trace
from .kv_cache import ContiguousKVCache, Int8PagedKVCache, PagedKVCache
from .page_pool import PagePool, PagePoolExhausted
from .request import (FAILED, FINISHED, REJECTED, TIMEOUT, DrainingError,
                      Request)
from .scheduler import Scheduler

__all__ = ["ServingConfig", "ServingEngine"]


def _pow2_buckets(lo: int, hi: int) -> tuple:
    out, b = [], lo
    while b < hi:
        out.append(b)
        b *= 2
    out.append(hi)
    return tuple(sorted(set(out)))


def _sample_tokens(logits, temp, top_k, seed, position):
    """Device-side per-slot token selection, shared by the prefill
    executable and the fused decode scan.

    ``logits`` [B,V]; ``temp``/``top_k``/``seed``/``position`` [B].
    ``temp[b] == 0`` returns EXACTLY ``argmax(logits[b])`` — the greedy
    path's own computation, selected by ``where``, so greedy requests are
    bit-identical whether or not sampling requests share the batch.
    ``temp[b] > 0`` draws via the Gumbel-argmax trick over the
    temperature-scaled logits, restricted to the ``top_k[b]`` largest when
    positive (threshold at the k-th sorted logit; ties below it are kept,
    matching the usual top-k convention of "never a logit SMALLER than the
    k-th"). The draw is keyed ``fold_in(PRNGKey(seed[b]), position[b])`` —
    a pure function of the request's own seed and the absolute context
    position of the token being consumed, so the stream is reproducible
    across ``decode_fuse`` widths and a slot re-admitted to a new request
    (new seed) can never replay the previous tenant's draws."""
    from ..ops.attention_ops import neg_inf

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    v = logits.shape[-1]
    scaled = logits.astype(jnp.float32) / jnp.maximum(
        temp.astype(jnp.float32), 1e-6)[:, None]
    k = jnp.clip(jnp.where(top_k > 0, top_k, v), 1, v)
    srt = jax.lax.sort(scaled, dimension=-1)[:, ::-1]  # descending
    kth = jnp.take_along_axis(srt, (k - 1)[:, None], axis=-1)
    masked = jnp.where(scaled >= kth, scaled, neg_inf(jnp.float32))

    def draw(seed_b, pos_b):
        key = jax.random.fold_in(jax.random.PRNGKey(seed_b), pos_b)
        return jax.random.gumbel(key, (v,), jnp.float32)

    sampled = jnp.argmax(masked + jax.vmap(draw)(seed, position),
                         axis=-1).astype(jnp.int32)
    return jnp.where(temp > 0, sampled, greedy)


class ServingConfig:
    """Engine geometry + policy knobs.

    ``slots``: fixed decode batch width. ``max_seq``: per-request context
    budget (prompt + generated), a multiple of ``page_size``. ``num_pages``
    defaults to full-occupancy worst case (``slots * max_seq/page_size``);
    size it SMALLER to oversubscribe — admission then backpressures on the
    pool instead of the slots. ``decode_fuse`` fuses that many decode steps
    into one dispatched scan (admission/retirement happen at chunk
    boundaries — latency trades against host dispatch overhead);
    ``decode_fuse="auto"`` consults the autotuned config table
    (paddle_tpu.tune, kernel key ``serving.decode_fuse``, bucketed by slot
    count + device kind) and falls back to 1 when no tuned entry exists —
    ``decode_fuse_source`` records which layer answered
    (tuned/shipped/default vs "explicit" for a literal int).
    ``continuous=False`` degrades to the padded static wave-drain baseline;
    ``paged=False`` swaps in the contiguous reference cache. ``eos_id=None``
    disables EOS stopping (generation runs to ``max_new_tokens``).
    ``kv_dtype="int8"`` requests quantized KV pages
    (:class:`~.kv_cache.Int8PagedKVCache` — half the bf16 page bytes, so
    the same HBM budget holds 2× the pages); it engages only when a
    calibrated scale for this model's KV fingerprint exists
    (``paddle_tpu.monitor.numerics``, ``PADDLE_TPU_NUMERICS=2``), and
    falls back to the fp cache otherwise — serving must come up even with
    no calibration table on disk.

    ``speculation`` arms speculative decoding (serving.speculative): the
    engine-default draft k per scheduler tick — ``0`` off, a positive int
    an explicit k (capped at ``speculative.SPEC_K_CAP``), ``"auto"`` the
    autotuned k (tune table, kernel key ``serving.speculation_k``,
    bucketed by slot count; ``speculation_source`` records which layer
    answered — off/explicit/tuned/shipped/default — exactly like
    ``decode_fuse_source``). ``None`` defers to the
    ``PADDLE_TPU_SPECULATION`` env var (same grammar; unset means off).
    ``spec_drafter`` names the drafter (``"ngram"`` — the zero-weight
    prompt-lookup drafter). Per-request ``submit(speculation=...)``
    overrides the default. Speculation silently disables when the model
    lacks the ``verify`` contract method.

    Failure policy: ``decode_retries`` bounds in-place retries of a decode
    dispatch whose failure classifies as transient
    (:func:`paddle_tpu.reliability.faults.classify`); past the budget — or
    on a fatal failure — the in-flight batch is FAILED, its pages return to
    the pool, and the engine keeps serving the queue. ``fail_fast=True``
    restores the old raise-through behavior (debugging).

    Telemetry: ``slos`` is an optional sequence of
    :class:`paddle_tpu.monitor.slo.SLO` specs evaluated on every telemetry
    export tick (``PADDLE_TPU_TELEMETRY_DIR`` arms the exporter; the
    engine starts/stops it with its own lifetime). A breached spec with
    ``degrade=True`` flips :meth:`ServingEngine.health` to ``degraded``
    until a clean tick — slow-death becomes visible to the same recovery
    ladder that sees exceptions. ``PADDLE_TPU_SLO`` (see
    :func:`paddle_tpu.monitor.slo.parse_slos`) appends env-declared specs.
    """

    def __init__(self, slots: int = 8, page_size: int = 16,
                 max_seq: int = 128, num_pages: Optional[int] = None,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 max_queue: int = 1024, eos_id: Optional[int] = None,
                 decode_fuse=1, paged: bool = True,
                 continuous: bool = True, collect_logits: bool = False,
                 pad_id: int = 0, decode_retries: int = 2,
                 fail_fast: bool = False,
                 slos: Optional[Sequence] = None,
                 drain_timeout_s: float = 30.0,
                 kv_dtype: Optional[str] = None,
                 prefix_cache_pages: int = 0,
                 speculation=None, spec_drafter: str = "ngram"):
        if kv_dtype not in (None, "int8"):
            raise ValueError("kv_dtype must be None or 'int8', got %r"
                             % (kv_dtype,))
        if max_seq % page_size != 0:
            raise ValueError("max_seq=%d must be a multiple of page_size=%d"
                             % (max_seq, page_size))
        self.slots = int(slots)
        self.page_size = int(page_size)
        self.max_seq = int(max_seq)
        self.num_pages = (self.slots * (self.max_seq // self.page_size)
                          if num_pages is None else int(num_pages))
        self.prompt_buckets = tuple(sorted(
            prompt_buckets if prompt_buckets is not None
            else _pow2_buckets(min(8, max_seq), max_seq)))
        if self.prompt_buckets[-1] > self.max_seq:
            raise ValueError("prompt bucket %d exceeds max_seq %d"
                             % (self.prompt_buckets[-1], self.max_seq))
        self.max_queue = int(max_queue)
        self.eos_id = None if eos_id is None else int(eos_id)
        self.decode_fuse_source = "explicit"
        if decode_fuse is None or decode_fuse == "auto":
            decode_fuse, self.decode_fuse_source = self._tuned_decode_fuse()
        self.decode_fuse = max(1, int(decode_fuse))
        self.paged = bool(paged)
        self.continuous = bool(continuous)
        self.collect_logits = bool(collect_logits)
        self.pad_id = int(pad_id)
        self.decode_retries = max(0, int(decode_retries))
        self.fail_fast = bool(fail_fast)
        self.slos = list(slos) if slos else []
        self.drain_timeout_s = float(drain_timeout_s)
        # "int8": quantized KV pages — honored only when paged AND a
        # calibrated scale exists for this model's KV fingerprint
        # (monitor.numerics.kv_scale); otherwise the engine falls back to
        # the fp cache with a vlog warning instead of refusing to serve
        self.kv_dtype = kv_dtype
        # >0 arms the fleet prefix cache (paged layout only): that many
        # pool pages may be pinned by cached prompt-prefix KV, LRU-evicted
        # under pressure. A hit skips the shared prefix's prefill compute
        # (pages are row-copied, the remainder runs the resume executable).
        self.prefix_cache_pages = max(0, int(prefix_cache_pages))
        if self.prefix_cache_pages >= self.num_pages:
            raise ValueError(
                "prefix_cache_pages=%d must leave serving pages free "
                "(num_pages=%d)" % (self.prefix_cache_pages, self.num_pages))
        from .speculative import parse_speculation

        self.spec_drafter = str(spec_drafter)
        if speculation is None:
            speculation = os.environ.get("PADDLE_TPU_SPECULATION") or None
        spec = parse_speculation(speculation)
        if spec == "auto":
            spec, self.speculation_source = self._tuned_speculation_k()
        else:
            self.speculation_source = "off" if not spec else "explicit"
        self.speculation = max(0, int(spec or 0))
        if self.speculation == 0:
            self.speculation_source = "off"

    def _tuned_decode_fuse(self):
        """(value, source) from the autotuned config table; (1, "default")
        when no entry (or any table failure — serving must come up even
        with a corrupt table on disk). tools/serve_bench reports through
        the SAME tune.resolve_decode_fuse, so bench and engine can't
        diverge."""
        from .. import tune

        return tune.resolve_decode_fuse(self.slots)

    def _tuned_speculation_k(self):
        """(value, source) for ``speculation="auto"`` from the autotuned
        config table — same contract as :meth:`_tuned_decode_fuse`: a
        missing/corrupt table yields the shipped-math default, never an
        exception."""
        from .. import tune

        return tune.resolve_speculation_k(self.slots)


class ServingEngine:
    """Drives a model implementing the serving contract:

    * ``model.cfg`` — exposes ``n_layer``/``n_head``/``d_head``/``max_seq``
      /``dtype`` (models.decoder_lm.DecoderConfig shape),
    * ``model.prefill(params, tokens[B,S], lengths[B]) -> (logits[B,S,V],
      kvs)`` with ``kvs`` one ``(k, v)`` ``[B,S,H,D]`` pair per layer,
    * ``model.decode(params, cache, cache_ops, tokens[B], pos[B],
      active[B]) -> (logits[B,V], cache)``.
    """

    def __init__(self, model, config: Optional[ServingConfig] = None,
                 params=None):
        self.model = model
        self.cfg = config or ServingConfig()
        mcfg = model.cfg
        if mcfg.max_seq < self.cfg.max_seq:
            raise ValueError(
                "model max_seq %d < serving max_seq %d (position table too "
                "small for the context budget)" % (mcfg.max_seq, self.cfg.max_seq))
        self.params = params if params is not None else model.params
        if self.cfg.paged:
            kv_scales = None
            if self.cfg.kv_dtype == "int8":
                kv_scales = self._calibrated_kv_scales(mcfg)
            if kv_scales is not None:
                self.cache_ops = Int8PagedKVCache(
                    mcfg.n_layer, mcfg.n_head, mcfg.d_head, self.cfg.slots,
                    self.cfg.max_seq, self.cfg.page_size, self.cfg.num_pages,
                    k_scale=kv_scales[0], v_scale=kv_scales[1],
                    dtype=mcfg.dtype)
            else:
                self.cache_ops = PagedKVCache(
                    mcfg.n_layer, mcfg.n_head, mcfg.d_head, self.cfg.slots,
                    self.cfg.max_seq, self.cfg.page_size, self.cfg.num_pages,
                    dtype=mcfg.dtype)
            self.pool: Optional[PagePool] = PagePool(
                self.cfg.num_pages, self.cfg.page_size)
        else:
            self.cache_ops = ContiguousKVCache(
                mcfg.n_layer, mcfg.n_head, mcfg.d_head, self.cfg.slots,
                self.cfg.max_seq, dtype=mcfg.dtype)
            self.pool = None
        self.scheduler = Scheduler(self.cfg.slots, self.cfg.max_queue,
                                   continuous=self.cfg.continuous)
        self._cache = self.cache_ops.init_state()
        b = self.cfg.slots
        self._len = jnp.zeros((b,), jnp.int32)
        self._tok = jnp.zeros((b,), jnp.int32)
        self._active = jnp.zeros((b,), jnp.bool_)
        self._gen = jnp.zeros((b,), jnp.int32)
        self._maxnew = jnp.ones((b,), jnp.int32)
        # per-slot sampling params (ride the decode dispatch as plain
        # arguments; 0-temperature slots run the exact greedy path)
        self._temp = jnp.zeros((b,), jnp.float32)
        self._topk = jnp.zeros((b,), jnp.int32)
        self._seed = jnp.zeros((b,), jnp.int32)
        self._prefill_exe: Dict[int, Any] = {}   # bucket -> AOT executable
        self._decode_exe: Dict[int, Any] = {}    # fuse length -> executable
        self._resume_exe: Dict[int, Any] = {}    # remainder bucket -> exe
        self._verify_exe: Dict[int, Any] = {}    # window width -> executable
        # speculative decoding: needs the model's ``verify`` contract
        # method; without it every speculation knob silently resolves off
        # (serving must come up on a decode-only model)
        self._spec_capable = hasattr(model, "verify")
        from .speculative import make_drafter

        self._drafter = make_drafter(self.cfg.spec_drafter)
        self._spec_k = np.zeros((b,), np.int32)  # per-slot resolved draft k
        self._spec_auto: Optional[tuple] = None  # cached "auto" resolution
        self._spec_enabled = False  # any slot ever armed with k > 0
        # fleet prefix cache: host-side index of donated prompt-prefix KV
        # pages (paged layout only; see paddle_tpu.fleet.prefix_cache)
        self.prefix_cache = None
        if self.cfg.paged and self.cfg.prefix_cache_pages > 0:
            from ..fleet.prefix_cache import PrefixCache

            self.prefix_cache = PrefixCache(self.cfg.prefix_cache_pages,
                                            self.cfg.page_size)
        self._captured_logits: Dict[int, List[np.ndarray]] = {}
        self._consecutive_failures = 0
        self._faults_absorbed = 0
        # per-ENGINE prefill accounting (the registry counters are shared
        # process-wide; a fleet replica's health doc needs its own)
        self._prefills = 0
        self._resumes = 0
        self._last_error: Optional[str] = None
        self._closed = False
        self._draining = False
        self.last_drain: Optional[dict] = None
        # drain re-entrancy latch: a nested drain (signal handler firing
        # mid-drain, monitor thread) must observe, not re-enter
        self._drain_active = False
        self._drain_summary: Optional[dict] = None
        # continuous telemetry: refcounted process exporter (None when
        # PADDLE_TPU_TELEMETRY_DIR is unset — that check is one env read)
        self._telemetry = _telemetry.acquire()
        self._slo_breach: Optional[_slo.Breach] = None
        self._slo_monitor: Optional[_slo.SLOMonitor] = None
        specs = list(self.cfg.slos)
        env_slos = os.environ.get("PADDLE_TPU_SLO", "").strip()
        if env_slos:
            specs.extend(_slo.parse_slos(env_slos))
        if specs:
            self._slo_monitor = _slo.SLOMonitor(
                specs, on_breach=self._on_slo_breach,
                on_clear=self._on_slo_clear)
            if self._telemetry is not None:
                self._telemetry.add_listener(self._slo_monitor.on_sample)
            else:
                # SLOs only evaluate on export ticks: without the exporter
                # they would be silently dead — say so once, loudly
                import logging

                logging.getLogger("paddle_tpu").warning(
                    "ServingEngine: %d SLO spec(s) configured but "
                    "PADDLE_TPU_TELEMETRY_DIR is unset — no export ticks "
                    "will run, so the SLOs are inert (health() cannot "
                    "degrade on them)", len(specs))

    @staticmethod
    def _calibrated_kv_scales(mcfg):
        """(k_scale, v_scale) for this model's KV fingerprint, or None when
        no calibration exists (or ANY lookup failure — the int8 request
        then degrades to the fp cache, because serving must come up even
        with a missing/corrupt calibration table)."""
        from ..log import vlog
        from ..monitor import numerics as _num

        try:
            fp = _num.kv_fingerprint(mcfg.n_layer, mcfg.n_head, mcfg.d_head,
                                     mcfg.dtype)
            scales = _num.kv_scale(fp)
        except Exception:
            scales = None
        if scales is None:
            vlog(1, "ServingEngine: kv_dtype='int8' requested but no "
                    "calibrated KV scale found (run a calibration pass: "
                    "PADDLE_TPU_NUMERICS=2 or numerics."
                    "record_kv_calibration) — falling back to fp pages")
        return scales

    # -- public API -----------------------------------------------------------
    def close(self) -> None:
        """Release the engine's telemetry resources: unhook the SLO
        monitor and drop the exporter reference (the LAST engine or
        supervisor releasing it stops the thread and flushes the final
        partial interval). Idempotent; compiled executables stay usable."""
        if self._closed:
            return
        self._closed = True
        if self._telemetry is not None:
            if self._slo_monitor is not None:
                self._telemetry.remove_listener(self._slo_monitor.on_sample)
            _telemetry.release(self._telemetry)
            self._telemetry = None

    def __enter__(self) -> "ServingEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _on_slo_breach(self, breach) -> None:
        self._slo_breach = breach

    def _on_slo_clear(self) -> None:
        self._slo_breach = None

    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               deadline_s: Optional[float] = None,
               temperature: float = 0.0, top_k: int = 0,
               seed: Optional[int] = None,
               trace_id: Optional[str] = None, attempt: int = 0,
               speculation=None) -> Request:
        """Queue a request. Raises ``ValueError`` for a request that can
        NEVER be served at this geometry, and ``BackpressureError`` when
        the bounded queue is full (shed/retry — transient). ``deadline_s``
        bounds the request's wall-clock life from submission: past it the
        request is retired with TIMEOUT status (queued or running) so it
        stops pinning a slot and KV pages. ``temperature``/``top_k``/
        ``seed`` select device-side sampled decoding for THIS request (see
        :class:`~.request.Request`); the default is exact greedy.
        ``speculation`` overrides the engine's speculative-decoding
        default for THIS request (``0`` off, int draft-k, ``"auto"`` the
        tuned k, ``None`` inherit) — pure go-faster knob: the emitted
        stream is bit-identical either way."""
        if self._draining:
            _sm.DRAIN_REJECTED.inc()
            raise DrainingError(
                "engine is draining (graceful shutdown): not admitting new "
                "requests — re-route to a peer")
        req = Request(prompt, max_new_tokens, deadline_s=deadline_s,
                      temperature=temperature, top_k=top_k, seed=seed,
                      trace_id=trace_id, attempt=attempt,
                      speculation=speculation)
        if req.prompt_len > self.cfg.prompt_buckets[-1]:
            raise ValueError(
                "prompt length %d exceeds the largest prefill bucket %d"
                % (req.prompt_len, self.cfg.prompt_buckets[-1]))
        total = req.prompt_len + req.max_new_tokens
        if total > self.cfg.max_seq:
            raise ValueError(
                "prompt+max_new_tokens=%d exceeds max_seq=%d" %
                (total, self.cfg.max_seq))
        if self.pool is not None and \
                self.pool.pages_needed(total) > self.pool.num_pages:
            raise ValueError(
                "request needs %d pages but the pool only has %d"
                % (self.pool.pages_needed(total), self.pool.num_pages))
        req = self.scheduler.submit(req)
        _trace.on_submitted(req)
        return req

    def step(self) -> List[Request]:
        """One multiplexer cycle: expire deadlines, retire/admit into free
        slots, prefill the admissions, then one fused decode dispatch.
        Returns requests that reached a terminal state during the cycle
        (FINISHED, TIMEOUT or FAILED — check ``req.state``)."""
        finished = self._expire_deadlines()
        finished.extend(self._admit())
        if self.scheduler.occupancy:
            finished.extend(self._decode_dispatch())
        return finished

    def run(self, max_steps: Optional[int] = None) -> List[Request]:
        """Drive :meth:`step` until queue and slots drain (or ``max_steps``).
        Updates the ``serving/tokens_per_sec`` gauge over the drive. A
        :meth:`request_drain` arriving mid-drive (a SIGTERM handler) flips
        the loop into :meth:`drain`: in-flight requests finish, queued
        ones are shed, the engine closes."""
        t0 = time.perf_counter()
        tok0 = _sm.TOKENS_GENERATED.value
        done: List[Request] = []
        steps = 0
        while not self.scheduler.idle():
            if self._draining:
                self.drain()
                break
            if max_steps is not None and steps >= max_steps:
                break
            done.extend(self.step())
            steps += 1
        dt = time.perf_counter() - t0
        if dt > 0:
            _sm.TOKENS_PER_SEC.set((_sm.TOKENS_GENERATED.value - tok0) / dt)
        return done

    def request_drain(self) -> None:
        """Signal-handler-safe drain request: new submissions start
        rejecting typed (:class:`~.request.DrainingError`) immediately;
        the driving loop (:meth:`run`) performs the actual drain at the
        next cycle boundary instead of tearing down mid-decode."""
        self._draining = True

    def drain(self, timeout_s: Optional[float] = None) -> dict:
        """Graceful shutdown: stop admitting (queued requests are shed
        with terminal state REJECTED — they never held slots or pages),
        FINISH the in-flight requests (continuing the normal decode loop,
        bounded by ``timeout_s``; stragglers past it retire TIMEOUT with
        their pages reclaimed), then :meth:`close`. Returns and stores
        (``engine.last_drain``) a summary dict; ticks ``serving/drains``
        and ``serving/drained_requests``.

        Idempotent AND re-entrant: a second drain on a drained engine
        returns the recorded summary untouched, and a nested call (a
        SIGTERM handler or monitor thread firing while a drain is already
        running its decode loop) returns a snapshot of the in-progress
        summary instead of re-entering the loop — the fleet router's
        respawn paths call drain from exactly those contexts."""
        if self.last_drain is not None:
            return self.last_drain
        if self._drain_active:
            return dict(self._drain_summary or {})
        self._drain_active = True
        summary = {"finished": 0, "timed_out": 0, "failed": 0,
                   "rejected": 0}
        self._drain_summary = summary
        try:
            if timeout_s is None:
                timeout_s = self.cfg.drain_timeout_s
            self._draining = True
            _sm.DRAINS.inc()
            now = time.perf_counter()
            for req in self.scheduler.drain_queue():
                req.finished_t = now
                _trace.on_terminal(req, REJECTED, None)
                summary["rejected"] += 1
            deadline = time.monotonic() + timeout_s
            while self.scheduler.occupancy and time.monotonic() < deadline:
                for req in self.step():
                    key = {FINISHED: "finished", TIMEOUT: "timed_out",
                           FAILED: "failed"}.get(req.state)
                    if key is not None:
                        summary[key] += 1
            for slot in range(self.cfg.slots):
                if self.scheduler.slot_request(slot) is not None:
                    # past the drain budget: cut the straggler loose —
                    # TIMEOUT is its terminal state, pages go to the pool
                    self._retire(slot, state=TIMEOUT)
                    summary["timed_out"] += 1
            if self.prefix_cache is not None and self.pool is not None:
                # cached prefix pages are engine-lifetime pins: a drained
                # engine returns them so accounting ends at zero used
                self.pool.free(self.prefix_cache.flush())
            _sm.DRAINED_REQUESTS.inc(summary["finished"])
            self.last_drain = summary
            self.close()
            return summary
        finally:
            self._drain_active = False

    def captured_logits(self, req: Request) -> List[np.ndarray]:
        """Per-emitted-token logits rows (``collect_logits=True`` only)."""
        return self._captured_logits.get(req.id, [])

    def decode_kernel_info(self) -> tuple:
        """``(kernel, source)`` of the decode-attention inner loop as THIS
        engine resolves it: ``("paged", <tuned|shipped|default>)`` when the
        ragged paged-attention Pallas kernel is armed
        (``FLAGS_paged_attention_kernel``, paged layout) — source is the
        tune-table layer answering its ``block_pages`` lookup, i.e. the
        provenance the compiled trace saw — else ``("gather", "n/a")``."""
        from ..ops import attention_ops

        if self.cfg.paged and attention_ops.paged_kernel_mode() is not None:
            from ..ops.pallas_kernels import paged_attention as _pa

            if _pa.paged_attention_supported(self.cache_ops.dtype):
                mcfg = self.model.cfg
                try:
                    from .. import tune

                    _c, src = tune.lookup(
                        "paged_attention",
                        tune.bucket_ctx(self.cfg.max_seq,
                                        mcfg.n_head * mcfg.d_head))
                except Exception:
                    src = "default"
                return "paged", src
        return "gather", "n/a"

    def speculation_info(self) -> tuple:
        """``(k, drafter_kind, source)`` of the speculative fast path as
        THIS engine resolves its default — the provenance twin of
        :meth:`decode_kernel_info`: ``k`` is the engine-default draft
        width (0 = off, including model-not-capable), ``drafter_kind``
        names the proposer, ``source`` the answering layer
        (off/explicit/tuned/shipped/default)."""
        if not self._spec_capable:
            return 0, "n/a", "off"
        k = self.cfg.speculation
        kind = self._drafter.kind if k > 0 else "off"
        return k, kind, self.cfg.speculation_source

    def stats(self) -> dict:
        kern, kern_src = self.decode_kernel_info()
        spec_k, spec_kind, spec_src = self.speculation_info()
        out = {
            "layout": self.cache_ops.layout,
            "queued": self.scheduler.queue_depth,
            "running": self.scheduler.occupancy,
            "cache_bytes": self.cache_ops.cache_bytes(self._cache),
            "decode_fuse": self.cfg.decode_fuse,
            "decode_fuse_source": getattr(self.cfg, "decode_fuse_source",
                                          "explicit"),
            "decode_kernel": kern,
            "decode_kernel_source": kern_src,
            "speculation": spec_k,
            "spec_drafter": spec_kind,
            "speculation_source": spec_src,
            # the layout actually serving (int8 requests silently fall back
            # to fp when uncalibrated — this is where that shows)
            "kv_layout": self.cache_ops.layout,
            "kv_dtype": ("int8" if isinstance(self.cache_ops,
                                              Int8PagedKVCache)
                         else str(self.cache_ops.dtype)),
        }
        if self.pool is not None:
            out["pages_in_use"] = self.pool.num_used
            out["page_pool_utilization"] = round(self.pool.utilization, 4)
        if self.prefix_cache is not None:
            out["prefix_cache"] = self.prefix_cache.stats()
        return out

    def health(self) -> dict:
        """Liveness/degradation snapshot for an external health checker:
        ``status`` is ``"ok"`` until a decode failure is absorbed and back
        to ``"ok"`` after the next clean dispatch (``"degraded"`` in
        between) — and, with SLO specs configured, while the most recent
        telemetry tick breached a ``degrade=True`` spec (slow-death
        detection, cleared by the next healthy tick). Counters are
        lifetime totals for THIS engine."""
        degraded = bool(self._consecutive_failures) or \
            self._slo_breach is not None
        out = {
            "status": "degraded" if degraded else "ok",
            "queued": self.scheduler.queue_depth,
            "running": self.scheduler.occupancy,
            "consecutive_failures": self._consecutive_failures,
            "faults_absorbed": self._faults_absorbed,
            "last_error": self._last_error,
            "page_accounting_ok": self.page_accounting_ok(),
            # full prefills vs prefix-resume ingests, THIS engine only —
            # what a router reads to prove a migrated prefix skipped work
            "prefills": self._prefills,
            "resumes": self._resumes,
        }
        if self._slo_breach is not None:
            out["slo_breach"] = self._slo_breach.to_doc()
        if self._slo_monitor is not None:
            out["slo_breaches_total"] = self._slo_monitor.breaches_total
        if self.pool is not None:
            out["pages_free"] = self.pool.num_free
            out["pages_total"] = self.pool.num_pages
        return out

    def page_accounting_ok(self) -> bool:
        """The no-leak invariant every retirement path must preserve: pages
        the pool counts as used == pages held by running requests."""
        if self.pool is None:
            return True
        held = sum(len(r.pages) for r in self.scheduler.running())
        if self.prefix_cache is not None:
            held += self.prefix_cache.pages_held
        return self.pool.num_used == held

    # -- cross-replica page migration -----------------------------------------
    # The shippable unit of state is a prefix-cache entry: page-aligned
    # prompt KV pages + the exact tokens they cover. Export COPIES bytes
    # (ownership never crosses a process boundary); import is atomic from
    # the pool's point of view — alloc, write, insert, and any failure
    # frees the reservation before returning, so ``page_accounting_ok``
    # holds on both sides of every migration outcome.
    def export_prefix_pages(self, tokens: Sequence[int]):
        """Serialize the prefix-cache entry exactly covering ``tokens`` to
        ``(meta, blobs)``; None when absent (evicted, never donated) or
        when this engine has no page concept (contiguous layout)."""
        if self.prefix_cache is None:
            return None
        entry = self.prefix_cache.get(tokens)
        if entry is None:
            return None
        return self.cache_ops.export_pages(self._cache, entry.pages)

    def ingest_prefix_pages(self, tokens: Sequence[int], meta: dict,
                            blobs) -> bool:
        """Land an exported prefix into THIS engine's pool + prefix cache.
        Returns False (never raises) when it cannot: no paged pool, no
        prefix cache, geometry mismatch, pool exhausted, or the cache
        refuses the insert — in every refusal the reservation is freed
        first. Re-ingesting an already-held prefix is a no-op success."""
        if self.pool is None or self.prefix_cache is None or self._closed:
            return False
        tokens = [int(t) for t in tokens]
        n = int(meta.get("n_pages", 0))
        if n < 1 or len(tokens) != n * self.cfg.page_size:
            return False
        if self.prefix_cache.contains(tokens):
            return True
        try:
            pages = self.pool.alloc(n)
        except PagePoolExhausted:
            return False
        try:
            self._cache = self.cache_ops.import_pages(
                self._cache, pages, meta, blobs)
        except ValueError:
            self.pool.free(pages)
            return False
        accepted, evicted = self.prefix_cache.insert(tokens, pages)
        if evicted:
            self.pool.free(evicted)
        if not accepted:
            self.pool.free(pages)
            return False
        return True

    def evict_prefix(self, tokens: Sequence[int]) -> int:
        """Drop one prefix entry and free its pages; returns pages freed.
        With :meth:`export_prefix_pages` on the other side this is the
        MOVE half of a rebalance: ship, then evict on the source."""
        if self.pool is None or self.prefix_cache is None:
            return 0
        pages = self.prefix_cache.evict(tokens)
        if pages:
            self.pool.free(pages)
        return len(pages)

    def export_request_prefix(self, req: Request):
        """Copy (never move) a live request's page-aligned PROMPT prefix —
        those rows are immutable once prefilled, whatever decode is doing
        — as ``(tokens, meta, blobs)``; None when there is less than one
        full page or no paged pool. The scale-down path ships these so a
        requeued request resumes from its prefill instead of redoing it."""
        if self.pool is None or not req.pages:
            return None
        ps = self.cfg.page_size
        n_tok = ((req.prompt_len - 1) // ps) * ps
        npages = n_tok // ps
        if npages < 1 or len(req.pages) < npages:
            return None
        meta, blobs = self.cache_ops.export_pages(
            self._cache, req.pages[:npages])
        return [int(t) for t in req.prompt[:n_tok]], meta, blobs

    # -- admission + prefill --------------------------------------------------
    def _bucket_for(self, n: int) -> int:
        for b in self.cfg.prompt_buckets:
            if n <= b:
                return b
        raise ValueError("no prefill bucket covers prompt length %d" % n)

    def _admit(self) -> List[Request]:
        finished: List[Request] = []
        slots = self.scheduler.admissible_slots()
        if not slots or self.scheduler.peek() is None:
            return finished
        wave_bucket = None
        if not self.cfg.continuous:
            # the padded static baseline: every prompt of the wave pays the
            # wave-max bucket, the classic fully-padded batch
            wave = self.scheduler.peek_n(len(slots))
            wave_bucket = self._bucket_for(max(r.prompt_len for r in wave))
        for slot in slots:
            req = self.scheduler.peek()
            if req is None:
                break
            pages: List[int] = []
            if self.pool is not None:
                need = self.pool.pages_needed(
                    req.prompt_len + req.max_new_tokens)
                try:
                    pages = self.pool.alloc(need)
                except PagePoolExhausted:
                    # graceful backpressure: the request stays at the queue
                    # head; retirements will free pages. Recorded for the
                    # flight recorder so a post-mortem sees the pressure.
                    self.scheduler.requeue_head_blocked()
                    fr = _dev.flight_recorder()
                    if fr is not None:
                        fr.record_event(
                            "serving_admission_blocked",
                            request_id=req.id, need_pages=need,
                            free_pages=self.pool.num_free,
                            batch=self._batch_spec())
                    break
            req = self.scheduler.admit(slot)
            req.admitted_t = time.perf_counter()
            req.pages = pages
            _trace.on_admitted(req, slot)
            bucket = wave_bucket or self._bucket_for(req.prompt_len)
            done = self._prefill(req, slot, bucket)
            if done is not None:
                finished.append(done)
        return finished

    def _prefill(self, req: Request, slot: int, bucket: int
                 ) -> Optional[Request]:
        """Run the per-bucket compiled prefill; returns the request if it
        finished immediately (EOS first token / max_new_tokens == 1). With
        a prefix cache armed, a prompt whose page-aligned prefix is cached
        skips the full prefill: its pages are row-copied and only the
        remainder runs (the resume executable)."""
        cfg = self.cfg
        if self.prefix_cache is not None:
            entry = self.prefix_cache.lookup(req.prompt)
            if entry is not None:
                return self._prefill_from_prefix(req, slot, entry)
        prompt = np.full((bucket,), cfg.pad_id, np.int32)
        prompt[:req.prompt_len] = req.prompt
        if cfg.paged:
            dest_np = self.cache_ops.prompt_dest(req.pages)
            dest = jnp.asarray(dest_np)
            self._cache["pt"] = self._cache["pt"].at[slot].set(dest)
        else:
            dest = jnp.asarray(self.cache_ops.prompt_dest(slot))
        exe = self._get_prefill_exe(bucket)
        t0 = time.perf_counter()
        self._cache, first_tok, last_logits = exe(
            self.params, self._cache, dest, jnp.asarray(prompt),
            jnp.asarray(req.prompt_len, jnp.int32),
            jnp.asarray(req.temperature, jnp.float32),
            jnp.asarray(req.top_k, jnp.int32),
            jnp.asarray(req.seed, jnp.int32))
        tok = int(np.asarray(first_tok))
        t1 = time.perf_counter()
        _trace.on_prefill(req, slot, bucket, t0, t1, cause="local")
        _sm.PREFILL_MS.observe((t1 - t0) * 1e3)
        _sm.PREFILL_COUNT.inc()
        self._prefills += 1
        return self._finish_prefill(req, slot, tok, last_logits)

    def _prefill_from_prefix(self, req: Request, slot: int, entry
                             ) -> Optional[Request]:
        """Serve admission from a prefix-cache hit: point this slot's page
        table at the request's pages, row-copy the cached prefix KV into
        them, then run ONLY the prompt remainder through the resume
        executable (teacher-forced decode over the model's own serving
        contract — model-agnostic, no second prefill trace). The first
        sampled token is keyed (seed, prompt_len-1), identical to the cold
        prefill path, so hit and miss generate the same stream."""
        ps = self.cfg.page_size
        n = entry.n_tokens
        npages = len(entry.pages)
        dest_np = self.cache_ops.prompt_dest(req.pages)
        self._cache["pt"] = self._cache["pt"].at[slot].set(
            jnp.asarray(dest_np))
        rows = np.arange(ps, dtype=np.int32)
        src = np.concatenate([p * ps + rows for p in entry.pages])
        dst = np.concatenate([p * ps + rows for p in req.pages[:npages]])
        t0 = time.perf_counter()
        self._cache["k"] = self._cache["k"].at[:, dst].set(
            self._cache["k"][:, src])
        self._cache["v"] = self._cache["v"].at[:, dst].set(
            self._cache["v"][:, src])
        # (int8 layout: per-page scales are fixed constants — rows copy 1:1)
        rbucket = self._bucket_for(req.prompt_len - n)
        remainder = np.full((rbucket,), self.cfg.pad_id, np.int32)
        remainder[:req.prompt_len - n] = req.prompt[n:]
        exe = self._get_resume_exe(rbucket)
        self._cache, first_tok, last_logits = exe(
            self.params, self._cache, jnp.asarray(remainder),
            jnp.asarray(n, jnp.int32),
            jnp.asarray(req.prompt_len, jnp.int32),
            jnp.asarray(slot, jnp.int32),
            jnp.asarray(req.temperature, jnp.float32),
            jnp.asarray(req.top_k, jnp.int32),
            jnp.asarray(req.seed, jnp.int32))
        tok = int(np.asarray(first_tok))
        t1 = time.perf_counter()
        _trace.on_prefill(req, slot, rbucket, t0, t1, cause="resume")
        _sm.PREFILL_MS.observe((t1 - t0) * 1e3)
        # deliberately NOT PREFILL_COUNT: the bench's "reduced prefill
        # dispatches vs cold" assertion reads that counter
        self._resumes += 1
        return self._finish_prefill(req, slot, tok, last_logits)

    def _finish_prefill(self, req: Request, slot: int, tok: int,
                        last_logits) -> Optional[Request]:
        """Post-prefill bookkeeping shared by the cold and prefix-hit
        paths: TTFT, first token, immediate retirement, slot arming."""
        cfg = self.cfg
        _sm.TOKENS_GENERATED.inc()
        now = time.perf_counter()
        req.first_token_t = now
        _sm.TTFT_MS.observe((now - req.submitted_t) * 1e3)
        req.tokens_out.append(tok)
        if cfg.collect_logits:
            self._captured_logits.setdefault(req.id, []).append(
                np.asarray(last_logits))
        if (cfg.eos_id is not None and tok == cfg.eos_id) \
                or req.max_new_tokens == 1:
            return self._retire(slot)
        self._len = self._len.at[slot].set(req.prompt_len)
        self._tok = self._tok.at[slot].set(tok)
        self._active = self._active.at[slot].set(True)
        self._gen = self._gen.at[slot].set(1)
        self._maxnew = self._maxnew.at[slot].set(req.max_new_tokens)
        self._temp = self._temp.at[slot].set(req.temperature)
        self._topk = self._topk.at[slot].set(req.top_k)
        self._seed = self._seed.at[slot].set(req.seed)
        k = self._request_spec_k(req)
        self._spec_k[slot] = k
        if k > 0:
            self._spec_enabled = True
        return None

    # -- decode ---------------------------------------------------------------
    def _cache_lost(self) -> bool:
        """True when a failed dispatch already consumed the donated cache
        buffers (``donate_argnums=(1,)``) — retrying would feed deleted
        arrays, so recovery must re-init the cache instead."""
        lost = False

        def probe(v):
            nonlocal lost
            deleted = getattr(v, "is_deleted", None)
            if deleted is not None and deleted():
                lost = True

        jax.tree_util.tree_map(probe, self._cache)
        return lost

    def _request_spec_k(self, req: Request) -> int:
        """Resolve the draft k THIS request decodes with: per-request
        override > engine default; ``"auto"`` goes through the tune table
        once per engine (cached — admission must not pay a table read per
        request). 0 when the model lacks the verify contract."""
        if not self._spec_capable:
            return 0
        from .speculative import SPEC_K_CAP

        s = req.speculation
        if s is None:
            return self.cfg.speculation
        if s == "auto":
            if self._spec_auto is None:
                from .. import tune

                self._spec_auto = tune.resolve_speculation_k(self.cfg.slots)
            return min(max(0, int(self._spec_auto[0])), SPEC_K_CAP)
        return min(max(0, int(s)), SPEC_K_CAP)

    def _build_drafts(self):
        """Host-side draft pass over the in-flight batch: ask the drafter
        for up to k proposals per speculative slot (its full prompt +
        generated history), capped at the slot's remaining emit budget —
        a draft step past ``max_new``/``max_ctx`` could never be emitted.
        Returns ``(draft [B,kmax], dlen [B], width)`` or None when no slot
        proposed anything (the tick then takes the plain fused-decode
        path — zero speculative overhead for non-speculative traffic)."""
        if not self._spec_enabled:
            return None
        b = self.cfg.slots
        props: Dict[int, List[int]] = {}
        kmax = 0
        for slot in range(b):
            req = self.scheduler.slot_request(slot)
            if req is None:
                continue
            k = int(self._spec_k[slot])
            if k <= 0:
                continue
            gen = len(req.tokens_out)
            ln = req.prompt_len + gen - 1
            k = min(k, req.max_new_tokens - gen, self.cfg.max_seq - ln - 1)
            if k <= 0:
                continue
            prop = self._drafter.propose(
                list(req.prompt) + req.tokens_out, k)
            if prop:
                props[slot] = prop
                kmax = max(kmax, len(prop))
        if kmax == 0:
            return None
        draft = np.zeros((b, kmax), np.int32)
        dlen = np.zeros((b,), np.int32)
        for slot, prop in props.items():
            draft[slot, :len(prop)] = prop
            dlen[slot] = len(prop)
        return draft, dlen, kmax + 1

    def _decode_dispatch(self) -> List[Request]:
        """One fused decode dispatch with the recovery ladder: transient
        failures retry in place (bounded by ``decode_retries``); a failure
        that exhausts the budget — or classifies fatal — FAILS the
        in-flight batch (pages reclaimed, requests marked FAILED, device
        slot state reset) and the engine keeps serving the queue. The
        flight recorder captures the batch spec either way.

        With speculation armed and the drafter proposing, the tick runs
        the verify executable instead: ONE windowed forward over each
        slot's (pending token + draft) window, per-step accept/rollback
        on device — up to k+1 tokens per dispatch, bit-identical stream
        (serving.speculative). Rollback is free under the worst-case page
        reservation: rejected positions sit beyond the rolled-back
        ``ctx_len``, masked out of every later read until overwritten."""
        drafts = self._build_drafts()
        if drafts is not None:
            draft_np, dlen_np, steps = drafts
            exe = self._get_verify_exe(steps)
            extra = (jnp.asarray(draft_np), jnp.asarray(dlen_np))
        else:
            dlen_np = None
            steps = self.cfg.decode_fuse
            exe = self._get_decode_exe(steps)
            extra = ()
        t0 = time.perf_counter()
        attempt = 0
        # Pre-dispatch snapshot: on an async backend a failed dispatch often
        # surfaces at host materialization (np.asarray below), AFTER the
        # self._* slots were reassigned to the failed step's outputs — a
        # retry from those half-advanced values would double-step every
        # in-flight request. The failure path always rolls back to this
        # snapshot first (the donated cache may be gone; _cache_lost() on
        # the restored ref detects that and downgrades retry to recovery).
        snap = (self._cache, self._len, self._tok, self._active, self._gen)
        while True:
            try:
                spec = _faults.fire("serving.decode")  # chaos drills
                if spec is not None and spec.kind == "exhausted":
                    raise PagePoolExhausted(
                        "injected pool exhaustion at serving.decode")
                out = exe(self.params, self._cache, self._len, self._tok,
                          self._active, self._gen, self._maxnew,
                          self._temp, self._topk, self._seed, *extra)
                if self.cfg.collect_logits:
                    (self._cache, self._len, self._tok, self._active,
                     self._gen, toks, emitted, fin, logseq) = out
                else:
                    (self._cache, self._len, self._tok, self._active,
                     self._gen, toks, emitted, fin) = out
                    logseq = None
                # one host sync per dispatch: the retire/admit decision needs
                # the emitted tokens (the serving analog of run_steps' fetch)
                toks = np.asarray(toks)
                emitted = np.asarray(emitted)
                fin = np.asarray(fin)
                break
            except Exception as e:
                (self._cache, self._len, self._tok, self._active,
                 self._gen) = snap
                if (_faults.classify(e) == "transient"
                        and attempt < self.cfg.decode_retries
                        and not self._cache_lost()):
                    attempt += 1
                    _sm.RETRIES.inc()
                    continue
                fr = _dev.flight_recorder()
                if fr is not None:
                    fr.record_event("serving_inflight_batch",
                                    **self._batch_spec())
                _safe_flight_dump(fr, "serving.decode", e)
                if self.cfg.fail_fast:
                    raise
                return self._fail_inflight_batch(e)
        self._consecutive_failures = 0
        t1 = time.perf_counter()
        spec_args = None
        if dlen_np is not None:
            # accepted drafts per slot = its run-steps beyond the first
            # (step 0 consumes the pending token, never a draft)
            runs = emitted.sum(axis=0)
            proposed = int(dlen_np.sum())
            accepted = int(np.maximum(runs - 1, 0).sum())
            spec_args = _speculative.verify_window_args(steps, proposed,
                                                        accepted)
        _trace.on_decode_chunk(
            [self.scheduler.slot_request(s) for s in range(self.cfg.slots)],
            steps, t0, t1, spec=spec_args)
        _sm.DECODE_STEP_MS.observe((t1 - t0) * 1e3)
        _sm.DECODE_DISPATCHES.inc()
        # a verify dispatch is ONE windowed model step however wide the
        # window — DECODE_STEPS keeps meaning "model forwards", so
        # tokens/steps > 1 is exactly the speculative win
        _sm.DECODE_STEPS.inc(1 if dlen_np is not None else steps)
        _sm.TOKENS_GENERATED.inc(int(emitted.sum()))
        if dlen_np is not None:
            _sm.SPEC_PROPOSED.inc(proposed)
            _sm.SPEC_ACCEPTED.inc(accepted)
            _sm.SPEC_REJECTED.inc(proposed - accepted)
            _sm.SPEC_DRAFTS.inc(int((dlen_np > 0).sum()))
            _sm.SPEC_VERIFY_DISPATCHES.inc()
            _sm.SPEC_ACCEPT_RATE.observe(accepted / max(1, proposed))
        finished: List[Request] = []
        for slot in range(self.cfg.slots):
            req = self.scheduler.slot_request(slot)
            if req is None:
                continue
            for f in range(steps):
                if emitted[f, slot]:
                    req.tokens_out.append(int(toks[f, slot]))
                    if logseq is not None:
                        self._captured_logits.setdefault(req.id, []).append(
                            np.asarray(logseq[f, slot]))
                if fin[f, slot]:
                    finished.append(self._retire(slot))
                    break
        return finished

    def _retire(self, slot: int, state: str = FINISHED,
                clear_slot: bool = True) -> Request:
        """EVERY slot-vacating path funnels through here — EOS/max_new
        (FINISHED), deadline (TIMEOUT), decode failure (FAILED) — so page
        reclamation can't be forgotten on a new path. ``clear_slot=False``
        is for callers about to reset ALL device slot state wholesale
        (``_fail_inflight_batch``) — no point in per-slot updates first."""
        req = self.scheduler.retire(slot, state)
        if self.pool is not None and req.pages:
            donated = 0
            if self.prefix_cache is not None:
                donated = self._donate_prefix_pages(req, state)
            if donated < len(req.pages):
                self.pool.free(req.pages[donated:])
            req.pages = []
        req.finished_t = time.perf_counter()
        _trace.on_terminal(req, state, slot)
        if state == FINISHED:
            _sm.REQUEST_LATENCY_MS.observe(
                (req.finished_t - req.submitted_t) * 1e3)
        elif state == TIMEOUT:
            _sm.TIMEOUTS.inc()
        elif state == FAILED:
            _sm.REQUESTS_FAILED.inc()
        if state != FINISHED and clear_slot:
            # the decode loop only deactivates slots it finished itself;
            # an out-of-band retirement must clear the device-side flag or
            # the next dispatch decodes a ghost
            self._active = self._active.at[slot].set(False)
        return req

    def _donate_prefix_pages(self, req: Request, state: str) -> int:
        """Zero-copy prefix-cache insert at retirement: a FINISHED
        request's leading full-prompt pages transfer ownership to the
        cache instead of returning to the pool. Returns how many of
        ``req.pages`` the cache now owns (a prefix of the list — the
        caller frees the rest). A request that did NOT finish never
        donates: its pages may hold garbage from the failed dispatch, and
        poisoned prefixes must be structurally unservable."""
        cache = self.prefix_cache
        n = cache.cacheable_len(req.prompt_len)
        if n <= 0:
            return 0
        if state != FINISHED:
            from ..fleet import metrics as _fm

            _fm.PREFIX_POISONED_SKIPPED.inc()
            return 0
        tokens = req.prompt[:n]
        if cache.contains(tokens):
            return 0
        npages = n // self.cfg.page_size
        accepted, evicted = cache.insert(tokens, req.pages[:npages])
        if evicted:
            self.pool.free(evicted)
        return npages if accepted else 0

    def _expire_deadlines(self) -> List[Request]:
        """Retire requests past their deadline — queued ones leave the
        queue (no pages to reclaim), running ones vacate slot + pages."""
        now = time.perf_counter()
        out: List[Request] = []
        for req in self.scheduler.drop_expired(now):
            req.finished_t = now
            _trace.on_terminal(req, TIMEOUT, None)
            _sm.TIMEOUTS.inc()
            out.append(req)
        for slot in range(self.cfg.slots):
            req = self.scheduler.slot_request(slot)
            if req is not None and req.expired(now):
                out.append(self._retire(slot, state=TIMEOUT))
        return out

    def _fail_inflight_batch(self, exc: BaseException) -> List[Request]:
        """Decode-failure recovery: mark every in-flight request FAILED,
        reclaim its pages, reset device slot state (re-init the cache if
        the failed dispatch consumed the donated buffers), and leave the
        engine serving. The queue is untouched — queued requests admit
        into the freed slots on the next cycle."""
        self._consecutive_failures += 1
        self._faults_absorbed += 1
        self._last_error = "%s: %s" % (type(exc).__name__, exc)
        _sm.FAULTS.inc()
        failed: List[Request] = []
        for slot in range(self.cfg.slots):
            req = self.scheduler.slot_request(slot)
            if req is None:
                continue
            req.error = self._last_error
            failed.append(self._retire(slot, state=FAILED,
                                       clear_slot=False))
        b = self.cfg.slots
        self._len = jnp.zeros((b,), jnp.int32)
        self._tok = jnp.zeros((b,), jnp.int32)
        self._active = jnp.zeros((b,), jnp.bool_)
        self._gen = jnp.zeros((b,), jnp.int32)
        self._maxnew = jnp.ones((b,), jnp.int32)
        self._temp = jnp.zeros((b,), jnp.float32)
        self._topk = jnp.zeros((b,), jnp.int32)
        self._seed = jnp.zeros((b,), jnp.int32)
        if self._cache_lost():
            self._cache = self.cache_ops.init_state()
            if self.prefix_cache is not None and self.pool is not None:
                # the rows backing every cached prefix died with the
                # donated buffers — the entries are lies now; drop them
                self.pool.free(self.prefix_cache.flush())
        return failed

    def _batch_spec(self) -> dict:
        """The in-flight batch, host view — what the flight recorder keeps
        when a decode dispatch fails or admission backpressures."""
        rows = []
        for slot in range(self.cfg.slots):
            req = self.scheduler.slot_request(slot)
            if req is None:
                continue
            rows.append({"slot": slot, "request_id": req.id,
                         "trace_id": req.trace_id,
                         "prompt_len": req.prompt_len,
                         "generated": len(req.tokens_out),
                         "max_new_tokens": req.max_new_tokens,
                         "spec_k": int(self._spec_k[slot]),
                         "pages": list(req.pages)})
        kern, kern_src = self.decode_kernel_info()
        spec_k, spec_kind, spec_src = self.speculation_info()
        return {"layout": self.cache_ops.layout, "slots": rows,
                "queue_depth": self.scheduler.queue_depth,
                "decode_fuse": self.cfg.decode_fuse,
                "decode_fuse_source": getattr(self.cfg, "decode_fuse_source",
                                              "explicit"),
                "decode_kernel": kern,
                "decode_kernel_source": kern_src,
                "speculation": spec_k,
                "spec_drafter": spec_kind,
                "speculation_source": spec_src}

    # -- AOT compilation ------------------------------------------------------
    def _get_prefill_exe(self, bucket: int):
        exe = self._prefill_exe.get(bucket)
        if exe is not None:
            return exe
        model, ops, cfg = self.model, self.cache_ops, self.cfg

        def prefill(params, cache, dest, prompt, length, temp, topk, seed):
            logits, kvs = model.prefill(params, prompt[None], length[None])
            for i, (k, v) in enumerate(kvs):
                cache = ops.write_prompt(cache, i, k[0], v[0], dest, length)
            last = logits[0, length - 1]
            # first generated token: same sampler as the decode scan, keyed
            # by the last PROMPT position (decode steps then key length,
            # length+1, ... — the streams can't collide)
            tok = _sample_tokens(last[None], temp[None], topk[None],
                                 seed[None], (length - 1)[None])[0]
            return cache, tok, last

        dest_abs = (jax.ShapeDtypeStruct((ops.pages_per_slot,), jnp.int32)
                    if cfg.paged else jax.ShapeDtypeStruct((), jnp.int32))
        exe = aot_compile(
            prefill,
            (self.params, self._cache, dest_abs,
             jax.ShapeDtypeStruct((bucket,), jnp.int32),
             jax.ShapeDtypeStruct((), jnp.int32),
             jax.ShapeDtypeStruct((), jnp.float32),
             jax.ShapeDtypeStruct((), jnp.int32),
             jax.ShapeDtypeStruct((), jnp.int32)),
            donate_argnums=(1,))
        self._prefill_exe[bucket] = exe
        return exe

    def _get_decode_exe(self, fuse: int):
        exe = self._decode_exe.get(fuse)
        if exe is not None:
            return exe
        model, ops, cfg = self.model, self.cache_ops, self.cfg
        eos = -1 if cfg.eos_id is None else cfg.eos_id
        max_ctx = cfg.max_seq
        collect = cfg.collect_logits

        def chunk(params, cache, lengths, tokens, active, gen, maxnew,
                  temp, topk, seed):
            def body(carry, _):
                cache, ln, tk, ac, gc = carry
                logits, cache = model.decode(params, cache, ops, tk, ln, ac)
                # device-side sampling: keyed by ln (the consumed token's
                # absolute position), which advances per STEP not per
                # dispatch — fuse=1 and fuse=4 draw identical streams
                nxt = _sample_tokens(logits, temp, topk, seed, ln)
                nxt = jnp.where(ac, nxt, tk)
                emitted = ac
                gc = gc + ac
                ln = ln + ac
                fin = ac & ((nxt == eos) | (gc >= maxnew) | (ln >= max_ctx))
                ac = ac & ~fin
                out = (nxt, emitted, fin, logits) if collect \
                    else (nxt, emitted, fin)
                return (cache, ln, nxt, ac, gc), out

            (cache, lengths, tokens, active, gen), outs = jax.lax.scan(
                body, (cache, lengths, tokens, active, gen), None,
                length=fuse)
            return (cache, lengths, tokens, active, gen) + tuple(outs)

        exe = aot_compile(
            chunk,
            (self.params, self._cache, self._len, self._tok, self._active,
             self._gen, self._maxnew, self._temp, self._topk, self._seed),
            donate_argnums=(1,))
        self._decode_exe[fuse] = exe
        return exe

    def _get_verify_exe(self, width: int):
        """The speculative draft-verify step, compiled once per window
        width (k+1 — the dict is bounded by ``speculative.SPEC_K_CAP``).

        One windowed model forward scores every slot's window — position
        0 its pending token, positions 1..k its draft — then a scan
        replays the plain decode chunk's EXACT per-step state machine
        over the window's target draws: step j emits
        ``_sample_tokens(logits_j, ..., position=len+j)`` (the same
        keying plain decode would use at that step), advances len/gen,
        applies the same eos/max_new/max_ctx fin logic, and continues
        speculatively only while the NEXT consumed token (the draft)
        equals this step's emitted one. Equality-accept against the
        target's own position-keyed draw is exact speculative sampling
        for a deterministic drafter (serving.speculative), so both the
        greedy and the seeded-sampled stream are bit-identical to plain
        decode. A rejected tail simply never advances ``len`` — its
        KV rows sit beyond every later read mask until overwritten —
        and slots with an empty draft degrade to one plain step inside
        the same dispatch. Output shape contract matches the decode
        chunk (outs stacked [width, B]), so the host retire loop is
        shared."""
        exe = self._verify_exe.get(width)
        if exe is not None:
            return exe
        model, ops, cfg = self.model, self.cache_ops, self.cfg
        eos = -1 if cfg.eos_id is None else cfg.eos_id
        max_ctx = cfg.max_seq
        collect = cfg.collect_logits
        w = width

        def verify(params, cache, lengths, tokens, active, gen, maxnew,
                   temp, topk, seed, draft, dlen):
            b = tokens.shape[0]
            steps = jnp.arange(w, dtype=jnp.int32)
            cons = jnp.concatenate([tokens[:, None], draft], axis=1)
            posw = lengths[:, None] + steps[None, :]
            # guard every window write to the positions plain decode could
            # itself reach (step j exists iff gen+j < max_new and
            # len+j < max_ctx): beyond them the slot's page table holds
            # UNRESERVED entries (parked on page 0) and an unguarded
            # scatter would land on another slot's page
            write_mask = (active[:, None]
                          & (gen[:, None] + steps[None, :] < maxnew[:, None])
                          & (posw < max_ctx))
            logits, cache = model.verify(params, cache, ops, cons, lengths,
                                         active, write_mask)
            # the target's own draw at every window position, keyed by the
            # SAME (seed, absolute position) as plain decode — [B,W] rows
            # through the [B*W]-batched sampler are per-row identical
            tt = _sample_tokens(
                logits.reshape(b * w, -1), jnp.repeat(temp, w),
                jnp.repeat(topk, w), jnp.repeat(seed, w),
                posw.reshape(b * w)).reshape(b, w)
            # token consumed by step j+1 (draft j); dummy past the window
            nxt_cons = jnp.concatenate(
                [draft, jnp.zeros((b, 1), jnp.int32)], axis=1)

            def body(carry, xs):
                ln, tk, ac, sp, gc = carry
                if collect:
                    tj, dj, j, lg = xs
                else:
                    tj, dj, j = xs
                run = ac & sp
                nxt = jnp.where(run, tj, tk)
                emitted = run
                gc = gc + run
                ln = ln + run
                fin = run & ((nxt == eos) | (gc >= maxnew) | (ln >= max_ctx))
                ac = ac & ~fin
                sp = sp & (j < dlen) & (nxt == dj) & ~fin
                out = (nxt, emitted, fin, lg) if collect \
                    else (nxt, emitted, fin)
                return (ln, nxt, ac, sp, gc), out

            xs = (tt.T, nxt_cons.T, steps)
            if collect:
                xs = xs + (logits.transpose(1, 0, 2),)
            spec0 = jnp.ones((b,), jnp.bool_)
            (lengths, tokens, active, _, gen), outs = jax.lax.scan(
                body, (lengths, tokens, active, spec0, gen), xs)
            return (cache, lengths, tokens, active, gen) + tuple(outs)

        exe = aot_compile(
            verify,
            (self.params, self._cache, self._len, self._tok, self._active,
             self._gen, self._maxnew, self._temp, self._topk, self._seed,
             jax.ShapeDtypeStruct((cfg.slots, w - 1), jnp.int32),
             jax.ShapeDtypeStruct((cfg.slots,), jnp.int32)),
            donate_argnums=(1,))
        self._verify_exe[width] = exe
        return exe

    def _get_resume_exe(self, rbucket: int):
        """Teacher-forced prompt-remainder ingest for a prefix-cache hit:
        consume the uncached prompt tail token by token through the
        model's own decode contract (each step writes KV at its absolute
        position), then sample the first generated token from the final
        step's logits, keyed (seed, prompt_len-1) — exactly the cold
        prefill's keying, so the sampled stream is path-independent.
        Compiled once per remainder bucket, cache donated like every other
        step function."""
        exe = self._resume_exe.get(rbucket)
        if exe is not None:
            return exe
        model, ops, cfg = self.model, self.cache_ops, self.cfg
        b = cfg.slots
        vocab = self.model.cfg.vocab_size

        def resume(params, cache, toks, start, length, slot, temp, topk,
                   seed):
            slotmask = jnp.arange(b, dtype=jnp.int32) == slot
            tempv = jnp.where(slotmask, temp, 0.0).astype(jnp.float32)
            topkv = jnp.where(slotmask, topk, 0).astype(jnp.int32)
            seedv = jnp.where(slotmask, seed, 0).astype(jnp.int32)

            def body(carry, i):
                cache, tok_acc, log_acc = carry
                pos = start + i
                ac = slotmask & (pos < length)
                tkb = jnp.where(slotmask, toks[i], 0).astype(jnp.int32)
                posb = jnp.full((b,), pos, jnp.int32)
                logits, cache = model.decode(params, cache, ops, tkb,
                                             posb, ac)
                is_last = ac & (pos == length - 1)
                cand = _sample_tokens(logits, tempv, topkv, seedv, posb)
                tok_acc = tok_acc + jnp.sum(
                    jnp.where(is_last, cand, 0).astype(jnp.int32))
                log_acc = log_acc + jnp.sum(
                    jnp.where(is_last[:, None],
                              logits.astype(jnp.float32), 0.0), axis=0)
                return (cache, tok_acc, log_acc), None

            init = (cache, jnp.zeros((), jnp.int32),
                    jnp.zeros((vocab,), jnp.float32))
            (cache, tok, last), _ = jax.lax.scan(
                body, init, jnp.arange(rbucket, dtype=jnp.int32))
            return cache, tok, last

        exe = aot_compile(
            resume,
            (self.params, self._cache,
             jax.ShapeDtypeStruct((rbucket,), jnp.int32),
             jax.ShapeDtypeStruct((), jnp.int32),
             jax.ShapeDtypeStruct((), jnp.int32),
             jax.ShapeDtypeStruct((), jnp.int32),
             jax.ShapeDtypeStruct((), jnp.float32),
             jax.ShapeDtypeStruct((), jnp.int32),
             jax.ShapeDtypeStruct((), jnp.int32)),
            donate_argnums=(1,))
        self._resume_exe[rbucket] = exe
        return exe

    def warmup(self, buckets: Optional[Sequence[int]] = None) -> None:
        """Pre-compile the decode chunk + the given (default: all) prefill
        buckets — with PADDLE_TPU_COMPILE_CACHE set this both warms and
        persists the executables before traffic arrives."""
        for b in (buckets or self.cfg.prompt_buckets):
            self._get_prefill_exe(self._bucket_for(b))
        self._get_decode_exe(self.cfg.decode_fuse)
        if self._spec_capable and self.cfg.speculation > 0:
            self._get_verify_exe(self.cfg.speculation + 1)
