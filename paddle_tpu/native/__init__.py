"""Native (C++) components, built on demand with g++ and loaded via ctypes.

The reference keeps its data plane native (recordio/, buffered_reader.cc,
data_feed.cc); this package is the TPU build's native layer. Build artifacts
land next to the sources and are reused across sessions.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_LIBS = {}


def build_and_load(name: str) -> ctypes.CDLL:
    """Compile ``<name>.cc`` into ``lib<name>.so`` (if stale) and dlopen it."""
    with _LOCK:
        if name in _LIBS:
            return _LIBS[name]
        src = os.path.join(_HERE, name + ".cc")
        so = os.path.join(_HERE, "lib%s.so" % name)
        if not os.path.exists(so) or os.path.getmtime(so) < os.path.getmtime(src):
            cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", src, "-o", so]
            r = subprocess.run(cmd, capture_output=True, text=True)
            if r.returncode != 0:
                raise RuntimeError(
                    "native build failed: %s\n%s" % (" ".join(cmd), r.stderr))
        lib = ctypes.CDLL(so)
        _LIBS[name] = lib
        return lib
