// RecordIO: chunked, CRC-checked record file format + scanner.
//
// Native C++ reimplementation of the reference's recordio library
// (reference: recordio/{header,chunk,scanner,writer}.{h,cc} — Chunk
// chunk.h:27, Scanner scanner.h:26), exposed through a C ABI consumed by
// ctypes (paddle_tpu/recordio.py). Data-plane work (framing, CRC32,
// buffering) stays native; Python only moves pointers.
//
// On-disk layout per chunk:
//   u32 magic  'PTRC'
//   u32 num_records
//   u64 payload_len
//   u32 crc32(payload)
//   u32 record_len[num_records]
//   u8  payload[payload_len]   (records back to back)

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x50545243;  // 'PTRC'
constexpr size_t kDefaultChunkRecords = 1024;
constexpr size_t kDefaultChunkBytes = 1 << 20;

// CRC-32 (IEEE 802.3), table-driven.
class Crc32 {
 public:
  Crc32() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table_[i] = c;
    }
  }
  uint32_t operator()(const uint8_t* data, size_t len) const {
    uint32_t c = 0xFFFFFFFFu;
    for (size_t i = 0; i < len; ++i) c = table_[(c ^ data[i]) & 0xFF] ^ (c >> 8);
    return c ^ 0xFFFFFFFFu;
  }

 private:
  uint32_t table_[256];
};

const Crc32& crc32() {
  static Crc32 c;
  return c;
}

struct Writer {
  FILE* f = nullptr;
  std::vector<uint32_t> lens;
  std::string payload;
  size_t max_records = kDefaultChunkRecords;
  size_t max_bytes = kDefaultChunkBytes;

  bool flush() {
    if (lens.empty()) return true;
    uint32_t n = static_cast<uint32_t>(lens.size());
    uint64_t plen = payload.size();
    uint32_t crc = crc32()(reinterpret_cast<const uint8_t*>(payload.data()),
                           payload.size());
    if (fwrite(&kMagic, 4, 1, f) != 1) return false;
    if (fwrite(&n, 4, 1, f) != 1) return false;
    if (fwrite(&plen, 8, 1, f) != 1) return false;
    if (fwrite(&crc, 4, 1, f) != 1) return false;
    if (n && fwrite(lens.data(), 4, n, f) != n) return false;
    if (plen && fwrite(payload.data(), 1, plen, f) != plen) return false;
    lens.clear();
    payload.clear();
    return true;
  }
};

struct Scanner {
  FILE* f = nullptr;
  std::vector<uint32_t> lens;
  std::string payload;
  size_t rec_idx = 0;
  size_t offset = 0;
  bool corrupt = false;

  // Hard caps so a corrupt/malicious header can't drive unbounded
  // allocations: 16M records / 1 GiB payload per chunk (writer emits far
  // smaller chunks; see kDefaultChunk*).
  static constexpr uint32_t kMaxRecordsPerChunk = 1u << 24;
  static constexpr uint64_t kMaxPayloadPerChunk = 1ull << 30;

  bool load_chunk() {
    uint32_t magic = 0, n = 0, crc = 0;
    uint64_t plen = 0;
    if (fread(&magic, 4, 1, f) != 1) return false;  // clean EOF
    if (magic != kMagic) {
      corrupt = true;
      return false;
    }
    if (fread(&n, 4, 1, f) != 1 || fread(&plen, 8, 1, f) != 1 ||
        fread(&crc, 4, 1, f) != 1) {
      corrupt = true;
      return false;
    }
    if (n > kMaxRecordsPerChunk || plen > kMaxPayloadPerChunk) {
      corrupt = true;
      return false;
    }
    lens.resize(n);
    if (n && fread(lens.data(), 4, n, f) != n) {
      lens.clear();
      corrupt = true;
      return false;
    }
    // The CRC covers the payload only; the record_len table must be
    // independently consistent or a tampered table would let the scanner
    // read past payload.data() (heap over-read).
    uint64_t total = 0;
    for (uint32_t l : lens) total += l;
    if (total != plen) {
      lens.clear();
      corrupt = true;
      return false;
    }
    payload.resize(plen);
    if (plen && fread(&payload[0], 1, plen, f) != plen) {
      corrupt = true;
      return false;
    }
    uint32_t got = crc32()(reinterpret_cast<const uint8_t*>(payload.data()),
                           payload.size());
    if (got != crc) {
      corrupt = true;
      return false;
    }
    rec_idx = 0;
    offset = 0;
    return true;
  }
};

}  // namespace

extern "C" {

void* ptrio_writer_open(const char* path) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  auto* w = new Writer();
  w->f = f;
  return w;
}

int ptrio_writer_write(void* handle, const char* data, uint64_t len) {
  auto* w = static_cast<Writer*>(handle);
  w->lens.push_back(static_cast<uint32_t>(len));
  w->payload.append(data, len);
  if (w->lens.size() >= w->max_records || w->payload.size() >= w->max_bytes) {
    return w->flush() ? 0 : -1;
  }
  return 0;
}

int ptrio_writer_close(void* handle) {
  auto* w = static_cast<Writer*>(handle);
  bool ok = w->flush();
  fclose(w->f);
  delete w;
  return ok ? 0 : -1;
}

void* ptrio_scanner_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  auto* s = new Scanner();
  s->f = f;
  return s;
}

// Returns pointer to the next record (valid until the next call), sets *len.
// NULL at EOF; NULL with *len == UINT64_MAX on corruption.
const char* ptrio_scanner_next(void* handle, uint64_t* len) {
  auto* s = static_cast<Scanner*>(handle);
  if (s->corrupt) {  // terminal: never serve records after a corrupt chunk
    *len = ~0ull;
    return nullptr;
  }
  if (s->rec_idx >= s->lens.size()) {
    if (!s->load_chunk()) {
      *len = s->corrupt ? ~0ull : 0ull;
      return nullptr;
    }
  }
  uint32_t l = s->lens[s->rec_idx];
  const char* p = s->payload.data() + s->offset;
  s->offset += l;
  s->rec_idx += 1;
  *len = l;
  return p;
}

void ptrio_scanner_close(void* handle) {
  auto* s = static_cast<Scanner*>(handle);
  fclose(s->f);
  delete s;
}

}  // extern "C"
