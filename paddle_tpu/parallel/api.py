"""Sharding annotations + model-parallel layers.

This replaces two reference subsystems with one mechanism:
- the parameter-server sharded tables (``slice_variable``
  ``transpiler/distribute_transpiler.py:84``, split_ids/prefetch) → a
  row-sharded embedding Parameter on the ``model`` mesh axis; GSPMD turns
  lookups into the gather/all-to-all communication the PS runtime hand-rolled;
- model parallelism (absent in the reference, SURVEY §2.3 checklist) →
  column/row-parallel FC via weight sharding annotations.

A Variable's ``sharding`` attr is a PartitionSpec-like tuple of mesh-axis
names (or None per dim). The Executor turns it into NamedShardings for the
jitted step's state; a ``shard_constraint`` op pins activations in-graph.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

from ..core.framework import Variable
from ..core.registry import OpContext, register_op
from ..layers.layer_helper import LayerHelper, ParamAttr

__all__ = [
    "annotate_sharding",
    "get_sharding",
    "shard_constraint",
    "sharded_embedding",
    "column_parallel_fc",
    "row_parallel_fc",
]


def annotate_sharding(var: Variable, spec: Sequence[Optional[str]]) -> Variable:
    """Mark a persistable var to live sharded over mesh axes, e.g.
    ('model', None) row-shards a [V, D] table."""
    var.sharding = tuple(spec)
    return var


def get_sharding(var: Variable):
    return getattr(var, "sharding", None)


@register_op("shard_constraint")
def shard_constraint_op(ctx: OpContext):
    import jax
    from jax.sharding import PartitionSpec

    x = ctx.input("X")
    spec = PartitionSpec(*ctx.attr("spec"))
    mesh = ctx.trace.mesh if hasattr(ctx.trace, "mesh") else None
    if mesh is None:
        ctx.set_output("Out", x)
        return
    from jax.sharding import NamedSharding

    ctx.set_output("Out", jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec)))


def shard_constraint(x: Variable, spec: Sequence[Optional[str]], name=None) -> Variable:
    """In-graph activation sharding pin (jax.lax.with_sharding_constraint)."""
    helper = LayerHelper("shard_constraint", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("shard_constraint", inputs={"X": x}, outputs={"Out": out},
                     attrs={"spec": list(spec)})
    return out


def sharded_embedding(input, size, mesh_axis="model", param_attr=None,
                      dtype="float32", padding_idx=None, is_sparse=False,
                      name=None):
    """Embedding with the table row-sharded over ``mesh_axis``.

    The idiomatic replacement of the reference's distributed lookup table
    (prefetch_op + listen_and_serv sparse path): XLA partitions the gather,
    each device holds V/n rows in HBM, and the result is all-gathered over
    ICI — no parameter server.

    ``is_sparse=True`` is the CTR-scale composition (``slice_variable`` +
    trainer-side sparse prefetch in one mechanism): the gradient stays a
    rows-only ``SparseGrad``, the optimizer update runs shard-local through
    ``core.sparse.sharded_rows_update`` (ids/rows reach owner shards via
    replication or, with ``FLAGS_ctr_alltoall_update``, an explicit
    ``all_to_all`` id exchange), the Adam moments inherit the row sharding,
    and the startup initializer materializes the table shard-by-shard — so
    param AND optimizer state cost V/n rows per device and no dense [V, D]
    buffer ever exists. At V=1e8, D=10, n=8 that is ~500 MB/chip for the
    table and ~1.5 GB/chip including both Adam moments, where the
    single-device init RESOURCE_EXHAUSTs outright.
    """
    from .. import layers
    from ..core.framework import default_startup_program

    helper = LayerHelper("sharded_embedding", name=name)
    attr = ParamAttr.to_attr(param_attr)
    out = layers.embedding(input, size=size, param_attr=attr, dtype=dtype,
                           padding_idx=padding_idx, is_sparse=is_sparse,
                           name=name)
    # the embedding layer registered the Parameter; annotate its rows — and
    # its startup twin, so the init op can materialize it shard-by-shard
    # instead of building the full [V, D] array on one device
    emb_op = out.op
    w_name = emb_op.input("W")[0]
    w_var = out.block.var(w_name)
    annotate_sharding(w_var, (mesh_axis, None))
    sb = default_startup_program().global_block
    if sb.has_var(w_name):
        annotate_sharding(sb.var(w_name), (mesh_axis, None))
    return out


def column_parallel_fc(input, size, mesh_axis="model", act=None, param_attr=None,
                       bias_attr=None, num_flatten_dims=1, name=None):
    """FC with weight column-sharded: [in, out/n] per device; output stays
    sharded on its feature dim (pair with row_parallel_fc to close)."""
    from .. import layers

    out = layers.fc(input, size=size, num_flatten_dims=num_flatten_dims,
                    param_attr=param_attr, bias_attr=bias_attr, act=act, name=name)
    _annotate_fc_params(out, col_spec=(None, mesh_axis), bias_spec=(mesh_axis,))
    return out


def row_parallel_fc(input, size, mesh_axis="model", act=None, param_attr=None,
                    bias_attr=None, num_flatten_dims=1, name=None):
    """FC with weight row-sharded: [in/n, out] per device; XLA inserts the
    psum over the contracted dim."""
    from .. import layers

    out = layers.fc(input, size=size, num_flatten_dims=num_flatten_dims,
                    param_attr=param_attr, bias_attr=bias_attr, act=act, name=name)
    _annotate_fc_params(out, col_spec=(mesh_axis, None), bias_spec=(None,))
    return out


def _annotate_fc_params(out_var, col_spec, bias_spec):
    """Walk back from the fc output to its mul/elementwise_add ops and
    annotate the weight (and bias) parameters."""
    block = out_var.block
    seen = set()
    frontier = [out_var.name]
    while frontier:
        name = frontier.pop()
        if name in seen:
            continue
        seen.add(name)
        var = block._find_var_recursive(name)
        if var is None or var.op is None:
            continue
        op = var.op
        if op.type == "mul":
            w = block.var(op.input("Y")[0])
            annotate_sharding(w, col_spec)
            continue
        if op.type == "elementwise_add" and len(op.input("Y")) == 1:
            maybe_bias = block._find_var_recursive(op.input("Y")[0])
            from ..core.framework import Parameter

            if isinstance(maybe_bias, Parameter):
                annotate_sharding(maybe_bias, bias_spec)
        for slot in op.inputs.values():
            frontier.extend(slot)
