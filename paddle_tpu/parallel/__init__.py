from .api import (  # noqa: F401
    annotate_sharding,
    column_parallel_fc,
    get_sharding,
    row_parallel_fc,
    sharded_embedding,
)
from .distributed import init_distributed  # noqa: F401
from .mesh import create_mesh, get_mesh, mesh_guard  # noqa: F401
from .ring_attention import ring_attention  # noqa: F401
from .pipeline import gpipe, pipeline_step, stack_stage_params  # noqa: F401
from .moe import make_switch_ffn, switch_moe  # noqa: F401
