"""Expert parallelism: Switch-style Mixture-of-Experts over a mesh axis.

The reference has no MoE (SURVEY §3 marks EP absent); this implements the
TPU-native design directly — the GShard/Switch dispatch formulation:
top-1 routing → capacity-limited one-hot dispatch tensor → einsum
dispatch/combine, with experts sharded over an ``expert`` mesh axis inside
``shard_map`` and tokens exchanged by ``all_to_all`` over ICI. Everything is
static-shape (capacity padding, dropped-token masking) and differentiable.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["switch_moe", "make_switch_ffn"]


def _dispatch_tensors(gate_logits, capacity):
    """gate_logits [N, E] → (dispatch [N, E, C] one-hot, combine [N, E, C],
    aux_loss). Top-1 routing with per-expert capacity (Switch Transformer
    semantics: overflow tokens are dropped from the expert but pass through
    the residual path as zeros here)."""
    n, e = gate_logits.shape
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)                 # [N]
    onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)
    # position of each token within its expert's queue
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0     # [N, E], -1 elsewhere
    pos_in_expert = jnp.sum(pos * onehot, axis=1)       # [N]
    keep = pos_in_expert < capacity
    gate = jnp.sum(probs * onehot, axis=1) * keep       # [N]
    slot = jax.nn.one_hot(pos_in_expert.astype(jnp.int32), capacity,
                          dtype=jnp.float32)            # [N, C]
    dispatch = onehot[:, :, None] * slot[:, None, :] * keep[:, None, None]
    combine = dispatch * gate[:, None, None]
    # load-balancing auxiliary loss (Switch eq. 4): E * Σ_e f_e · p_e
    frac_tokens = jnp.mean(onehot, axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(frac_tokens * frac_probs)
    return dispatch, combine, aux


def switch_moe(x, gate_w, expert_params, expert_fn: Callable, mesh: Mesh,
               axis: str = "expert", capacity_factor: float = 1.25):
    """Top-1 MoE layer, expert-parallel over ``axis``.

    - x [B, T, D] (replicated across the expert axis here; compose with a
      data axis for dp×ep)
    - gate_w [D, E]
    - expert_params: pytree with leading [E, ...] axis, sharded over ``axis``
      (each device holds its experts)
    - expert_fn(params_one_expert, tokens [C, D]) -> [C, D]

    Returns (y [B, T, D], aux_loss). Differentiable; all_to_all moves only
    the capacity-packed token buffers between experts.
    """
    from jax.experimental.shard_map import shard_map

    b, t, d = x.shape
    n = b * t
    e = gate_w.shape[-1]
    n_shards = mesh.shape[axis]
    assert e % n_shards == 0, "experts must divide the expert axis"
    capacity = max(1, int(capacity_factor * n / e))

    flat = x.reshape(n, d)
    gate_logits = flat @ gate_w
    dispatch, combine, aux = _dispatch_tensors(gate_logits, capacity)
    # token buffers per expert: [E, C, D]
    expert_in = jnp.einsum("nd,nec->ecd", flat.astype(jnp.float32), dispatch)

    def shard_body(params, buf):
        # buf arrives [E/n_shards, C, D] for THIS shard's experts
        return jax.vmap(expert_fn)(params, buf)

    expert_out = shard_map(
        shard_body, mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(axis), expert_params), P(axis)),
        out_specs=P(axis), check_rep=False,
    )(expert_params, expert_in.astype(x.dtype))

    y = jnp.einsum("ecd,nec->nd", expert_out.astype(jnp.float32), combine)
    return y.reshape(b, t, d).astype(x.dtype), aux.astype(x.dtype)


def make_switch_ffn(d_model: int, d_ff: int):
    """Standard per-expert FFN for switch_moe: params [E, ...] maker + fn."""

    def init(key, n_experts):
        k1, k2 = jax.random.split(key)
        s1 = (2.0 / (d_model + d_ff)) ** 0.5
        return {
            "w1": jax.random.normal(k1, (n_experts, d_model, d_ff)) * s1,
            "w2": jax.random.normal(k2, (n_experts, d_ff, d_model)) * s1,
        }

    def fn(p, tokens):
        return jax.nn.relu(tokens @ p["w1"]) @ p["w2"]

    return init, fn
