"""Expert parallelism: Switch-style Mixture-of-Experts over a mesh axis.

The reference has no MoE (SURVEY §3 marks EP absent); this implements the
TPU-native design directly — top-1 routing with **sort-based dispatch**
(the MaxText/Praxis formulation): tokens are argsorted by their chosen
expert and scattered into capacity-packed per-expert buffers, so dispatch
memory is O(N·D + E·C·D) instead of the GShard one-hot formulation's
O(N·E·C) dispatch tensor (which dominates at real expert counts). Experts
are sharded over an ``expert`` mesh axis inside ``shard_map`` with the
packed buffers exchanged over ICI. Everything is static-shape (capacity
padding, dropped-token masking) and differentiable.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ._compat import shard_map as _shard_map

__all__ = ["switch_moe", "make_switch_ffn"]


def switch_moe(x, gate_w, expert_params, expert_fn: Callable, mesh: Mesh,
               axis: str = "expert", capacity_factor: float = 1.25):
    """Top-1 MoE layer, expert-parallel over ``axis``.

    - x [B, T, D] (replicated across the expert axis here; compose with a
      data axis for dp×ep)
    - gate_w [D, E]
    - expert_params: pytree with leading [E, ...] axis, sharded over ``axis``
      (each device holds its experts)
    - expert_fn(params_one_expert, tokens [C, D]) -> [C, D]

    Returns (y [B, T, D], aux_loss). Switch semantics: overflow tokens
    beyond an expert's capacity are dropped (pass through as zeros).
    Differentiable; only the capacity-packed [E, C, D] buffers move
    between experts.
    """
    b, t, d = x.shape
    n = b * t
    e = gate_w.shape[-1]
    n_shards = mesh.shape[axis]
    assert e % n_shards == 0, "experts must divide the expert axis"
    capacity = max(1, int(capacity_factor * n / e))

    flat = x.reshape(n, d)
    gate_logits = flat @ gate_w
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)                  # [N]
    gate = jnp.max(probs, axis=-1)                       # prob of chosen expert

    # sort-based dispatch: group tokens by expert, position within group
    order = jnp.argsort(expert)                          # stable
    sorted_expert = expert[order]
    counts = jnp.bincount(expert, length=e)              # [E]
    starts = jnp.cumsum(counts) - counts
    pos = jnp.arange(n) - starts[sorted_expert]          # rank inside expert
    keep = pos < capacity
    # dropped tokens target a dummy row that is sliced off (zero cotangent)
    slot = jnp.where(keep, sorted_expert * capacity + pos, e * capacity)
    vals = flat[order] * keep[:, None].astype(x.dtype)
    buf = jnp.zeros((e * capacity + 1, d), x.dtype).at[slot].set(vals)
    expert_in = buf[:-1].reshape(e, capacity, d)

    def shard_body(params, buf_):
        # buf_ arrives [E/n_shards, C, D] for THIS shard's experts
        return jax.vmap(expert_fn)(params, buf_)

    expert_out = _shard_map(
        shard_body, mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(axis), expert_params), P(axis)),
        out_specs=P(axis),
    )(expert_params, expert_in)

    # combine: gather each token's expert output, weight by its gate prob
    out_flat = expert_out.reshape(e * capacity, d)
    safe_slot = jnp.clip(slot, 0, e * capacity - 1)
    gathered = out_flat[safe_slot] * keep[:, None].astype(x.dtype)
    y_sorted = gathered * (gate[order].astype(x.dtype))[:, None]
    inv = jnp.argsort(order)
    y = y_sorted[inv]

    # load-balancing auxiliary loss (Switch eq. 4): E * Σ_e f_e · p_e
    frac_tokens = counts.astype(jnp.float32) / n
    frac_probs = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(frac_tokens * frac_probs)
    return y.reshape(b, t, d).astype(x.dtype), aux.astype(x.dtype)


def make_switch_ffn(d_model: int, d_ff: int):
    """Standard per-expert FFN for switch_moe: params [E, ...] maker + fn."""

    def init(key, n_experts):
        k1, k2 = jax.random.split(key)
        s1 = (2.0 / (d_model + d_ff)) ** 0.5
        return {
            "w1": jax.random.normal(k1, (n_experts, d_model, d_ff)) * s1,
            "w2": jax.random.normal(k2, (n_experts, d_ff, d_model)) * s1,
        }

    def fn(p, tokens):
        return jax.nn.relu(tokens @ p["w1"]) @ p["w2"]

    return init, fn
