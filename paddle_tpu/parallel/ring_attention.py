"""Ring attention — sequence/context parallelism over the mesh ``sp`` axis.

The reference has no sequence parallelism (SURVEY §5.7: LoD is its only long-
sequence story). This is the TPU-native long-context design: the sequence
dim is sharded across devices; each device computes attention for its Q shard
while K/V blocks rotate around the ICI ring via ``lax.ppermute``, merging
per-block results with streaming (online) softmax — memory per device is
O(S/n · S/n) per step instead of O(S²), and comm overlaps compute around the
ring. Differentiable (lax.scan carries, not while_loop), so it is the
training path for long sequences.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..core.registry import OpContext, register_op
from ..monitor.device import record_collective as _record_collective

__all__ = ["ring_attention"]

_NEG_INF = -1e30


# --------------------------------------------------------------------------
# Ring + flash composition (round 4).
#
# The ring's per-step block computation is itself an attention over
# [B, H, S_local, S_local]; with the v5e-tuned Pallas flash kernel winning
# from S~2048 (ops/attention_ops.py), the block compute should ride it too.
# Structure: an FA2-style blockwise attention with a custom VJP —
#   fwd: each ring step computes a NORMALIZED block output plus its
#        softmax stats (l, m) via the Pallas kernel's save_residuals path,
#        merged into running (acc, l, m) by online softmax; K/V rotate via
#        ppermute. Saves (q, k, v, out, lse) — O(S_local) residuals.
#   bwd: a second ring pass; per block the FA2 backward with the GLOBAL
#        lse (the Pallas bwd kernels compute p = exp(logits - m)/l, so
#        passing m=lse, l=1 yields exact global probabilities). dK/dV
#        accumulators travel around the ring WITH their block and arrive
#        home after n steps; dQ accumulates locally.
# Off-TPU (CPU tests, dryrun) the same ring/merge/backward code runs with a
# composed per-block reference, so the sp=4 math is testable on the
# virtual CPU mesh while the kernel path is exercised on real hardware.
# --------------------------------------------------------------------------


def _block_sizes_for(s_loc: int):
    from ..ops.attention_ops import _pick_block

    try:
        return _pick_block(s_loc)
    except ValueError:
        return None


@functools.lru_cache(maxsize=1)
def _ring_flash_available() -> bool:
    """The block kernels are vendored into ops/pallas_kernels/flash_attention
    .py (project-owned since r5 — a JAX upgrade can no longer change their
    semantics under us); this only checks that Pallas itself imports. TPU
    parity of the flash vs composed block paths is asserted by
    tests/test_ring_flash_parity.py."""
    import warnings

    try:
        from ..ops.pallas_kernels import flash_attention  # noqa: F401

        return True
    except Exception as e:  # pragma: no cover - pallas unavailable
        warnings.warn(
            "ring attention: Pallas flash block kernels unavailable (%s); "
            "using the composed block path" % e,
            RuntimeWarning, stacklevel=2)
        return False


def _use_flash_blocks(q, s_loc: int) -> bool:
    from ..flags import get_flag
    from ..ops.attention_ops import _flash_fn, _on_tpu

    if _flash_fn()[0] is None or not _on_tpu():
        return False
    if q.dtype not in (jnp.float32, jnp.bfloat16):
        return False
    if _block_sizes_for(s_loc) is None:
        return False
    if s_loc < int(get_flag("ring_flash_min_block")):
        return False
    return _ring_flash_available()


def _block_fwd_flash(q, k_blk, v_blk, causal, sm_scale):
    """Pallas flash over one block pair; returns (o_normalized, l, m)."""
    from ..ops.pallas_kernels import flash_attention as fa

    bq = _block_sizes_for(q.shape[2])
    bk = _block_sizes_for(k_blk.shape[2])
    return fa._flash_attention_impl(
        q, k_blk, v_blk, None, None, True, causal, sm_scale, 1, bq, bk, bk,
        False)


def _block_fwd_ref(q, k_blk, v_blk, causal, sm_scale):
    """Composed-reference block attention with the same (o, l, m) contract."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k_blk.astype(jnp.float32)) * sm_scale
    if causal:
        sl = s.shape[-1]
        cm = jnp.tril(jnp.ones((sl, sl), bool))
        s = jnp.where(cm, s, _NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32))
    o = o / jnp.maximum(l[..., None], 1e-30)
    return o.astype(q.dtype), l, m


def _block_bwd_flash(q, k_blk, v_blk, lse, do, di, causal, sm_scale):
    """Pallas FA2 block backward with global lse; returns (dq, dk, dv)."""
    from ..ops.pallas_kernels import flash_attention as fa

    bq = _block_sizes_for(q.shape[2])
    bk = _block_sizes_for(k_blk.shape[2])
    ones = jnp.ones_like(lse)
    dk, dv = fa._flash_attention_bwd_dkv(
        q, k_blk, v_blk, None, None, ones, lse, do, di,
        block_q_major=bq, block_q=bq, block_k_major=bk, block_k=bk,
        sm_scale=sm_scale, causal=causal,
        mask_value=fa.DEFAULT_MASK_VALUE, debug=False)
    dq, _ = fa._flash_attention_bwd_dq(
        q, k_blk, v_blk, None, None, ones, lse, do, di,
        block_q_major=bq, block_k_major=bk, block_k=bk,
        sm_scale=sm_scale, causal=causal,
        mask_value=fa.DEFAULT_MASK_VALUE, debug=False)
    return dq, dk, dv


def _block_bwd_ref(q, k_blk, v_blk, lse, do, di, causal, sm_scale):
    """Composed-reference FA2 block backward (p = exp(scaled logits - lse))."""
    qf = q.astype(jnp.float32)
    kf = k_blk.astype(jnp.float32)
    vf = v_blk.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * sm_scale
    if causal:
        sl = s.shape[-1]
        cm = jnp.tril(jnp.ones((sl, sl), bool))
        s = jnp.where(cm, s, _NEG_INF)
    p = jnp.exp(s - lse[..., None])
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
    dp = jnp.einsum("bhqd,bhkd->bhqk", dof, vf)
    ds = p * (dp - di[..., None]) * sm_scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, kf)
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
    # stay f32: per-block contributions feed the ring's f32 accumulators
    # (a bf16 round-trip per block would grow error ~sqrt(n_blocks))
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _ring_blockwise(axis_name, causal, sm_scale, use_flash, q, k, v):
    out, _ = _ring_blockwise_fwd(axis_name, causal, sm_scale, use_flash,
                                 q, k, v)
    return out


def _ring_blockwise_fwd(axis_name, causal, sm_scale, use_flash, q, k, v):
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]
    block_fwd = _block_fwd_flash if use_flash else _block_fwd_ref

    def full_blk(k_blk, v_blk):
        return block_fwd(q, k_blk, v_blk, False, sm_scale)

    def diag_blk(k_blk, v_blk):
        return block_fwd(q, k_blk, v_blk, True, sm_scale)

    def skip_blk(k_blk, v_blk):
        return (jnp.zeros_like(q), jnp.zeros((b, h, s_loc), jnp.float32),
                jnp.full((b, h, s_loc), _NEG_INF, jnp.float32))

    def step(carry, i):
        k_blk, v_blk, acc, l, m = carry
        src = (my - i) % n
        if causal:
            idx = jnp.where(src == my, 1, jnp.where(src < my, 0, 2))
            o_b, l_b, m_b = lax.switch(idx, (full_blk, diag_blk, skip_blk),
                                       k_blk, v_blk)
        else:
            o_b, l_b, m_b = full_blk(k_blk, v_blk)
        m_new = jnp.maximum(m, m_b)
        a = l * jnp.exp(m - m_new)
        bb = l_b * jnp.exp(m_b - m_new)
        acc = acc * a[..., None] / jnp.maximum(a + bb, 1e-30)[..., None] \
            + o_b.astype(jnp.float32) * (bb / jnp.maximum(a + bb, 1e-30))[..., None]
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return (k_next, v_next, acc, a + bb, m_new), None

    # byte accounting for the scan-body rotations: 2 buffers x n hops/step
    _record_collective("ppermute", axis_name, k, per_step_calls=n)
    _record_collective("ppermute", axis_name, v, per_step_calls=n)

    acc0 = jnp.zeros(q.shape[:3] + (v.shape[-1],), jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)
    m0 = jnp.full((b, h, s_loc), _NEG_INF, jnp.float32)
    (kf, vf, acc, l, m), _ = lax.scan(step, (k, v, acc0, l0, m0),
                                      jnp.arange(n))
    out = acc.astype(q.dtype)
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    return out, (q, k, v, out, lse)


def _ring_blockwise_bwd(axis_name, causal, sm_scale, use_flash, res, do):
    q, k, v, out, lse = res
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    block_bwd = _block_bwd_flash if use_flash else _block_bwd_ref
    di = jnp.sum(out.astype(jnp.float32) * do.astype(jnp.float32), axis=-1)
    do = do.astype(q.dtype)

    def full_blk(k_blk, v_blk):
        # f32 on every branch: the switch requires matching dtypes and the
        # ring accumulators are f32 (flash bwd kernels emit input dtype)
        return tuple(x.astype(jnp.float32) for x in
                     block_bwd(q, k_blk, v_blk, lse, do, di, False, sm_scale))

    def diag_blk(k_blk, v_blk):
        return tuple(x.astype(jnp.float32) for x in
                     block_bwd(q, k_blk, v_blk, lse, do, di, True, sm_scale))

    def skip_blk(k_blk, v_blk):
        return (jnp.zeros(q.shape, jnp.float32),
                jnp.zeros(k_blk.shape, jnp.float32),
                jnp.zeros(v_blk.shape, jnp.float32))

    def step(carry, i):
        k_blk, v_blk, dk_blk, dv_blk, dq_acc = carry
        src = (my - i) % n
        if causal:
            idx = jnp.where(src == my, 1, jnp.where(src < my, 0, 2))
            dq_b, dk_b, dv_b = lax.switch(idx, (full_blk, diag_blk, skip_blk),
                                          k_blk, v_blk)
        else:
            dq_b, dk_b, dv_b = full_blk(k_blk, v_blk)
        dq_acc = dq_acc + dq_b
        dk_blk = dk_blk + dk_b
        dv_blk = dv_blk + dv_b
        # dK/dV accumulators travel with their block; after n rotations the
        # fully-summed gradients are back on the block's home device.
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        dk_next = lax.ppermute(dk_blk, axis_name, perm)
        dv_next = lax.ppermute(dv_blk, axis_name, perm)
        return (k_next, v_next, dk_next, dv_next, dq_acc), None

    # bwd ring rotates K/V (input dtype) and travels the dK/dV
    # accumulators (f32) — 4 buffers x n hops/step
    _record_collective("ppermute", axis_name, k, per_step_calls=n)
    _record_collective("ppermute", axis_name, v, per_step_calls=n)
    _record_collective("ppermute", axis_name,
                       jax.ShapeDtypeStruct(k.shape, jnp.float32),
                       per_step_calls=n)
    _record_collective("ppermute", axis_name,
                       jax.ShapeDtypeStruct(v.shape, jnp.float32),
                       per_step_calls=n)

    dk0 = jnp.zeros(k.shape, jnp.float32)
    dv0 = jnp.zeros(v.shape, jnp.float32)
    dq0 = jnp.zeros(q.shape, jnp.float32)
    (kf, vf, dk, dv, dq), _ = lax.scan(step, (k, v, dk0, dv0, dq0),
                                       jnp.arange(n))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_blockwise.defvjp(_ring_blockwise_fwd, _ring_blockwise_bwd)


def ring_attention(q, k, v, mesh: Mesh, *, axis_name: str = "sp",
                   causal: bool = False, sm_scale: float = 1.0,
                   batch_axis: Optional[str] = None):
    """Sequence-parallel attention over logically-global [B, H, S, D] arrays
    whose S dim is sharded on ``axis_name``. Call under jit with the mesh.

    Per-block compute rides the v5e-tuned Pallas flash kernel when the local
    shard qualifies (TPU, S_local >= FLAGS_ring_flash_min_block, 128-aligned)
    and the composed reference otherwise — both through the same FA2-style
    custom-VJP ring, so backward memory is O(S_local) residuals either way
    (the pre-r4 autodiff-through-scan path saved per-step score blocks)."""
    from ._compat import shard_map

    if batch_axis is None:
        batch_axis = "data" if "data" in mesh.axis_names else None
    spec = P(batch_axis, None, axis_name, None)
    n = mesh.shape[axis_name]
    s_loc = q.shape[2] // max(n, 1)
    use_flash = _use_flash_blocks(q, s_loc)
    fn = functools.partial(_ring_blockwise, axis_name, causal, sm_scale,
                           use_flash)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec)(q, k, v)


@register_op("ring_attention")
def ring_attention_op(ctx: OpContext):
    """Graph-level op: uses the trace mesh's ``sp`` axis; falls back to the
    fused single-device attention when no sp axis is available."""
    q, k, v = ctx.input("Q"), ctx.input("K"), ctx.input("V")
    causal = ctx.attr("causal", False)
    sm_scale = ctx.attr("sm_scale", 1.0)
    mesh = getattr(ctx.trace, "mesh", None)
    if mesh is None or "sp" not in mesh.axis_names:
        from ..ops.attention_ops import sdpa

        ctx.set_output("Out", sdpa(q, k, v, causal=causal, sm_scale=sm_scale))
        return
    ctx.set_output("Out", ring_attention(q, k, v, mesh, causal=causal,
                                         sm_scale=sm_scale))
