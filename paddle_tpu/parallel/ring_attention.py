"""Ring attention — sequence/context parallelism over the mesh ``sp`` axis.

The reference has no sequence parallelism (SURVEY §5.7: LoD is its only long-
sequence story). This is the TPU-native long-context design: the sequence
dim is sharded across devices; each device computes attention for its Q shard
while K/V blocks rotate around the ICI ring via ``lax.ppermute``, merging
per-block results with streaming (online) softmax — memory per device is
O(S/n · S/n) per step instead of O(S²), and comm overlaps compute around the
ring. Differentiable (lax.scan carries, not while_loop), so it is the
training path for long sequences.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..core.registry import OpContext, register_op

__all__ = ["ring_attention"]

_NEG_INF = -1e30


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool, sm_scale: float):
    """Per-device body under shard_map. q/k/v: [B, H, S_local, D] shards."""
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    s_local = q.shape[2]

    qf = q.astype(jnp.float32) * sm_scale
    pos_q = my_idx * s_local + jnp.arange(s_local)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, i):
        k_blk, v_blk, o, m, l = carry
        src_block = (my_idx - i) % n
        scores = jnp.einsum("bhqd,bhkd->bhqk", qf, k_blk.astype(jnp.float32))
        if causal:
            pos_k = src_block * s_local + jnp.arange(s_local)
            mask = pos_k[None, None, None, :] <= pos_q[None, None, :, None]
            scores = jnp.where(mask, scores, _NEG_INF)
        blk_max = jnp.max(scores, axis=-1)
        new_m = jnp.maximum(m, blk_max)
        # rescale the running accumulators to the new max
        alpha = jnp.exp(m - new_m)
        p = jnp.exp(scores - new_m[..., None])
        new_l = l * alpha + jnp.sum(p, axis=-1)
        new_o = o * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32))
        # rotate K/V to the next device on the ring
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_next, v_next, new_o, new_m, new_l), None

    o0 = jnp.zeros(q.shape[:3] + (v.shape[-1],), jnp.float32)
    m0 = jnp.full(q.shape[:3], _NEG_INF, jnp.float32)
    l0 = jnp.zeros(q.shape[:3], jnp.float32)
    (kf, vf, o, m, l), _ = jax.lax.scan(
        step, (k, v, o0, m0, l0), jnp.arange(n))
    out = o / jnp.maximum(l[..., None], 1e-30)
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, *, axis_name: str = "sp",
                   causal: bool = False, sm_scale: float = 1.0,
                   batch_axis: Optional[str] = None):
    """Sequence-parallel attention over logically-global [B, H, S, D] arrays
    whose S dim is sharded on ``axis_name``. Call under jit with the mesh."""
    shard_map = jax.shard_map

    if batch_axis is None:
        batch_axis = "data" if "data" in mesh.axis_names else None
    spec = P(batch_axis, None, axis_name, None)
    fn = functools.partial(
        _ring_attention_local, axis_name=axis_name, causal=causal,
        sm_scale=sm_scale)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)


@register_op("ring_attention")
def ring_attention_op(ctx: OpContext):
    """Graph-level op: uses the trace mesh's ``sp`` axis; falls back to the
    fused single-device attention when no sp axis is available."""
    q, k, v = ctx.input("Q"), ctx.input("K"), ctx.input("V")
    causal = ctx.attr("causal", False)
    sm_scale = ctx.attr("sm_scale", 1.0)
    mesh = getattr(ctx.trace, "mesh", None)
    if mesh is None or "sp" not in mesh.axis_names:
        from ..ops.attention_ops import sdpa

        ctx.set_output("Out", sdpa(q, k, v, causal=causal, sm_scale=sm_scale))
        return
    ctx.set_output("Out", ring_attention(q, k, v, mesh, causal=causal,
                                         sm_scale=sm_scale))
