"""Multi-host initialization.

Replaces the reference's NCCL2 bootstrap (``gen_nccl_id_op.cc:31`` —
trainer0 generates an ncclUniqueId and RPC-broadcasts it) and the
``PADDLE_TRAINER_*`` env protocol (``distribute_transpiler.py``): one call to
``jax.distributed.initialize`` and every host joins the same global device
mesh; the same pjit program then spans ICI within a slice and DCN across
slices with no further code changes.
"""

from __future__ import annotations

import os
from typing import Optional

__all__ = ["init_distributed"]


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids=None,
):
    """Initialize multi-host JAX. Args default from the reference's env
    protocol (PADDLE_TRAINER_ENDPOINTS / PADDLE_TRAINERS_NUM /
    PADDLE_TRAINER_ID) so reference launch scripts keep working."""
    import jax

    if coordinator_address is None:
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        coordinator_address = eps.split(",")[0] if eps else None
    if num_processes is None:
        num_processes = int(os.environ.get("PADDLE_TRAINERS_NUM", "0")) or None
    if process_id is None:
        pid = os.environ.get("PADDLE_TRAINER_ID")
        process_id = int(pid) if pid is not None else None
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    return jax.process_index(), jax.process_count()
