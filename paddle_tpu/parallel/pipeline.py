"""Pipeline parallelism over a mesh axis (SURVEY §2 component: the
reference's pipeline trainer — paddle/fluid/framework/device_worker
section-program pipeline; reimagined TPU-first).

Design (the collective-pipelining recipe from the public scaling
literature): stages are laid out along a ``pipe`` mesh axis; a GPipe
schedule runs M microbatches through S stages in M+S-1 ticks inside a
``lax.fori_loop``, rotating activations between neighbouring stages with
``lax.ppermute`` over ICI. The whole schedule — including the bubble — is
one compiled XLA computation, and the *backward* pipeline schedule falls
out of JAX AD transposing the loop (ppermute transposes to the reverse
rotation), so there is no hand-written 1F1B scheduler.

Stage parameters live stacked on a leading [S, ...] axis sharded over
``pipe`` — each device holds only its own stage's weights (the memory win
that motivates pipeline parallelism).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["gpipe", "pipeline_step", "stack_stage_params"]


def stack_stage_params(per_stage_params):
    """[pytree per stage] → single pytree with leading stage axis [S, ...]."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def gpipe(stage_fn: Callable, mesh: Mesh, axis: str = "pipe"):
    """Build a pipelined forward: ``fn(stacked_params, microbatches)``.

    - ``stage_fn(params, x) -> y`` — one stage; activations must keep one
      shape across stages (standard for transformer blocks).
    - ``stacked_params``: leading [S] axis (see stack_stage_params).
    - ``microbatches``: [M, mb, ...] — the caller's batch split into M
      microbatches.

    Returns outputs [M, mb, ...], replicated (the last stage's results are
    broadcast back so the loss is computable everywhere). Differentiable.
    """
    s = mesh.shape[axis]
    from jax.experimental.shard_map import shard_map

    def shard_body(params, x_mb):
        # params: this device's stage slice, leading dim 1 — drop it
        params = jax.tree.map(lambda p: p[0], params)
        idx = jax.lax.axis_index(axis)
        m = x_mb.shape[0]
        ticks = m + s - 1
        out0 = jnp.zeros_like(x_mb)
        recv0 = jnp.zeros_like(x_mb[0])
        fwd_perm = [(i, i + 1) for i in range(s - 1)]

        def tick(t, carry):
            recv, out = carry
            mb_idx = t - idx
            active = (mb_idx >= 0) & (mb_idx < m)
            inp = jnp.where(idx == 0, x_mb[jnp.clip(t, 0, m - 1)], recv)
            y = stage_fn(params, inp)
            y = jnp.where(active, y, jnp.zeros_like(y))
            write = (idx == s - 1) & active
            slot = jnp.clip(mb_idx, 0, m - 1)
            out = out.at[slot].set(jnp.where(write, y, out[slot]))
            recv = jax.lax.ppermute(y, axis, fwd_perm)
            return recv, out

        _, out = jax.lax.fori_loop(0, ticks, tick, (recv0, out0))
        # broadcast the last stage's outputs to every pipe position so the
        # caller can compute the loss anywhere: all-reduce of the masked
        # buffer (only stage S-1 holds nonzeros)
        out = jnp.where(idx == s - 1, out, jnp.zeros_like(out))
        return jax.lax.psum(out, axis)

    def fn(stacked_params, microbatches):
        in_specs = (
            jax.tree.map(lambda _: P(axis), stacked_params),
            P(),  # microbatches replicated; stage 0 reads them
        )
        return shard_map(
            shard_body, mesh=mesh, in_specs=in_specs, out_specs=P(),
            check_rep=False,
        )(stacked_params, microbatches)

    return fn


def pipeline_step(stage_fn: Callable, loss_fn: Callable, mesh: Mesh,
                  axis: str = "pipe"):
    """Training-step builder: returns ``step(stacked_params, microbatches,
    labels_mb) -> (loss, grads)`` with the full fwd+bwd pipeline compiled as
    one XLA program."""
    fwd = gpipe(stage_fn, mesh, axis)

    def step(stacked_params, microbatches, labels_mb):
        def total_loss(p):
            outs = fwd(p, microbatches)
            return loss_fn(outs, labels_mb)

        return jax.value_and_grad(total_loss)(stacked_params)

    return step
