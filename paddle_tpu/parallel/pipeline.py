"""Pipeline parallelism over a mesh axis (SURVEY §2 component: the
reference's pipeline trainer — paddle/fluid/framework/device_worker
section-program pipeline; reimagined TPU-first).

Design (the collective-pipelining recipe from the public scaling
literature): stages are laid out along a ``pipe`` mesh axis; a GPipe
schedule runs M microbatches through S stages in M+S-1 ticks, rotating
activations between neighbouring stages with ``lax.ppermute`` over ICI.
The tick loop is unrolled at trace time so the feed/collect permutes have
static source/destination pairs. The whole schedule — including the
bubble — is one compiled XLA computation, and the *backward* pipeline
schedule falls out of JAX AD transposing the permutes, so there is no
hand-written 1F1B scheduler.

Memory layout (the point of pipeline parallelism):
  - stage params: stacked [S, ...], sharded over ``pipe`` — each device
    holds only its own stage's weights;
  - microbatches [M, mb, ...]: sharded over ``pipe`` on the M axis — each
    device stores M/S microbatches, feeding stage 0 one microbatch per
    tick via a single-pair ppermute (an mb-sized ICI hop);
  - outputs: collected back to the same [M/S per device] layout; at no
    tick does any device hold more than its input slab + one in-flight
    microbatch activation.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..monitor.device import record_collective as _record_collective
from ._compat import shard_map as _shard_map

__all__ = ["gpipe", "pipeline_step", "stack_stage_params"]


def stack_stage_params(per_stage_params):
    """[pytree per stage] → single pytree with leading stage axis [S, ...]."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def gpipe(stage_fn: Callable, mesh: Mesh, axis: str = "pipe"):
    """Build a pipelined forward: ``fn(stacked_params, microbatches)``.

    - ``stage_fn(params, x) -> y`` — one stage; activations must keep one
      shape across stages (standard for transformer blocks).
    - ``stacked_params``: leading [S] axis (see stack_stage_params).
    - ``microbatches``: [M, mb, ...] with M divisible by the pipe size —
      sharded over the pipe axis (a replicated array is resharded by GSPMD
      on entry).

    Returns outputs [M, mb, ...], sharded over the pipe axis on the M dim.
    Differentiable.

    Compile-time note: the tick loop is unrolled at trace time (static
    ppermute pairs are what let the feed/collect hops be single-pair ICI
    sends), so the traced graph holds M+S-1 copies of ``stage_fn`` forward
    — and its AD transpose again in the backward. Compile time and HLO
    size scale linearly with microbatch count; past a few dozen
    microbatches prefer fewer, larger microbatches (the bubble fraction
    (S-1)/(M+S-1) has diminishing returns in M anyway). A warning fires at
    trace time beyond ~64 ticks.
    """
    s = mesh.shape[axis]

    def shard_body(params, x_loc):
        # params: this device's stage slice, leading dim 1 — drop it.
        # x_loc: [M/S, mb, ...] — this device's slab of microbatches.
        params = jax.tree.map(lambda p: p[0], params)
        idx = jax.lax.axis_index(axis)
        mloc = x_loc.shape[0]
        m = mloc * s
        ticks = m + s - 1
        if ticks > 64:
            import warnings

            warnings.warn(
                "gpipe: %d microbatches over %d stages unrolls %d copies of "
                "stage_fn into the traced graph (plus transposes in the "
                "backward) — expect slow compiles; prefer fewer, larger "
                "microbatches" % (m, s, ticks), stacklevel=3)
        out = jnp.zeros_like(x_loc)
        recv = jnp.zeros_like(x_loc[0])
        fwd_perm = [(i, i + 1) for i in range(s - 1)]

        # Unrolled schedule: tick t processes microbatch t-stage on each
        # stage. Static t makes the feed/collect ppermute pairs static.
        for t in range(ticks):
            if t < m:
                owner, loc = divmod(t, mloc)
                feed = x_loc[loc]
                if owner != 0:
                    # owner ships microbatch t to stage 0 (mb-sized ICI hop)
                    _record_collective("ppermute", axis, feed)
                    feed = jax.lax.ppermute(feed, axis, [(owner, 0)])
            else:
                feed = jnp.zeros_like(recv)
            inp = jnp.where(idx == 0, feed, recv)
            y = stage_fn(params, inp)
            mb_idx = t - idx
            active = (mb_idx >= 0) & (mb_idx < m)
            y = jnp.where(active, y, jnp.zeros_like(y))
            done = t - (s - 1)  # microbatch finishing at the last stage
            if done >= 0:
                owner_out, loc_out = divmod(done, mloc)
                w = y
                if owner_out != s - 1:
                    _record_collective("ppermute", axis, w)
                    w = jax.lax.ppermute(w, axis, [(s - 1, owner_out)])
                out = out.at[loc_out].set(
                    jnp.where(idx == owner_out, w, out[loc_out]))
            if t < ticks - 1:
                # the unrolled tick loop traces each hop separately, so the
                # collectives/ppermute counters sum to the true per-step total
                _record_collective("ppermute", axis, y)
                recv = jax.lax.ppermute(y, axis, fwd_perm)
        return out

    def fn(stacked_params, microbatches):
        m = microbatches.shape[0]
        mpad = -(-m // s) * s
        if mpad != m:  # ragged M: zero microbatches ride the bubble, sliced off
            pad = [(0, mpad - m)] + [(0, 0)] * (microbatches.ndim - 1)
            microbatches = jnp.pad(microbatches, pad)
        in_specs = (
            jax.tree.map(lambda _: P(axis), stacked_params),
            P(axis),  # microbatch slabs live with their owner stage
        )
        out = _shard_map(
            shard_body, mesh=mesh, in_specs=in_specs, out_specs=P(axis),
        )(stacked_params, microbatches)
        return out[:m] if mpad != m else out

    return fn


def pipeline_step(stage_fn: Callable, loss_fn: Callable, mesh: Mesh,
                  axis: str = "pipe"):
    """Training-step builder: returns ``step(stacked_params, microbatches,
    labels_mb) -> (loss, grads)`` with the full fwd+bwd pipeline compiled as
    one XLA program."""
    fwd = gpipe(stage_fn, mesh, axis)

    def step(stacked_params, microbatches, labels_mb):
        def total_loss(p):
            outs = fwd(p, microbatches)
            return loss_fn(outs, labels_mb)

        return jax.value_and_grad(total_loss)(stacked_params)

    return step
