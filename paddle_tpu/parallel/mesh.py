"""Device mesh management.

The TPU-native replacement of the reference's device lists + NCCLContextMap
(``platform/nccl_helper.h:86``): a named ``jax.sharding.Mesh`` whose axes
carry the parallelism meaning (``data``, ``model``, ...). Collectives are
inserted by XLA/GSPMD from sharding annotations; there is no communicator
bootstrap — multi-host joins the same mesh after ``init_distributed``.
"""

from __future__ import annotations

import contextlib
from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["create_mesh", "get_mesh", "mesh_guard"]

_current_mesh: Optional[Mesh] = None


def create_mesh(axes: Dict[str, int], devices=None) -> Mesh:
    """create_mesh({'data': 4, 'model': 2}) → 2D mesh over the first 8 devices.

    An axis size of -1 means "all remaining devices".
    """
    devices = list(devices if devices is not None else jax.devices())
    names = list(axes)
    sizes = list(axes.values())
    n_fixed = int(np.prod([s for s in sizes if s != -1]))
    for i, s in enumerate(sizes):
        if s == -1:
            sizes[i] = len(devices) // n_fixed
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError("mesh %s needs %d devices, have %d" % (axes, total, len(devices)))
    grid = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(grid, axis_names=tuple(names))


def get_mesh() -> Optional[Mesh]:
    return _current_mesh


@contextlib.contextmanager
def mesh_guard(mesh: Mesh):
    global _current_mesh
    prev, _current_mesh = _current_mesh, mesh
    try:
        yield mesh
    finally:
        _current_mesh = prev
