"""shard_map across JAX versions.

Newer JAX exposes ``jax.shard_map`` whose replication-check kwarg is
``check_vma``; older releases have ``jax.experimental.shard_map.shard_map``
with ``check_rep``. Every shard_map call site in the package routes through
:func:`shard_map` so the whole multi-chip surface (pipeline, MoE, ring
attention, sharded sparse updates) works on either.
"""

from __future__ import annotations

try:  # jax >= 0.6 exposes shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map as _shard_map

__all__ = ["shard_map"]


def shard_map(f, mesh, in_specs, out_specs, check=False):
    """``shard_map`` with the replication check disabled, any JAX version."""
    try:
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=check)
    except TypeError:
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check)
