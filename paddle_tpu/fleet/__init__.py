"""paddle_tpu.fleet — fleet serving: a health-aware router over N engine
replicas.

The layer above one ``serving.ServingEngine``: a bounded-queue router
(:class:`~.router.Router`) dispatching over N replicas — in-process
engines for tests/benches, ``python -m paddle_tpu.fleet.worker``
subprocesses speaking the length-prefixed frame protocol in production
shape — with health-aware routing, session/prefix affinity, a bounded
LRU prefix cache of prefilled KV pages, kill-tolerant exactly-once
request accounting, and per-replica telemetry aggregated into one fleet
snapshot — plus the fleet observability plane: cross-process
distributed tracing with clock-aligned merge (:mod:`.trace`,
tools/fleet_trace.py), two-scope SLO evaluation over the telemetry
rings (:mod:`.slo`), the run-stamped fleet event journal
(:mod:`.events`), and the request autopsy plane (:mod:`.autopsy` +
``serving.phases``): per-request phase ledgers derived from the merged
span stream, ``fleet/phase/*`` latency budgets, and automatic
SLO-breach root-cause verdicts (tools/fleet_autopsy.py). See ROADMAP
item 2, tools/fleet_bench.py and tools/fleet_top.py.
"""

from . import metrics  # registers every fleet/* instrument
from .autopsy import (BreachAutopsy, autopsy_breaches, build_ledgers,
                      phase_stats, run_autopsy)
from .events import FleetEventLog, read_events
from .prefix_cache import PrefixCache, PrefixEntry, prefix_key
from .protocol import FrameReader, read_frame, send_frame
from .replica import (InProcessReplica, ProcessReplica, SimConfig,
                      SimEngine, sim_token)
from .router import (FleetBackpressure, FleetConfig, FleetRequest, Router,
                     aggregate_telemetry)
from .slo import FleetSLO, fleet_slos_from_env, merge_fleet_docs
from .trace import (close_orphans, fleet_request_spans, load_fragments,
                    validate_fleet_spans)

__all__ = [
    "Router", "FleetConfig", "FleetRequest", "FleetBackpressure",
    "aggregate_telemetry",
    "PrefixCache", "PrefixEntry", "prefix_key",
    "InProcessReplica", "ProcessReplica", "SimConfig", "SimEngine",
    "sim_token",
    "FrameReader", "read_frame", "send_frame",
    "FleetEventLog", "read_events",
    "FleetSLO", "fleet_slos_from_env", "merge_fleet_docs",
    "close_orphans", "fleet_request_spans", "load_fragments",
    "validate_fleet_spans",
    "BreachAutopsy", "autopsy_breaches", "build_ledgers", "phase_stats",
    "run_autopsy",
    "metrics",
]
