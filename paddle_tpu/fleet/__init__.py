"""paddle_tpu.fleet — fleet serving: a health-aware router over N engine
replicas.

The layer above one ``serving.ServingEngine``: a bounded-queue router
(:class:`~.router.Router`) dispatching over N replicas — in-process
engines for tests/benches, ``python -m paddle_tpu.fleet.worker``
subprocesses speaking the length-prefixed frame protocol in production
shape — with health-aware routing, session/prefix affinity, a bounded
LRU prefix cache of prefilled KV pages, kill-tolerant exactly-once
request accounting, and per-replica telemetry aggregated into one fleet
snapshot. See ROADMAP item 2 and tools/fleet_bench.py.
"""

from . import metrics  # registers every fleet/* instrument
from .prefix_cache import PrefixCache, PrefixEntry, prefix_key
from .protocol import FrameReader, read_frame, send_frame
from .replica import (InProcessReplica, ProcessReplica, SimConfig,
                      SimEngine, sim_token)
from .router import (FleetBackpressure, FleetConfig, FleetRequest, Router,
                     aggregate_telemetry)

__all__ = [
    "Router", "FleetConfig", "FleetRequest", "FleetBackpressure",
    "aggregate_telemetry",
    "PrefixCache", "PrefixEntry", "prefix_key",
    "InProcessReplica", "ProcessReplica", "SimConfig", "SimEngine",
    "sim_token",
    "FrameReader", "read_frame", "send_frame",
    "metrics",
]
