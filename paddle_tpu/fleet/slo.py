"""Fleet-level SLO evaluation over aggregated telemetry interval deltas.

One ``ServingEngine`` already evaluates ``PADDLE_TPU_SLO`` specs on its
own exporter ticks (monitor.slo.SLOMonitor). A fleet needs the same
grammar evaluated at TWO scopes:

* **per replica** — each replica's ring samples (``<base>/replica_<i>/``)
  run through their own :class:`~paddle_tpu.monitor.slo.SLOMonitor`, so a
  breach names the replica and the router can mark exactly it degraded
  (drained of new traffic, not killed — same policy as an engine-local
  breach);
* **fleet aggregate** — every replica's NEW interval deltas since the
  last evaluation merge into ONE synthetic
  :class:`~paddle_tpu.monitor.telemetry.TelemetrySample` (counter deltas
  sum, histogram bucket deltas sum bucket-wise, gauges sum — queue
  depths add across a fleet) and the same specs run over it, so a p99
  ceiling is judged against the fleet-wide latency distribution, not any
  one replica's.

Both scopes reuse the existing spec machinery end to end: breaches tick
``slo/breaches`` and ``slo/<spec>/breaches``, hit the flight recorder,
and surface through the monitor callbacks the router wires into
``Router.snapshot()`` health and the fleet event log.

:class:`FleetSLO` is a pull evaluator — the router calls
:meth:`evaluate` from its pump (every ``health_every`` ticks) or a drill
calls it synchronously after ``force_tick``-style flushes; per-(replica,
pid) seq cursors make each sample evaluate exactly once.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..monitor import telemetry as _telemetry
from ..monitor.slo import SLO, Breach, SLOMonitor, parse_slos

__all__ = ["FleetSLO", "sample_from_doc", "merge_fleet_docs",
           "fleet_slos_from_env"]


def fleet_slos_from_env() -> List[SLO]:
    """``PADDLE_TPU_FLEET_SLO`` → specs (same grammar as
    ``PADDLE_TPU_SLO``; empty/unset/malformed → no specs, never fatal)."""
    text = os.environ.get("PADDLE_TPU_FLEET_SLO", "").strip()
    if not text:
        return []
    try:
        return parse_slos(text)
    except ValueError:
        import logging

        logging.getLogger("paddle_tpu").warning(
            "PADDLE_TPU_FLEET_SLO: unparseable spec %r ignored", text)
        return []


def sample_from_doc(doc: dict) -> _telemetry.TelemetrySample:
    """Rehydrate one ring-file sample doc into a TelemetrySample the SLO
    specs can evaluate (the doc is ``TelemetrySample.to_doc`` output)."""
    return _telemetry.TelemetrySample(
        int(doc.get("seq", 0)), float(doc.get("t", 0.0)),
        float(doc.get("dt_s", 0.0)), doc.get("metrics") or {},
        doc.get("deltas") or {"counters": {}, "histograms": {},
                              "gauges": {}})


def merge_fleet_docs(docs: Sequence[dict], seq: int
                     ) -> Optional[_telemetry.TelemetrySample]:
    """Merge sample docs from N replicas into one fleet-aggregate sample.

    Deltas: counters and histogram (count/sum/bucket) deltas sum — the
    union of every replica's interval observations. Gauges sum across
    replicas (fleet queue depth = sum of per-replica depths) in both the
    delta map and the merged snapshot. Histogram SNAPSHOTS merge
    bucket-wise so the full bound grid survives for interval-percentile
    interpolation. ``dt_s`` is the widest contributing window (replica
    windows overlap in wall time; summing them would understate rates).
    """
    if not docs:
        return None
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    hists: Dict[str, dict] = {}
    metrics: Dict[str, dict] = {}
    t = 0.0
    dt = 0.0
    for doc in docs:
        t = max(t, float(doc.get("t", 0.0)))
        dt = max(dt, float(doc.get("dt_s", 0.0)))
        d = doc.get("deltas") or {}
        for n, v in (d.get("counters") or {}).items():
            counters[n] = counters.get(n, 0.0) + v
        for n, v in (d.get("gauges") or {}).items():
            gauges[n] = gauges.get(n, 0.0) + v
        for n, h in (d.get("histograms") or {}).items():
            agg = hists.setdefault(n, {"count": 0, "sum": 0.0, "buckets": {}})
            agg["count"] += h.get("count", 0)
            agg["sum"] += h.get("sum", 0.0)
            for k, c in (h.get("buckets") or {}).items():
                agg["buckets"][k] = agg["buckets"].get(k, 0) + c
        for n, snap in (doc.get("metrics") or {}).items():
            cur = metrics.get(n)
            if cur is None:
                cur = dict(snap)
                if isinstance(cur.get("buckets"), dict):
                    cur["buckets"] = dict(cur["buckets"])
                metrics[n] = cur
                continue
            kind = snap.get("type")
            if kind in ("counter", "gauge"):
                cur["value"] = (float(cur.get("value", 0.0))
                                + float(snap.get("value", 0.0)))
            elif kind == "histogram":
                cur["count"] = cur.get("count", 0) + snap.get("count", 0)
                cur["sum"] = cur.get("sum", 0.0) + snap.get("sum", 0.0)
                b = cur.setdefault("buckets", {})
                for k, c in (snap.get("buckets") or {}).items():
                    b[k] = b.get(k, 0) + c
    return _telemetry.TelemetrySample(
        seq, t, dt, metrics,
        {"counters": counters, "histograms": hists, "gauges": gauges})


class FleetSLO:
    """Per-replica + fleet-aggregate SLO evaluation over the telemetry
    base dir. Callbacks: ``on_replica_breach(index, breach)`` /
    ``on_replica_clear(index)`` for replica-scoped outcomes and
    ``on_fleet_breach(breach)`` / ``on_fleet_clear()`` for the aggregate
    — the router maps these onto snapshot health and the event log."""

    def __init__(self, specs: Sequence[SLO],
                 on_replica_breach: Optional[Callable[[int, Breach],
                                                      None]] = None,
                 on_replica_clear: Optional[Callable[[int], None]] = None,
                 on_fleet_breach: Optional[Callable[[Breach], None]] = None,
                 on_fleet_clear: Optional[Callable[[], None]] = None):
        self.specs = list(specs)
        self._on_rep_breach = on_replica_breach
        self._on_rep_clear = on_replica_clear
        self._cursors: Dict[Tuple[int, int], int] = {}  # (replica,pid)->seq
        self._agg_seq = 0
        self._rep_monitors: Dict[int, SLOMonitor] = {}
        self._fleet_monitor = SLOMonitor(
            self.specs, on_breach=on_fleet_breach, on_clear=on_fleet_clear)

    def _monitor(self, index: int) -> SLOMonitor:
        mon = self._rep_monitors.get(index)
        if mon is None:
            def _breach(b, i=index):
                if self._on_rep_breach is not None:
                    self._on_rep_breach(i, b)

            def _clear(i=index):
                if self._on_rep_clear is not None:
                    self._on_rep_clear(i)

            mon = SLOMonitor(self.specs, on_breach=_breach, on_clear=_clear)
            self._rep_monitors[index] = mon
        return mon

    def _new_docs(self, base_dir: str, index: int) -> List[dict]:
        sub = os.path.join(base_dir, "replica_%d" % index)
        if not os.path.isdir(sub):
            return []
        try:
            series = _telemetry.read_series(sub)
        except Exception:
            return []
        fresh = []
        for doc in series:
            key = (index, int(doc.get("pid", 0)))
            if int(doc.get("seq", 0)) > self._cursors.get(key, 0):
                fresh.append(doc)
                self._cursors[key] = int(doc.get("seq", 0))
        return fresh

    def evaluate(self, base_dir: str, replica_indices: Sequence[int]
                 ) -> dict:
        """One evaluation pass over every replica's unseen samples plus
        one merged fleet-aggregate sample; returns
        ``{"replica": {index: [breach docs]}, "fleet": [breach docs]}``
        for the breaches of THIS pass."""
        out: Dict[str, object] = {"replica": {}, "fleet": []}
        if not self.specs or not base_dir:
            return out
        all_new: List[dict] = []
        for idx in replica_indices:
            docs = self._new_docs(base_dir, idx)
            if not docs:
                continue
            all_new.extend(docs)
            mon = self._monitor(idx)
            breaches: List[Breach] = []
            for doc in docs:
                breaches.extend(mon.on_sample(sample_from_doc(doc)))
            if breaches:
                out["replica"][idx] = [b.to_doc() for b in breaches]
        if all_new:
            self._agg_seq += 1
            merged = merge_fleet_docs(all_new, self._agg_seq)
            if merged is not None:
                out["fleet"] = [
                    b.to_doc()
                    for b in self._fleet_monitor.on_sample(merged)]
        return out
