"""Token-prefix KV-page cache: reuse prefilled pages for shared prompts.

The paged layout (serving.kv_cache.PagedKVCache) stores a sequence's KV
rows in page-granular blocks, which makes "two requests share a system
prompt" a page-level fact: the first ``page_size``-aligned tokens of both
prompts produce identical KV pages. This module is the host-side index of
that fact:

* keys are :func:`prefix_key` — a SHA-1 over the raw token ids, stable
  across processes and Python hash randomization (a router and N worker
  replicas must agree on it);
* entries OWN their pages. The engine donates a FINISHED request's
  leading full pages instead of freeing them (zero-copy insert), and gets
  pages back to free on eviction/flush — so the cache can never leak and
  the engine's ``page_accounting_ok`` invariant extends to it;
* only FINISHED requests donate. A request that FAILED or timed out never
  inserts (``fleet/prefix_cache/poisoned_skipped``), so poisoned pages are
  structurally unservable, not filtered at lookup;
* bounded by a page budget with LRU eviction (``fleet/prefix_cache/*``
  counters account hits/misses/inserts/evictions/pages).

The cache is engine-agnostic bookkeeping: it never touches device memory.
The engine performs the device-side page copy + remainder ingest on a hit
(see ServingEngine._prefill_from_prefix).
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

from . import metrics as _fm

__all__ = ["PrefixCache", "PrefixEntry", "prefix_key"]


def prefix_key(tokens: Sequence[int]) -> str:
    """Stable cross-process key for a token prefix: SHA-1 over the ids'
    canonical text encoding (NOT Python ``hash()``, which is salted per
    process — a router and its worker replicas must derive the same key
    from the same tokens)."""
    data = ",".join(str(int(t)) for t in tokens).encode("ascii")
    return hashlib.sha1(data).hexdigest()


class PrefixEntry:
    """One cached prefix: the exact token ids it covers (verified on hit —
    the digest alone is not trusted) and the KV pages it owns."""

    __slots__ = ("key", "tokens", "pages", "hits")

    def __init__(self, key: str, tokens: Tuple[int, ...], pages: List[int]):
        self.key = key
        self.tokens = tokens
        self.pages = list(pages)
        self.hits = 0

    @property
    def n_tokens(self) -> int:
        return len(self.tokens)

    def __repr__(self):
        return ("PrefixEntry(tokens=%d, pages=%d, hits=%d)"
                % (len(self.tokens), len(self.pages), self.hits))


class PrefixCache:
    """LRU page-budgeted prefix index. All methods are host bookkeeping;
    page ownership moves through return values (the caller frees evicted
    pages back to ITS pool — the cache holds ids, never the pool)."""

    def __init__(self, page_budget: int, page_size: int):
        if page_budget < 1:
            raise ValueError("page_budget must be >= 1, got %d" % page_budget)
        if page_size < 1:
            raise ValueError("page_size must be >= 1, got %d" % page_size)
        self.page_budget = int(page_budget)
        self.page_size = int(page_size)
        # key -> entry, most-recently-used last (move_to_end on hit)
        self._entries: "OrderedDict[str, PrefixEntry]" = OrderedDict()
        self.pages_held = 0

    def __len__(self) -> int:
        return len(self._entries)

    def cacheable_len(self, prompt_len: int) -> int:
        """Longest page-aligned prefix STRICTLY shorter than the prompt
        (the remainder must keep >= 1 token: the first sampled token is
        keyed off the last prompt position, which must run through the
        ingest step on a hit)."""
        return ((int(prompt_len) - 1) // self.page_size) * self.page_size

    def contains(self, tokens: Sequence[int]) -> bool:
        e = self._entries.get(prefix_key(tokens))
        return e is not None and e.tokens == tuple(int(t) for t in tokens)

    def get(self, tokens: Sequence[int]) -> Optional[PrefixEntry]:
        """Exact-match accessor (token-verified, no LRU refresh, no
        hit/miss accounting) — the migration export path reads an entry
        without pretending a request was served from it."""
        e = self._entries.get(prefix_key(tokens))
        if e is not None and e.tokens == tuple(int(t) for t in tokens):
            return e
        return None

    def evict(self, tokens: Sequence[int]) -> List[int]:
        """Drop one exact entry; returns its pages for the caller to free
        ([] when absent). The rebalance path: ship a prefix to a peer,
        then evict here — export + evict = move, and the freed pages are
        the pool relief."""
        e = self.get(tokens)
        if e is None:
            return []
        del self._entries[e.key]
        self.pages_held -= len(e.pages)
        _fm.PREFIX_EVICTIONS.inc()
        self._export_gauges()
        return e.pages

    def lookup(self, prompt: Sequence[int]) -> Optional[PrefixEntry]:
        """Longest-match lookup for ``prompt``: probe page-aligned prefix
        lengths from the longest cacheable one down. A hit verifies token
        equality (never trusts the digest), refreshes LRU recency, and
        ticks the hit/tokens-reused counters; a full miss ticks misses."""
        ps = self.page_size
        prompt = [int(t) for t in prompt]
        for n in range(self.cacheable_len(len(prompt)), 0, -ps):
            key = prefix_key(prompt[:n])
            entry = self._entries.get(key)
            if entry is not None and entry.tokens == tuple(prompt[:n]):
                self._entries.move_to_end(key)
                entry.hits += 1
                _fm.PREFIX_HITS.inc()
                _fm.PREFIX_TOKENS_REUSED.inc(entry.n_tokens)
                return entry
        _fm.PREFIX_MISSES.inc()
        return None

    def insert(self, tokens: Sequence[int], pages: Sequence[int]
               ) -> Tuple[bool, List[int]]:
        """Register a prefix whose KV lives in ``pages`` (one page per
        ``page_size`` tokens, donated by the caller).

        Returns ``(accepted, evicted_pages)``: when accepted the cache now
        owns ``pages`` and the caller must free ``evicted_pages`` back to
        the pool; when refused (duplicate, over-budget even when empty, or
        length/page mismatch) the caller keeps ``pages`` and nothing was
        evicted."""
        tokens = tuple(int(t) for t in tokens)
        pages = list(pages)
        if (not tokens or not pages
                or len(tokens) != len(pages) * self.page_size
                or len(pages) > self.page_budget):
            return False, []
        key = prefix_key(tokens)
        if key in self._entries:
            return False, []
        evicted: List[int] = []
        while self.pages_held + len(pages) > self.page_budget:
            evicted.extend(self._evict_lru())
        self._entries[key] = PrefixEntry(key, tokens, pages)
        self.pages_held += len(pages)
        _fm.PREFIX_INSERTS.inc()
        self._export_gauges()
        return True, evicted

    def _evict_lru(self) -> List[int]:
        _key, entry = self._entries.popitem(last=False)
        self.pages_held -= len(entry.pages)
        _fm.PREFIX_EVICTIONS.inc()
        return entry.pages

    def flush(self) -> List[int]:
        """Drop every entry; returns ALL owned pages for the caller to
        free. Called when the device cache is reinitialized (a failed
        dispatch consumed the donated buffers — the rows backing these
        pages are gone) and at engine drain."""
        pages: List[int] = []
        for entry in self._entries.values():
            pages.extend(entry.pages)
        self._entries.clear()
        self.pages_held = 0
        self._export_gauges()
        return pages

    def _export_gauges(self) -> None:
        _fm.PREFIX_ENTRIES.set(len(self._entries))
        _fm.PREFIX_PAGES.set(self.pages_held)

    def stats(self) -> Dict[str, int]:
        return {"entries": len(self._entries),
                "pages_held": self.pages_held,
                "page_budget": self.page_budget,
                "hits": sum(e.hits for e in self._entries.values())}
