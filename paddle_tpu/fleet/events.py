"""Structured fleet event log: one JSONL line per lifecycle event.

The router's flight journal — the third leg of the fleet observability
plane next to the telemetry rings (continuous numbers) and the merged
trace (per-request timelines). Every line is one JSON object::

    {"schema": "paddle_tpu.fleet_events/v1", "t": <unix time>,
     "run_id": "<monitor.runlog.run_id()>", "kind": "<event>", ...}

Kinds the router emits today: ``fleet_start``/``fleet_stop``, ``spawn``,
``kill_detected``, ``requeue``, ``reroute``, ``drain``, ``restart``,
``rolling_restart``, ``slo_breach``/``slo_clear``, and — when a traced
run closes with breaches on the books — ``breach_autopsy`` (the typed
:class:`~paddle_tpu.fleet.autopsy.BreachAutopsy` verdict joining the
breach window against the span-derived phase ledger). The vocabulary is
open — the SLO-driven autoscaler (ROADMAP item 3) will add ``scale``
events through the same writer. Request-scoped events carry
``trace_id`` and replica-scoped ones ``replica``, so ledger records,
flight dumps, telemetry windows, and the merged Perfetto trace all join
on shared keys (``run_id`` across artifacts, ``trace_id`` across a
request's attempts).

Flight-recorder durability rule: every ``emit`` is one line + flush, so
a SIGKILLed router loses at most the line being written; ``read_events``
skips a torn tail instead of failing — the log is a post-mortem artifact
first.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from ..monitor import runlog as _runlog

__all__ = ["FleetEventLog", "read_events", "EVENT_SCHEMA",
           "KIND_SLO_BREACH", "KIND_SLO_CLEAR", "KIND_BREACH_AUTOPSY"]

EVENT_SCHEMA = "paddle_tpu.fleet_events/v1"

# Event kinds tools join on (the rest of the vocabulary is free-form
# strings at the emit sites; these three are cross-referenced by the
# autopsy plane and its CLI, so they get names).
KIND_SLO_BREACH = "slo_breach"
KIND_SLO_CLEAR = "slo_clear"
KIND_BREACH_AUTOPSY = "breach_autopsy"


class FleetEventLog:
    """Append-only JSONL event writer. Write failures disable the log
    (observability must never take the fleet down with it)."""

    def __init__(self, path: str):
        self.path = path
        self._fp = None
        try:
            d = os.path.dirname(os.path.abspath(path))
            if d:
                os.makedirs(d, exist_ok=True)
            self._fp = open(path, "a")
        except OSError:
            self._fp = None

    @property
    def armed(self) -> bool:
        return self._fp is not None

    def emit(self, kind: str, **fields: Any) -> Optional[dict]:
        """One event line; returns the doc written (None when disarmed).
        Non-JSON-serializable field values degrade to ``repr``."""
        if self._fp is None:
            return None
        doc: Dict[str, Any] = {"schema": EVENT_SCHEMA, "t": time.time(),
                               "run_id": _runlog.run_id(), "kind": str(kind)}
        doc.update(fields)
        try:
            line = json.dumps(doc, default=repr)
        except (TypeError, ValueError):
            return None
        try:
            self._fp.write(line + "\n")
            self._fp.flush()
        except OSError:
            self.close()
            return None
        return doc

    def close(self) -> None:
        fp, self._fp = self._fp, None
        if fp is not None:
            try:
                fp.close()
            except OSError:
                pass


def read_events(path: str, kind: Optional[str] = None) -> List[dict]:
    """Load the event log back (optionally one ``kind`` only). Torn or
    foreign trailing lines are skipped, not fatal."""
    out: List[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except ValueError:
                    continue  # torn tail
                if doc.get("schema") != EVENT_SCHEMA:
                    continue
                if kind is not None and doc.get("kind") != kind:
                    continue
                out.append(doc)
    except OSError:
        pass
    return out
