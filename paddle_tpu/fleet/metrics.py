"""fleet/* instruments: the monitor-registry face of the fleet router.

One module owns every ``fleet/*`` name so the router, replicas and the
prefix cache never race a get-or-create, and tools (``tools/fleet_bench``,
``tools/dump_metrics --selftest``) can assert the full set exists by
importing this module alone. Same hot-path contract as serving.metrics:
module-level handles, a single disabled-branch per call.
"""

from __future__ import annotations

from ..monitor import metrics as _mx
from ..serving import phases as _phases

__all__ = [
    "PHASE_MS",
    "SUBMITTED", "ROUTED", "REQUEUED", "COMPLETED", "REJECTED",
    "DUPLICATE_RESULTS", "QUEUE_DEPTH", "REPLICAS_ALIVE",
    "REPLICA_RESTARTS", "ROLLING_RESTARTS", "NO_HEALTHY_REPLICA",
    "REROUTED",
    "PREFIX_HITS", "PREFIX_MISSES", "PREFIX_INSERTS", "PREFIX_EVICTIONS",
    "PREFIX_ENTRIES", "PREFIX_PAGES", "PREFIX_TOKENS_REUSED",
    "PREFIX_POISONED_SKIPPED",
    "MIGRATIONS_STARTED", "MIGRATIONS_COMPLETED", "MIGRATIONS_FAILED",
    "MIGRATED_PAGES", "MIGRATION_MS",
    "REMOTE_HITS", "REMOTE_MISSES", "REMOTE_SHIPS",
]

SUBMITTED = _mx.counter(
    "fleet/submitted", help="requests accepted into the router's queue")
ROUTED = _mx.counter(
    "fleet/routed", help="request dispatches to a replica (re-dispatches "
                         "after a requeue count again)")
REQUEUED = _mx.counter(
    "fleet/requeued",
    help="in-flight requests re-queued after their replica was lost "
         "(crash/SIGKILL) — replayed idempotently by request id")
COMPLETED = _mx.counter(
    "fleet/completed", help="requests that reached exactly one terminal "
                            "state at the router")
REJECTED = _mx.counter(
    "fleet/rejected",
    help="submissions refused at the router (bounded queue full, or the "
         "router is draining) — typed backpressure, never a silent drop")
DUPLICATE_RESULTS = _mx.counter(
    "fleet/duplicate_results",
    help="late results for an already-terminal request id, ignored "
         "(the exactly-once accounting absorbed a replay race)")
QUEUE_DEPTH = _mx.gauge(
    "fleet/queue_depth", help="requests waiting in the router's queue")
REPLICAS_ALIVE = _mx.gauge(
    "fleet/replicas_alive", help="replicas currently alive")
REPLICA_RESTARTS = _mx.counter(
    "fleet/replica_restarts",
    help="replica respawns (after a crash or a rolling-restart drain)")
ROLLING_RESTARTS = _mx.counter(
    "fleet/rolling_restarts",
    help="completed rolling restarts of the whole fleet")
NO_HEALTHY_REPLICA = _mx.counter(
    "fleet/no_healthy_replica",
    help="dispatch attempts deferred because no healthy replica was "
         "accepting traffic (requests stay queued — degraded replicas "
         "are drained of NEW traffic, not fed)")
REROUTED = _mx.counter(
    "fleet/rerouted",
    help="requests re-routed to a peer after a replica-side typed "
         "rejection (draining/backpressure) — never surfaced as a "
         "terminal rejection")

PREFIX_HITS = _mx.counter(
    "fleet/prefix_cache/hits",
    help="prefill requests served from cached prefix KV pages (prefill "
         "compute skipped for the shared prefix)")
PREFIX_MISSES = _mx.counter(
    "fleet/prefix_cache/misses",
    help="prefill lookups that found no cached prefix")
PREFIX_INSERTS = _mx.counter(
    "fleet/prefix_cache/inserts",
    help="prefix entries inserted (pages donated by a FINISHED request)")
PREFIX_EVICTIONS = _mx.counter(
    "fleet/prefix_cache/evictions",
    help="LRU evictions under page-budget pressure")
PREFIX_ENTRIES = _mx.gauge(
    "fleet/prefix_cache/entries", help="live prefix entries")
PREFIX_PAGES = _mx.gauge(
    "fleet/prefix_cache/pages_held",
    help="KV pages owned by the prefix cache (counted by the engine's "
         "page-accounting invariant)")
PREFIX_TOKENS_REUSED = _mx.counter(
    "fleet/prefix_cache/tokens_reused",
    help="prompt tokens whose prefill compute was skipped via a cached "
         "prefix")
PREFIX_POISONED_SKIPPED = _mx.counter(
    "fleet/prefix_cache/poisoned_skipped",
    help="cacheable prefixes NOT inserted because their request did not "
         "FINISH (failed/timed-out pages are never served to a later "
         "request)")

MIGRATIONS_STARTED = _mx.counter(
    "fleet/migrations_started",
    help="cross-replica KV-page migrations begun (disaggregated "
         "prefill->decode handoff, fleet prefix-cache ship, rebalance, "
         "scale-down)")
MIGRATIONS_COMPLETED = _mx.counter(
    "fleet/migrations_completed",
    help="migrations whose pages landed on the destination replica")
MIGRATIONS_FAILED = _mx.counter(
    "fleet/migrations_failed",
    help="migrations aborted (replica died / export miss / import "
         "refused / timeout) — the carried request falls back to a cold "
         "dispatch, never to a loss")
MIGRATED_PAGES = _mx.counter(
    "fleet/migrated_pages",
    help="KV pages shipped across replicas over the binary page frame")
MIGRATION_MS = _mx.histogram(
    "fleet/migration_ms",
    help="end-to-end migration latency (export op sent -> import ack)")

REMOTE_HITS = _mx.counter(
    "fleet/prefix_cache/remote_hits",
    help="requests served on one replica from prefix pages prefilled on "
         "ANOTHER (the fleet-wide prefix cache paid off)")
REMOTE_MISSES = _mx.counter(
    "fleet/prefix_cache/remote_misses",
    help="fleet prefix-index probes whose owner could no longer produce "
         "the entry (evicted/restarted) — the request prefills cold")
REMOTE_SHIPS = _mx.counter(
    "fleet/prefix_cache/remote_ships",
    help="prefix entries shipped between replicas' prefix caches")

# Per-phase latency budgets (the request-autopsy plane): one histogram
# per phase of the serving/phases.py taxonomy, observed per REQUEST from
# the span-derived phase ledger when the router closes a traced run —
# fleet/phase/<name>/ms explains where serving/request_latency_ms went.
PHASE_MS = {
    name: _mx.histogram(
        "fleet/phase/%s/ms" % name,
        help="per-request milliseconds attributed to the %r phase by the "
             "span-derived phase ledger (serving/phases.py)" % name)
    for name in _phases.PHASES
}
