"""Replica wrappers: the uniform surface the router dispatches over.

Three concrete replicas behind one duck-typed contract (``submit(rdoc)``
/ ``poll() -> events`` / ``health()`` / ``drain()`` / ``kill()`` /
``alive``):

* :class:`InProcessReplica` — wraps an engine object living in the
  router's process (a real ``serving.ServingEngine`` or a
  :class:`SimEngine`). The test/bench mode: no pipes, no pickling,
  deterministic pumping.
* :class:`ProcessReplica` — a ``python -m paddle_tpu.fleet.worker``
  subprocess speaking the length-prefixed frame protocol over its
  stdin/stdout. The production shape: SIGKILLing it is a real kill, and
  the router's only view of its death is EOF/exit — exactly what the
  crash-tolerance drill needs to exercise.
* :class:`SimEngine` — a device-bound engine model: each step sleeps
  ``step_ms`` (the host-blocks-on-accelerator regime — on a TPU replica
  the host waits on the device, it does not compute) and advances every
  running slot one deterministic token. Sim tokens are a pure function
  of (seed, absolute position) like the real engine's sampler, so
  requeue-replay bit-identity holds by the same mechanism. This is what
  makes router/protocol QPS scaling honestly measurable on a 1-core CI
  host: replicas overlap their device waits, not Python compute.

Events (worker -> router), all plain dicts with an ``ev`` key:
``ready``/``result``/``health``/``drained``/``stats``. A ``result``
carries the fleet request id, terminal ``state`` (finished/failed/
timeout — or ``rejected`` with a ``kind`` of draining/backpressure,
which the router treats as re-routable, never terminal), ``tokens`` and
``error``.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

from ..monitor import tracer as _tracer
from ..reliability import faults as _faults
from ..serving import metrics as _sm
from ..serving import trace as _sv
from ..serving.request import (FAILED, FINISHED, REJECTED, BackpressureError,
                               DrainingError, Request)
from .protocol import (Binary, FrameReader, pack_pages, send_binary_frame,
                       send_frame, unpack_pages)

__all__ = ["SimConfig", "SimEngine", "InProcessReplica", "ProcessReplica",
           "sim_token"]

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def sim_token(seed: int, pos: int, vocab: int) -> int:
    """The sim decoder's next token: a stable hash of (seed, absolute
    position) — the same keying shape as the real engine's device-side
    sampler (fold_in(PRNGKey(seed), position)), so a replayed request
    regenerates the identical stream on any replica, by construction."""
    h = hashlib.sha1(b"%d:%d" % (int(seed), int(pos))).digest()
    return int.from_bytes(h[:4], "big") % max(1, int(vocab))


class SimConfig:
    """Geometry + the modeled device latency of one sim replica.

    The prefill cost model (all default-off, so existing benches are
    untouched): admission of a prompt blocks ``prefill_ms_per_token`` per
    token NOT covered by a known prefix — prefill is compute-bound and
    stalls the whole engine, exactly the contention continuous batching
    suffers. ``interference`` multiplies that stall while any slot is
    mid-decode (mixed prefill/decode batches thrash batch shapes and HBM
    — the published motivation for prefill/decode disaggregation): a
    replica doing ONLY prefill (or only decode) never pays it.
    ``page_size`` is the prefix granularity for the migration surface."""

    def __init__(self, slots: int = 4, step_ms: float = 0.0,
                 vocab: int = 256, max_queue: int = 1024,
                 drain_timeout_s: float = 30.0, page_size: int = 16,
                 prefill_ms_per_token: float = 0.0,
                 interference: float = 1.0,
                 serving_spans: bool = False):
        self.slots = int(slots)
        self.step_ms = float(step_ms)
        self.vocab = int(vocab)
        self.max_queue = int(max_queue)
        self.drain_timeout_s = float(drain_timeout_s)
        self.page_size = max(1, int(page_size))
        self.prefill_ms_per_token = float(prefill_ms_per_token)
        self.interference = max(1.0, float(interference))
        # Emit the serving-cat request-lifecycle spans (serving.trace) when
        # the host tracer is armed. Default OFF: serving spans ride virtual
        # tracks keyed by track NAME, so two in-process sims would collide
        # on "serving slot k" — only the fleet WORKER (one engine per
        # process) flips this on, giving the phase ledger the same span
        # vocabulary the real engine emits.
        self.serving_spans = bool(serving_spans)


class SimEngine:
    """Engine-shaped simulator: the ServingEngine slice the fleet layer
    drives (submit/step/idle/health/drain/request_drain/close), minus the
    device. Used in-process for router unit tests and as the worker's
    ``"engine": "sim"`` mode for protocol-scaling benches."""

    def __init__(self, config: Optional[SimConfig] = None):
        self.cfg = config or SimConfig()
        self._queue: List[Request] = []
        self._running: List[Request] = []
        self._free_slots: List[int] = list(range(self.cfg.slots))
        self._draining = False
        self._closed = False
        self._drain_active = False
        self.last_drain: Optional[dict] = None
        self.force_degraded = False  # tests flip this to exercise routing
        self.steps = 0
        # known prefixes (token tuple -> True): the sim analog of the real
        # engine's prefix cache — a covered prefix skips its prefill stall
        self._prefixes: Dict[tuple, bool] = {}
        self._prefills = 0
        self._resumes = 0

    # -- the engine contract --------------------------------------------------
    def submit(self, prompt, max_new_tokens, deadline_s=None,
               temperature=0.0, top_k=0, seed=None, trace_id=None,
               attempt=0, speculation=None) -> Request:
        # ``speculation`` is accepted for submit-surface parity with the
        # real engine and ignored: the sim emits (seed, position)-keyed
        # tokens directly, which is exactly the stream the speculative
        # path would produce anyway
        if self._draining:
            raise DrainingError("sim engine is draining")
        if len(self._queue) >= self.cfg.max_queue:
            raise BackpressureError("sim queue full")
        req = Request(prompt, max_new_tokens, deadline_s=deadline_s,
                      temperature=temperature, top_k=top_k, seed=seed,
                      trace_id=trace_id, attempt=attempt)
        self._queue.append(req)
        _sm.REQUESTS_SUBMITTED.inc()
        if self.cfg.serving_spans:
            _sv.on_submitted(req)
        return req

    def idle(self) -> bool:
        return not self._queue and not self._running

    def _emit(self, req: Request) -> None:
        pos = req.prompt_len - 1 + len(req.tokens_out)
        req.tokens_out.append(sim_token(req.seed, pos, self.cfg.vocab))

    def _cacheable_len(self, n: int) -> int:
        # same alignment rule as fleet.prefix_cache: longest page-aligned
        # prefix STRICTLY shorter than the prompt
        return ((int(n) - 1) // self.cfg.page_size) * self.cfg.page_size

    def _known_prefix_len(self, prompt) -> int:
        ps = self.cfg.page_size
        prompt = [int(t) for t in prompt]
        for n in range(self._cacheable_len(len(prompt)), 0, -ps):
            if tuple(prompt[:n]) in self._prefixes:
                return n
        return 0

    def _prefill_stall(self, req: Request) -> int:
        """The modeled prefill cost of admitting ``req``: per uncovered
        token, multiplied by ``interference`` when the stall lands in the
        middle of live decodes (the mixed-batch penalty disaggregation
        exists to remove). Returns the known-prefix length (the phase
        ledger's local/resume cause attribution)."""
        if self.cfg.prefill_ms_per_token <= 0:
            if self.cfg.serving_spans:
                return self._known_prefix_len(req.prompt)
            return 0
        known = self._known_prefix_len(req.prompt)
        if known:
            self._resumes += 1
        else:
            self._prefills += 1
        ms = (req.prompt_len - known) * self.cfg.prefill_ms_per_token
        if any(len(r.tokens_out) < r.max_new_tokens for r in self._running):
            ms *= self.cfg.interference
        if ms > 0:
            time.sleep(ms / 1e3)
        return known

    def _retire(self, req: Request, state: str) -> None:
        """Terminal bookkeeping shared by step() and drain(): emit the
        lifecycle spans (when armed) and free the request's slot."""
        if self.cfg.serving_spans:
            _sv.on_terminal(req, state, req.slot)
        if req.slot is not None:
            self._free_slots.append(req.slot)
            self._free_slots.sort()

    def step(self) -> List[Request]:
        """One sim cycle: admit into free slots (first token emitted at
        admission, like prefill — paying the modeled prefill stall first),
        block ``step_ms`` on the modeled device, advance every running
        request one token."""
        finished: List[Request] = []
        while self._queue and len(self._running) < self.cfg.slots:
            req = self._queue.pop(0)
            req.state = "running"
            req.slot = self._free_slots.pop(0)
            req.admitted_t = time.perf_counter()
            known = self._prefill_stall(req)
            n = self._cacheable_len(req.prompt_len)
            if n >= self.cfg.page_size:
                # the sim donates at admission (prefilled rows exist now)
                self._prefixes[tuple(int(t) for t in req.prompt[:n])] = True
            self._emit(req)
            # the +epsilon floor keeps the prefill span strictly inside
            # the lifetime span even when the modeled stall is zero (the
            # nesting validator treats equal-start spans as a partial
            # overlap, and sub-µs windows truncate to equal starts)
            req.first_token_t = max(time.perf_counter(),
                                    req.admitted_t + 4e-6)
            self._running.append(req)
            _sm.REQUESTS_ADMITTED.inc()
            if self.cfg.serving_spans:
                _sv.on_admitted(req, req.slot)
                _sv.on_prefill(req, req.slot, req.prompt_len,
                               req.admitted_t + 2e-6, req.first_token_t,
                               cause="resume" if known else "local")
                _sm.TTFT_MS.observe(
                    (req.first_token_t - req.submitted_t) * 1e3)
                _sm.PREFILL_MS.observe(
                    (req.first_token_t - req.admitted_t) * 1e3)
        _sm.QUEUE_DEPTH.set(len(self._queue))
        if not self._running:
            return finished
        # same chaos chokepoint as the real decode loop: a ``latency``
        # fault sleeps here, so per-replica fault plans can degrade one
        # sim replica's tail without touching its peers. The decode span
        # window opens BEFORE the fault fires — injected decode latency
        # lands inside the decode phase, where the autopsy should find it.
        t0d = time.perf_counter()
        if self.cfg.serving_spans:
            # the epsilon-floored first_token_t of a just-admitted request
            # can sit ahead of the wall clock; open the decode window at
            # or after every prefill close so slot tracks stay well-nested
            for req in self._running:
                if req.first_token_t is not None:
                    t0d = max(t0d, req.first_token_t)
        _faults.fire("serving.decode")
        if self.cfg.step_ms > 0:
            time.sleep(self.cfg.step_ms / 1e3)
        self.steps += 1
        still: List[Request] = []
        done: List[Request] = []
        for req in self._running:
            if len(req.tokens_out) < req.max_new_tokens:
                self._emit(req)
            if len(req.tokens_out) >= req.max_new_tokens:
                done.append(req)
            else:
                still.append(req)
        t1d = max(time.perf_counter(), t0d)
        if self.cfg.serving_spans:
            by_slot: List[Optional[Request]] = [None] * self.cfg.slots
            for req in self._running:
                if req.slot is not None:
                    by_slot[req.slot] = req
            _sv.on_decode_chunk(by_slot, 1, t0d, t1d)
            _sm.DECODE_STEP_MS.observe((t1d - t0d) * 1e3)
        for req in done:
            req.state = FINISHED
            req.finished_t = max(time.perf_counter(), t1d)
            finished.append(req)
            _sm.REQUESTS_RETIRED.inc()
            _sm.REQUEST_LATENCY_MS.observe(
                (req.finished_t - req.submitted_t) * 1e3)
            self._retire(req, FINISHED)
        self._running = still
        return finished

    def health(self) -> dict:
        return {"status": "degraded" if self.force_degraded else "ok",
                "queued": len(self._queue), "running": len(self._running),
                "consecutive_failures": 0, "faults_absorbed": 0,
                "last_error": None, "page_accounting_ok": True,
                "prefills": self._prefills, "resumes": self._resumes}

    # -- migration surface (same duck type as ServingEngine) ------------------
    def export_prefix_pages(self, tokens):
        tokens = tuple(int(t) for t in tokens)
        if tokens not in self._prefixes:
            return None
        return {"layout": "sim", "page_size": self.cfg.page_size,
                "n_pages": len(tokens) // self.cfg.page_size}, []

    def ingest_prefix_pages(self, tokens, meta: dict, blobs) -> bool:
        if self._closed or meta.get("layout") != "sim":
            return False  # a real-engine payload is not importable here
        tokens = tuple(int(t) for t in tokens)
        if not tokens or len(tokens) % self.cfg.page_size:
            return False
        self._prefixes[tokens] = True
        return True

    def evict_prefix(self, tokens) -> int:
        if self._prefixes.pop(tuple(int(t) for t in tokens), None):
            return max(1, len(tokens) // self.cfg.page_size)
        return 0

    def export_request_prefix(self, req: Request):
        n = self._cacheable_len(req.prompt_len)
        if n < self.cfg.page_size:
            return None
        tokens = [int(t) for t in req.prompt[:n]]
        self._prefixes[tuple(tokens)] = True  # prefilled rows exist
        return tokens, {"layout": "sim", "page_size": self.cfg.page_size,
                        "n_pages": n // self.cfg.page_size}, []

    def request_drain(self) -> None:
        self._draining = True

    def drain(self, timeout_s: Optional[float] = None) -> dict:
        """Same contract (and re-entrancy discipline) as the real engine's
        drain: shed queued as REJECTED, finish running, idempotent."""
        if self.last_drain is not None:
            return self.last_drain
        if self._drain_active:
            return {"finished": 0, "timed_out": 0, "failed": 0,
                    "rejected": 0, "nested": True}
        self._drain_active = True
        try:
            if timeout_s is None:
                timeout_s = self.cfg.drain_timeout_s
            self._draining = True
            summary = {"finished": 0, "timed_out": 0, "failed": 0,
                       "rejected": 0}
            for req in self._queue:
                req.state = REJECTED
                req.finished_t = time.perf_counter()
                summary["rejected"] += 1
                if self.cfg.serving_spans:
                    _sv.on_terminal(req, REJECTED, None)
            self._queue = []
            deadline = time.monotonic() + timeout_s
            while self._running and time.monotonic() < deadline:
                summary["finished"] += len(self.step())
            for req in self._running:
                req.state = "timeout"
                req.finished_t = time.perf_counter()
                summary["timed_out"] += 1
                self._retire(req, "timeout")
            self._running = []
            self.last_drain = summary
            self.close()
            return summary
        finally:
            self._drain_active = False

    def close(self) -> None:
        self._closed = True

    def stats(self) -> dict:
        return {"layout": "sim", "queued": len(self._queue),
                "running": len(self._running), "steps": self.steps,
                "step_ms": self.cfg.step_ms, "slots": self.cfg.slots}


def _engine_idle(engine) -> bool:
    if hasattr(engine, "idle"):
        return engine.idle()
    return engine.scheduler.idle()


def _decode_frames(frames) -> List[dict]:
    """Normalize a frame batch: binary page frames unpack to their meta
    dict with the blobs attached under ``"_blobs"`` (a foreign/garbled
    payload is dropped — same tolerance as a torn JSON line in the event
    log); JSON frames pass through."""
    out: List[dict] = []
    for fr in frames:
        if isinstance(fr, Binary):
            try:
                meta, blobs = unpack_pages(fr.payload)
            except ValueError:
                continue
            meta["_blobs"] = blobs
            out.append(meta)
        else:
            out.append(fr)
    return out


class InProcessReplica:
    """A replica living in the router's process. ``poll()`` pumps the
    engine one step when it has work — the router's pump loop IS the
    engine's drive loop in this mode."""

    kind = "inprocess"

    def __init__(self, engine, index: int = 0):
        self.engine = engine
        self.index = int(index)
        self.name = "replica-%d" % self.index
        self.role = "uniform"  # the router stamps prefill/decode roles
        self.accepting = True
        self.alive = True
        self.inflight: Dict[int, dict] = {}   # fleet id -> request doc
        self._by_req: Dict[int, int] = {}     # engine Request.id -> fleet id
        self._requests: Dict[int, Request] = {}  # engine Request.id -> obj
        self._events: List[dict] = []

    def submit(self, rdoc: dict) -> None:
        try:
            req = self.engine.submit(
                rdoc["prompt"], rdoc["max_new_tokens"],
                deadline_s=rdoc.get("deadline_s"),
                temperature=rdoc.get("temperature", 0.0),
                top_k=rdoc.get("top_k", 0), seed=rdoc.get("seed"),
                trace_id=rdoc.get("trace_id"),
                attempt=int(rdoc.get("attempt", 0)),
                speculation=rdoc.get("speculation"))
        except DrainingError:
            self._events.append({"ev": "result", "id": rdoc["id"],
                                 "state": REJECTED, "kind": "draining"})
            return
        except BackpressureError:
            self._events.append({"ev": "result", "id": rdoc["id"],
                                 "state": REJECTED, "kind": "backpressure"})
            return
        except ValueError as e:  # never servable at this geometry: terminal
            self._events.append({"ev": "result", "id": rdoc["id"],
                                 "state": FAILED, "tokens": [],
                                 "error": str(e)})
            return
        self.inflight[rdoc["id"]] = rdoc
        self._by_req[req.id] = rdoc["id"]
        self._requests[req.id] = req

    def _result(self, req: Request) -> Optional[dict]:
        fid = self._by_req.pop(req.id, None)
        self._requests.pop(req.id, None)
        if fid is None:
            return None
        self.inflight.pop(fid, None)
        return {"ev": "result", "id": fid, "state": req.state,
                "tokens": list(req.tokens_out), "error": req.error}

    def poll(self) -> List[dict]:
        evs, self._events = self._events, []  # drain events outlive alive
        if self.alive and not _engine_idle(self.engine):
            for req in self.engine.step():
                r = self._result(req)
                if r is not None:
                    evs.append(r)
        return evs

    def health(self) -> dict:
        if not self.alive:
            return {"status": "dead"}
        return self.engine.health()

    def drain(self, timeout_s: Optional[float] = None) -> dict:
        """Graceful stop: the engine finishes in-flight work and sheds its
        queue; every tracked request's terminal state is reported as a
        normal result event (shed ones come back ``rejected`` so the
        router re-routes them — never a terminal rejection)."""
        summary = self.engine.drain(timeout_s)
        # every still-tracked request now has a terminal state on the
        # Request object the engine handed back at submit; report each as
        # a normal result event. Shed ones surface ``rejected`` with
        # kind=draining so the router re-routes them (never terminal).
        for rid in list(self._by_req):
            fid = self._by_req.pop(rid)
            req = self._requests.pop(rid, None)
            self.inflight.pop(fid, None)
            if req is None:
                continue
            state = req.state if req.state != "running" else "timeout"
            ev = {"ev": "result", "id": fid, "state": state,
                  "tokens": list(req.tokens_out), "error": req.error}
            if state == REJECTED:
                ev["kind"] = "draining"
            self._events.append(ev)
        self.accepting = False
        self.alive = False  # a drained engine is closed; respawn to reuse
        return summary

    # -- migration ops (answers surface as events, like the wire mode) --------
    def request_export_prefix(self, xid: int, tokens) -> None:
        res = None
        if self.alive and hasattr(self.engine, "export_prefix_pages"):
            try:
                res = self.engine.export_prefix_pages(tokens)
            except ValueError:
                res = None  # layout refuses pages: an honest export miss
        if res is None:
            self._events.append({"ev": "pages", "xid": xid, "ok": False})
            return
        meta, blobs = res
        head = dict(meta, ev="pages", xid=xid, ok=True,
                    tokens=[int(t) for t in tokens])
        # round-trip the wire encoding even in-process, so every mode
        # exercises the same serialization the binary frame carries
        meta2, blobs2 = unpack_pages(pack_pages(head, blobs))
        meta2["_blobs"] = blobs2
        self._events.append(meta2)

    def request_export_request(self, xid: int, fid: int) -> None:
        res = None
        rid = next((r for r, f in self._by_req.items() if f == fid), None)
        req = self._requests.get(rid) if rid is not None else None
        if self.alive and req is not None \
                and hasattr(self.engine, "export_request_prefix"):
            try:
                res = self.engine.export_request_prefix(req)
            except ValueError:
                res = None
        if res is None:
            self._events.append({"ev": "pages", "xid": xid, "ok": False})
            return
        tokens, meta, blobs = res
        head = dict(meta, ev="pages", xid=xid, ok=True, tokens=tokens)
        meta2, blobs2 = unpack_pages(pack_pages(head, blobs))
        meta2["_blobs"] = blobs2
        self._events.append(meta2)

    def request_import_prefix(self, xid: int, tokens, meta: dict,
                              blobs) -> None:
        ok = False
        if self.alive and hasattr(self.engine, "ingest_prefix_pages"):
            try:
                ok = bool(self.engine.ingest_prefix_pages(tokens, meta,
                                                          blobs))
            except Exception:
                ok = False
        self._events.append(
            {"ev": "imported", "xid": xid, "ok": ok,
             "pages": int(meta.get("n_pages", 0)) if ok else 0})

    def request_evict_prefix(self, xid: int, tokens) -> None:
        n = 0
        if self.alive and hasattr(self.engine, "evict_prefix"):
            try:
                n = int(self.engine.evict_prefix(tokens))
            except Exception:
                n = 0
        self._events.append({"ev": "evicted", "xid": xid, "pages": n})

    def kill(self) -> None:
        """The in-process analog of SIGKILL: the engine vanishes with its
        in-flight work. ``inflight`` keeps the lost request docs for the
        router's requeue path."""
        self.alive = False
        self.accepting = False
        try:
            self.engine.close()
        except Exception:
            pass

    def close(self) -> None:
        if self.alive:
            try:
                self.engine.close()
            except Exception:
                pass
        self.alive = False


class ProcessReplica:
    """One ``python -m paddle_tpu.fleet.worker`` subprocess. The router
    writes op frames to its stdin and tails event frames from its stdout
    (non-blocking; pumped by ``poll()``). Death — clean exit or SIGKILL —
    surfaces as EOF/exit, flips ``alive`` False, and leaves ``inflight``
    holding exactly the request docs the router must requeue."""

    kind = "process"

    def __init__(self, spec: dict, index: int = 0,
                 telemetry_dir: Optional[str] = None,
                 trace_file: Optional[str] = None,
                 ready_timeout_s: float = 120.0):
        self.spec = dict(spec)
        self.index = int(index)
        self.name = "replica-%d" % self.index
        self.role = "uniform"  # the router stamps prefill/decode roles
        self.accepting = True
        self.inflight: Dict[int, dict] = {}
        self._events: List[dict] = []
        self._dead = False
        self.pid: Optional[int] = None
        self.trace_file = trace_file
        self.clock_offset_us = 0   # worker span clock − router span clock
        self.clock_rtt_us = 0      # min handshake round trip (error bound)
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
        if telemetry_dir:
            os.makedirs(telemetry_dir, exist_ok=True)
            env["PADDLE_TPU_TELEMETRY_DIR"] = telemetry_dir
        else:
            # never let N workers share the parent's ring dir by accident
            env.pop("PADDLE_TPU_TELEMETRY_DIR", None)
        if trace_file:
            d = os.path.dirname(os.path.abspath(trace_file))
            if d:
                os.makedirs(d, exist_ok=True)
            env["PADDLE_TPU_TRACE_FILE"] = trace_file
        else:
            # N workers inheriting the parent's trace file would clobber
            # each other's fragment — arm per-replica or not at all
            env.pop("PADDLE_TPU_TRACE_FILE", None)
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.fleet.worker"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env)
        os.set_blocking(self.proc.stdout.fileno(), False)
        self.reader = FrameReader(self.proc.stdout.fileno())
        send_frame(self.proc.stdin, {"op": "spec", "spec": self.spec})
        self._wait_ready(ready_timeout_s)
        self._clock_sync()

    def _drain_frames(self) -> List[dict]:
        return _decode_frames(self.reader.drain())

    def _wait_ready(self, timeout_s: float) -> None:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            for ev in self._drain_frames():
                if ev.get("ev") == "ready":
                    self.pid = ev.get("pid")
                    return
                self._events.append(ev)
            if self.proc.poll() is not None:
                raise RuntimeError(
                    "fleet worker %d died during startup (rc=%s)"
                    % (self.index, self.proc.returncode))
            time.sleep(0.01)
        self.kill()
        raise RuntimeError("fleet worker %d not ready after %.0fs"
                           % (self.index, timeout_s))

    def _clock_sync(self, probes: int = 3, timeout_s: float = 5.0) -> None:
        """Measure this worker's span-clock offset with an NTP-style
        midpoint handshake: offset = worker_t − (t0+t1)/2, keeping the
        probe with the smallest round trip (its midpoint estimate has the
        tightest error bound, ±rtt/2). Runs AFTER ready — probing during
        engine build would fold warmup time into the midpoint. The
        offsets land in the trace-dir manifest so the merge can move
        every worker fragment onto the router's clock."""
        best_rtt = None
        best_off = 0
        for _ in range(probes):
            t0 = _tracer.now_us()
            if not self._send({"op": "clock"}):
                break
            reply = None
            t1 = t0
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                evs = self._drain_frames()
                t1 = _tracer.now_us()
                for ev in evs:
                    if ev.get("ev") == "clock" and reply is None:
                        reply = ev
                    else:
                        self._events.append(ev)
                if reply is not None:
                    break
                if self.reader.eof or self.proc.poll() is not None:
                    break
                time.sleep(0.001)
            if reply is None:
                break
            rtt = max(1, t1 - t0)
            off = int(reply.get("t_us", 0)) - (t0 + t1) // 2
            if best_rtt is None or rtt < best_rtt:
                best_rtt, best_off = rtt, off
        self.clock_offset_us = int(best_off)
        self.clock_rtt_us = int(best_rtt or 0)

    @property
    def alive(self) -> bool:
        return not self._dead

    def _send(self, op: dict) -> bool:
        if self._dead:
            return False
        try:
            send_frame(self.proc.stdin, op)
            return True
        except (BrokenPipeError, OSError):
            return False  # poll() will observe the death and requeue

    def submit(self, rdoc: dict) -> None:
        # track BEFORE sending: if the pipe breaks mid-write the request
        # is in inflight and the death path requeues it — never dropped
        self.inflight[rdoc["id"]] = rdoc
        self._send(dict(rdoc, op="submit"))

    def poll(self) -> List[dict]:
        evs, self._events = self._events, []  # drain events outlive alive
        if self._dead:
            return evs
        evs.extend(self._drain_frames())
        for ev in evs:
            if ev.get("ev") == "result":
                self.inflight.pop(ev.get("id"), None)
        if self.reader.eof or self.proc.poll() is not None:
            # peer gone: any frames already buffered were just returned;
            # what remains in inflight is the router's requeue set
            self._dead = True
            try:
                self.proc.wait(timeout=5)
            except Exception:
                pass
        return evs

    def health(self) -> dict:
        """Last health event wins; this just asks for a fresh one (the
        answer arrives on a later poll). Returns nothing synchronous —
        the router caches health from the event stream."""
        self._send({"op": "health"})
        return {}

    # -- migration ops: answers arrive as pages/imported/evicted events -------
    def request_export_prefix(self, xid: int, tokens) -> None:
        self._send({"op": "export_prefix", "xid": xid,
                    "tokens": [int(t) for t in tokens]})

    def request_export_request(self, xid: int, fid: int) -> None:
        self._send({"op": "export_request", "xid": xid, "id": fid})

    def request_import_prefix(self, xid: int, tokens, meta: dict,
                              blobs) -> None:
        head = {k: v for k, v in meta.items() if k != "_blobs"}
        head.update(op="import_prefix", xid=xid,
                    tokens=[int(t) for t in tokens])
        if self._dead:
            return
        try:
            send_binary_frame(self.proc.stdin, pack_pages(head, blobs))
        except (BrokenPipeError, OSError):
            pass  # poll() observes the death; the migration times out
        except ValueError:
            # oversize payload: the import can never be delivered —
            # synthesize the refusal so the router falls back immediately
            self._events.append({"ev": "imported", "xid": xid,
                                 "ok": False, "pages": 0})

    def request_evict_prefix(self, xid: int, tokens) -> None:
        self._send({"op": "evict_prefix", "xid": xid,
                    "tokens": [int(t) for t in tokens]})

    def drain(self, timeout_s: Optional[float] = None) -> dict:
        """Graceful stop: the worker drains its engine, reports every
        tracked request's terminal state, emits ``drained`` and exits.
        Result events collected here surface through the next poll()."""
        self.accepting = False
        if not self._send({"op": "drain", "timeout_s": timeout_s}):
            return {}
        summary: dict = {}
        deadline = time.monotonic() + (timeout_s or 30.0) + 10.0
        while time.monotonic() < deadline:
            for ev in self._drain_frames():
                if ev.get("ev") == "drained":
                    summary = ev.get("summary", {})
                else:
                    if ev.get("ev") == "result":
                        self.inflight.pop(ev.get("id"), None)
                    self._events.append(ev)
            if summary:
                break
            if self.proc.poll() is not None and self.reader.eof:
                break
            time.sleep(0.005)
        try:
            self.proc.wait(timeout=10)
        except Exception:
            self.proc.kill()
        self._dead = True
        return summary

    def kill(self) -> None:
        """SIGKILL — the crash drill's hammer. No goodbye frames: the
        router finds out the same way it would in production (EOF)."""
        try:
            self.proc.kill()
        except Exception:
            pass
        try:
            self.proc.wait(timeout=10)
        except Exception:
            pass

    def close(self) -> None:
        if not self._dead:
            self._send({"op": "shutdown"})
            try:
                self.proc.wait(timeout=10)
            except Exception:
                self.kill()
            self._dead = True
