"""Fleet-side request tracing: router tracks, fragment merge, validation.

The cross-process half of the serving trace story (serving/trace.py).
When a router is armed with ``FleetConfig.trace_dir`` it starts the host
tracer in its own process and emits ROUTER-side spans for every fleet
request, on virtual tracks:

* ``fleet queue`` — ``submitted`` instants, one ``queued`` span per wait
  (submission → dispatch, and requeue → re-dispatch for replays), and
  exactly one terminal instant (``finished``/``failed``/``timeout``/
  ``rejected``) per request;
* ``replica <i>`` — a ``dispatch`` instant plus an ``attempt <n>`` span
  per dispatch (dispatch → result received). A replica death closes the
  open attempt synthetically at detection time, tagged ``killed`` +
  ``synthetic_close`` — the requeued replay then opens ``attempt <n+1>``
  under the SAME ``trace_id``;
* ``fleet lifecycle`` — ``drain replica <i>``, ``rolling_restart`` and
  ``drain`` windows, spawn/death instants.

Workers are armed per-replica (``PADDLE_TPU_TRACE_FILE`` injected by the
router, one fragment file per spawn generation) and additionally emit a
``serve`` span per request on their ``worker engine`` track; a real
engine's serving-cat spans (queued/prefill/decode/lifetime) carry the
FLEET trace id + attempt because the router propagates both through the
submit frames. A SIGKILLed worker writes no fragment — its side of the
timeline is exactly the hole the router's synthetic closure documents.

Per-worker clocks are aligned by the handshake offset the router
measured at spawn (see ``ProcessReplica._clock_sync``): the router
writes ``fleet_manifest.json`` into the trace dir mapping every fragment
to its pid/replica/generation/offset, and :func:`load_fragments` applies
the offsets so all fragments land on the ROUTER's span clock.
:func:`validate_fleet_spans` is the fleet-level analogue of
``serving.trace.validate_request_spans``: every traced request joins
into one well-nested cross-process tree with exactly one terminal, and
orphaned spans (a dispatch whose attempt never closed, a request with no
terminal — a crashed router's leftovers) are closed synthetically and
tagged before the invariants run. ``tools/fleet_trace.py`` is the CLI.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..monitor import tracer as _tr
from ..serving import trace as _sv

__all__ = [
    "CAT", "QUEUE_TRACK", "LIFECYCLE_TRACK", "WORKER_TRACK", "MANIFEST",
    "MANIFEST_SCHEMA", "replica_track",
    "on_submitted", "on_dispatch", "on_attempt_end", "on_terminal",
    "on_lifecycle_span", "on_lifecycle_instant", "on_worker_serve",
    "write_manifest", "load_fragments", "process_names",
    "fleet_request_spans", "close_orphans", "validate_fleet_spans",
]

CAT = "fleet"
QUEUE_TRACK = "fleet queue"
LIFECYCLE_TRACK = "fleet lifecycle"
WORKER_TRACK = "worker engine"
MANIFEST = "fleet_manifest.json"
MANIFEST_SCHEMA = "paddle_tpu.fleet_trace/v1"

_TERMINALS = ("finished", "failed", "timeout", "rejected")


def replica_track(index: int) -> str:
    return "replica %d" % index


def _us(t_s: float) -> int:
    return int(t_s * 1e6)


# -- router-side emission (callers guard on Router._trace; these guard on
# tracer.active() so a stray call without the tracer is one bool read) -------

def on_submitted(fr) -> None:
    if not _tr.active():
        return
    _tr.record_instant(
        "submitted", _us(fr.submitted_t), cat=CAT, track=QUEUE_TRACK,
        args={"trace_id": fr.trace_id, "prompt_len": len(fr.prompt),
              "max_new_tokens": fr.max_new_tokens})


def on_dispatch(fr, replica_index: int) -> None:
    """Close the open queue-wait span and mark the dispatch on the
    replica's track. ``fr.dispatches`` must already count this dispatch
    (it is the 1-based attempt number)."""
    if not _tr.active():
        return
    now = time.perf_counter()
    if fr.queued_since is not None:
        # phase-ledger tags: the first wait is router queue time, every
        # re-dispatch wait is a retry/requeue gap (serving/phases.py)
        retry = fr.dispatches >= 2
        _tr.record_span(
            "queued", _us(fr.queued_since), _us(now) - _us(fr.queued_since),
            cat=CAT, track=QUEUE_TRACK,
            args={"trace_id": fr.trace_id, "attempt": fr.dispatches,
                  "replica": replica_index,
                  "phase": "retry" if retry else "queue",
                  "cause": "requeue" if retry else "router"})
    _tr.record_instant(
        "dispatch", _us(now), cat=CAT, track=replica_track(replica_index),
        args={"trace_id": fr.trace_id, "attempt": fr.dispatches})


def on_attempt_end(fr, replica_index: int, outcome: str,
                   killed: bool = False) -> None:
    """The attempt window: dispatch → result received, or dispatch →
    death detected (then ``killed`` tags the synthetic close — the worker
    never reported, the router is closing the orphan)."""
    if not _tr.active() or fr.dispatched_t is None:
        return
    args = {"trace_id": fr.trace_id, "attempt": fr.dispatches,
            "outcome": outcome}
    if killed:
        args["killed"] = True
        args["synthetic_close"] = True
    _tr.record_span(
        "attempt %d" % fr.dispatches, _us(fr.dispatched_t),
        max(1, _us(time.perf_counter()) - _us(fr.dispatched_t)),
        cat=CAT, track=replica_track(replica_index), args=args)


def on_terminal(fr) -> None:
    """Exactly-once terminal instant on the queue track (plus the close
    of a queue wait that never reached a dispatch — a drain shedding
    queued work)."""
    if not _tr.active():
        return
    end = fr.finished_t if fr.finished_t is not None else time.perf_counter()
    if fr.queued_since is not None:
        _tr.record_span(
            "queued", _us(fr.queued_since), _us(end) - _us(fr.queued_since),
            cat=CAT, track=QUEUE_TRACK,
            args={"trace_id": fr.trace_id, "attempt": None,
                  "phase": "queue", "cause": "shed"})
    _tr.record_instant(
        fr.state, _us(end), cat=CAT, track=QUEUE_TRACK,
        args={"trace_id": fr.trace_id, "state": fr.state,
              "attempts": fr.dispatches})


def on_lifecycle_span(name: str, t0_s: float, t1_s: float,
                      args: Optional[dict] = None) -> None:
    if not _tr.active():
        return
    _tr.record_span(name, _us(t0_s), max(1, _us(t1_s) - _us(t0_s)), cat=CAT,
                    track=LIFECYCLE_TRACK, args=args)


def on_lifecycle_instant(name: str, args: Optional[dict] = None) -> None:
    if not _tr.active():
        return
    _tr.record_instant(name, _us(time.perf_counter()), cat=CAT,
                       track=LIFECYCLE_TRACK, args=args)


def on_worker_serve(trace_id: Optional[str], attempt: int, state: str,
                    t0_s: float, t1_s: float) -> None:
    """Worker-side: one ``serve`` span per request, frame-received →
    result-sent, on the worker's own track. Emitted for sim AND real
    engines, so the cross-process join exists even when the engine has no
    serving-cat tracing of its own."""
    if not _tr.active() or not trace_id:
        return
    _tr.record_span(
        "serve", _us(t0_s), max(1, _us(t1_s) - _us(t0_s)), cat=CAT,
        track=WORKER_TRACK,
        args={"trace_id": trace_id, "attempt": attempt, "state": state})


# -- manifest + merge ---------------------------------------------------------

def write_manifest(trace_dir: str, router_entry: dict,
                   worker_entries: Sequence[dict], run_id: str) -> str:
    """``fleet_manifest.json``: the merge recipe — which fragment file is
    whose, and each worker's measured clock offset (µs; subtracting it
    moves that worker's timestamps onto the router's clock)."""
    doc = {"schema": MANIFEST_SCHEMA, "run_id": run_id,
           "router": dict(router_entry), "workers": list(worker_entries)}
    path = os.path.join(trace_dir, MANIFEST)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
    os.replace(tmp, path)
    return path


def process_names(manifest: dict) -> Dict[int, str]:
    names: Dict[int, str] = {}
    router = manifest.get("router") or {}
    if router.get("pid") is not None:
        names[router["pid"]] = "fleet router"
    for e in manifest.get("workers") or []:
        if e.get("pid") is not None:
            names[e["pid"]] = ("fleet worker replica %s (gen %s)"
                               % (e.get("replica", "?"), e.get("gen", 0)))
    return names


def load_fragments(trace_dir: str
                   ) -> Tuple[List[dict], dict, List[dict]]:
    """Load every fragment the manifest names, apply per-worker clock
    offsets, and return (spans, manifest, problems). A missing or
    unreadable fragment (a SIGKILLed worker never flushes one) is a
    PROBLEM entry, never an exception — the merged timeline of the
    survivors is exactly the post-mortem artifact wanted."""
    with open(os.path.join(trace_dir, MANIFEST)) as f:
        manifest = json.load(f)
    spans: List[dict] = []
    problems: List[dict] = []
    entries = []
    router = manifest.get("router") or {}
    if router.get("file"):
        entries.append(dict(router, replica=None))
    entries.extend(manifest.get("workers") or [])
    for e in entries:
        fname = e.get("file")
        if not fname:
            continue
        path = os.path.join(trace_dir, fname)
        if not os.path.exists(path):
            problems.append({"file": fname, "replica": e.get("replica"),
                             "gen": e.get("gen"), "problem": "missing"})
            continue
        try:
            frag = _tr.load_spans(path)
        except Exception as ex:
            problems.append({"file": fname, "replica": e.get("replica"),
                             "gen": e.get("gen"),
                             "problem": "unreadable: %s" % ex})
            continue
        off = int(e.get("offset_us", 0) or 0)
        for s in frag:
            if off:
                s = dict(s, ts_us=int(s.get("ts_us", 0)) - off)
            spans.append(s)
    return spans, manifest, problems


# -- read-back / validation ---------------------------------------------------

def fleet_request_spans(spans: Sequence[dict]) -> Dict[str, List[dict]]:
    """Group spans of EVERY category by ``args.trace_id``, keeping only
    trace ids rooted by a fleet ``submitted`` instant (the router's view
    defines the request set; engine-local ``req-*`` ids without a fleet
    root are not fleet requests)."""
    by_id: Dict[str, List[dict]] = {}
    roots = set()
    for s in spans:
        tid = (s.get("args") or {}).get("trace_id")
        if not tid:
            continue
        by_id.setdefault(tid, []).append(s)
        if s.get("cat") == CAT and s.get("name") == "submitted":
            roots.add(tid)
    return {tid: v for tid, v in by_id.items() if tid in roots}


def _attempt_no(s: dict) -> Optional[int]:
    a = (s.get("args") or {}).get("attempt")
    return int(a) if a is not None else None


def close_orphans(spans: Sequence[dict]) -> Tuple[List[dict], int]:
    """Synthesize closure for what a death left open: a ``dispatch``
    instant whose attempt span never closed becomes a synthetic attempt
    span (tagged ``synthetic``/``killed``) running to the end of the
    trace, and a submitted request with no terminal instant gets a
    synthetic ``failed`` terminal. Returns (spans + synthesized, count).
    A cleanly drained router produces zero orphans — the router itself
    closes killed attempts at death-detection time."""
    spans = list(spans)
    t_max = max((int(s.get("ts_us", 0)) + int(s.get("dur_us", 0))
                 for s in spans), default=0)
    synth: List[dict] = []
    for tid, mine in fleet_request_spans(spans).items():
        fleet_mine = [s for s in mine if s.get("cat") == CAT]
        closed = {_attempt_no(s) for s in fleet_mine
                  if s.get("name", "").startswith("attempt")
                  and s.get("dur_us")}
        for s in fleet_mine:
            if s.get("name") != "dispatch" or s.get("dur_us"):
                continue
            a = _attempt_no(s)
            if a in closed:
                continue
            synth.append({
                "name": "attempt %s" % a, "cat": CAT,
                "ts_us": int(s["ts_us"]),
                "dur_us": max(1, t_max - int(s["ts_us"])),
                "pid": s.get("pid", 0), "tid": s.get("tid", 0),
                **({"track": s["track"]} if s.get("track") else {}),
                "args": {"trace_id": tid, "attempt": a, "outcome": "lost",
                         "killed": True, "synthetic": True}})
        if not any(s.get("name") in _TERMINALS and not s.get("dur_us")
                   for s in fleet_mine):
            anchor = fleet_mine[0]
            synth.append({
                "name": "failed", "cat": CAT, "ts_us": t_max, "dur_us": 0,
                "pid": anchor.get("pid", 0),
                "tid": anchor.get("tid", 0), "track": QUEUE_TRACK,
                "args": {"trace_id": tid, "state": "failed",
                         "synthetic": True}})
    return spans + synth, len(synth)


def validate_fleet_spans(spans: Sequence[dict], slack_us: int = 20000
                         ) -> Dict[str, dict]:
    """The fleet-level analogue of ``serving.trace.validate_request_spans``
    over a MERGED multi-process span set (offsets already applied).

    Per fleet request: a ``submitted`` instant, >= 1 ``queued`` span,
    exactly ONE terminal instant; attempt spans with strictly increasing
    attempt numbers and non-overlapping windows in order; every
    worker-side span of the request (the worker ``serve`` span, a real
    engine's serving-cat spans) contained in its attempt's window within
    ``slack_us`` (the clock-offset correction error bound — an unaligned
    merge fails HERE). Orphans are closed synthetically first (tagged, so
    the digest reports them). Per-process serving-cat tracks must be
    well-nested (the shared serving validator core). Returns
    {trace_id: digest}."""
    spans, n_synth = close_orphans(spans)
    digests: Dict[str, dict] = {}
    for tid, mine in fleet_request_spans(spans).items():
        fleet_mine = [s for s in mine if s.get("cat") == CAT]
        router_pid = next(s.get("pid") for s in fleet_mine
                          if s.get("name") == "submitted")
        names = [s.get("name") for s in fleet_mine]
        assert "queued" in names, \
            "request %s: no queued span (names: %s)" % (tid, names)
        terminals = [s for s in fleet_mine
                     if s.get("name") in _TERMINALS and not s.get("dur_us")]
        assert len(terminals) == 1, \
            "request %s: %d terminal instants (want exactly 1)" \
            % (tid, len(terminals))
        attempts = sorted(
            (s for s in fleet_mine
             if s.get("name", "").startswith("attempt") and s.get("dur_us")),
            key=lambda s: _attempt_no(s) or 0)
        nums = [_attempt_no(s) for s in attempts]
        assert nums == sorted(set(nums)), \
            "request %s: attempt numbers not strictly increasing: %s" \
            % (tid, nums)
        windows: Dict[int, Tuple[int, int]] = {}
        prev_hi = None
        for s in attempts:
            lo = int(s["ts_us"])
            hi = lo + int(s["dur_us"])
            if prev_hi is not None:
                assert lo >= prev_hi - slack_us, (
                    "request %s: attempt %s [%d,%d] overlaps the previous "
                    "attempt (ended %d)" % (tid, _attempt_no(s), lo, hi,
                                            prev_hi))
            prev_hi = hi
            windows[_attempt_no(s)] = (lo, hi)
        union = list(windows.values())
        worker_spans = 0
        for s in mine:
            if s.get("pid") == router_pid and s.get("cat") == CAT:
                continue
            worker_spans += 1
            lo = int(s.get("ts_us", 0))
            hi = lo + int(s.get("dur_us", 0))
            a = _attempt_no(s)
            cands = [windows[a]] if a in windows else union
            assert any(w[0] - slack_us <= lo and hi <= w[1] + slack_us
                       for w in cands), (
                "request %s: worker span %r [%d,%d] escapes its attempt "
                "window(s) %s (+/-%dus) — clock offsets misapplied?"
                % (tid, s.get("name"), lo, hi, cands, slack_us))
        outcomes = {n: (s.get("args") or {}).get("outcome")
                    for n, s in zip(nums, attempts)}
        digests[tid] = {
            "state": terminals[0].get("name"),
            "attempts": nums,
            "outcomes": outcomes,
            "killed": [n for n, s in zip(nums, attempts)
                       if (s.get("args") or {}).get("killed")],
            "worker_spans": worker_spans,
            "synthetic": any((s.get("args") or {}).get("synthetic")
                             for s in fleet_mine),
        }
    # worker engine internals: each process's serving tracks well-nested
    _sv.assert_well_nested(spans, cat=_sv.CAT)
    # lifecycle windows (drain-within-rolling-restart) nest too
    life = [s for s in spans
            if s.get("cat") == CAT
            and (s.get("name") == "rolling_restart"
                 or str(s.get("name", "")).startswith("drain"))]
    _sv.assert_well_nested(life, cat=CAT, exempt=())
    digests["_meta"] = {"synthetic_closures": n_synth,
                        "requests": len(digests)}
    return digests
