"""Breach autopsy: SLO breaches joined against the span-derived phase ledger.

``monitor/slo.py`` says *that* an objective was violated; this module says
*why*. It is pure read-side — nothing here runs in a hot path:

1. :func:`build_ledgers` replays a traced fleet run (the merged fragment
   stream ``fleet.trace.load_fragments`` produces) through
   ``serving.phases.ledgers_from_spans``, using the trace manifest to map
   worker pids onto replica indices — every request becomes a
   :class:`~paddle_tpu.serving.phases.RequestLedger` whose intervals carry
   (phase, cause, replica, attempt).
2. :func:`phase_stats` folds the ledgers into per-phase percentile budgets
   at fleet and per-replica scope, and
   :func:`observe_phase_histograms` feeds the same totals into the
   ``fleet/phase/<name>/ms`` registry histograms so the ordinary metrics
   surfaces (snapshot/telemetry/fleet_top) can render the decomposition.
3. :func:`autopsy_breaches` joins each recorded SLO breach against the
   ledgers (and, when available, the per-replica telemetry interval
   deltas of the breach window) and emits a typed :class:`BreachAutopsy`
   verdict: the dominant phase, the offending replica(s), exemplar
   ``trace_id``s to pull up in the merged timeline, and an actionable
   hint. The router journals each verdict in the fleet event log
   (``kind=breach_autopsy``, under the run's ``run_id``) and the flight
   ring when it closes a traced run; ``tools/fleet_autopsy.py`` is the
   offline CLI over the same artifacts.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

from ..monitor import metrics as _mx
from ..monitor import telemetry as _telemetry
from ..serving import phases as _phases
from . import metrics as _fm
from .slo import sample_from_doc

__all__ = ["BreachAutopsy", "build_ledgers", "pid_to_replica",
           "phase_stats", "observe_phase_histograms", "autopsy_breaches",
           "run_autopsy"]

# dominant phase -> what an operator should actually do about it
_HINTS = {
    _phases.QUEUE: "queue-bound: requests waited for capacity — add "
                   "replicas, raise engine slots, or shed load earlier",
    _phases.ADMISSION: "admission-bound: slot arming / page reservation "
                       "gap between admission and prefill — check page "
                       "pool pressure",
    _phases.PREFILL: "prefill-bound: prompt compute dominates — enable "
                     "the prefix cache or disaggregate prefill",
    _phases.SHIP: "migration-bound: KV-page shipping dominates — check "
                  "page frame sizes and the migration path",
    _phases.DECODE: "decode-bound: per-step decode latency is the "
                    "problem on the offending replica — look for "
                    "interference, injected faults, or an overloaded "
                    "host",
    _phases.VERIFY: "speculation-bound: draft-verify windows dominate "
                    "with low acceptance — lower draft k or disable "
                    "speculation for this traffic",
    _phases.RETRY: "churn-bound: requeue gaps after replica loss — "
                   "check replica crash/restart history",
    _phases.TAIL: "tail-bound: drain/timeout tails past the last "
                  "dispatch — raise drain budget or deadlines",
}


class BreachAutopsy:
    """One SLO breach explained: which phase ate the time, where, and
    which requests to look at. ``to_doc`` is the event-log payload."""

    __slots__ = ("breach", "scope", "replica", "dominant_phase",
                 "dominant_ms", "dominant_share", "phase_ms", "offenders",
                 "exemplars", "requests", "hint")

    def __init__(self, breach: dict, scope: str, replica: Optional[int],
                 dominant_phase: Optional[str], dominant_ms: float,
                 dominant_share: float, phase_ms: Dict[str, float],
                 offenders: List[dict], exemplars: List[str],
                 requests: int, hint: str):
        self.breach = breach
        self.scope = scope
        self.replica = replica
        self.dominant_phase = dominant_phase
        self.dominant_ms = dominant_ms
        self.dominant_share = dominant_share
        self.phase_ms = phase_ms
        self.offenders = offenders
        self.exemplars = exemplars
        self.requests = requests
        self.hint = hint

    def to_doc(self) -> dict:
        return {
            "slo": self.breach.get("slo"),
            "metric": self.breach.get("metric"),
            "scope": self.scope,
            "replica": self.replica,
            "dominant_phase": self.dominant_phase,
            "dominant_ms": round(self.dominant_ms, 3),
            "dominant_share": round(self.dominant_share, 4),
            "phase_ms": {k: round(v, 3)
                         for k, v in self.phase_ms.items() if v > 0},
            "offenders": self.offenders,
            "exemplars": self.exemplars,
            "requests": self.requests,
            "hint": self.hint,
            "breach": self.breach,
        }

    def __repr__(self):
        off = (self.offenders[0].get("replica")
               if self.offenders else self.replica)
        return ("BreachAutopsy(%s: dominant=%s %.1fms (%.0f%%), "
                "replica=%s)" % (self.breach.get("slo"),
                                 self.dominant_phase, self.dominant_ms,
                                 self.dominant_share * 100.0, off))


def pid_to_replica(manifest: Optional[dict]) -> Dict[int, int]:
    """Worker pid -> replica index from the trace manifest (the join that
    gives engine-side serving spans their replica attribution)."""
    out: Dict[int, int] = {}
    for e in (manifest or {}).get("workers") or []:
        if e.get("pid") is not None and e.get("replica") is not None:
            out[int(e["pid"])] = int(e["replica"])
    return out


def build_ledgers(spans: Sequence[dict], manifest: Optional[dict] = None
                  ) -> Dict[str, "_phases.RequestLedger"]:
    """Phase ledgers for every traced request of a merged fleet stream
    (clock offsets must already be applied — ``load_fragments`` output)."""
    return _phases.ledgers_from_spans(spans, pid_to_replica(manifest))


def _per_request_phase_ms(led) -> Dict[str, float]:
    return {p: v for p, v in led.phase_ms().items() if v > 0}


def _replica_phase_ms(led) -> Dict[int, Dict[str, float]]:
    out: Dict[int, Dict[str, float]] = {}
    for iv in led.intervals:
        if iv.replica is None:
            continue
        d = out.setdefault(int(iv.replica), {})
        d[iv.phase] = d.get(iv.phase, 0.0) + iv.ms
    return out


def phase_stats(ledgers: Dict[str, "_phases.RequestLedger"]) -> dict:
    """Fold ledgers into per-phase budgets: per-request distributions at
    fleet scope and per replica. ``{"fleet": {phase: {count, total_ms,
    p50_ms, p99_ms}}, "replicas": {index: {...}}, "requests": n}``."""
    fleet_vals: Dict[str, List[float]] = {p: [] for p in _phases.PHASES}
    rep_vals: Dict[int, Dict[str, List[float]]] = {}
    n = 0
    for led in ledgers.values():
        if led.state is None:
            continue
        n += 1
        for p, v in _per_request_phase_ms(led).items():
            fleet_vals.setdefault(p, []).append(v)
        for r, pm in _replica_phase_ms(led).items():
            d = rep_vals.setdefault(r, {})
            for p, v in pm.items():
                if v > 0:
                    d.setdefault(p, []).append(v)

    def _fold(vals: Dict[str, List[float]]) -> Dict[str, dict]:
        out = {}
        for p, xs in vals.items():
            if not xs:
                continue
            xs = sorted(xs)
            out[p] = {"count": len(xs),
                      "total_ms": round(sum(xs), 3),
                      "p50_ms": round(_mx.sorted_percentile(xs, 50), 3),
                      "p99_ms": round(_mx.sorted_percentile(xs, 99), 3)}
        return out

    return {"fleet": _fold(fleet_vals),
            "replicas": {r: _fold(v) for r, v in sorted(rep_vals.items())},
            "requests": n}


def observe_phase_histograms(ledgers: Dict[str, "_phases.RequestLedger"]
                             ) -> int:
    """Feed per-request phase totals into the ``fleet/phase/<name>/ms``
    registry histograms (one observation per request per non-zero phase)
    — the metrics-surface face of the decomposition. Returns the number
    of requests observed."""
    n = 0
    for led in ledgers.values():
        if led.state is None:
            continue
        n += 1
        for p, v in _per_request_phase_ms(led).items():
            h = _fm.PHASE_MS.get(p)
            if h is not None:
                h.observe(v)
    return n


def _telemetry_offenders(breach: dict, telemetry_base: str) -> List[dict]:
    """Rank replicas by the breached metric's interval mean in (or near)
    the breach window, from each replica's telemetry ring. Only histogram
    metrics rank this way (the latency-shaped breaches); an empty list
    means the caller falls back to ledger attribution."""
    metric = breach.get("metric")
    if not metric or not telemetry_base or not os.path.isdir(telemetry_base):
        return []
    window = breach.get("window") or {}
    t_b = float(window.get("t", 0.0) or 0.0)
    dt_b = float(window.get("dt_s", 0.0) or 0.0)
    ranked: List[dict] = []
    for name in sorted(os.listdir(telemetry_base)):
        if not name.startswith("replica_"):
            continue
        try:
            idx = int(name.split("_", 1)[1])
        except (IndexError, ValueError):
            continue
        try:
            docs = _telemetry.read_series(
                os.path.join(telemetry_base, name))
        except Exception:
            continue
        in_window: List[float] = []
        anywhere: List[float] = []
        for doc in docs:
            s = sample_from_doc(doc)
            v = s.histogram_interval_mean(metric)
            if v is None:
                continue
            anywhere.append(v)
            if not t_b or abs(s.t - t_b) <= 2.0 * max(dt_b, s.dt_s, 1.0):
                in_window.append(v)
        vals = in_window or anywhere
        if vals:
            ranked.append({"replica": idx,
                           "mean_ms": round(max(vals), 3),
                           "source": "telemetry",
                           "in_window": bool(in_window)})
    ranked.sort(key=lambda d: -d["mean_ms"])
    return ranked


def _ledger_offenders(candidates, phase: str) -> List[dict]:
    """Rank replicas by mean per-request milliseconds attributed to
    ``phase`` across the candidate ledgers."""
    per_rep: Dict[int, List[float]] = {}
    for led in candidates:
        for r, pm in _replica_phase_ms(led).items():
            v = pm.get(phase, 0.0)
            if v > 0:
                per_rep.setdefault(r, []).append(v)
    ranked = [{"replica": r, "mean_ms": round(sum(xs) / len(xs), 3),
               "requests": len(xs), "source": "ledger"}
              for r, xs in per_rep.items()]
    ranked.sort(key=lambda d: -d["mean_ms"])
    return ranked


def autopsy_breaches(breaches: Sequence[dict],
                     ledgers: Dict[str, "_phases.RequestLedger"],
                     telemetry_base: Optional[str] = None
                     ) -> List[BreachAutopsy]:
    """One :class:`BreachAutopsy` per distinct recorded breach.

    ``breaches`` are breach docs (``Breach.to_doc()``) optionally
    enriched with ``scope`` ("replica"/"fleet") and ``replica`` the way
    the router's event log records them; duplicates (same slo/scope/
    replica across evaluation ticks) collapse to the LAST occurrence.
    Attribution: candidate requests are the terminal ledgers (restricted
    to the breached replica for replica-scope breaches); the dominant
    phase is the largest total-milliseconds phase across candidates;
    offenders rank by the breach window's telemetry interval deltas when
    a ring is available, else by per-replica ledger totals; exemplars are
    the candidate requests that spent the most time in the dominant
    phase."""
    terminal = [led for led in ledgers.values() if led.state is not None]
    dedup: Dict[tuple, dict] = {}
    for b in breaches:
        key = (b.get("slo"), b.get("scope", "fleet"), b.get("replica"))
        dedup[key] = b  # keep-last
    out: List[BreachAutopsy] = []
    for (slo, scope, replica), b in dedup.items():
        if replica is not None:
            replica = int(replica)
            candidates = [led for led in terminal
                          if replica in led.replicas]
            # an unattributable breach window still gets a fleet-wide read
            if not candidates:
                candidates = terminal
        else:
            candidates = terminal
        totals: Dict[str, float] = {p: 0.0 for p in _phases.PHASES}
        for led in candidates:
            for p, v in _per_request_phase_ms(led).items():
                totals[p] = totals.get(p, 0.0) + v
        all_ms = sum(totals.values())
        dominant = max(totals, key=totals.get) if all_ms > 0 else None
        dominant_ms = totals.get(dominant, 0.0) if dominant else 0.0
        if replica is not None:
            offenders = _ledger_offenders(candidates, dominant) \
                if dominant else []
            offenders = [o for o in offenders
                         if o["replica"] == replica] or \
                [{"replica": replica, "source": "breach"}]
        else:
            offenders = (_telemetry_offenders(b, telemetry_base or "")
                         or (_ledger_offenders(candidates, dominant)
                             if dominant else []))
        offender_rep = (offenders[0].get("replica") if offenders
                        else replica)
        ex_pool = [led for led in candidates
                   if offender_rep is None
                   or offender_rep in led.replicas] or candidates
        ex_pool.sort(key=lambda led: -led.phase_ms().get(dominant or "", 0.0))
        exemplars = [led.trace_id for led in ex_pool[:3]]
        hint = _HINTS.get(dominant or "", "no phase attribution available")
        if offender_rep is not None and dominant:
            hint = "replica %s is the offender — %s" % (offender_rep, hint)
        out.append(BreachAutopsy(
            breach=b, scope=scope or "fleet", replica=replica,
            dominant_phase=dominant, dominant_ms=dominant_ms,
            dominant_share=(dominant_ms / all_ms) if all_ms > 0 else 0.0,
            phase_ms=totals, offenders=offenders[:4], exemplars=exemplars,
            requests=len(candidates), hint=hint))
    return out


def run_autopsy(trace_dir: str, event_log: Optional[str] = None,
                telemetry_base: Optional[str] = None) -> dict:
    """Offline autopsy over a finished run's artifacts: merge the trace
    fragments, build the ledgers, and (when an event log is given) join
    its recorded ``slo_breach`` events. Returns ``{"ledgers", "stats",
    "autopsies", "manifest", "problems"}`` — the CLI's whole input."""
    from . import trace as _ftr
    from .events import KIND_SLO_BREACH, read_events

    spans, manifest, problems = _ftr.load_fragments(trace_dir)
    ledgers = build_ledgers(spans, manifest)
    breaches: List[dict] = []
    if event_log:
        breaches = read_events(event_log, kind=KIND_SLO_BREACH)
    return {
        "ledgers": ledgers,
        "stats": phase_stats(ledgers),
        "autopsies": autopsy_breaches(breaches, ledgers,
                                      telemetry_base=telemetry_base),
        "manifest": manifest,
        "problems": problems,
    }
