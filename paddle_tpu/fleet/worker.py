"""Replica worker: one engine in one process, driven over stdin/stdout.

``python -m paddle_tpu.fleet.worker`` is what a :class:`ProcessReplica`
spawns. The first frame on stdin is the engine spec; everything the
router needs afterwards rides the frame protocol (protocol.py):

ops (router -> worker)::

    {"op": "spec", "spec": {...}}            # first frame only
    {"op": "submit", "id": <fleet id>, "prompt": [...],
     "max_new_tokens": n, "temperature": t, "top_k": k, "seed": s,
     "deadline_s": d, "speculation": None|0|k|"auto"}
    {"op": "health"}                         # answered by a health event
    {"op": "clock"}                          # answered by a clock event
    {"op": "export_prefix", "xid", "tokens"}   # -> pages (binary) | miss
    {"op": "export_request", "xid", "id"}      # -> pages (binary) | miss
    {"op": "evict_prefix", "xid", "tokens"}    # -> evicted
    <binary frame: pack_pages({"op": "import_prefix", "xid", "tokens",
     ...geometry...}, blobs)>                  # -> imported
    {"op": "drain", "timeout_s": t}          # graceful stop, then exit
    {"op": "shutdown"}                       # immediate close, then exit

events (worker -> router)::

    {"ev": "ready", "pid": ...}              # spec accepted, engine warm
    {"ev": "clock", "t_us": ...}             # tracer.now_us() snapshot
    {"ev": "result", "id", "state", "tokens", "error"[, "kind"]}
    {"ev": "health", "health": {...}}
    <binary frame: pack_pages({"ev": "pages", "xid", "ok": true, "tokens",
     ...geometry...}, blobs)>                # a KV-page export answer
    {"ev": "pages", "xid", "ok": false}      # export miss/refusal
    {"ev": "imported", "xid", "ok", "pages"} # import ack
    {"ev": "evicted", "xid", "pages"}        # evict ack
    {"ev": "drained", "summary": {...}}      # last frame before exit

Tracing: submits carry the fleet ``trace_id`` + ``attempt``, threaded
into the engine's Request so a real engine's serving spans join the
cross-process tree; the worker additionally emits one fleet-cat
``serve`` span per request (frame received → result sent) so sim-engine
workers are joinable too. The fragment file itself is the ordinary
``PADDLE_TPU_TRACE_FILE`` autostart (armed per-replica by the router);
the ``clock`` op is the router's offset handshake. An optional spec key
``"fault_plan"`` installs a ``reliability.faults`` plan process-wide —
per-replica chaos (e.g. a latency fault degrading one replica's tail).

The spec is the ISSUE's "engine handle extraction": the serving engine's
construction knobs, serialized. ``{"engine": "real", "model": {DecoderConfig
kwargs}, "model_seed": n, "serving": {ServingConfig kwargs}, "warmup": true}``
builds a DecoderLM + ServingEngine; ``{"engine": "sim", "sim": {SimConfig
kwargs}}`` builds the device-latency simulator (protocol/scaling benches on
hosts with no parallel compute to give).

fd hygiene: the frame channel is a dup of fd 1 taken at startup, after
which fd 1 is pointed at stderr — a stray ``print`` inside jax or user
code can then never corrupt the frame stream.

Request accounting mirrors InProcessReplica: every submitted id gets
exactly one result event — typed rejections (draining/backpressure)
carry ``kind`` so the router re-routes instead of terminating them, and a
drain reports the terminal state of everything still tracked before the
``drained`` frame.
"""

from __future__ import annotations

import os
import select
import sys
import time
from typing import Dict, Optional

from ..monitor import tracer as _tracer
from ..serving.request import (FAILED, REJECTED, BackpressureError,
                               DrainingError, Request)
from . import trace as _ftrace
from .protocol import (Binary, FrameReader, pack_pages, send_binary_frame,
                       send_frame, unpack_pages)

__all__ = ["main"]


def _build_engine(spec: dict):
    if spec.get("engine", "real") == "sim":
        from .replica import SimConfig, SimEngine

        cfg = SimConfig(**spec.get("sim", {}))
        # one engine per worker process: serving-slot virtual tracks are
        # collision-free here, so the sim emits the full serving-cat
        # request lifecycle the phase ledger decomposes
        cfg.serving_spans = True
        return SimEngine(cfg)
    from ..models.decoder_lm import DecoderConfig, DecoderLM
    from ..serving.engine import ServingConfig, ServingEngine

    mcfg = DecoderConfig(**spec.get("model", {}))
    model = DecoderLM(mcfg, seed=int(spec.get("model_seed", 0)))
    engine = ServingEngine(model, ServingConfig(**spec.get("serving", {})))
    if spec.get("warmup"):
        engine.warmup()
    return engine


class _Worker:
    def __init__(self, chan, engine):
        self.chan = chan
        self.engine = engine
        self._by_req: Dict[int, int] = {}      # engine Request.id -> fleet id
        self._requests: Dict[int, Request] = {}
        # engine Request.id -> (trace_id, attempt, frame-received time);
        # feeds the per-request ``serve`` span on the worker's own track
        self._meta: Dict[int, tuple] = {}

    def emit(self, ev: dict) -> None:
        send_frame(self.chan, ev)

    def _result(self, req: Request) -> None:
        fid = self._by_req.pop(req.id, None)
        self._requests.pop(req.id, None)
        meta = self._meta.pop(req.id, None)
        if fid is None:
            return
        if meta is not None:
            _ftrace.on_worker_serve(meta[0], meta[1], req.state, meta[2],
                                    time.perf_counter())
        self.emit({"ev": "result", "id": fid, "state": req.state,
                   "tokens": list(req.tokens_out), "error": req.error})

    def submit(self, op: dict) -> None:
        t_recv = time.perf_counter()
        try:
            req = self.engine.submit(
                op["prompt"], op["max_new_tokens"],
                deadline_s=op.get("deadline_s"),
                temperature=op.get("temperature", 0.0),
                top_k=op.get("top_k", 0), seed=op.get("seed"),
                trace_id=op.get("trace_id"),
                attempt=int(op.get("attempt", 0)),
                speculation=op.get("speculation"))
        except DrainingError:
            self.emit({"ev": "result", "id": op["id"], "state": REJECTED,
                       "kind": "draining"})
            return
        except BackpressureError:
            self.emit({"ev": "result", "id": op["id"], "state": REJECTED,
                       "kind": "backpressure"})
            return
        except ValueError as e:
            self.emit({"ev": "result", "id": op["id"], "state": FAILED,
                       "tokens": [], "error": str(e)})
            return
        self._by_req[req.id] = op["id"]
        self._requests[req.id] = req
        self._meta[req.id] = (op.get("trace_id"), int(op.get("attempt", 0)),
                              t_recv)

    def pump(self) -> None:
        for req in self.engine.step():
            self._result(req)

    # -- KV-page migration ops ------------------------------------------------
    # export answers ride ONE binary frame (meta envelope + raw page
    # blobs, see protocol.pack_pages); misses and import acks are plain
    # JSON events. Engines without the migration surface (or layouts
    # without pages) answer honest misses/refusals, never crash.
    def _emit_pages(self, xid, res, tokens=None) -> None:
        if res is None:
            self.emit({"ev": "pages", "xid": xid, "ok": False})
            return
        if tokens is None:
            tokens, meta, blobs = res
        else:
            meta, blobs = res
        head = dict(meta, ev="pages", xid=xid, ok=True,
                    tokens=[int(t) for t in tokens])
        send_binary_frame(self.chan, pack_pages(head, blobs))

    def export_prefix(self, op: dict) -> None:
        res = None
        if hasattr(self.engine, "export_prefix_pages"):
            try:
                res = self.engine.export_prefix_pages(op.get("tokens") or [])
            except ValueError:
                res = None
        self._emit_pages(op.get("xid"), res, tokens=op.get("tokens") or [])

    def export_request(self, op: dict) -> None:
        res = None
        fid = op.get("id")
        rid = next((r for r, f in self._by_req.items() if f == fid), None)
        req = self._requests.get(rid) if rid is not None else None
        if req is not None and hasattr(self.engine, "export_request_prefix"):
            try:
                res = self.engine.export_request_prefix(req)
            except ValueError:
                res = None
        self._emit_pages(op.get("xid"), res)

    def import_prefix(self, meta: dict, blobs) -> None:
        ok = False
        if hasattr(self.engine, "ingest_prefix_pages"):
            try:
                ok = bool(self.engine.ingest_prefix_pages(
                    meta.get("tokens") or [], meta, blobs))
            except Exception:
                ok = False
        self.emit({"ev": "imported", "xid": meta.get("xid"), "ok": ok,
                   "pages": int(meta.get("n_pages", 0)) if ok else 0})

    def evict_prefix(self, op: dict) -> None:
        n = 0
        if hasattr(self.engine, "evict_prefix"):
            try:
                n = int(self.engine.evict_prefix(op.get("tokens") or []))
            except Exception:
                n = 0
        self.emit({"ev": "evicted", "xid": op.get("xid"), "pages": n})

    def busy(self) -> bool:
        if hasattr(self.engine, "idle"):
            return not self.engine.idle()
        return not self.engine.scheduler.idle()

    def drain(self, timeout_s: Optional[float]) -> None:
        summary = self.engine.drain(timeout_s)
        for rid in list(self._by_req):
            req = self._requests.pop(rid, None)
            fid = self._by_req.pop(rid)
            meta = self._meta.pop(rid, None)
            if req is None:
                continue
            state = req.state if req.state != "running" else "timeout"
            if meta is not None:
                _ftrace.on_worker_serve(meta[0], meta[1], state, meta[2],
                                        time.perf_counter())
            ev = {"ev": "result", "id": fid, "state": state,
                  "tokens": list(req.tokens_out), "error": req.error}
            if state == REJECTED:
                # shed by the drain, not refused by policy: the router
                # re-routes these to a peer — zero rejected-by-bug
                ev["kind"] = "draining"
            self.emit(ev)
        self.emit({"ev": "drained", "summary": summary})


def main() -> int:
    # claim the frame channel, then point fd 1 at stderr so stray prints
    # (jax warnings, user hooks) can never tear a frame
    chan = os.fdopen(os.dup(1), "wb", buffering=0)
    os.dup2(2, 1)
    sys.stdout = sys.stderr

    stdin_fd = sys.stdin.fileno()
    os.set_blocking(stdin_fd, False)
    reader = FrameReader(stdin_fd)

    spec = None
    deadline = time.monotonic() + 60.0
    while spec is None and time.monotonic() < deadline:
        select.select([stdin_fd], [], [], 1.0)
        for frame in reader.drain():
            if isinstance(frame, Binary):
                continue
            if frame.get("op") == "spec":
                spec = frame.get("spec", {})
                break
        if reader.eof:
            return 1
    if spec is None:
        return 1

    # the worker process owns its telemetry ring (PADDLE_TPU_TELEMETRY_DIR
    # is set per-replica by ProcessReplica): sim engines get a series too,
    # and release() flushes a final partial sample even for short lives
    from ..monitor import telemetry as _telemetry

    if spec.get("fault_plan"):
        # per-replica chaos: the router passes a plan for THIS replica
        # only (FleetConfig.spec_overrides), e.g. a latency fault that
        # degrades one replica's tail for the fleet-SLO drill
        from ..reliability import faults as _faults

        _faults.install(_faults.FaultPlan.parse(str(spec["fault_plan"])))

    tele = _telemetry.acquire()
    try:
        worker = _Worker(chan, _build_engine(spec))
        worker.emit({"ev": "ready", "pid": os.getpid()})

        while True:
            timeout = 0.0 if worker.busy() else 0.05
            select.select([stdin_fd], [], [], timeout)
            for op in reader.drain():
                if isinstance(op, Binary):
                    # the bulk lane: one self-describing page payload
                    try:
                        meta, blobs = unpack_pages(op.payload)
                    except ValueError:
                        continue  # foreign/garbled payload: drop
                    if meta.get("op") == "import_prefix":
                        worker.import_prefix(meta, blobs)
                    continue
                kind = op.get("op")
                if kind == "submit":
                    worker.submit(op)
                elif kind == "export_prefix":
                    worker.export_prefix(op)
                elif kind == "export_request":
                    worker.export_request(op)
                elif kind == "evict_prefix":
                    worker.evict_prefix(op)
                elif kind == "health":
                    worker.emit({"ev": "health",
                                 "health": worker.engine.health()})
                elif kind == "clock":
                    # offset handshake: one span-clock sample, answered
                    # immediately (the router brackets it with its own
                    # now_us() reads and takes the midpoint)
                    worker.emit({"ev": "clock", "t_us": _tracer.now_us()})
                elif kind == "drain":
                    worker.drain(op.get("timeout_s"))
                    return 0
                elif kind == "shutdown":
                    worker.engine.close()
                    return 0
            if reader.eof:
                # router gone: nothing to report results to — close + exit
                worker.engine.close()
                return 0
            if worker.busy():
                worker.pump()
    finally:
        _telemetry.release(tele)


if __name__ == "__main__":
    sys.exit(main())
