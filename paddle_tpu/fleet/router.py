"""Health-aware fleet router: one bounded queue over N engine replicas.

The front door of the serving fleet. The router owns a bounded request
queue and a set of replicas (:class:`~.replica.InProcessReplica` for
tests/benches, :class:`~.replica.ProcessReplica` workers in production
shape) and guarantees, through every failure mode it knows about:

* **exactly-once terminal accounting** — every accepted request reaches
  exactly ONE terminal state (finished/failed/timeout/rejected), recorded
  on its :class:`FleetRequest`. Late/duplicate results after a requeue
  race are absorbed (``fleet/duplicate_results``), never double-counted;
* **crash tolerance** — a replica that dies (SIGKILL, OOM) is detected via
  its pipe/exit status; its in-flight requests requeue idempotently by
  fleet id (``fleet/requeued``) and replay bit-identically: the router
  pins every request's seed at submission, and sampling is keyed (seed,
  absolute position), so a retried stream equals the unkilled twin's;
* **health-aware dispatch** — replicas whose ``health()`` reports
  ``degraded`` (SLO breach, absorbed faults) are drained of NEW traffic
  but not killed; with no healthy replica accepting, requests stay queued
  (``fleet/no_healthy_replica``) rather than failing;
* **graceful rollout** — :meth:`rolling_restart` = per replica
  ``drain(timeout_s)`` → respawn. Requests the drain sheds come back as
  typed ``draining`` rejections and are re-routed to peers — zero
  rejected-by-bug.

Affinity: ``affinity="prefix"`` routes by a stable hash of the first
``affinity_tokens`` prompt tokens, so one conversation/system-prompt
cohort lands on one replica and its KV pages (and prefix-cache entries)
stay hot there; ``"round_robin"`` is the reference spread.

Disaggregation (``roles="P:D"``): the fleet splits into prefill-heavy
and decode-heavy replicas. A request prefills on a prefill replica (an
internal one-token job), its KV pages ship to a decode replica over the
binary page frame (fleet.protocol), and decoding resumes there through
the engine's prefix-resume path — so prefill bursts never interleave
with (and stall) in-flight decode steps. The same page-migration
primitive powers the fleet-wide prefix cache (a prefix cached on
replica A serves a request routed to B), pool-pressure rebalancing, and
live :meth:`Router.scale_down`. Migrated streams are bit-identical to
their unmigrated twins (sampling is keyed (seed, position), and KV
pages are exact byte copies); a failed migration falls back to a cold
dispatch, never to a loss.

The router is single-threaded by design: :meth:`pump` is the event loop
tick (poll replicas → account results → detect deaths → dispatch), and
everything else composes on it. No locks, no callback hell — the same
drive-loop shape as ``ServingEngine.step``.
"""

from __future__ import annotations

import itertools
import json
import os
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Sequence

from ..monitor import runlog as _runlog
from ..monitor import tracer as _tr
from ..serving.request import FAILED, FINISHED, REJECTED, TIMEOUT
from . import autopsy as _autopsy
from . import metrics as _fm
from . import trace as _ftr
from .events import KIND_BREACH_AUTOPSY, FleetEventLog
from .prefix_cache import prefix_key
from .replica import InProcessReplica, ProcessReplica
from .slo import FleetSLO, fleet_slos_from_env

__all__ = ["FleetConfig", "FleetRequest", "FleetBackpressure", "Router",
           "aggregate_telemetry"]

# distinguishes trace ids of two Routers in one process (the chaos
# drill's replay twin must never collide with the original's ids)
_ROUTER_SEQ = itertools.count()

_TERMINAL = (FINISHED, FAILED, TIMEOUT, REJECTED)


class FleetBackpressure(RuntimeError):
    """The router's bounded queue is full (or it is draining): typed
    shed-or-retry, mirroring serving.BackpressureError one level up."""


class FleetRequest:
    """One request as the ROUTER accounts it. The id is router-assigned
    and stable across requeues (the idempotency key); the seed is ALWAYS
    pinned at submission — derived deterministically from the id when the
    caller passes None — so a replay after a replica loss regenerates the
    identical sampled stream."""

    __slots__ = ("id", "prompt", "max_new_tokens", "deadline_s",
                 "temperature", "top_k", "seed", "speculation", "state",
                 "tokens", "error",
                 "attempts", "last_replica", "submitted_t", "finished_t",
                 "trace_id", "dispatches", "dispatched_t", "queued_since",
                 "internal", "pin_replica", "no_migrate")

    def __init__(self, rid: int, prompt: Sequence[int], max_new_tokens: int,
                 deadline_s: Optional[float] = None, temperature: float = 0.0,
                 top_k: int = 0, seed: Optional[int] = None,
                 trace_id: Optional[str] = None, speculation=None):
        self.id = int(rid)
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.deadline_s = deadline_s
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        # never let a replica pick an id-derived seed: engine-local request
        # ids differ between the first attempt and a requeued replay
        self.seed = (int(seed) if seed is not None
                     else (self.id * 1000003 + 0x5EED) & 0x7FFFFFFF)
        # per-request speculative decoding override (None = inherit the
        # replica engine's config; 0 = off; k; "auto" = tune table). The
        # draft-verify path emits the same (seed, position)-keyed stream
        # as plain decode, so a requeued replay stays bit-identical even
        # if the respawned replica resolves a different k.
        from ..serving.speculative import parse_speculation

        self.speculation = parse_speculation(speculation)
        self.state = "queued"
        self.tokens: List[int] = []
        self.error: Optional[str] = None
        self.attempts = 0
        self.last_replica: Optional[int] = None
        self.submitted_t = time.perf_counter()
        self.finished_t: Optional[float] = None
        # tracing: one trace_id across every attempt of this request;
        # ``dispatches`` is the 1-based attempt number the spans carry
        self.trace_id = trace_id if trace_id else "fr-%d" % self.id
        self.dispatches = 0
        self.dispatched_t: Optional[float] = None   # open attempt start
        self.queued_since: Optional[float] = self.submitted_t
        # router-side flags (never on the wire): ``internal`` marks the
        # scaffolding prefill jobs of a disaggregated handoff (excluded
        # from user accounting); ``pin_replica`` targets a dispatch at the
        # replica a migration warmed; ``no_migrate`` is the one-shot fuse
        # that sends a request cold after its migration failed
        self.internal = False
        self.pin_replica: Optional[int] = None
        self.no_migrate = False

    @property
    def terminal(self) -> bool:
        return self.state in _TERMINAL

    @property
    def latency_s(self) -> Optional[float]:
        if self.finished_t is None:
            return None
        return self.finished_t - self.submitted_t

    def doc(self) -> dict:
        """The wire/replica form of this request. ``attempt`` is the
        current dispatch count, so the replica's engine stamps its spans
        with the attempt they belong to (a requeued replay is attempt 2
        of the SAME trace_id)."""
        return {"id": self.id, "prompt": self.prompt,
                "max_new_tokens": self.max_new_tokens,
                "deadline_s": self.deadline_s,
                "temperature": self.temperature, "top_k": self.top_k,
                "seed": self.seed, "speculation": self.speculation,
                "trace_id": self.trace_id,
                "attempt": self.dispatches}

    def __repr__(self):
        return ("FleetRequest(id=%d, state=%s, out=%d, attempts=%d)"
                % (self.id, self.state, len(self.tokens), self.attempts))


class FleetConfig:
    """Router geometry + policy.

    ``replicas``: replica count, or ``"auto"`` to consult the autotuned
    config table (tune kernel ``fleet.router``; falls back to 2).
    ``mode``: ``"inprocess"`` (requires ``engine_factory``, a callable
    ``index -> engine``) or ``"process"`` (requires ``engine_spec``, the
    worker spec dict — see fleet.worker). ``affinity``: ``"prefix"`` or
    ``"round_robin"``; ``affinity_tokens`` is the prefix-hash window.
    ``max_outstanding`` caps dispatched-but-unresolved requests per
    replica (bounds the requeue set a crash can strand). ``requeue_limit``
    bounds replays per request before it terminally FAILs ("replica
    lost"). ``telemetry_base``: per-replica telemetry ring dirs are
    created under it (``replica_<i>/``) in process mode.

    Observability plane (all default-off; env fallbacks make every tool
    armable without code changes):

    * ``trace_dir`` (env ``PADDLE_TPU_FLEET_TRACE_DIR``) — distributed
      tracing: the router runs the host tracer, workers get per-spawn
      fragment files + a clock-offset handshake, and ``close()`` writes
      the fragments manifest ``tools/fleet_trace.py`` merges;
    * ``slos`` (env ``PADDLE_TPU_FLEET_SLO``, ``monitor.slo`` grammar) —
      evaluated per replica AND fleet-aggregate over the telemetry rings
      (needs ``telemetry_base``); a replica in breach is drained of new
      traffic like any degraded replica;
    * ``event_log`` (env ``PADDLE_TPU_FLEET_EVENTS``) — JSONL fleet
      lifecycle journal (fleet.events);
    * ``spec_overrides`` — {replica index: spec keys merged over
      ``engine_spec`` for that replica} (process mode), e.g. a per-replica
      ``fault_plan`` for chaos drills.

    Disaggregation / migration plane (see the migration section of
    :class:`Router`):

    * ``roles`` (env ``PADDLE_TPU_FLEET_ROLES``) — ``None`` keeps every
      replica uniform; ``"P:D"`` (or ``{"prefill": P, "decode": D}``)
      splits the fleet into P prefill-heavy + D decode-heavy replicas
      (overrides ``replicas`` to P+D); ``"auto"`` consults the tune table
      (kernel ``fleet.roles``, fallback 1:1);
    * ``page_size`` — granularity of the fleet prefix index; MUST match
      the replica engines' KV page size for migrated prefixes to resume;
    * ``migrate_min_tokens`` (env ``PADDLE_TPU_FLEET_MIGRATE_MIN``) —
      prompts whose page-aligned prefix is shorter dispatch cold (a ship
      costs a round trip; tiny prefixes are not worth it);
    * ``migration_timeout_s`` (env ``PADDLE_TPU_FLEET_MIGRATION_TIMEOUT_S``)
      — a migration not acknowledged in time fails and its requests fall
      back to a cold dispatch (never lost);
    * ``fleet_prefix`` (env ``PADDLE_TPU_FLEET_PREFIX``) — arm the
      fleet-wide prefix index in a uniform fleet (role-split fleets arm
      it implicitly: the handoff rides the same index);
    * ``rebalance_util`` (env ``PADDLE_TPU_FLEET_REBALANCE_UTIL``) — KV
      page-pool utilization above which a replica's prefix entries are
      migrated (shipped + evicted) to the least-loaded peer; 0 disables.
    """

    def __init__(self, replicas=2, mode: str = "inprocess",
                 affinity: str = "prefix", affinity_tokens: int = 16,
                 max_queue: int = 1024, max_outstanding: int = 16,
                 requeue_limit: int = 2, drain_timeout_s: float = 30.0,
                 engine_factory: Optional[Callable] = None,
                 engine_spec: Optional[dict] = None,
                 auto_restart: bool = True,
                 telemetry_base: Optional[str] = None,
                 health_every: int = 16,
                 trace_dir: Optional[str] = None,
                 slos: Optional[Sequence] = None,
                 event_log: Optional[str] = None,
                 spec_overrides: Optional[Dict[int, dict]] = None,
                 roles=None, page_size: int = 16,
                 migrate_min_tokens: Optional[int] = None,
                 migration_timeout_s: Optional[float] = None,
                 fleet_prefix: Optional[bool] = None,
                 rebalance_util: Optional[float] = None):
        if mode not in ("inprocess", "process"):
            raise ValueError("mode must be 'inprocess' or 'process'")
        if affinity not in ("prefix", "round_robin"):
            raise ValueError("affinity must be 'prefix' or 'round_robin'")
        self.replicas_source = "explicit"
        if replicas in (None, "auto"):
            replicas, affinity_cfg, self.replicas_source = \
                self._tuned_router(affinity)
            affinity = affinity_cfg
        self.replicas = max(1, int(replicas))
        if roles is None:
            roles = os.environ.get("PADDLE_TPU_FLEET_ROLES") or None
        self.roles: Optional[Dict[str, int]] = None
        self.roles_source = "none"
        if roles:
            self.roles, self.roles_source = self._parse_roles(roles)
            self.replicas = self.roles["prefill"] + self.roles["decode"]
        self.page_size = max(1, int(page_size))
        if migrate_min_tokens is None:
            migrate_min_tokens = int(os.environ.get(
                "PADDLE_TPU_FLEET_MIGRATE_MIN", self.page_size))
        self.migrate_min_tokens = max(1, int(migrate_min_tokens))
        if migration_timeout_s is None:
            migration_timeout_s = float(os.environ.get(
                "PADDLE_TPU_FLEET_MIGRATION_TIMEOUT_S", "10.0"))
        self.migration_timeout_s = float(migration_timeout_s)
        if fleet_prefix is None:
            env = os.environ.get("PADDLE_TPU_FLEET_PREFIX")
            fleet_prefix = None if env is None else \
                env.strip().lower() in ("1", "true", "yes", "on")
        self.fleet_prefix = fleet_prefix
        if rebalance_util is None:
            rebalance_util = float(os.environ.get(
                "PADDLE_TPU_FLEET_REBALANCE_UTIL", "0.85"))
        self.rebalance_util = float(rebalance_util)
        self.mode = mode
        self.affinity = affinity
        self.affinity_tokens = max(1, int(affinity_tokens))
        self.max_queue = int(max_queue)
        self.max_outstanding = max(1, int(max_outstanding))
        self.requeue_limit = max(0, int(requeue_limit))
        self.drain_timeout_s = float(drain_timeout_s)
        self.engine_factory = engine_factory
        self.engine_spec = engine_spec
        self.auto_restart = bool(auto_restart)
        self.telemetry_base = telemetry_base
        self.health_every = max(1, int(health_every))
        if trace_dir is None:
            trace_dir = os.environ.get("PADDLE_TPU_FLEET_TRACE_DIR") or None
        self.trace_dir = trace_dir
        self.slos = list(slos) if slos is not None else fleet_slos_from_env()
        if event_log is None:
            event_log = os.environ.get("PADDLE_TPU_FLEET_EVENTS") or None
        self.event_log = event_log
        self.spec_overrides = dict(spec_overrides or {})
        if mode == "inprocess" and engine_factory is None:
            raise ValueError("inprocess mode needs engine_factory")
        if mode == "process" and engine_spec is None:
            raise ValueError("process mode needs engine_spec")

    @staticmethod
    def _parse_roles(spec):
        """(roles dict, source) from ``"P:D"`` / dict / ``"auto"``. The
        tune-table path never raises — a role-split fleet must come up
        with no table on disk (1:1 fallback)."""
        if spec == "auto":
            try:
                from .. import tune

                cfg, src = tune.resolve_fleet_roles()
                return ({"prefill": max(1, int(cfg.get("prefill", 1))),
                         "decode": max(1, int(cfg.get("decode", 1)))}, src)
            except Exception:
                return {"prefill": 1, "decode": 1}, "default"
        if isinstance(spec, str):
            p_str, _, d_str = spec.partition(":")
            try:
                spec = {"prefill": int(p_str), "decode": int(d_str)}
            except ValueError:
                raise ValueError(
                    "roles spec must be 'P:D', 'auto' or a dict; got %r"
                    % (spec,))
        p = int(spec.get("prefill", 0))
        d = int(spec.get("decode", 0))
        if p < 1 or d < 1:
            raise ValueError(
                "roles needs >= 1 prefill and >= 1 decode replica, got "
                "prefill=%d decode=%d" % (p, d))
        return {"prefill": p, "decode": d}, "explicit"

    @staticmethod
    def _tuned_router(affinity_default: str):
        """(replicas, affinity, source) from the tune table; a safe
        (2, default-affinity, "default") on any failure — the fleet must
        come up with no table on disk."""
        try:
            from .. import tune

            cfg, src = tune.resolve_fleet_router()
            return (int(cfg.get("replicas", 2)),
                    cfg.get("affinity", affinity_default), src)
        except Exception:
            return 2, affinity_default, "default"


class _Migration:
    """One in-flight cross-replica KV-page ship, whatever its purpose:

    * ``disagg`` — prefill/decode handoff: an internal prefill job warms
      ``src`` (a prefill replica), the donated pages ship to ``dst`` (a
      decode replica), the user request dispatches pinned to ``dst``;
    * ``remote_hit`` — the fleet prefix index says another replica owns
      this prompt's prefix: ship it to the picked replica first;
    * ``rebalance`` — pool-pressure relief: ship one prefix entry to the
      least-loaded peer, then evict it at the source (ship+evict = move);
    * ``scale_down`` — a retiring replica exports its running requests'
      immutable prompt-prefix pages so their re-dispatch lands warm.

    Stages: ``prefill`` (disagg only: waiting on the internal job) →
    ``export`` (export op sent to src) → ``import`` (binary page frame
    sent to dst, waiting for the ack). ANY failure — export miss, import
    refusal, replica death, timeout — fails the migration and every
    carried request falls back to a cold dispatch with its ``no_migrate``
    fuse blown; a migration can delay a request, never lose one."""

    __slots__ = ("xid", "purpose", "key", "tokens", "src", "dst", "fr",
                 "waiters", "stage", "t0", "prefill_id", "n_pages")

    def __init__(self, xid: int, purpose: str, tokens, fr):
        self.xid = int(xid)
        self.purpose = purpose
        self.tokens = tuple(int(t) for t in tokens)
        self.key = prefix_key(self.tokens)
        self.fr = fr                       # user request carried (or None)
        self.waiters: List[FleetRequest] = []
        self.src: Optional[int] = None
        self.dst: Optional[int] = None
        self.stage = "start"
        self.t0 = time.perf_counter()
        self.prefill_id: Optional[int] = None  # disagg internal job id
        self.n_pages = 0

    def requests(self) -> List["FleetRequest"]:
        out = [self.fr] if self.fr is not None else []
        out.extend(self.waiters)
        return out

    def __repr__(self):
        return ("_Migration(xid=%d, %s, stage=%s, src=%s, dst=%s)"
                % (self.xid, self.purpose, self.stage, self.src, self.dst))


class Router:
    """See module docstring. Lifecycle: construct (spawns replicas) →
    ``submit``/``pump`` (or ``wait_all``) → ``drain``/``close``."""

    def __init__(self, config: FleetConfig):
        self.cfg = config
        self._seq = next(_ROUTER_SEQ)
        self._queue: Deque[FleetRequest] = deque()
        self._requests: Dict[int, FleetRequest] = {}
        self._next_id = 0
        self._rr = 0          # round-robin cursor
        self._ticks = 0
        self._draining = False
        self._closed = False
        self._t0 = time.perf_counter()
        self._health: Dict[int, dict] = {}       # replica index -> last doc
        self._rep_done: Dict[int, int] = {}      # replica index -> completed
        self._rep_lat: Dict[int, List[float]] = {}
        # -- observability plane --------------------------------------------
        self._trace = bool(config.trace_dir)
        self._own_tracer = False
        self._spawn_gen: Dict[int, int] = {}     # replica -> spawn count
        self._worker_frags: List[dict] = []      # manifest worker entries
        if self._trace:
            os.makedirs(config.trace_dir, exist_ok=True)
            if not _tr.active():
                _tr.start_tracing()
                self._own_tracer = True
        self._events = (FleetEventLog(config.event_log)
                        if config.event_log else None)
        self._slo_breached: Dict[int, dict] = {}  # replica -> last breach doc
        self._fleet_breach: Optional[dict] = None
        self._fleet_breach_count = 0
        # every breach this run, scope-tagged: the close-time autopsy's
        # input (bounded by dedup inside autopsy_breaches)
        self._breach_log: List[dict] = []
        self._phase_stats: Optional[dict] = None  # set by _run_autopsy
        self._autopsies: List[dict] = []
        self._slo: Optional[FleetSLO] = None
        if config.slos and config.telemetry_base:
            self._slo = FleetSLO(
                config.slos,
                on_replica_breach=self._on_replica_slo_breach,
                on_replica_clear=self._on_replica_slo_clear,
                on_fleet_breach=self._on_fleet_slo_breach,
                on_fleet_clear=self._on_fleet_slo_clear)
        self._last_obs_t = 0.0   # throttles ring reads + snapshot writes
        # -- migration / disaggregation plane -------------------------------
        # fleet prefix index: prefix key -> {"tokens", "owners"} — which
        # replicas (probably) hold this prefix in their LOCAL prefix
        # cache. Ownership is optimistic (recorded at FINISH, confirmed
        # or corrected by the export op), so the index is a routing hint,
        # never a correctness dependency.
        self._fleet_prefix = (config.fleet_prefix
                              if config.fleet_prefix is not None
                              else config.roles is not None)
        self._prefix_index: Dict[str, dict] = {}
        self._migrations: Dict[int, _Migration] = {}
        self._mig_seq = itertools.count(1)
        self._retired: set = set()   # scale-down'd indices: never respawn
        self._replicas = [self._spawn(i) for i in range(self.cfg.replicas)]
        _fm.REPLICAS_ALIVE.set(len(self._replicas))
        self._emit_event("fleet_start", replicas=self.cfg.replicas,
                         mode=self.cfg.mode, roles=self.cfg.roles,
                         trace_dir=self.cfg.trace_dir,
                         telemetry_base=self.cfg.telemetry_base)

    # -- observability callbacks/sinks ----------------------------------------
    def _emit_event(self, kind: str, **fields) -> None:
        if self._events is not None:
            self._events.emit(kind, **fields)

    def _on_replica_slo_breach(self, index: int, breach) -> None:
        doc = breach.to_doc()
        self._slo_breached[index] = doc
        self._breach_log.append(dict(doc, scope="replica", replica=index))
        self._emit_event("slo_breach", scope="replica", replica=index, **doc)

    def _on_replica_slo_clear(self, index: int) -> None:
        if self._slo_breached.pop(index, None) is not None:
            self._emit_event("slo_clear", scope="replica", replica=index)

    def _on_fleet_slo_breach(self, breach) -> None:
        self._fleet_breach = breach.to_doc()
        self._fleet_breach_count += 1
        self._breach_log.append(dict(self._fleet_breach, scope="fleet"))
        self._emit_event("slo_breach", scope="fleet", **self._fleet_breach)

    def _on_fleet_slo_clear(self) -> None:
        if self._fleet_breach is not None:
            self._fleet_breach = None
            self._emit_event("slo_clear", scope="fleet")

    # -- replica lifecycle ----------------------------------------------------
    def _spawn(self, index: int):
        self._health[index] = {"status": "ok"}
        self._rep_done.setdefault(index, 0)
        self._rep_lat.setdefault(index, [])
        gen = self._spawn_gen.get(index, 0) + 1
        self._spawn_gen[index] = gen
        if self.cfg.mode == "inprocess":
            rep = InProcessReplica(self.cfg.engine_factory(index), index)
            rep.role = self._role_for(index)
            self._emit_event("spawn", replica=index, gen=gen,
                             mode="inprocess", role=rep.role)
            return rep
        tdir = None
        if self.cfg.telemetry_base:
            tdir = os.path.join(self.cfg.telemetry_base,
                                "replica_%d" % index)
        tfile = None
        if self._trace:
            # one fragment file per SPAWN: a respawned replica must not
            # clobber its predecessor's (possibly never-flushed) fragment
            tfile = os.path.join(self.cfg.trace_dir,
                                 "worker_r%d_g%d.json" % (index, gen))
        spec = dict(self.cfg.engine_spec)
        spec.update(self.cfg.spec_overrides.get(index, {}))
        rep = ProcessReplica(spec, index, telemetry_dir=tdir,
                             trace_file=tfile)
        rep.role = self._role_for(index)
        if tfile:
            self._worker_frags.append({
                "file": os.path.basename(tfile), "replica": index,
                "gen": gen, "pid": rep.pid,
                "offset_us": rep.clock_offset_us,
                "rtt_us": rep.clock_rtt_us})
        if self._trace:
            _ftr.on_lifecycle_instant(
                "spawn replica %d" % index,
                args={"replica": index, "gen": gen, "pid": rep.pid})
        self._emit_event("spawn", replica=index, gen=gen, pid=rep.pid,
                         role=rep.role,
                         clock_offset_us=rep.clock_offset_us,
                         clock_rtt_us=rep.clock_rtt_us)
        return rep

    def _role_for(self, index: int) -> str:
        """Replica role under the configured split: the first P indices
        are prefill-heavy, the rest decode-heavy; no split = uniform."""
        r = self.cfg.roles
        if not r:
            return "uniform"
        return "prefill" if index < r["prefill"] else "decode"

    def _respawn(self, index: int) -> None:
        # a respawned replica starts with empty caches: whatever prefixes
        # the index credited to it are gone
        self._drop_owner_everywhere(index)
        self._replicas[index] = self._spawn(index)
        _fm.REPLICA_RESTARTS.inc()
        self._emit_event("restart", replica=index,
                         gen=self._spawn_gen.get(index))

    # -- submission -----------------------------------------------------------
    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               deadline_s: Optional[float] = None, temperature: float = 0.0,
               top_k: int = 0, seed: Optional[int] = None,
               speculation=None) -> FleetRequest:
        """Accept a request into the bounded queue. Raises
        :class:`FleetBackpressure` (typed, accounted) when full or
        draining — the router never silently drops. ``speculation`` is
        the per-request speculative-decoding override, carried on the
        request doc to whichever replica (or replicas, across requeues)
        serves it."""
        if self._closed or self._draining:
            _fm.REJECTED.inc()
            raise FleetBackpressure("router is draining/closed")
        if len(self._queue) >= self.cfg.max_queue:
            _fm.REJECTED.inc()
            raise FleetBackpressure(
                "fleet queue full (%d)" % self.cfg.max_queue)
        fr = FleetRequest(self._next_id, prompt, max_new_tokens,
                          deadline_s=deadline_s, temperature=temperature,
                          top_k=top_k, seed=seed, speculation=speculation,
                          trace_id="fr%d-%d" % (self._seq, self._next_id))
        self._next_id += 1
        self._requests[fr.id] = fr
        self._queue.append(fr)
        _fm.SUBMITTED.inc()
        _fm.QUEUE_DEPTH.set(len(self._queue))
        if self._trace:
            _ftr.on_submitted(fr)
        return fr

    # -- accounting -----------------------------------------------------------
    def _finalize(self, fr: FleetRequest, state: str,
                  tokens: Optional[List[int]] = None,
                  error: Optional[str] = None) -> None:
        """THE exactly-once funnel: every terminal outcome lands here, and
        an already-terminal request absorbs the duplicate instead of
        flipping state (a SIGKILL race can produce both a late result and
        a requeued completion — first one wins, deterministically)."""
        if fr.terminal:
            _fm.DUPLICATE_RESULTS.inc()
            return
        fr.state = state
        if tokens is not None:
            fr.tokens = list(tokens)
        fr.error = error
        fr.finished_t = time.perf_counter()
        fr.queued_since = None
        if fr.internal:
            # scaffolding (disagg prefill job): no user-facing accounting,
            # no trace spans — but its outcome advances (or fails) the
            # migration that spawned it
            if state == FINISHED and self._fleet_prefix \
                    and fr.last_replica is not None:
                self._record_prefix(fr.prompt, fr.last_replica)
            self._on_internal_done(fr)
            return
        _fm.COMPLETED.inc()
        if self._trace:
            _ftr.on_terminal(fr)   # also closes a never-dispatched wait
        if fr.last_replica is not None:
            self._rep_done[fr.last_replica] = \
                self._rep_done.get(fr.last_replica, 0) + 1
            self._rep_lat.setdefault(fr.last_replica, []).append(
                (fr.finished_t - fr.submitted_t) * 1e3)
        if state == FINISHED and self._fleet_prefix \
                and fr.last_replica is not None:
            # its engine (probably) cached the aligned prefix at retire:
            # record optimistic ownership in the fleet index
            self._record_prefix(fr.prompt, fr.last_replica)

    def _requeue(self, fr: FleetRequest, why: str) -> None:
        if fr.terminal:
            return
        fr.attempts += 1
        if fr.attempts > self.cfg.requeue_limit:
            self._finalize(fr, FAILED,
                           error="replica lost %d times (%s)"
                                 % (fr.attempts, why))
            return
        fr.state = "queued"
        fr.queued_since = time.perf_counter()  # second queued span opens
        self._queue.appendleft(fr)  # retries go to the head: oldest first
        self._emit_event("requeue", trace_id=fr.trace_id, id=fr.id,
                         attempts=fr.attempts, why=why)

    def _handle_event(self, rep, ev: dict) -> None:
        kind = ev.get("ev")
        if kind == "health":
            self._health[rep.index] = ev.get("health", {"status": "ok"})
            return
        if kind == "pages":
            self._on_pages(rep, ev)
            return
        if kind == "imported":
            self._on_imported(rep, ev)
            return
        if kind == "evicted":
            self._emit_event("prefix_evicted", replica=rep.index,
                             xid=ev.get("xid"), pages=ev.get("pages"))
            return
        if kind != "result":
            return
        fr = self._requests.get(ev.get("id"))
        if fr is None:
            return
        state = ev.get("state")
        if state == REJECTED and ev.get("kind") in ("draining",
                                                    "backpressure"):
            # replica-side typed shed: route to a peer, never terminal
            _fm.REROUTED.inc()
            if self._trace and not fr.terminal and not fr.internal:
                _ftr.on_attempt_end(fr, rep.index, "rerouted")
            fr.dispatched_t = None
            self._emit_event("reroute", trace_id=fr.trace_id, id=fr.id,
                             replica=rep.index, why=ev.get("kind"))
            self._requeue_reroute(fr)
            return
        if self._trace and not fr.terminal and not fr.internal:
            _ftr.on_attempt_end(fr, rep.index, state)
        fr.dispatched_t = None
        self._finalize(fr, state, ev.get("tokens"), ev.get("error"))

    def _requeue_reroute(self, fr: FleetRequest) -> None:
        """A typed reroute (peer draining/backpressured) does not count
        against the requeue budget — nothing was lost, only refused."""
        if fr.terminal:
            return
        fr.state = "queued"
        fr.queued_since = time.perf_counter()
        self._queue.appendleft(fr)

    def _lose(self, fr: FleetRequest, replica_index: int, why: str,
              tag: str = "killed") -> None:
        """One lost in-flight request, accounted by kind: user requests
        requeue idempotently; internal prefill jobs terminate FAILED
        (their migration fails and its user request falls back cold —
        re-running scaffolding on a respawned replica buys nothing)."""
        if fr.internal:
            self._finalize(fr, FAILED, error=why)
            return
        _fm.REQUEUED.inc()
        if self._trace:
            # the worker never reported: close its attempt at detection
            # time, tagged killed+synthetic
            _ftr.on_attempt_end(fr, replica_index, tag, killed=True)
        fr.dispatched_t = None
        self._requeue(fr, why)

    # -- the event-loop tick --------------------------------------------------
    def pump(self) -> int:
        """One router cycle: poll replicas (pumps in-process engines one
        step), account events, detect/recover deaths, dispatch the queue.
        Returns the number of requests still unresolved."""
        self._ticks += 1
        for rep in list(self._replicas):
            for ev in rep.poll():
                self._handle_event(rep, ev)
        for i, rep in enumerate(self._replicas):
            if not rep.alive:
                lost = list(rep.inflight.values())
                rep.inflight.clear()
                if lost or rep.accepting:
                    # accepting distinguishes a detected death from an
                    # already-accounted drain (accepting was lowered)
                    self._emit_event("kill_detected", replica=i,
                                     pid=getattr(rep, "pid", None),
                                     lost=len(lost))
                    if self._trace:
                        _ftr.on_lifecycle_instant(
                            "replica %d died" % i,
                            args={"replica": i, "lost": len(lost)})
                rep.accepting = False
                for rdoc in lost:
                    fr = self._requests.get(rdoc["id"])
                    if fr is not None and not fr.terminal:
                        self._lose(fr, i, "replica %d died" % i)
                # the dead replica's caches died with it; any migration
                # touching it can never complete — fail them now so their
                # requests fall back immediately instead of timing out
                self._drop_owner_everywhere(i)
                self._fail_migrations_for(i, "replica %d died" % i)
                if self.cfg.auto_restart and not self._draining \
                        and not self._closed and i not in self._retired:
                    self._respawn(i)
        if self._migrations:
            now = time.perf_counter()
            for m in list(self._migrations.values()):
                if now - m.t0 > self.cfg.migration_timeout_s:
                    self._fail_migration(m, "timeout after %.1fs"
                                         % (now - m.t0))
        if self.cfg.mode == "process" \
                and self._ticks % self.cfg.health_every == 0:
            for rep in self._replicas:
                if rep.alive:
                    rep.health()  # answer arrives as a health event
        if (self._slo is not None or self.cfg.telemetry_base) \
                and self._ticks % self.cfg.health_every == 0:
            now = time.monotonic()
            if now - self._last_obs_t >= 0.5:  # ring reads are file I/O
                self._last_obs_t = now
                if self._slo is not None:
                    self.evaluate_slos()
                self._write_snapshot()
        if self._fleet_prefix and self.cfg.rebalance_util > 0 \
                and self._ticks % self.cfg.health_every == 0:
            self._auto_rebalance()
        self._dispatch()
        _fm.QUEUE_DEPTH.set(len(self._queue))
        _fm.REPLICAS_ALIVE.set(sum(1 for r in self._replicas if r.alive))
        return sum(1 for fr in self._requests.values() if not fr.terminal)

    def _replica_healthy(self, rep) -> bool:
        if not rep.alive or not rep.accepting:
            return False
        if rep.index in self._slo_breached:
            return False   # SLO breach == degraded: drained, not killed
        if rep.kind == "inprocess":
            h = rep.health()
        else:
            h = self._health.get(rep.index, {"status": "ok"})
        return h.get("status", "ok") == "ok"

    def _role_ok(self, rep, fr: FleetRequest) -> bool:
        """Role gate in a split fleet: user requests decode on
        decode-heavy replicas; internal prefill jobs run on
        prefill-heavy ones; uniform replicas take anything."""
        if fr.internal:
            return rep.role in ("prefill", "uniform")
        return rep.role in ("decode", "uniform")

    def _dispatchable(self, rep, fr: FleetRequest) -> bool:
        return (self._replica_healthy(rep)
                and rep.index not in self._retired
                and self._role_ok(rep, fr)
                and len(rep.inflight) < self.cfg.max_outstanding)

    def _pick_replica(self, fr: FleetRequest):
        if fr.pin_replica is not None:
            # a migration warmed (or a disagg handoff targets) exactly one
            # replica: dispatch there or wait for it — unless it is gone,
            # in which case the pin dissolves into a cold pick
            pin = fr.pin_replica
            if 0 <= pin < len(self._replicas):
                rep = self._replicas[pin]
                if self._dispatchable(rep, fr):
                    return rep
                if rep.alive and rep.accepting \
                        and pin not in self._retired:
                    return None   # busy/degraded, not gone: stay queued
            fr.pin_replica = None
        n = len(self._replicas)
        if self.cfg.affinity == "prefix":
            window = fr.prompt[:self.cfg.affinity_tokens]
            start = int(prefix_key(window)[:8], 16) % n
        else:
            start = self._rr % n
            self._rr += 1
        for off in range(n):
            rep = self._replicas[(start + off) % n]
            if self._dispatchable(rep, fr):
                return rep
        return None

    def _dispatch_to(self, fr: FleetRequest, rep) -> None:
        fr.state = "dispatched"
        fr.last_replica = rep.index
        fr.dispatches += 1
        if self._trace and not fr.internal:
            _ftr.on_dispatch(fr, rep.index)  # closes the queued span
        fr.queued_since = None
        fr.dispatched_t = time.perf_counter()
        rep.submit(fr.doc())
        if not fr.internal:
            _fm.ROUTED.inc()

    def _dispatch(self) -> None:
        # one pass over the queue: each request either dispatches, starts
        # (or joins) a migration, or goes back where it was. A pinned or
        # internal request whose one target is busy must not block the
        # unpinned traffic behind it, so it is skipped, not a barrier.
        skipped: List[FleetRequest] = []
        while self._queue:
            fr = self._queue.popleft()
            if fr.terminal:  # finalized while queued (router drain race)
                continue
            if self._maybe_migrate(fr):
                continue
            rep = self._pick_replica(fr)
            if rep is None:
                _fm.NO_HEALTHY_REPLICA.inc()
                skipped.append(fr)
                if fr.pin_replica is None and not fr.internal:
                    # nothing can take an unconstrained request: peers
                    # will not take the rest of the queue either
                    break
                continue
            self._dispatch_to(fr, rep)
        for fr in reversed(skipped):
            self._queue.appendleft(fr)

    # -- cross-replica KV-page migration --------------------------------------
    # One primitive — ship a prefix's KV pages over the binary page frame
    # from the replica that has them to the replica that needs them —
    # bought four ways: the disaggregated prefill->decode handoff, the
    # fleet-wide prefix cache, pool-pressure rebalancing, and live
    # scale-down. Pages are COPIED, never moved, across the wire: the
    # source keeps (or explicitly evicts) its entry, the destination
    # allocates from its own pool inside the engine's atomic ingest, and
    # a process death on either side therefore cannot strand a page.

    def _aligned_len(self, prompt_len: int) -> int:
        ps = self.cfg.page_size
        return ((int(prompt_len) - 1) // ps) * ps

    def _record_prefix(self, prompt: Sequence[int], owner: int) -> None:
        n = self._aligned_len(len(prompt))
        if n < self.cfg.migrate_min_tokens:
            return
        tokens = tuple(int(t) for t in prompt[:n])
        self._add_owner(prefix_key(tokens), tokens, owner)

    def _add_owner(self, key: str, tokens, owner: int) -> None:
        ent = self._prefix_index.get(key)
        tokens = tuple(int(t) for t in tokens)
        if ent is None or ent["tokens"] != tokens:
            ent = {"tokens": tokens, "owners": set()}
            self._prefix_index[key] = ent
        ent["owners"].add(int(owner))

    def _drop_owner(self, key: str, owner: int) -> None:
        ent = self._prefix_index.get(key)
        if ent is None:
            return
        ent["owners"].discard(owner)
        if not ent["owners"]:
            del self._prefix_index[key]

    def _drop_owner_everywhere(self, owner: int) -> None:
        for key in [k for k, e in self._prefix_index.items()
                    if owner in e["owners"]]:
            self._drop_owner(key, owner)

    def _rep_or_none(self, index: Optional[int]):
        if index is None or not (0 <= index < len(self._replicas)):
            return None
        return self._replicas[index]

    def _owner_usable(self, index: int) -> bool:
        """Can this index answer an export op? (Alive is enough — a
        replica drained of NEW traffic still ships its cached pages.)"""
        rep = self._rep_or_none(index)
        return rep is not None and rep.alive and index not in self._retired

    def _pick_prefill(self):
        """Least-loaded prefill-heavy replica, for internal prefill jobs."""
        best = None
        for rep in self._replicas:
            if rep.role != "prefill" or rep.index in self._retired \
                    or not self._replica_healthy(rep) \
                    or len(rep.inflight) >= self.cfg.max_outstanding:
                continue
            if best is None or len(rep.inflight) < len(best.inflight):
                best = rep
        return best

    def _least_loaded_peer(self, exclude: int):
        """Least-loaded replica that can take user traffic (migration
        destination for rebalance/scale-down shipments)."""
        best = None
        for rep in self._replicas:
            if rep.index == exclude or rep.index in self._retired \
                    or rep.role == "prefill" \
                    or not self._replica_healthy(rep):
                continue
            if best is None or len(rep.inflight) < len(best.inflight):
                best = rep
        return best

    def _maybe_migrate(self, fr: FleetRequest) -> bool:
        """Dispatch-time migration decision for one queued request. True
        when the request was captured (held by a migration, or dispatched
        pinned at an owner) — False sends it down the cold path."""
        if fr.internal or fr.no_migrate or fr.pin_replica is not None \
                or not self._fleet_prefix:
            return False
        n_max = self._aligned_len(len(fr.prompt))
        if n_max < self.cfg.migrate_min_tokens:
            return False
        ps = self.cfg.page_size
        for n in range(n_max, self.cfg.migrate_min_tokens - 1, -ps):
            tokens = tuple(fr.prompt[:n])
            key = prefix_key(tokens)
            for m in self._migrations.values():
                if m.key == key and m.purpose in ("disagg", "remote_hit"):
                    # the same prefix is already in flight: piggyback —
                    # one ship serves every waiter
                    m.waiters.append(fr)
                    fr.state = "migrating"
                    fr.queued_since = None
                    return True
            ent = self._prefix_index.get(key)
            if ent is None or ent["tokens"] != tokens:
                continue
            owners = [i for i in sorted(ent["owners"])
                      if self._owner_usable(i)]
            if not owners:
                del self._prefix_index[key]   # every owner is gone
                continue
            for i in owners:
                rep = self._replicas[i]
                if self._dispatchable(rep, fr):
                    # an owner can serve directly: a LOCAL prefix-cache
                    # hit there, no ship needed
                    self._dispatch_to(fr, rep)
                    return True
            dst = self._pick_replica(fr)
            if dst is None:
                return False   # nowhere to ship to; retry next pump
            src = self._replicas[owners[0]]
            purpose = ("disagg" if src.role == "prefill" else "remote_hit")
            self._start_ship(purpose, tokens, src, dst, fr)
            return True
        if self.cfg.roles:
            # no cached prefix anywhere: in a role-split fleet, warm it on
            # a prefill replica and ship; uniform fleets dispatch cold
            return self._start_disagg(fr, n_max)
        return False

    def _new_migration(self, purpose: str, tokens, fr) -> _Migration:
        m = _Migration(next(self._mig_seq), purpose, tokens, fr)
        self._migrations[m.xid] = m
        _fm.MIGRATIONS_STARTED.inc()
        return m

    def _hold(self, fr: Optional[FleetRequest]) -> None:
        if fr is not None:
            fr.state = "migrating"
            fr.queued_since = None

    def _start_ship(self, purpose: str, tokens, src, dst,
                    fr: Optional[FleetRequest]) -> None:
        m = self._new_migration(purpose, tokens, fr)
        m.src, m.dst = src.index, dst.index
        m.stage = "export"
        self._hold(fr)
        self._emit_event("migration_start", xid=m.xid, purpose=purpose,
                         key=m.key, src=m.src, dst=m.dst,
                         id=(fr.id if fr is not None else None),
                         tokens=len(m.tokens))
        src.request_export_prefix(m.xid, list(m.tokens))

    def _start_disagg(self, fr: FleetRequest, n_aligned: int) -> bool:
        src = self._pick_prefill()
        if src is None:
            return False   # no prefill capacity right now: stay queued
        # the internal prefill job: the aligned prefix + one remainder
        # token, ONE generated token — the engine prefills the prompt,
        # FINISHES immediately, and retirement donates the aligned
        # prefix's pages to its local prefix cache, where the export op
        # finds them. Temperature 0 keeps it cheap and deterministic;
        # the KV pages depend only on the prompt tokens anyway.
        ifr = FleetRequest(self._next_id, fr.prompt[:n_aligned + 1], 1,
                           temperature=0.0, top_k=0, seed=fr.seed,
                           trace_id="fr%d-%d-prefill"
                                    % (self._seq, self._next_id))
        self._next_id += 1
        ifr.internal = True
        ifr.pin_replica = src.index
        self._requests[ifr.id] = ifr
        m = self._new_migration("disagg", fr.prompt[:n_aligned], fr)
        m.src = src.index
        m.prefill_id = ifr.id
        m.stage = "prefill"
        self._hold(fr)
        self._emit_event("migration_start", xid=m.xid, purpose="disagg",
                         key=m.key, src=m.src, dst=None, id=fr.id,
                         tokens=len(m.tokens), prefill_id=ifr.id)
        self._queue.append(ifr)   # dispatches this same pass, pinned
        return True

    def _on_internal_done(self, ifr: FleetRequest) -> None:
        for m in list(self._migrations.values()):
            if m.prefill_id != ifr.id:
                continue
            if ifr.state != FINISHED:
                self._fail_migration(m, "prefill job %s: %s"
                                     % (ifr.state, ifr.error))
            else:
                self._advance_export(m)

    def _advance_export(self, m: _Migration) -> None:
        src = self._rep_or_none(m.src)
        if src is None or not src.alive:
            self._fail_migration(m, "source replica lost")
            return
        if m.dst is None:
            dst = self._pick_replica(m.fr) if m.fr is not None else None
            if dst is None:
                self._fail_migration(m, "no destination replica")
                return
            m.dst = dst.index
        m.stage = "export"
        src.request_export_prefix(m.xid, list(m.tokens))

    def _on_pages(self, rep, ev: dict) -> None:
        """The export answer: a binary page payload (ok) or a typed miss.
        Forward the pages to the destination's import, or fail over."""
        m = self._migrations.get(ev.get("xid"))
        if m is None or rep.index != m.src or m.stage != "export":
            return   # late/alien answer: the migration already resolved
        if not ev.get("ok"):
            if m.purpose == "remote_hit":
                _fm.REMOTE_MISSES.inc()
            self._drop_owner(m.key, m.src)   # the hint was stale
            self._fail_migration(m, "export miss at replica %d" % m.src)
            return
        if ev.get("tokens") and not m.tokens:
            # scale-down exports name their own prefix (the router did
            # not know the aligned length of a running request's prompt)
            m.tokens = tuple(int(t) for t in ev["tokens"])
            m.key = prefix_key(m.tokens)
        dst = self._rep_or_none(m.dst)
        if dst is None or not dst.alive:
            self._fail_migration(m, "destination replica lost")
            return
        meta = {k: v for k, v in ev.items()
                if k not in ("ev", "xid", "ok", "tokens", "_blobs")}
        m.n_pages = int(meta.get("n_pages", 0))
        m.stage = "import"
        _fm.REMOTE_SHIPS.inc()
        dst.request_import_prefix(m.xid, list(m.tokens), meta,
                                  ev.get("_blobs", []))

    def _on_imported(self, rep, ev: dict) -> None:
        m = self._migrations.get(ev.get("xid"))
        if m is None or rep.index != m.dst or m.stage != "import":
            return
        if not ev.get("ok"):
            self._fail_migration(m, "import refused at replica %d" % m.dst)
            return
        self._complete_migration(m, int(ev.get("pages", m.n_pages)))

    def _complete_migration(self, m: _Migration, pages: int) -> None:
        self._migrations.pop(m.xid, None)
        dt_ms = (time.perf_counter() - m.t0) * 1e3
        _fm.MIGRATIONS_COMPLETED.inc()
        _fm.MIGRATED_PAGES.inc(pages)
        _fm.MIGRATION_MS.observe(dt_ms)
        if m.tokens:
            self._add_owner(m.key, m.tokens, m.dst)
        served = [fr for fr in m.requests() if not fr.terminal]
        if m.purpose == "remote_hit" and served:
            _fm.REMOTE_HITS.inc(len(served))
        if self._trace:
            # phase-ledger tags: the ledger joins this window in as a
            # ``ship`` interval of every request the migration served
            _ftr.on_lifecycle_span(
                "migrate %s" % m.purpose, m.t0, time.perf_counter(),
                args={"xid": m.xid, "src": m.src, "dst": m.dst,
                      "pages": pages, "served": len(served),
                      "phase": "ship", "cause": m.purpose,
                      "trace_ids": [fr.trace_id for fr in served][:8]})
        self._emit_event("migration_done", xid=m.xid, purpose=m.purpose,
                         key=m.key, src=m.src, dst=m.dst, pages=pages,
                         ms=round(dt_ms, 3), served=len(served))
        if m.purpose == "rebalance":
            # ship + evict = move: the source frees its copy, and the
            # index forgets it owned one, only AFTER the import landed
            src = self._rep_or_none(m.src)
            if src is not None and src.alive:
                src.request_evict_prefix(m.xid, list(m.tokens))
            self._drop_owner(m.key, m.src)
        for fr in served:
            # dispatch pinned at the replica that now holds the prefix:
            # its local prefix cache turns the dispatch into a resume
            fr.pin_replica = m.dst
            fr.state = "queued"
            fr.queued_since = time.perf_counter()
            self._queue.appendleft(fr)

    def _fail_migration(self, m: _Migration, why: str) -> None:
        """ANY failure funnels here, idempotently: the migration is
        forgotten and every carried request falls back to an ordinary
        cold dispatch — a migration can delay a request, never lose one."""
        if self._migrations.pop(m.xid, None) is None:
            return
        _fm.MIGRATIONS_FAILED.inc()
        self._emit_event("migration_failed", xid=m.xid, purpose=m.purpose,
                         key=m.key, src=m.src, dst=m.dst, why=why)
        if self._trace:
            _ftr.on_lifecycle_instant(
                "migration %d failed" % m.xid,
                args={"purpose": m.purpose, "src": m.src, "dst": m.dst,
                      "why": why})
        for fr in m.requests():
            if fr.terminal:
                continue
            fr.no_migrate = True
            fr.pin_replica = None
            fr.state = "queued"
            fr.queued_since = time.perf_counter()
            self._queue.appendleft(fr)

    def _fail_migrations_for(self, index: int, why: str) -> None:
        for m in list(self._migrations.values()):
            if m.src == index or m.dst == index:
                self._fail_migration(m, why)

    def _auto_rebalance(self) -> None:
        """Pool-pressure relief: when a replica's KV page pool runs above
        ``rebalance_util``, move ONE of its solely-owned prefix entries
        to the least-loaded peer (at most one ship per evaluation — the
        next pass sees the post-move utilization, so relief converges
        instead of oscillating)."""
        for rep in self._replicas:
            i = rep.index
            if not rep.alive or i in self._retired:
                continue
            h = (rep.health() if rep.kind == "inprocess"
                 else self._health.get(i, {}))
            total = h.get("pages_total") or 0
            if not total:
                continue
            util = 1.0 - float(h.get("pages_free", total)) / total
            if util < self.cfg.rebalance_util:
                continue
            for key, ent in self._prefix_index.items():
                if ent["owners"] != {i}:
                    continue
                if any(m.key == key for m in self._migrations.values()):
                    continue
                dst = self._least_loaded_peer(i)
                if dst is None:
                    return
                self._start_ship("rebalance", ent["tokens"], rep, dst,
                                 None)
                return

    def rebalance(self, src_index: int, dst_index: int,
                  tokens: Sequence[int]) -> Optional[int]:
        """Manually move one prefix entry ``src -> dst`` (ship + evict).
        Returns the migration id, or None when either side cannot serve.
        The move resolves through ``pump()`` like any migration."""
        src = self._rep_or_none(src_index)
        dst = self._rep_or_none(dst_index)
        if src is None or dst is None or not src.alive or not dst.alive:
            return None
        m = self._new_migration("rebalance",
                                tuple(int(t) for t in tokens), None)
        m.src, m.dst = src.index, dst.index
        m.stage = "export"
        self._emit_event("migration_start", xid=m.xid, purpose="rebalance",
                         key=m.key, src=m.src, dst=m.dst,
                         tokens=len(m.tokens))
        src.request_export_prefix(m.xid, list(m.tokens))
        return m.xid

    def scale_down(self, index: int,
                   timeout_s: Optional[float] = None) -> dict:
        """Retire one replica WITHOUT losing its in-flight work: stop its
        new traffic, export each running request's immutable prompt-prefix
        pages to the least-loaded peer, requeue those requests (typed
        reroute — no requeue-budget hit, nothing was lost), and close the
        replica. The re-dispatch probes the fleet prefix index, finds the
        shipped prefix at the peer, and resumes warm there. pump() will
        not respawn a retired index; the fleet is permanently one smaller."""
        if timeout_s is None:
            timeout_s = self.cfg.drain_timeout_s
        rep = self._replicas[index]
        self._retired.add(index)
        rep.accepting = False
        t0 = time.perf_counter()
        xids: List[int] = []
        for fid in list(rep.inflight):
            fr = self._requests.get(fid)
            if fr is None or fr.terminal or fr.internal:
                continue
            dst = self._least_loaded_peer(index)
            if dst is None:
                break   # nowhere to ship: plain requeue still holds
            m = self._new_migration("scale_down", (), None)
            m.src, m.dst = index, dst.index
            m.stage = "export"
            self._emit_event("migration_start", xid=m.xid,
                             purpose="scale_down", src=index,
                             dst=dst.index, id=fid)
            rep.request_export_request(m.xid, fid)
            xids.append(m.xid)
        # let the ships settle (complete/fail) before the replica goes —
        # a request may also simply FINISH here, which wins outright
        deadline = time.monotonic() + max(0.1, float(timeout_s))
        while any(x in self._migrations for x in xids) \
                and time.monotonic() < deadline:
            self.pump()
            if self.cfg.mode == "process":
                time.sleep(0.002)
        for x in xids:
            m = self._migrations.get(x)
            if m is not None:
                self._fail_migration(m, "scale-down budget exhausted")
        requeued = 0
        lost = list(rep.inflight.values())
        rep.inflight.clear()
        for rdoc in lost:
            fr = self._requests.get(rdoc["id"])
            if fr is None or fr.terminal:
                continue
            if fr.internal:
                self._finalize(fr, FAILED,
                               error="replica %d retired" % index)
                continue
            if self._trace:
                _ftr.on_attempt_end(fr, index, "migrated", killed=True)
            fr.dispatched_t = None
            self._requeue_reroute(fr)
            requeued += 1
        self._drop_owner_everywhere(index)
        self._fail_migrations_for(index, "replica %d retired" % index)
        try:
            rep.close()
        except Exception:
            pass
        out = {"replica": index, "migrations": len(xids),
               "requeued": requeued,
               "duration_s": round(time.perf_counter() - t0, 6)}
        self._emit_event("scale_down", **out)
        if self._trace:
            _ftr.on_lifecycle_span("scale_down replica %d" % index, t0,
                                   time.perf_counter(), args=dict(out))
        self.pump()   # the rerouted work lands on the warmed peers
        return out

    def wait_all(self, timeout_s: float = 60.0,
                 idle_sleep_s: float = 0.002) -> bool:
        """Pump until every accepted request is terminal (True) or the
        timeout passes (False)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.pump() == 0:
                return True
            if self.cfg.mode == "process":
                time.sleep(idle_sleep_s)
        return self.pump() == 0

    # -- lifecycle ------------------------------------------------------------
    def rolling_restart(self, timeout_s: Optional[float] = None) -> dict:
        """Zero-downtime rollout: one replica at a time, stop its new
        traffic, ``drain(timeout_s)`` (in-flight finishes; engine-queued
        work is shed as typed ``draining`` rejections that re-route to
        peers), respawn, move on. Traffic keeps flowing through the
        others for the whole pass."""
        if timeout_s is None:
            timeout_s = self.cfg.drain_timeout_s
        t_pass = time.perf_counter()
        summaries = {}
        for i in range(len(self._replicas)):
            rep = self._replicas[i]
            rep.accepting = False
            t_leg = time.perf_counter()
            if rep.alive:
                summaries[rep.name] = rep.drain(timeout_s)
            for ev in rep.poll():  # drain's result events (incl. sheds)
                self._handle_event(rep, ev)
            # anything the drain could not resolve is a lost in-flight set
            lost = list(rep.inflight.values())
            rep.inflight.clear()
            for rdoc in lost:
                fr = self._requests.get(rdoc["id"])
                if fr is not None and not fr.terminal:
                    self._lose(fr, i, "rolling restart of replica %d" % i,
                               tag="lost_in_drain")
            self._fail_migrations_for(i, "rolling restart of replica %d"
                                      % i)
            if self._trace:
                _ftr.on_lifecycle_span(
                    "drain replica %d" % i, t_leg, time.perf_counter(),
                    args=dict(summaries.get(rep.name) or {}, replica=i))
            self._emit_event("drain", replica=i,
                             summary=summaries.get(rep.name),
                             lost=len(lost))
            self._respawn(i)
            self.pump()  # rerouted work lands on peers before the next leg
        _fm.ROLLING_RESTARTS.inc()
        if self._trace:
            _ftr.on_lifecycle_span("rolling_restart", t_pass,
                                   time.perf_counter(),
                                   args={"replicas": len(self._replicas)})
        self._emit_event("rolling_restart", replicas=len(self._replicas),
                         duration_s=round(time.perf_counter() - t_pass, 6))
        return summaries

    def drain(self, timeout_s: Optional[float] = None) -> dict:
        """Fleet-wide graceful stop: no new submissions, finish what can
        finish within the budget, account everything else (queued work
        sheds as terminal REJECTED — typed, counted, never silent)."""
        if timeout_s is None:
            timeout_s = self.cfg.drain_timeout_s
        t0 = time.perf_counter()
        self._draining = True
        self.wait_all(timeout_s)
        for rep in self._replicas:
            if rep.alive:
                rep.drain(timeout_s)
            for ev in rep.poll():
                self._handle_event(rep, ev)
        out = {"finished": 0, "failed": 0, "timeout": 0, "rejected": 0}
        for fr in list(self._requests.values()):
            if fr.internal:
                if not fr.terminal:
                    self._finalize(fr, REJECTED, error="router drained")
                continue
            if not fr.terminal:
                _fm.REJECTED.inc()
                if self._trace and fr.dispatched_t is not None:
                    _ftr.on_attempt_end(fr, fr.last_replica or 0, "shed",
                                        killed=True)
                    fr.dispatched_t = None
                self._finalize(fr, REJECTED, error="router drained")
            out[fr.state] = out.get(fr.state, 0) + 1
        self._queue.clear()
        _fm.QUEUE_DEPTH.set(0)
        if self._trace:
            _ftr.on_lifecycle_span("drain", t0, time.perf_counter(),
                                   args=dict(out))
        self._emit_event("drain", scope="fleet", summary=out)
        self.close()
        return out

    def close(self) -> None:
        """Stop the fleet. Idempotent; replicas still alive are shut down
        (process workers get a graceful shutdown op, then SIGKILL)."""
        if self._closed:
            return
        self._closed = True
        # outstanding migrations can never resolve once the replicas are
        # gone; their held requests stay accounted through _requests (a
        # drain() sweep finalizes them as REJECTED before reaching here)
        self._migrations.clear()
        for rep in self._replicas:
            try:
                rep.close()
            except Exception:
                pass
        _fm.REPLICAS_ALIVE.set(0)
        if self._slo is not None:
            # closing the workers flushed their final telemetry samples;
            # evaluate them now, while the event log is still open, so a
            # breach in the last interval is journaled, not lost
            try:
                self.evaluate_slos()
            except Exception:
                pass
        self._emit_event("fleet_stop",
                         requests=len(self._requests),
                         states=dict(self._request_states()))
        # workers flushed their fragments on close (atexit); now the
        # router's own fragment + the merge manifest complete the set
        self._write_trace()
        # the merged fragments exist and the event log is still open:
        # replay the run through the phase ledger and autopsy any breach
        self._run_autopsy()
        self._write_snapshot()
        if self._events is not None:
            self._events.close()

    def _write_trace(self) -> None:
        if not self._trace:
            return
        try:
            _tr.save_chrome_trace(
                os.path.join(self.cfg.trace_dir, "router.json"),
                process_names={os.getpid(): "fleet router"})
            _ftr.write_manifest(
                self.cfg.trace_dir,
                {"file": "router.json", "pid": os.getpid(), "offset_us": 0},
                self._worker_frags, _runlog.run_id())
        except OSError:
            pass
        if self._own_tracer:
            _tr.stop_tracing()
            self._own_tracer = False

    def _run_autopsy(self) -> None:
        """Close-time request autopsy over the just-written trace: build
        the phase ledgers from the merged fragments, feed the
        ``fleet/phase/*`` histograms + snapshot stats, and — when this
        run recorded SLO breaches — journal one typed ``breach_autopsy``
        verdict per distinct breach in the event log (and the flight
        ring). Best-effort: an autopsy failure must never take down
        close()."""
        if not self._trace:
            return
        try:
            spans, manifest, _problems = _ftr.load_fragments(
                self.cfg.trace_dir)
            ledgers = _autopsy.build_ledgers(spans, manifest)
            if not ledgers:
                return
            _autopsy.observe_phase_histograms(ledgers)
            self._phase_stats = _autopsy.phase_stats(ledgers)
            if not self._breach_log:
                return
            verdicts = _autopsy.autopsy_breaches(
                self._breach_log, ledgers,
                telemetry_base=self.cfg.telemetry_base)
            self._autopsies = [v.to_doc() for v in verdicts]
            from ..monitor import device as _dev

            ring = _dev.flight_recorder()
            for doc in self._autopsies:
                self._emit_event(KIND_BREACH_AUTOPSY, **doc)
                if ring is not None:
                    ring.record_event(KIND_BREACH_AUTOPSY, **doc)
        except Exception:
            import logging

            logging.getLogger("paddle_tpu").exception(
                "breach autopsy failed (run artifacts are intact)")

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- introspection --------------------------------------------------------
    def accounting(self) -> Dict[int, str]:
        """fleet id -> state for every USER request ever accepted — the
        drill's zero-silent-drops ledger. Internal prefill jobs (disagg
        scaffolding) are router bookkeeping, not accepted work, and are
        excluded."""
        return {fid: fr.state for fid, fr in self._requests.items()
                if not fr.internal}

    def request(self, fid: int) -> Optional[FleetRequest]:
        return self._requests.get(fid)

    @staticmethod
    def _p99(lat_ms: List[float]) -> Optional[float]:
        if not lat_ms:
            return None
        s = sorted(lat_ms)
        return s[min(len(s) - 1, int(0.99 * len(s)))]

    def _request_states(self) -> Dict[str, int]:
        states: Dict[str, int] = {}
        for fr in self._requests.values():
            if fr.internal:
                continue
            states[fr.state] = states.get(fr.state, 0) + 1
        return states

    def evaluate_slos(self) -> dict:
        """One fleet-SLO evaluation pass (per-replica + aggregate) over
        the telemetry base. The pump calls this periodically; drills call
        it synchronously after workers flushed their final samples."""
        if self._slo is None or not self.cfg.telemetry_base:
            return {"replica": {}, "fleet": []}
        return self._slo.evaluate(self.cfg.telemetry_base,
                                  [rep.index for rep in self._replicas])

    def snapshot(self) -> dict:
        """One fleet-wide observability document: router counters,
        per-replica liveness/health/throughput (with SLO-breach overlay),
        the active breach set, joinable ids (run_id) and artifact paths
        (trace dir, event log), and (process mode with a telemetry base)
        the merged last-sample view of every replica's telemetry ring."""
        now = time.perf_counter()
        dt = max(now - self._t0, 1e-9)
        reps = []
        for rep in self._replicas:
            idx = rep.index
            lat = self._rep_lat.get(idx, [])
            health = (rep.health() if rep.kind == "inprocess" and rep.alive
                      else self._health.get(idx, {"status": "ok"}))
            breach = self._slo_breached.get(idx)
            if breach is not None:
                health = dict(health, status="degraded", slo_breached=True,
                              slo=breach.get("slo"))
            row = {
                "name": rep.name, "alive": rep.alive,
                "accepting": rep.accepting,
                "role": rep.role,
                "retired": idx in self._retired,
                "health": health,
                "inflight": len(rep.inflight),
                "completed": self._rep_done.get(idx, 0),
                "qps": round(self._rep_done.get(idx, 0) / dt, 3),
                "p99_ms": self._p99(lat),
            }
            if self._phase_stats is not None:
                row["phases"] = self._phase_stats.get(
                    "replicas", {}).get(idx, {})
            reps.append(row)
        out = {"queue_depth": len(self._queue),
               "requests": sum(1 for fr in self._requests.values()
                               if not fr.internal),
               "states": self._request_states(),
               "replicas": reps,
               "uptime_s": round(dt, 3),
               "run_id": _runlog.run_id()}
        if self.cfg.roles:
            out["roles"] = dict(self.cfg.roles,
                                source=self.cfg.roles_source)
        if self._fleet_prefix:
            out["migration"] = {
                "active": len(self._migrations),
                "prefix_index_entries": len(self._prefix_index)}
        if self.cfg.trace_dir:
            out["trace_dir"] = self.cfg.trace_dir
        if self._phase_stats is not None:
            out["phases"] = self._phase_stats.get("fleet", {})
        if self._autopsies:
            out["autopsies"] = self._autopsies
        if self._events is not None and self._events.armed:
            out["event_log"] = self._events.path
        if self._slo is not None:
            out["slo"] = {
                "specs": [s.name for s in self.cfg.slos],
                "breached_replicas": sorted(self._slo_breached),
                "fleet_breaches": self._fleet_breach_count,
                "fleet_breach": self._fleet_breach,
            }
        if self.cfg.telemetry_base:
            out["telemetry"] = aggregate_telemetry(
                self.cfg.telemetry_base,
                expected=[rep.index for rep in self._replicas])
        return out

    def _write_snapshot(self) -> None:
        """Drop ``snapshot.json`` under the telemetry base (atomically) so
        out-of-process viewers (tools/fleet_top.py --watch) can render the
        router's live view without a control channel."""
        base = self.cfg.telemetry_base
        if not base:
            return
        try:
            os.makedirs(base, exist_ok=True)
            path = os.path.join(base, "snapshot.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self.snapshot(), f, default=repr)
            os.replace(tmp, path)
        except OSError:
            pass


def _replica_index(name: str) -> int:
    """Numeric index from a ``replica_<i>`` dir name; unparsable names
    sort last (after replica_9 comes replica_10, not replica_1)."""
    try:
        return int(name.split("_", 1)[1])
    except (IndexError, ValueError):
        return 1 << 30


def aggregate_telemetry(base_dir: str,
                        expected: Optional[Sequence[int]] = None) -> dict:
    """Merge N replicas' telemetry rings (``<base>/replica_<i>/``, each an
    exporter dir of JSONL ring files) into one fleet view: per replica,
    the LAST sample of each of its processes, in NUMERIC replica order.
    The same files ``tools/dump_metrics --watch dir1,dir2,...`` tails
    live.

    Degenerate rings never throw — a freshly spawned replica that has not
    ticked yet, a SIGKILLed one that left only a torn tail, or a ring dir
    that never appeared (pass ``expected`` indices to detect that) each
    yield an entry with a ``flag`` explaining the gap, so the aggregate
    stays healthy and the hole stays visible."""
    from ..monitor import telemetry as _telemetry

    out: Dict[str, dict] = {}
    if not base_dir or not os.path.isdir(base_dir):
        if expected:
            for idx in expected:
                out["replica_%d" % idx] = {"samples": 0,
                                           "flag": "ring dir missing"}
        return out
    names = [n for n in os.listdir(base_dir)
             if n.startswith("replica_")
             and os.path.isdir(os.path.join(base_dir, n))]
    for name in sorted(names, key=_replica_index):
        sub = os.path.join(base_dir, name)
        try:
            series = _telemetry.read_series(sub)
        except Exception as e:
            out[name] = {"samples": 0, "flag": "unreadable: %s" % e}
            continue
        if series:
            out[name] = {"samples": len(series), "last": series[-1]}
        else:
            out[name] = {"samples": 0, "flag": "no complete samples"}
    for idx in (expected or ()):
        name = "replica_%d" % idx
        if name not in out:
            out[name] = {"samples": 0, "flag": "ring dir missing"}
    return out
