"""Health-aware fleet router: one bounded queue over N engine replicas.

The front door of the serving fleet. The router owns a bounded request
queue and a set of replicas (:class:`~.replica.InProcessReplica` for
tests/benches, :class:`~.replica.ProcessReplica` workers in production
shape) and guarantees, through every failure mode it knows about:

* **exactly-once terminal accounting** — every accepted request reaches
  exactly ONE terminal state (finished/failed/timeout/rejected), recorded
  on its :class:`FleetRequest`. Late/duplicate results after a requeue
  race are absorbed (``fleet/duplicate_results``), never double-counted;
* **crash tolerance** — a replica that dies (SIGKILL, OOM) is detected via
  its pipe/exit status; its in-flight requests requeue idempotently by
  fleet id (``fleet/requeued``) and replay bit-identically: the router
  pins every request's seed at submission, and sampling is keyed (seed,
  absolute position), so a retried stream equals the unkilled twin's;
* **health-aware dispatch** — replicas whose ``health()`` reports
  ``degraded`` (SLO breach, absorbed faults) are drained of NEW traffic
  but not killed; with no healthy replica accepting, requests stay queued
  (``fleet/no_healthy_replica``) rather than failing;
* **graceful rollout** — :meth:`rolling_restart` = per replica
  ``drain(timeout_s)`` → respawn. Requests the drain sheds come back as
  typed ``draining`` rejections and are re-routed to peers — zero
  rejected-by-bug.

Affinity: ``affinity="prefix"`` routes by a stable hash of the first
``affinity_tokens`` prompt tokens, so one conversation/system-prompt
cohort lands on one replica and its KV pages (and prefix-cache entries)
stay hot there; ``"round_robin"`` is the reference spread.

The router is single-threaded by design: :meth:`pump` is the event loop
tick (poll replicas → account results → detect deaths → dispatch), and
everything else composes on it. No locks, no callback hell — the same
drive-loop shape as ``ServingEngine.step``.
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Sequence

from ..serving.request import FAILED, FINISHED, REJECTED, TIMEOUT
from . import metrics as _fm
from .prefix_cache import prefix_key
from .replica import InProcessReplica, ProcessReplica

__all__ = ["FleetConfig", "FleetRequest", "FleetBackpressure", "Router",
           "aggregate_telemetry"]

_TERMINAL = (FINISHED, FAILED, TIMEOUT, REJECTED)


class FleetBackpressure(RuntimeError):
    """The router's bounded queue is full (or it is draining): typed
    shed-or-retry, mirroring serving.BackpressureError one level up."""


class FleetRequest:
    """One request as the ROUTER accounts it. The id is router-assigned
    and stable across requeues (the idempotency key); the seed is ALWAYS
    pinned at submission — derived deterministically from the id when the
    caller passes None — so a replay after a replica loss regenerates the
    identical sampled stream."""

    __slots__ = ("id", "prompt", "max_new_tokens", "deadline_s",
                 "temperature", "top_k", "seed", "state", "tokens", "error",
                 "attempts", "last_replica", "submitted_t", "finished_t")

    def __init__(self, rid: int, prompt: Sequence[int], max_new_tokens: int,
                 deadline_s: Optional[float] = None, temperature: float = 0.0,
                 top_k: int = 0, seed: Optional[int] = None):
        self.id = int(rid)
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.deadline_s = deadline_s
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        # never let a replica pick an id-derived seed: engine-local request
        # ids differ between the first attempt and a requeued replay
        self.seed = (int(seed) if seed is not None
                     else (self.id * 1000003 + 0x5EED) & 0x7FFFFFFF)
        self.state = "queued"
        self.tokens: List[int] = []
        self.error: Optional[str] = None
        self.attempts = 0
        self.last_replica: Optional[int] = None
        self.submitted_t = time.perf_counter()
        self.finished_t: Optional[float] = None

    @property
    def terminal(self) -> bool:
        return self.state in _TERMINAL

    @property
    def latency_s(self) -> Optional[float]:
        if self.finished_t is None:
            return None
        return self.finished_t - self.submitted_t

    def doc(self) -> dict:
        """The wire/replica form of this request."""
        return {"id": self.id, "prompt": self.prompt,
                "max_new_tokens": self.max_new_tokens,
                "deadline_s": self.deadline_s,
                "temperature": self.temperature, "top_k": self.top_k,
                "seed": self.seed}

    def __repr__(self):
        return ("FleetRequest(id=%d, state=%s, out=%d, attempts=%d)"
                % (self.id, self.state, len(self.tokens), self.attempts))


class FleetConfig:
    """Router geometry + policy.

    ``replicas``: replica count, or ``"auto"`` to consult the autotuned
    config table (tune kernel ``fleet.router``; falls back to 2).
    ``mode``: ``"inprocess"`` (requires ``engine_factory``, a callable
    ``index -> engine``) or ``"process"`` (requires ``engine_spec``, the
    worker spec dict — see fleet.worker). ``affinity``: ``"prefix"`` or
    ``"round_robin"``; ``affinity_tokens`` is the prefix-hash window.
    ``max_outstanding`` caps dispatched-but-unresolved requests per
    replica (bounds the requeue set a crash can strand). ``requeue_limit``
    bounds replays per request before it terminally FAILs ("replica
    lost"). ``telemetry_base``: per-replica telemetry ring dirs are
    created under it (``replica_<i>/``) in process mode.
    """

    def __init__(self, replicas=2, mode: str = "inprocess",
                 affinity: str = "prefix", affinity_tokens: int = 16,
                 max_queue: int = 1024, max_outstanding: int = 16,
                 requeue_limit: int = 2, drain_timeout_s: float = 30.0,
                 engine_factory: Optional[Callable] = None,
                 engine_spec: Optional[dict] = None,
                 auto_restart: bool = True,
                 telemetry_base: Optional[str] = None,
                 health_every: int = 16):
        if mode not in ("inprocess", "process"):
            raise ValueError("mode must be 'inprocess' or 'process'")
        if affinity not in ("prefix", "round_robin"):
            raise ValueError("affinity must be 'prefix' or 'round_robin'")
        self.replicas_source = "explicit"
        if replicas in (None, "auto"):
            replicas, affinity_cfg, self.replicas_source = \
                self._tuned_router(affinity)
            affinity = affinity_cfg
        self.replicas = max(1, int(replicas))
        self.mode = mode
        self.affinity = affinity
        self.affinity_tokens = max(1, int(affinity_tokens))
        self.max_queue = int(max_queue)
        self.max_outstanding = max(1, int(max_outstanding))
        self.requeue_limit = max(0, int(requeue_limit))
        self.drain_timeout_s = float(drain_timeout_s)
        self.engine_factory = engine_factory
        self.engine_spec = engine_spec
        self.auto_restart = bool(auto_restart)
        self.telemetry_base = telemetry_base
        self.health_every = max(1, int(health_every))
        if mode == "inprocess" and engine_factory is None:
            raise ValueError("inprocess mode needs engine_factory")
        if mode == "process" and engine_spec is None:
            raise ValueError("process mode needs engine_spec")

    @staticmethod
    def _tuned_router(affinity_default: str):
        """(replicas, affinity, source) from the tune table; a safe
        (2, default-affinity, "default") on any failure — the fleet must
        come up with no table on disk."""
        try:
            from .. import tune

            cfg, src = tune.resolve_fleet_router()
            return (int(cfg.get("replicas", 2)),
                    cfg.get("affinity", affinity_default), src)
        except Exception:
            return 2, affinity_default, "default"


class Router:
    """See module docstring. Lifecycle: construct (spawns replicas) →
    ``submit``/``pump`` (or ``wait_all``) → ``drain``/``close``."""

    def __init__(self, config: FleetConfig):
        self.cfg = config
        self._queue: Deque[FleetRequest] = deque()
        self._requests: Dict[int, FleetRequest] = {}
        self._next_id = 0
        self._rr = 0          # round-robin cursor
        self._ticks = 0
        self._draining = False
        self._closed = False
        self._t0 = time.perf_counter()
        self._health: Dict[int, dict] = {}       # replica index -> last doc
        self._rep_done: Dict[int, int] = {}      # replica index -> completed
        self._rep_lat: Dict[int, List[float]] = {}
        self._replicas = [self._spawn(i) for i in range(self.cfg.replicas)]
        _fm.REPLICAS_ALIVE.set(len(self._replicas))

    # -- replica lifecycle ----------------------------------------------------
    def _spawn(self, index: int):
        self._health[index] = {"status": "ok"}
        self._rep_done.setdefault(index, 0)
        self._rep_lat.setdefault(index, [])
        if self.cfg.mode == "inprocess":
            return InProcessReplica(self.cfg.engine_factory(index), index)
        tdir = None
        if self.cfg.telemetry_base:
            tdir = os.path.join(self.cfg.telemetry_base,
                                "replica_%d" % index)
        return ProcessReplica(self.cfg.engine_spec, index,
                              telemetry_dir=tdir)

    def _respawn(self, index: int) -> None:
        self._replicas[index] = self._spawn(index)
        _fm.REPLICA_RESTARTS.inc()

    # -- submission -----------------------------------------------------------
    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               deadline_s: Optional[float] = None, temperature: float = 0.0,
               top_k: int = 0, seed: Optional[int] = None) -> FleetRequest:
        """Accept a request into the bounded queue. Raises
        :class:`FleetBackpressure` (typed, accounted) when full or
        draining — the router never silently drops."""
        if self._closed or self._draining:
            _fm.REJECTED.inc()
            raise FleetBackpressure("router is draining/closed")
        if len(self._queue) >= self.cfg.max_queue:
            _fm.REJECTED.inc()
            raise FleetBackpressure(
                "fleet queue full (%d)" % self.cfg.max_queue)
        fr = FleetRequest(self._next_id, prompt, max_new_tokens,
                          deadline_s=deadline_s, temperature=temperature,
                          top_k=top_k, seed=seed)
        self._next_id += 1
        self._requests[fr.id] = fr
        self._queue.append(fr)
        _fm.SUBMITTED.inc()
        _fm.QUEUE_DEPTH.set(len(self._queue))
        return fr

    # -- accounting -----------------------------------------------------------
    def _finalize(self, fr: FleetRequest, state: str,
                  tokens: Optional[List[int]] = None,
                  error: Optional[str] = None) -> None:
        """THE exactly-once funnel: every terminal outcome lands here, and
        an already-terminal request absorbs the duplicate instead of
        flipping state (a SIGKILL race can produce both a late result and
        a requeued completion — first one wins, deterministically)."""
        if fr.terminal:
            _fm.DUPLICATE_RESULTS.inc()
            return
        fr.state = state
        if tokens is not None:
            fr.tokens = list(tokens)
        fr.error = error
        fr.finished_t = time.perf_counter()
        _fm.COMPLETED.inc()
        if fr.last_replica is not None:
            self._rep_done[fr.last_replica] = \
                self._rep_done.get(fr.last_replica, 0) + 1
            self._rep_lat.setdefault(fr.last_replica, []).append(
                (fr.finished_t - fr.submitted_t) * 1e3)

    def _requeue(self, fr: FleetRequest, why: str) -> None:
        if fr.terminal:
            return
        fr.attempts += 1
        if fr.attempts > self.cfg.requeue_limit:
            self._finalize(fr, FAILED,
                           error="replica lost %d times (%s)"
                                 % (fr.attempts, why))
            return
        fr.state = "queued"
        self._queue.appendleft(fr)  # retries go to the head: oldest first

    def _handle_event(self, rep, ev: dict) -> None:
        kind = ev.get("ev")
        if kind == "health":
            self._health[rep.index] = ev.get("health", {"status": "ok"})
            return
        if kind != "result":
            return
        fr = self._requests.get(ev.get("id"))
        if fr is None:
            return
        state = ev.get("state")
        if state == REJECTED and ev.get("kind") in ("draining",
                                                    "backpressure"):
            # replica-side typed shed: route to a peer, never terminal
            _fm.REROUTED.inc()
            self._requeue_reroute(fr)
            return
        self._finalize(fr, state, ev.get("tokens"), ev.get("error"))

    def _requeue_reroute(self, fr: FleetRequest) -> None:
        """A typed reroute (peer draining/backpressured) does not count
        against the requeue budget — nothing was lost, only refused."""
        if fr.terminal:
            return
        fr.state = "queued"
        self._queue.appendleft(fr)

    # -- the event-loop tick --------------------------------------------------
    def pump(self) -> int:
        """One router cycle: poll replicas (pumps in-process engines one
        step), account events, detect/recover deaths, dispatch the queue.
        Returns the number of requests still unresolved."""
        self._ticks += 1
        for rep in list(self._replicas):
            for ev in rep.poll():
                self._handle_event(rep, ev)
        for i, rep in enumerate(self._replicas):
            if not rep.alive:
                lost = list(rep.inflight.values())
                rep.inflight.clear()
                for rdoc in lost:
                    fr = self._requests.get(rdoc["id"])
                    if fr is not None and not fr.terminal:
                        _fm.REQUEUED.inc()
                        self._requeue(fr, "replica %d died" % i)
                if self.cfg.auto_restart and not self._draining \
                        and not self._closed:
                    self._respawn(i)
        if self.cfg.mode == "process" \
                and self._ticks % self.cfg.health_every == 0:
            for rep in self._replicas:
                if rep.alive:
                    rep.health()  # answer arrives as a health event
        self._dispatch()
        _fm.QUEUE_DEPTH.set(len(self._queue))
        _fm.REPLICAS_ALIVE.set(sum(1 for r in self._replicas if r.alive))
        return sum(1 for fr in self._requests.values() if not fr.terminal)

    def _replica_healthy(self, rep) -> bool:
        if not rep.alive or not rep.accepting:
            return False
        if rep.kind == "inprocess":
            h = rep.health()
        else:
            h = self._health.get(rep.index, {"status": "ok"})
        return h.get("status", "ok") == "ok"

    def _pick_replica(self, fr: FleetRequest):
        n = len(self._replicas)
        if self.cfg.affinity == "prefix":
            window = fr.prompt[:self.cfg.affinity_tokens]
            start = int(prefix_key(window)[:8], 16) % n
        else:
            start = self._rr % n
            self._rr += 1
        for off in range(n):
            rep = self._replicas[(start + off) % n]
            if self._replica_healthy(rep) \
                    and len(rep.inflight) < self.cfg.max_outstanding:
                return rep
        return None

    def _dispatch(self) -> None:
        stuck = False
        while self._queue and not stuck:
            fr = self._queue[0]
            if fr.terminal:  # finalized while queued (router drain race)
                self._queue.popleft()
                continue
            rep = self._pick_replica(fr)
            if rep is None:
                _fm.NO_HEALTHY_REPLICA.inc()
                stuck = True  # stays queued; degraded peers get no traffic
                break
            self._queue.popleft()
            fr.state = "dispatched"
            fr.last_replica = rep.index
            rep.submit(fr.doc())
            _fm.ROUTED.inc()

    def wait_all(self, timeout_s: float = 60.0,
                 idle_sleep_s: float = 0.002) -> bool:
        """Pump until every accepted request is terminal (True) or the
        timeout passes (False)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.pump() == 0:
                return True
            if self.cfg.mode == "process":
                time.sleep(idle_sleep_s)
        return self.pump() == 0

    # -- lifecycle ------------------------------------------------------------
    def rolling_restart(self, timeout_s: Optional[float] = None) -> dict:
        """Zero-downtime rollout: one replica at a time, stop its new
        traffic, ``drain(timeout_s)`` (in-flight finishes; engine-queued
        work is shed as typed ``draining`` rejections that re-route to
        peers), respawn, move on. Traffic keeps flowing through the
        others for the whole pass."""
        if timeout_s is None:
            timeout_s = self.cfg.drain_timeout_s
        summaries = {}
        for i in range(len(self._replicas)):
            rep = self._replicas[i]
            rep.accepting = False
            if rep.alive:
                summaries[rep.name] = rep.drain(timeout_s)
            for ev in rep.poll():  # drain's result events (incl. sheds)
                self._handle_event(rep, ev)
            # anything the drain could not resolve is a lost in-flight set
            lost = list(rep.inflight.values())
            rep.inflight.clear()
            for rdoc in lost:
                fr = self._requests.get(rdoc["id"])
                if fr is not None and not fr.terminal:
                    _fm.REQUEUED.inc()
                    self._requeue(fr, "rolling restart of replica %d" % i)
            self._respawn(i)
            self.pump()  # rerouted work lands on peers before the next leg
        _fm.ROLLING_RESTARTS.inc()
        return summaries

    def drain(self, timeout_s: Optional[float] = None) -> dict:
        """Fleet-wide graceful stop: no new submissions, finish what can
        finish within the budget, account everything else (queued work
        sheds as terminal REJECTED — typed, counted, never silent)."""
        if timeout_s is None:
            timeout_s = self.cfg.drain_timeout_s
        self._draining = True
        self.wait_all(timeout_s)
        for rep in self._replicas:
            if rep.alive:
                rep.drain(timeout_s)
            for ev in rep.poll():
                self._handle_event(rep, ev)
        out = {"finished": 0, "failed": 0, "timeout": 0, "rejected": 0}
        for fr in self._requests.values():
            if not fr.terminal:
                _fm.REJECTED.inc()
                self._finalize(fr, REJECTED, error="router drained")
            out[fr.state] = out.get(fr.state, 0) + 1
        self._queue.clear()
        _fm.QUEUE_DEPTH.set(0)
        self.close()
        return out

    def close(self) -> None:
        """Stop the fleet. Idempotent; replicas still alive are shut down
        (process workers get a graceful shutdown op, then SIGKILL)."""
        if self._closed:
            return
        self._closed = True
        for rep in self._replicas:
            try:
                rep.close()
            except Exception:
                pass
        _fm.REPLICAS_ALIVE.set(0)

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- introspection --------------------------------------------------------
    def accounting(self) -> Dict[int, str]:
        """fleet id -> state for every request ever accepted — the drill's
        zero-silent-drops ledger."""
        return {fid: fr.state for fid, fr in self._requests.items()}

    def request(self, fid: int) -> Optional[FleetRequest]:
        return self._requests.get(fid)

    @staticmethod
    def _p99(lat_ms: List[float]) -> Optional[float]:
        if not lat_ms:
            return None
        s = sorted(lat_ms)
        return s[min(len(s) - 1, int(0.99 * len(s)))]

    def snapshot(self) -> dict:
        """One fleet-wide observability document: router counters,
        per-replica liveness/health/throughput, and (process mode with a
        telemetry base) the merged last-sample view of every replica's
        telemetry ring."""
        now = time.perf_counter()
        dt = max(now - self._t0, 1e-9)
        states: Dict[str, int] = {}
        for fr in self._requests.values():
            states[fr.state] = states.get(fr.state, 0) + 1
        reps = []
        for rep in self._replicas:
            idx = rep.index
            lat = self._rep_lat.get(idx, [])
            reps.append({
                "name": rep.name, "alive": rep.alive,
                "accepting": rep.accepting,
                "health": (rep.health() if rep.kind == "inprocess"
                           and rep.alive
                           else self._health.get(idx, {"status": "ok"})),
                "inflight": len(rep.inflight),
                "completed": self._rep_done.get(idx, 0),
                "qps": round(self._rep_done.get(idx, 0) / dt, 3),
                "p99_ms": self._p99(lat),
            })
        out = {"queue_depth": len(self._queue),
               "requests": len(self._requests),
               "states": states,
               "replicas": reps,
               "uptime_s": round(dt, 3)}
        if self.cfg.telemetry_base:
            out["telemetry"] = aggregate_telemetry(self.cfg.telemetry_base)
        return out


def aggregate_telemetry(base_dir: str) -> dict:
    """Merge N replicas' telemetry rings (``<base>/replica_<i>/``, each an
    exporter dir of JSONL ring files) into one fleet view: per replica,
    the LAST sample of each of its processes. The same files
    ``tools/dump_metrics --watch dir1,dir2,...`` tails live."""
    from ..monitor import telemetry as _telemetry

    out: Dict[str, dict] = {}
    if not base_dir or not os.path.isdir(base_dir):
        return out
    for name in sorted(os.listdir(base_dir)):
        sub = os.path.join(base_dir, name)
        if not (name.startswith("replica_") and os.path.isdir(sub)):
            continue
        try:
            series = _telemetry.read_series(sub)
        except Exception:
            continue
        if series:
            last = series[-1]
            out[name] = {"samples": len(series), "last": last}
    return out
