"""Length-prefixed JSON frames: the router <-> worker wire protocol.

One frame = a 4-byte big-endian length followed by that many bytes of
UTF-8 JSON. Deliberately primitive — the protocol rides anonymous pipes
(worker stdin/stdout), must survive a SIGKILLed peer mid-frame (the
reader just sees a torn tail and EOF), and must be decodable by a human
with ``xxd``. Router->worker ops and worker->router events are plain
dicts; the op/event vocabulary lives in worker.py/replica.py, not here.

:class:`FrameReader` is the incremental decoder for the non-blocking
side (the router tails N worker stdouts through a selector): ``feed()``
pulls whatever bytes the fd has, ``frames()`` yields every complete
frame buffered so far, and a half-received frame simply stays buffered
until the next feed.
"""

from __future__ import annotations

import errno
import json
import os
import struct
from typing import Any, Iterator, List, Optional

__all__ = ["MAX_FRAME", "send_frame", "read_frame", "FrameReader"]

_HDR = struct.Struct(">I")
MAX_FRAME = 32 << 20  # one generation result is KBs; 32MB = corrupt stream


def send_frame(fp, obj: Any) -> None:
    """Serialize ``obj`` and write one frame to binary file object ``fp``
    (flushes — a worker's result must not sit in userspace buffers while
    the router waits on select)."""
    data = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    fp.write(_HDR.pack(len(data)) + data)
    fp.flush()


def read_frame(fp) -> Optional[Any]:
    """Blocking read of one frame from binary file object ``fp``; None on
    a clean EOF at a frame boundary. A torn frame (EOF mid-body — the
    peer died mid-write) also returns None: the caller treats both as
    "peer gone", which is the only honest reading of either."""
    hdr = fp.read(_HDR.size)
    if not hdr or len(hdr) < _HDR.size:
        return None
    (n,) = _HDR.unpack(hdr)
    if n > MAX_FRAME:
        raise ValueError("frame length %d exceeds MAX_FRAME" % n)
    body = fp.read(n)
    if body is None or len(body) < n:
        return None
    return json.loads(body.decode("utf-8"))


class FrameReader:
    """Incremental frame decoder over a (typically non-blocking) fd."""

    def __init__(self, fd: int):
        self.fd = fd
        self._buf = bytearray()
        self.eof = False

    def feed(self) -> int:
        """Drain whatever the fd has right now into the buffer; returns
        bytes read. Sets ``eof`` when the peer closed (or died)."""
        total = 0
        while True:
            try:
                chunk = os.read(self.fd, 65536)
            except BlockingIOError:
                break
            except OSError as e:  # EIO from a dead pty counts as EOF
                if e.errno == errno.EAGAIN:
                    break
                self.eof = True
                break
            if not chunk:
                self.eof = True
                break
            self._buf.extend(chunk)
            total += len(chunk)
        return total

    def frames(self) -> Iterator[Any]:
        """Yield every complete frame currently buffered (a torn tail
        stays buffered; after ``eof`` it is unrecoverable and ignored)."""
        while len(self._buf) >= _HDR.size:
            (n,) = _HDR.unpack(bytes(self._buf[:_HDR.size]))
            if n > MAX_FRAME:
                raise ValueError("frame length %d exceeds MAX_FRAME" % n)
            if len(self._buf) < _HDR.size + n:
                return
            body = bytes(self._buf[_HDR.size:_HDR.size + n])
            del self._buf[:_HDR.size + n]
            yield json.loads(body.decode("utf-8"))

    def drain(self) -> List[Any]:
        """feed() + collect frames() — the router's per-tick pump."""
        self.feed()
        return list(self.frames())
