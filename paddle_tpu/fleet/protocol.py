"""Length-prefixed frames: the router <-> worker wire protocol.

One frame = a 4-byte big-endian length word followed by that many bytes
of body. Two frame kinds share the stream, discriminated by the top bit
of the length word:

- **JSON frames** (top bit clear): UTF-8 JSON body — router->worker ops
  and worker->router events as plain dicts. Deliberately primitive: the
  protocol rides anonymous pipes (worker stdin/stdout), must survive a
  SIGKILLed peer mid-frame (the reader just sees a torn tail and EOF),
  and must be decodable by a human with ``xxd``. The op/event vocabulary
  lives in worker.py/replica.py, not here.
- **Binary frames** (top bit set): an opaque byte payload, delivered as
  a :class:`Binary` wrapper. This is the KV-page migration bulk lane —
  page bytes (and int8 pages with their per-page scales) must never
  round-trip through JSON. Oversize and torn binary frames get exactly
  the same typed treatment as JSON frames: a length over ``MAX_FRAME``
  raises ``ValueError``, a torn body reads as peer-gone EOF (blocking
  reader) or stays buffered until the next feed (incremental reader).

:func:`pack_pages` / :func:`unpack_pages` define the page-payload body
carried inside a binary frame: a small JSON meta header (layout,
geometry, dtype, blob lengths) followed by the raw page blobs,
concatenated. The meta dict doubles as the op/event envelope
(``{"op": "import_prefix", ...}`` / ``{"ev": "pages", ...}``) so one
binary frame is a complete, self-describing message.

:class:`FrameReader` is the incremental decoder for the non-blocking
side (the router tails N worker stdouts through a selector): ``feed()``
pulls whatever bytes the fd has, ``frames()`` yields every complete
frame buffered so far, and a half-received frame simply stays buffered
until the next feed.
"""

from __future__ import annotations

import errno
import json
import os
import struct
from typing import Any, Iterator, List, Optional, Sequence, Tuple

__all__ = ["MAX_FRAME", "Binary", "send_frame", "send_binary_frame",
           "read_frame", "FrameReader", "pack_pages", "unpack_pages"]

_HDR = struct.Struct(">I")
MAX_FRAME = 32 << 20  # one generation result is KBs; 32MB = corrupt stream
_BINARY_BIT = 0x80000000  # top bit of the length word marks a binary frame
_LEN_MASK = _BINARY_BIT - 1


class Binary:
    """A received binary frame: ``payload`` is the raw body bytes. A
    typed wrapper (not a bare ``bytes``) so dispatch loops can tell the
    bulk lane from JSON dicts without sniffing."""

    __slots__ = ("payload",)

    def __init__(self, payload: bytes):
        self.payload = payload

    def __repr__(self) -> str:  # keep event-log reprs short
        return "Binary(%d bytes)" % len(self.payload)


def send_frame(fp, obj: Any) -> None:
    """Serialize ``obj`` and write one JSON frame to binary file object
    ``fp`` (flushes — a worker's result must not sit in userspace
    buffers while the router waits on select)."""
    data = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    fp.write(_HDR.pack(len(data)) + data)
    fp.flush()


def send_binary_frame(fp, payload: bytes) -> None:
    """Write one binary frame. Refuses oversize payloads with the same
    typed error the reader would raise — the sender fails fast instead
    of poisoning the stream."""
    n = len(payload)
    if n > MAX_FRAME:
        raise ValueError("frame length %d exceeds MAX_FRAME" % n)
    fp.write(_HDR.pack(_BINARY_BIT | n))
    fp.write(payload)
    fp.flush()


def read_frame(fp) -> Optional[Any]:
    """Blocking read of one frame from binary file object ``fp``; None on
    a clean EOF at a frame boundary. A torn frame (EOF mid-body — the
    peer died mid-write) also returns None: the caller treats both as
    "peer gone", which is the only honest reading of either. Binary
    frames come back as :class:`Binary`."""
    hdr = fp.read(_HDR.size)
    if not hdr or len(hdr) < _HDR.size:
        return None
    (word,) = _HDR.unpack(hdr)
    binary = bool(word & _BINARY_BIT)
    n = word & _LEN_MASK
    if n > MAX_FRAME:
        raise ValueError("frame length %d exceeds MAX_FRAME" % n)
    body = fp.read(n)
    if body is None or len(body) < n:
        return None
    if binary:
        return Binary(body)
    return json.loads(body.decode("utf-8"))


class FrameReader:
    """Incremental frame decoder over a (typically non-blocking) fd."""

    def __init__(self, fd: int):
        self.fd = fd
        self._buf = bytearray()
        self.eof = False

    def feed(self) -> int:
        """Drain whatever the fd has right now into the buffer; returns
        bytes read. Sets ``eof`` when the peer closed (or died)."""
        total = 0
        while True:
            try:
                chunk = os.read(self.fd, 65536)
            except BlockingIOError:
                break
            except OSError as e:  # EIO from a dead pty counts as EOF
                if e.errno == errno.EAGAIN:
                    break
                self.eof = True
                break
            if not chunk:
                self.eof = True
                break
            self._buf.extend(chunk)
            total += len(chunk)
        return total

    def frames(self) -> Iterator[Any]:
        """Yield every complete frame currently buffered (a torn tail —
        JSON or binary — stays buffered; after ``eof`` it is
        unrecoverable and ignored). Binary frames yield :class:`Binary`."""
        while len(self._buf) >= _HDR.size:
            (word,) = _HDR.unpack(bytes(self._buf[:_HDR.size]))
            binary = bool(word & _BINARY_BIT)
            n = word & _LEN_MASK
            if n > MAX_FRAME:
                raise ValueError("frame length %d exceeds MAX_FRAME" % n)
            if len(self._buf) < _HDR.size + n:
                return
            body = bytes(self._buf[_HDR.size:_HDR.size + n])
            del self._buf[:_HDR.size + n]
            if binary:
                yield Binary(body)
            else:
                yield json.loads(body.decode("utf-8"))

    def drain(self) -> List[Any]:
        """feed() + collect frames() — the router's per-tick pump."""
        self.feed()
        return list(self.frames())


# -- page payloads ------------------------------------------------------------

def pack_pages(meta: dict, blobs: Sequence[bytes]) -> bytes:
    """Encode a page payload: 4-byte meta length + JSON meta (with
    ``blob_lens`` recorded) + the raw blobs concatenated. The result is
    the body of ONE binary frame — meta carries the op/event envelope so
    the frame is self-describing."""
    doc = dict(meta)
    doc["blob_lens"] = [len(b) for b in blobs]
    head = json.dumps(doc, separators=(",", ":")).encode("utf-8")
    return b"".join([_HDR.pack(len(head)), head] + [bytes(b) for b in blobs])


def unpack_pages(payload: bytes) -> Tuple[dict, List[bytes]]:
    """Decode a :func:`pack_pages` payload into ``(meta, blobs)``. A
    short/torn payload raises ``ValueError`` — inside an intact binary
    frame the payload is structurally complete, so a mismatch means the
    sender and receiver disagree on the format, never a slow pipe."""
    if len(payload) < _HDR.size:
        raise ValueError("torn page payload: %d bytes" % len(payload))
    (hn,) = _HDR.unpack(payload[:_HDR.size])
    if _HDR.size + hn > len(payload):
        raise ValueError("torn page payload: meta %d > %d bytes"
                         % (hn, len(payload)))
    meta = json.loads(payload[_HDR.size:_HDR.size + hn].decode("utf-8"))
    lens = [int(x) for x in meta.get("blob_lens", [])]
    off = _HDR.size + hn
    if off + sum(lens) != len(payload):
        raise ValueError("torn page payload: blobs %d != %d bytes"
                         % (sum(lens), len(payload) - off))
    blobs: List[bytes] = []
    for ln in lens:
        blobs.append(payload[off:off + ln])
        off += ln
    return meta, blobs
