"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle
Fluid's capabilities (reference: BrianZhu01/Paddle, surveyed in SURVEY.md),
built on JAX/XLA/Pallas/pjit.

Typical use mirrors Fluid:

    import paddle_tpu as fluid

    x = fluid.layers.data("x", shape=[784])
    y = fluid.layers.data("y", shape=[1], dtype="int64")
    out = fluid.layers.fc(x, size=10, act="softmax")
    loss = fluid.layers.mean(fluid.layers.cross_entropy(out, y))
    fluid.optimizer.Adam(1e-3).minimize(loss)

    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(fluid.default_startup_program())
    loss_val, = exe.run(feed={"x": xb, "y": yb}, fetch_list=[loss])
"""

# Wire the persistent XLA compile cache BEFORE anything can trigger a
# compile — PADDLE_TPU_COMPILE_CACHE=<dir> makes restarts skip re-compiles.
from . import compile_cache as _compile_cache  # noqa: F401

_compile_cache.setup_compile_cache()

# Sharding-invariant RNG: with the legacy threefry lowering, random values
# change when XLA partitions the generating computation — which would make a
# mesh-sharded table's shard-by-shard init (ops/tensor_ops._run_init) and a
# data-parallel dropout mask diverge from their single-device twins. The
# partitionable lowering keeps every random stream bit-identical no matter
# how GSPMD splits it (and is what later JAX releases default to), so loss
# parity between single-device and mesh runs includes the RNG. An explicit
# JAX_THREEFRY_PARTITIONABLE env setting wins — a host app pinning the
# legacy streams keeps them (and forfeits mesh/single-device RNG parity).
import os as _os

if "JAX_THREEFRY_PARTITIONABLE" not in _os.environ:
    import jax as _jax

    _jax.config.update("jax_threefry_partitionable", True)

from . import (  # noqa: F401
    amp,
    backward,
    clip,
    contrib,
    data,
    dataset,
    debugger,
    imperative,
    initializer,
    io,
    layers,
    log,
    metrics,
    monitor,
    nets,
    optimizer,
    parallel,
    passes,
    profiler,
    reader,
    regularizer,
    transpiler,
)
from .data_feeder import DataFeeder  # noqa: F401
from .flags import flags, get_flag, set_flag  # noqa: F401
from .compiler import BuildStrategy, CompiledProgram, ExecutionStrategy  # noqa: F401
from .backward import append_backward, calc_gradient, gradients  # noqa: F401
from .core.framework import (  # noqa: F401
    Program,
    Variable,
    default_main_program,
    default_startup_program,
    name_scope,
    program_guard,
    test_mode,
)
from .core import unique_name  # noqa: F401
from .parallel_executor import ParallelExecutor  # noqa: F401
from .core.pass_framework import (  # noqa: F401
    Pass,
    PassBuilder,
    get_pass,
    register_pass,
    registered_passes,
)
from .core.place import CPUPlace, CUDAPinnedPlace, TPUPlace, is_compiled_with_tpu  # noqa: F401
from .core.scope import Scope, global_scope, scope_guard  # noqa: F401
from .executor import Executor, FetchHandle  # noqa: F401
from .layers.layer_helper import ParamAttr, WeightNormParamAttr  # noqa: F401

# Fluid compatibility: CUDAPlace maps to the accelerator (TPU) place.
CUDAPlace = TPUPlace

__version__ = "0.1.0"

from .async_executor import AsyncExecutor  # noqa: F401
from .data_feed_desc import DataFeedDesc  # noqa: F401
from .reader.py_reader import EOFException  # noqa: F401
