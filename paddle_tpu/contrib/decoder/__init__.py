"""Seq2seq decoding helpers (reference: contrib/decoder/beam_search_decoder.py).

The reference builds decoding from StateCell/TrainingDecoder/
BeamSearchDecoder classes over LoD beam ops. Here decoding is the batched
beam machinery in ``paddle_tpu.layers.beam_search`` (fixed-capacity
TensorArray + while-loop decode, verified against a numpy beam search in
tests/test_beam_search.py); this namespace re-exports it under the contrib
path for API discovery parity.
"""

from ...layers.beam_search import (  # noqa: F401
    array_length,
    array_read,
    array_to_tensor,
    array_write,
    beam_search,
    beam_search_decode,
    create_array,
)

__all__ = ["beam_search", "beam_search_decode", "create_array", "array_write",
           "array_read", "array_length", "array_to_tensor"]
