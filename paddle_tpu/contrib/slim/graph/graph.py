"""Graph wrappers (reference: contrib/slim/graph/graph.py — Graph /
ImitationGraph hold the Program and expose op/param iteration for
strategies)."""

from __future__ import annotations

__all__ = ["Graph", "ImitationGraph"]


class Graph:
    def all_parameters(self):
        raise NotImplementedError


class ImitationGraph(Graph):
    def __init__(self, program=None):
        from ....core.framework import default_main_program

        self.program = program or default_main_program()

    def all_parameters(self):
        return self.program.all_parameters()

    def all_ops(self):
        return [op for b in self.program.blocks for op in b.ops]
