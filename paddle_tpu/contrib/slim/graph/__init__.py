from .graph import Graph, ImitationGraph  # noqa: F401
