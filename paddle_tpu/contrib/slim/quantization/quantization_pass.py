"""Quantization passes on the Program-pass framework (reference:
contrib/slim/quantization/quantization_pass.py — QuantizationTransformPass,
QuantizationFreezePass, ConvertToInt8Pass over IrGraph).

Our IR is the Program itself, so each pass is a registered
``core.pass_framework.Pass`` applying the corresponding phase of the
QuantizeTranspiler (contrib/quantize/quantize_transpiler.py) — same
rewrites, composable in a PassBuilder pipeline alongside user passes.
"""

from __future__ import annotations

from ....core.pass_framework import Pass, register_pass
from ...quantize.quantize_transpiler import QuantizeTranspiler

__all__ = ["QuantizationTransformPass", "QuantizationFreezePass",
           "ConvertToInt8Pass"]


@register_pass("quantization_transform_pass")
class QuantizationTransformPass(Pass):
    """Insert fake quant/dequant around quantizable ops (QAT training phase)."""

    def __init__(self, scope=None, place=None, weight_bits=8, activation_bits=8,
                 activation_quantize_type="abs_max",
                 weight_quantize_type="abs_max", window_size=10000,
                 moving_rate=0.9):
        super().__init__()
        self._t = QuantizeTranspiler(
            weight_bits=weight_bits, activation_bits=activation_bits,
            activation_quantize_type=activation_quantize_type,
            weight_quantize_type=weight_quantize_type,
            window_size=window_size, moving_rate=moving_rate)
        if scope is not None:
            self.set_attr("scope", scope)
        if place is not None:
            self.set_attr("place", place)

    def apply(self, program, startup_program=None):  # reference signature
        if startup_program is not None:
            # explicit arg wins, but only for THIS apply: a startup program
            # pairs with one main program, so letting it persist would
            # inject a later program's scale initializers into the wrong
            # startup. An attr set via set_attr (the only channel through
            # PassBuilder.apply_all, which calls apply(program) bare) is a
            # deliberate standing pairing and survives.
            had_prior = self.has_attr("startup_program")
            prior = self._attrs.get("startup_program")
            self.set_attr("startup_program", startup_program)
            try:
                return super().apply(program)
            finally:
                if had_prior:
                    self._attrs["startup_program"] = prior
                else:
                    self._attrs.pop("startup_program", None)
        return super().apply(program)

    def apply_impl(self, program):
        startup = (self.attr("startup_program")
                   if self.has_attr("startup_program") else None)
        return self._t.training_transpile(program, startup)


@register_pass("quantization_freeze_pass")
class QuantizationFreezePass(Pass):
    """Fold trained quant scales into inference-time quantize ops."""

    def __init__(self, scope=None, place=None, weight_bits=8, activation_bits=8,
                 weight_quantize_type="abs_max"):
        super().__init__()
        self._t = QuantizeTranspiler(
            weight_bits=weight_bits, activation_bits=activation_bits,
            weight_quantize_type=weight_quantize_type)
        if scope is not None:
            self.set_attr("scope", scope)
        if place is not None:
            self.set_attr("place", place)

    def apply_impl(self, program):
        return self._t.freeze_program(program, self.attr("place"),
                                      self.attr("scope"))


@register_pass("convert_to_int8_pass")
class ConvertToInt8Pass(Pass):
    """Store weights as int8 for the frozen inference program."""

    def __init__(self, scope=None, place=None):
        super().__init__()
        self._t = QuantizeTranspiler()
        if scope is not None:
            self.set_attr("scope", scope)
        if place is not None:
            self.set_attr("place", place)

    def apply_impl(self, program):
        return self._t.convert_to_int8(program, self.attr("place"),
                                       self.attr("scope"))
