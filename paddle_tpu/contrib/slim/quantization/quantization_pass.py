"""Pass-styled quantization API (reference:
contrib/slim/quantization/quantization_pass.py — QuantizationTransformPass,
QuantizationFreezePass, ConvertToInt8Pass over IrGraph).

Our IR is the Program itself, so each pass applies the corresponding phase
of the QuantizeTranspiler (contrib/quantize/quantize_transpiler.py) — same
rewrites, pass-shaped interface.
"""

from __future__ import annotations

from ...quantize.quantize_transpiler import QuantizeTranspiler

__all__ = ["QuantizationTransformPass", "QuantizationFreezePass",
           "ConvertToInt8Pass"]


class QuantizationTransformPass:
    """reference: quantization_pass.py QuantizationTransformPass."""

    def __init__(self, scope=None, place=None, weight_bits=8, activation_bits=8,
                 activation_quantize_type="abs_max",
                 weight_quantize_type="abs_max", window_size=10000,
                 moving_rate=0.9):
        self._t = QuantizeTranspiler(
            weight_bits=weight_bits, activation_bits=activation_bits,
            activation_quantize_type=activation_quantize_type,
            weight_quantize_type=weight_quantize_type,
            window_size=window_size, moving_rate=moving_rate)
        self._scope = scope
        self._place = place

    def apply(self, program, startup_program=None):
        """Insert fake quant/dequant around quantizable ops (QAT)."""
        return self._t.training_transpile(program, startup_program)


class QuantizationFreezePass:
    """reference: quantization_pass.py QuantizationFreezePass."""

    def __init__(self, scope=None, place=None, weight_bits=8, activation_bits=8,
                 weight_quantize_type="abs_max"):
        self._t = QuantizeTranspiler(
            weight_bits=weight_bits, activation_bits=activation_bits,
            weight_quantize_type=weight_quantize_type)
        self._scope = scope
        self._place = place

    def apply(self, program):
        return self._t.freeze_program(program, self._place, self._scope)


class ConvertToInt8Pass:
    """reference: quantization_pass.py ConvertToInt8Pass."""

    def __init__(self, scope=None, place=None):
        self._t = QuantizeTranspiler()
        self._scope = scope
        self._place = place

    def apply(self, program):
        return self._t.convert_to_int8(program, self._place, self._scope)
