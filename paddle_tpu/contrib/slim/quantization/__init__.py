from .quantization_pass import (  # noqa: F401
    QuantizationFreezePass,
    QuantizationTransformPass,
    ConvertToInt8Pass,
)

__all__ = ["QuantizationTransformPass", "QuantizationFreezePass",
           "ConvertToInt8Pass"]
