"""Model-compression namespace (reference: contrib/slim/ — quantization,
pruning, distillation behind a Compressor config).

Quantization delegates to the QuantizeTranspiler machinery over Program IR
via registered framework passes; pruning operates on scope values directly
(host-visible arrays need no mask programs); distillation losses are layer
compositions. The Compressor (core/) drives Strategy callbacks per
epoch/batch like the reference CompressPass.
"""

from . import distillation, quantization  # noqa: F401
from .core import CompressPass, Context, Strategy, build_compressor  # noqa: F401
from .graph import Graph, ImitationGraph  # noqa: F401
from .prune import (  # noqa: F401
    MagnitudePruner,
    PruneStrategy,
    Pruner,
    RatioPruner,
    SensitivePruneStrategy,
)
