"""Model-compression namespace (reference: contrib/slim/ — quantization,
distillation, pruning behind a Compressor config).

Quantization is real (see quantization/): the graph passes delegate to the
QuantizeTranspiler machinery (contrib/quantize) over Program IR. The
reference's distillation/pruning strategies are config-driven wrappers over
ordinary layers (losses + mask ops) — compose them directly; there is no
hidden runtime to port.
"""

from . import quantization  # noqa: F401
