from .compress_pass import CompressPass, Context, build_compressor  # noqa: F401
from .strategy import Strategy  # noqa: F401
