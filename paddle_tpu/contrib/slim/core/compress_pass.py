"""Compression driver (reference: contrib/slim/core/compress_pass.py —
CompressPass walks epochs/batches calling each Strategy's callbacks).

The context carries what strategies need: scope (parameter values are
host-visible arrays — weight surgery between steps needs no mask programs),
the executor, the graph wrapper, and epoch/batch counters.
"""

from __future__ import annotations

from ....core.scope import global_scope
from ..graph.graph import ImitationGraph

__all__ = ["Context", "CompressPass", "build_compressor"]


class Context:
    def __init__(self, graph, scope, program_exe=None, place=None):
        self.graph = graph
        self.scope = scope
        self.program_exe = program_exe
        self.place = place
        self.epoch_id = 0
        self.batch_id = 0


class CompressPass:
    """reference: CompressPass.apply — run the training loop with strategy
    callbacks around it. ``data_reader`` yields feed dicts; ``train_step``
    is called per batch (defaults to exe.run of the given program)."""

    def __init__(self, place=None, data_reader=None, epoch=1,
                 program_exe=None, scope=None):
        self.place = place
        self.data_reader = data_reader
        self.epoch = epoch
        self.program_exe = program_exe
        self.scope = scope
        self.strategies = []

    def add_strategy(self, strategy):
        self.strategies.append(strategy)
        return self

    def apply(self, graph_or_program, train_step=None):
        graph = (graph_or_program
                 if isinstance(graph_or_program, ImitationGraph)
                 else ImitationGraph(graph_or_program))
        context = Context(graph, self.scope or global_scope(),
                          program_exe=self.program_exe, place=self.place)
        for s in self.strategies:
            s.on_compress_begin(context)
        for epoch in range(self.epoch):
            context.epoch_id = epoch
            for s in self.strategies:
                s.on_epoch_begin(context)
            context.batch_id = 0
            for feed in (self.data_reader() if self.data_reader else ()):
                for s in self.strategies:
                    s.on_batch_begin(context)
                if train_step is not None:
                    train_step(context, feed)
                elif self.program_exe is not None:
                    self.program_exe.run(graph.program, feed=feed)
                for s in self.strategies:
                    s.on_batch_end(context)
                context.batch_id += 1
            for s in self.strategies:
                s.on_epoch_end(context)
        for s in self.strategies:
            s.on_compress_end(context)
        return context


def build_compressor(place=None, data_reader=None, epoch=1, program_exe=None,
                     scope=None, strategies=None):
    """reference: contrib/slim/core/compress_pass.py build_compressor."""
    c = CompressPass(place=place, data_reader=data_reader, epoch=epoch,
                     program_exe=program_exe, scope=scope)
    for s in strategies or []:
        c.add_strategy(s)
    return c
