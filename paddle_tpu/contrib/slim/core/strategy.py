"""Compression strategy base (reference: contrib/slim/core/strategy.py) —
epoch/batch lifecycle callbacks driven by the Compressor."""

__all__ = ["Strategy"]


class Strategy:
    def __init__(self, start_epoch=0, end_epoch=10):
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch

    def on_compress_begin(self, context):
        pass

    def on_epoch_begin(self, context):
        pass

    def on_epoch_end(self, context):
        pass

    def on_batch_begin(self, context):
        pass

    def on_batch_end(self, context):
        pass

    def on_compress_end(self, context):
        pass
