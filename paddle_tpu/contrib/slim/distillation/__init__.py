from .distillation_strategy import (  # noqa: F401
    DistillationStrategy,
    fsp_loss,
    l2_distill_loss,
    soft_label_loss,
)
