"""Distillation scaffolding (reference direction: contrib/slim's
distillation strategies in later releases; the v1.3 tree carries only the
config hooks). Provides the standard distill losses as layer compositions
over a combined teacher+student program, plus a Strategy shell.
"""

from __future__ import annotations

from .... import layers
from ..core.strategy import Strategy

__all__ = ["soft_label_loss", "l2_distill_loss", "fsp_loss",
           "DistillationStrategy"]


def soft_label_loss(teacher_logits, student_logits, temperature=1.0):
    """KL(teacher_T || student_T) · T² — Hinton soft-label distillation."""
    t = float(temperature)
    teacher = layers.softmax(layers.scale(teacher_logits, scale=1.0 / t))
    log_p = layers.log_softmax(layers.scale(student_logits, scale=1.0 / t))
    ce = layers.reduce_sum(layers.elementwise_mul(teacher, log_p), dim=-1)
    return layers.scale(layers.mean(ce), scale=-(t * t))


def l2_distill_loss(teacher_feature, student_feature):
    """Feature-map L2 imitation loss."""
    diff = layers.elementwise_sub(teacher_feature, student_feature)
    return layers.mean(layers.square(diff))


def fsp_loss(teacher_a, teacher_b, student_a, student_b):
    """Flow-of-solution-procedure loss (Yim et al.): L2 between layer-pair
    Gram matrices. Inputs are [N, C, H, W] feature maps; a/b pairs must
    share spatial size."""

    def fsp_matrix(a, b):
        n, ca, h, w = a.shape
        cb = b.shape[1]
        af = layers.reshape(a, [n, ca, h * w])
        bf = layers.reshape(b, [n, cb, h * w])
        return layers.scale(
            layers.matmul(af, layers.transpose(bf, [0, 2, 1])),
            scale=1.0 / float(h * w))

    t = fsp_matrix(teacher_a, teacher_b)
    s = fsp_matrix(student_a, student_b)
    return l2_distill_loss(t, s)


class DistillationStrategy(Strategy):
    """Config shell: the distill loss is an ordinary layer composition added
    to the student's objective at graph-construction time (see the loss
    builders above); the strategy only gates which epochs train with it."""

    def __init__(self, distillers=None, start_epoch=0, end_epoch=10):
        super().__init__(start_epoch, end_epoch)
        self.distillers = distillers or []
