from .pruner import MagnitudePruner, Pruner, RatioPruner  # noqa: F401
from .prune_strategy import PruneStrategy, SensitivePruneStrategy  # noqa: F401
