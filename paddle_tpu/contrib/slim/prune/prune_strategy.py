"""Pruning strategies (reference: contrib/slim/prune/prune_strategy.py).

PruneStrategy re-applies the pruner's masks to the live parameter values in
the scope every ``mini_batch_pruning_frequency`` batches inside the active
epoch window — iterative magnitude pruning with recovery training between
prunings.
"""

from __future__ import annotations

import numpy as np

from ..core.strategy import Strategy

__all__ = ["PruneStrategy", "SensitivePruneStrategy"]


class PruneStrategy(Strategy):
    def __init__(self, pruner, mini_batch_pruning_frequency=1, start_epoch=0,
                 end_epoch=10, params=None):
        super().__init__(start_epoch, end_epoch)
        self.pruner = pruner
        self.mini_batch_pruning_frequency = mini_batch_pruning_frequency
        self.params = set(params) if params else None

    def _trigger(self, context):
        return (context.batch_id % self.mini_batch_pruning_frequency == 0
                and self.start_epoch <= context.epoch_id < self.end_epoch)

    def _prune_all(self, context):
        for p in context.graph.all_parameters():
            if self.params is not None and p.name not in self.params:
                continue
            val = context.scope.find_var(p.name)
            if val is None:
                continue
            v = np.asarray(val)
            mask = self.pruner.prune(v, name=p.name)
            context.scope.set_var(p.name, (v * mask).astype(v.dtype))

    def on_batch_end(self, context):
        if self._trigger(context):
            self._prune_all(context)


class SensitivePruneStrategy(Strategy):
    """Scaffolding parity (reference: SensitivePruneStrategy holds
    sensitivities config; the full sensitivity search was never finished in
    the reference either — the fields are carried for config parity)."""

    def __init__(self, pruner=None, start_epoch=0, end_epoch=10,
                 delta_rate=0.20, acc_loss_threshold=0.2, sensitivities=None):
        super().__init__(start_epoch, end_epoch)
        self.pruner = pruner
        self.delta_rate = delta_rate
        self.acc_loss_threshold = acc_loss_threshold
        self.sensitivities = sensitivities or {}
