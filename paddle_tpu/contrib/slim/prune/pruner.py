"""Pruners (reference: contrib/slim/prune/pruner.py — Pruner /
MagnitudePruner / RatioPruner).

The reference builds little mask programs (less_than/topk) and runs them to
zero weights. Here scope values are host-visible arrays, so pruners compute
masks with numpy directly — same masks, no auxiliary program execution.
``prune`` returns the zero/one mask for a parameter value.
"""

from __future__ import annotations

import numpy as np

__all__ = ["Pruner", "MagnitudePruner", "RatioPruner"]


class Pruner:
    def prune(self, value, **kw):
        raise NotImplementedError


class MagnitudePruner(Pruner):
    """Zero weights with |w| below a fixed threshold."""

    def __init__(self, threshold):
        self.threshold = threshold

    def prune(self, value, threshold=None):
        t = self.threshold if threshold is None else threshold
        v = np.asarray(value)
        return (np.abs(v) >= t).astype(v.dtype)


class RatioPruner(Pruner):
    """Keep the largest-|w| ``ratio`` fraction per parameter.

    ``ratios`` maps param name -> keep-ratio ('*' is the default), matching
    the reference's `ratio=40%` == "prune the other 60%" convention.
    """

    def __init__(self, ratios=None):
        self.ratios = ratios or {"*": 1.0}

    def ratio_for(self, name):
        return self.ratios.get(name, self.ratios.get("*", 1.0))

    def prune(self, value, ratio=None, name=None):
        v = np.asarray(value)
        r = ratio if ratio is not None else self.ratio_for(name)
        if r >= 1.0:
            return np.ones_like(v)
        k = max(int(r * v.size), 1)
        flat = np.abs(v).reshape(-1)
        thresh = np.partition(flat, -k)[-k]
        return (np.abs(v) >= thresh).astype(v.dtype)
