"""Contrib namespace (reference: python/paddle/fluid/contrib/): quantization
(QAT transpiler + fake-quant ops), with the reference's other contrib areas
(slim, int8_inference, decoder) layered on the same primitives."""

from . import decoder, int8_inference, quantize, slim, utils  # noqa: F401
from .int8_inference import Calibrator  # noqa: F401
from .quantize import QuantizeTranspiler  # noqa: F401
from .utils import memory_usage, op_freq_statistic  # noqa: F401
