"""Small contrib debug utilities.

``memory_usage`` — static per-program memory estimate (reference:
contrib/memory_usage_calc.py:46 ``memory_usage``): sum of op-output tensor
sizes with the batch dim substituted, returned as a (lower, upper, unit)
band. On TPU this is a pre-compile sanity number only — XLA's buffer
assignment reuses/donates aggressively, so the authoritative figure for a
COMPILED step is ``compiled.memory_analysis()``, exposed as
``Executor.memory_report(program, feed=..., fetch_list=...)``: it
AOT-compiles the specialization without running it and returns
argument/output/temp/peak-HBM bytes (also published as the
``device_profile/*`` monitor gauges). This API exists for parity and for
sizing batch before paying that compile.

``op_freq_statistic`` — op-type frequency histogram (reference:
contrib/op_frequence.py ``op_freq_statistic``): single-op counts plus
adjacent-pair counts, useful for spotting fusion candidates in a Program.
"""

from __future__ import annotations

from collections import OrderedDict

from ..core.framework import Program

__all__ = ["memory_usage", "op_freq_statistic"]

_DTYPE_SIZE = {
    "float16": 2, "bfloat16": 2, "float32": 4, "float64": 8,
    "int16": 2, "int32": 4, "int64": 8, "bool": 1, "uint8": 1, "int8": 1,
}


def memory_usage(program: Program, batch_size: int):
    """Estimate a program's tensor memory at ``batch_size``.

    Returns ``(lower, upper, unit_str)`` — the reference's 5%-10% headroom
    band over the summed op-output sizes (batch dims, encoded as -1,
    multiplied out by ``batch_size``). For the authoritative compiled-step
    figure use ``Executor.memory_report`` (module docstring).
    """
    if not isinstance(program, Program):
        raise TypeError("Calculating Memory Usage requires Program as its "
                        "Parameter. But you passed in %s" % type(program))
    if batch_size <= 0:
        raise ValueError("The batch size need to be positive.")

    total = 0.0
    seen = set()
    block = program.global_block
    for op in block.ops:
        for var_name in op.output_arg_names:
            if var_name in seen:
                continue
            seen.add(var_name)
            var = block._find_var_recursive(var_name)
            if var is None or var.shape is None:
                continue
            count = 1
            neg_dims = 0
            for x in var.shape:
                if x is None:
                    continue
                if x < 0:
                    neg_dims += 1
                    if neg_dims > 1:
                        raise ValueError(
                            "Var %s has more than one negative dim" % var_name)
                    count *= batch_size * (-x)
                else:
                    count *= x
            total += count * _DTYPE_SIZE.get(str(var.dtype), 4)

    unit = "B"
    for u in ("KB", "MB"):
        if total > 1024:
            total /= 1024
            unit = u
    return total * 1.05, total * 1.1, unit


def op_freq_statistic(program: Program):
    """Op frequency statistics over block 0.

    Returns ``(uni_op_freq, adj_2_op_freq)`` — ordered dicts of single-op
    and adjacent-pair ("a->b") counts, most frequent first.
    """
    if not isinstance(program, Program):
        raise TypeError("The input type should be Program. But you passed "
                        "in %s" % type(program))
    uni = OrderedDict()
    adj = OrderedDict()
    prev = None
    for op in program.global_block.ops:
        uni[op.type] = uni.get(op.type, 0) + 1
        if prev is not None:
            key = "%s->%s" % (prev, op.type)
            adj[key] = adj.get(key, 0) + 1
        prev = op.type
    uni = OrderedDict(sorted(uni.items(), key=lambda kv: -kv[1]))
    adj = OrderedDict(sorted(adj.items(), key=lambda kv: -kv[1]))
    return uni, adj
