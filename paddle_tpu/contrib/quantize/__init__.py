from .quantize_transpiler import QuantizeTranspiler  # noqa: F401
