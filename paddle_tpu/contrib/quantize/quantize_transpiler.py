"""QuantizeTranspiler — QAT Program rewrite (reference:
python/paddle/fluid/contrib/quantize/quantize_transpiler.py:81, ops in
operators/fake_quantize_op.cc).

Same three phases as the reference:
- ``training_transpile``: insert fake quant/dequant pairs before every
  quantizable op (conv2d/depthwise_conv2d/mul/matmul) and rewire inputs.
  Only the forward needs rewriting here — gradients are derived by JAX AD
  from the rewritten forward, with the straight-through estimator baked
  into the quant ops (ops/quantize_ops.py), so the reference's backward
  rename pass has no equivalent.
- ``freeze_program``: for inference — weights stored on the int grid in the
  scope, activation quants switch to their frozen scales, dequants fold
  into one post-op ``fake_dequantize_max_abs``.
- ``convert_to_int8``: rewrite frozen weights as int8 arrays in the scope.
"""

from __future__ import annotations

import numpy as np

from ...core.framework import Parameter, default_main_program, default_startup_program, program_guard
from ...core.scope import global_scope

__all__ = ["QuantizeTranspiler"]

_QUANTIZABLE_OP_TYPES = ("conv2d", "depthwise_conv2d", "mul", "matmul")
_FAKE_QUANT_TYPES = ("fake_quantize_abs_max", "fake_quantize_range_abs_max",
                     "fake_quantize_moving_average_abs_max")
_FAKE_DEQUANT_TYPES = ("fake_dequantize_max_abs",)


def _quant_name(name):
    return name + ".quantized"


def _dequant_name(name):
    return name + ".dequantized"


def _scale_name(name):
    return name + ".scale"


def _original_var_name(name):
    for suf in (".quantized.dequantized", ".quantized", ".dequantized", ".scale"):
        if name.endswith(suf):
            return name[: -len(suf)]
    return name


class QuantizeTranspiler:
    """reference: quantize_transpiler.py:81."""

    def __init__(self, weight_bits: int = 8, activation_bits: int = 8,
                 activation_quantize_type: str = "abs_max",
                 weight_quantize_type: str = "abs_max",
                 window_size: int = 10000, moving_rate: float = 0.9):
        valid = ("abs_max", "range_abs_max", "moving_average_abs_max")
        if activation_quantize_type not in valid:
            raise ValueError("Unknown activation_quantize_type %r (want one of %s)"
                             % (activation_quantize_type, valid))
        if weight_quantize_type not in ("abs_max", "range_abs_max"):
            raise ValueError("Unknown weight_quantize_type %r" % weight_quantize_type)
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.activation_quantize_type = activation_quantize_type
        self.weight_quantize_type = weight_quantize_type
        self.window_size = window_size
        self.moving_rate = moving_rate
        self._step_var = None

    # -- phase 1: training ----------------------------------------------------
    def training_transpile(self, program=None, startup_program=None):
        program = program or default_main_program()
        startup_program = startup_program or default_startup_program()
        params = {p.name for b in program.blocks for p in b.vars.values()
                  if isinstance(p, Parameter)}
        with program_guard(program, startup_program):
            if self.activation_quantize_type == "range_abs_max":
                from ...layers import tensor as tensor_layers

                self._step_var = tensor_layers.create_global_var(
                    shape=[1], value=0, dtype="int64", persistable=True,
                    name="@quant_step@")
                # one increment per step
                program.global_block.append_op(
                    "increment", inputs={"X": self._step_var},
                    outputs={"Out": self._step_var}, attrs={"step": 1.0})
            for block in program.blocks:
                dequanted = {}
                for op in list(block.ops):
                    if op.type in _QUANTIZABLE_OP_TYPES:
                        self._transpile_forward(block, op, params, dequanted,
                                                startup_program)
        return program

    def _transpile_forward(self, block, op, params, dequanted, startup):
        for name in list(op.input_arg_names):
            if name in dequanted:
                op._rename_input(name, dequanted[name])
                continue
            var = block.var(name)
            is_w = name in params
            bits = self.weight_bits if is_w else self.activation_bits
            qtype = self.weight_quantize_type if is_w else self.activation_quantize_type
            idx = block.ops.index(op)
            qvar, svar = self._insert_quant_op(block, idx, var, bits, qtype, startup)
            dqvar = self._insert_dequant_op(block, block.ops.index(op), qvar, svar, bits)
            dequanted[name] = dqvar.name
            op._rename_input(name, dqvar.name)

    def _insert_quant_op(self, block, idx, var, bits, qtype, startup):
        qvar = block.create_var(name=_quant_name(var.name), dtype=var.dtype,
                                shape=var.shape)
        svar = block.create_var(name=_scale_name(var.name), dtype=var.dtype,
                                shape=[1], persistable=qtype != "abs_max")
        if qtype == "abs_max":
            block.insert_op(idx, "fake_quantize_abs_max",
                            inputs={"X": var}, outputs={"Out": qvar, "OutScale": svar},
                            attrs={"bit_length": bits})
            return qvar, svar
        # stateful variants need startup-initialized scale state
        self._init_state(startup, svar.name, [1], 0.001)
        if qtype == "range_abs_max":
            wvar = block.create_var(name=var.name + ".scales_window",
                                    dtype=var.dtype, shape=[self.window_size],
                                    persistable=True)
            self._init_state(startup, wvar.name, [self.window_size], 0.0)
            block.insert_op(
                idx, "fake_quantize_range_abs_max",
                inputs={"X": var, "InScale": svar, "Iter": self._step_var,
                        "OutScales": wvar},
                outputs={"Out": qvar, "OutScale": svar, "OutScales": wvar},
                attrs={"bit_length": bits, "window_size": self.window_size})
        else:  # moving_average_abs_max
            avar = block.create_var(name=var.name + ".quant_accum", dtype=var.dtype,
                                    shape=[1], persistable=True)
            tvar = block.create_var(name=var.name + ".quant_state", dtype=var.dtype,
                                    shape=[1], persistable=True)
            self._init_state(startup, avar.name, [1], 0.0)
            self._init_state(startup, tvar.name, [1], 0.0)
            block.insert_op(
                idx, "fake_quantize_moving_average_abs_max",
                inputs={"X": var, "InScale": svar, "InAccum": avar, "InState": tvar},
                outputs={"Out": qvar, "OutScale": svar, "OutAccum": avar,
                         "OutState": tvar},
                attrs={"bit_length": bits, "moving_rate": self.moving_rate})
        return qvar, svar

    def _init_state(self, startup, name, shape, value):
        blk = startup.global_block
        if not blk.has_var(name):
            blk.create_var(name=name, shape=shape, dtype="float32", persistable=True)
        blk.append_op("fill_constant", outputs={"Out": name},
                      attrs={"shape": list(shape), "dtype": "float32",
                             "value": float(value)})

    def _insert_dequant_op(self, block, idx, qvar, svar, bits):
        base = _original_var_name(qvar.name)
        dqvar = block.create_var(name=_dequant_name(qvar.name), dtype=qvar.dtype,
                                 shape=qvar.shape)
        block.insert_op(idx, "fake_dequantize_max_abs",
                        inputs={"X": qvar, "Scale": svar},
                        outputs={"Out": dqvar},
                        attrs={"max_range": float((1 << (bits - 1)) - 1)})
        return dqvar

    # -- phase 2: freeze ------------------------------------------------------
    def freeze_program(self, program=None, place=None, scope=None):
        """reference: quantize_transpiler.py:218 — rewires the trained
        program for int-grid inference."""
        program = program or default_main_program()
        scope = scope or global_scope()
        persistable = {v.name for b in program.blocks for v in b.vars.values()
                       if v.persistable or isinstance(v, Parameter)}
        pr = float((1 << (self.weight_bits - 1)) - 1)
        ar = float((1 << (self.activation_bits - 1)) - 1)

        for block in program.blocks:
            in_rename, out_rename, scale_map = {}, {}, {}

            def remove(op):
                i = block.ops.index(op)
                out = op.outputs["Out"][0]
                src = op.inputs["X"][0]
                in_rename[out] = in_rename.get(src, src)
                block.remove_op(i)

            for op in list(block.ops):
                for name in list(op.input_arg_names):
                    if name in out_rename:
                        op._rename_input(name, out_rename[name])
                if op.type in _FAKE_QUANT_TYPES:
                    x_name = op.inputs["X"][0]
                    if x_name in persistable:
                        w = np.asarray(scope.find_var(x_name))
                        scale_v = float(np.max(np.abs(w)))
                        scale_map[x_name] = scale_v
                        remove(op)
                        q = np.round(np.clip(w / max(scale_v, 1e-8), -1, 1) * pr)
                        scope.set_var(x_name, q.astype(w.dtype))
                    else:
                        op.attrs["is_test"] = True
                        scale_map[x_name] = op.outputs["OutScale"][0]
                elif op.type in _FAKE_DEQUANT_TYPES:
                    remove(op)
                elif op.type in _QUANTIZABLE_OP_TYPES:
                    max_range, scale_var = None, None
                    for name in list(op.input_arg_names):
                        if name in in_rename:
                            op._rename_input(name, in_rename[name])
                            name = in_rename[name]
                        orig = _original_var_name(name)
                        sv = scale_map.get(orig)
                        if isinstance(sv, float):
                            max_range = pr * ar / sv
                        elif sv is not None:
                            scale_var = sv
                    if max_range is None or scale_var is None:
                        continue  # op wasn't quantized
                    out_name = op.output_arg_names[0]
                    out_var = block.var(out_name)
                    dq = block.create_var(name=_dequant_name(out_name),
                                          dtype=out_var.dtype, shape=out_var.shape)
                    block.insert_op(block.ops.index(op) + 1,
                                    "fake_dequantize_max_abs",
                                    inputs={"X": out_var, "Scale": scale_var},
                                    outputs={"Out": dq},
                                    attrs={"max_range": float(max_range)})
                    out_rename[out_name] = dq.name
        return program

    # -- phase 3: int8 storage ------------------------------------------------
    def convert_to_int8(self, program=None, place=None, scope=None):
        """Store frozen int-grid weights as int8 arrays in the scope
        (reference: quantize_transpiler.py:348)."""
        program = program or default_main_program()
        scope = scope or global_scope()
        converted = []
        for block in program.blocks:
            for op in block.ops:
                if op.type not in _QUANTIZABLE_OP_TYPES:
                    continue
                for name in op.input_arg_names:
                    orig = _original_var_name(name)
                    v = scope.find_var(orig)
                    if v is None or orig in converted:
                        continue
                    arr = np.asarray(v)
                    if np.issubdtype(arr.dtype, np.floating) and np.all(
                            np.abs(arr - np.round(arr)) < 1e-6) and np.max(np.abs(arr)) <= 127:
                        scope.set_var(orig, arr.astype(np.int8))
                        converted.append(orig)
        return converted
