"""Post-training int8 calibration — quantize a TRAINED fp32 program without
any retraining (reference: contrib/int8_inference/utility.py Calibrator;
its KL algorithm follows the classic 8-bit-inference entropy calibration).

Flow (mirrors the reference's sample → optimal scales → rewritten program):

1. ``sample_data(feed)``: run the fp32 inference program over calibration
   batches, observing every ACTIVATION that feeds a quantizable op
   (conv2d/depthwise_conv2d/mul/matmul) — accumulating abs-max and, for the
   KL algorithm, a fixed-range histogram per var.
2. ``calibrate()``: compute per-activation scales (``abs_max`` or ``KL``
   entropy-optimal thresholds), then reuse the existing QAT machinery:
   transpile quant/dequant pairs into a clone of the program
   (``range_abs_max`` activations read their frozen InScale in test mode),
   write the calibrated scales into the scope, and ``freeze_program`` —
   weights land on the int8 grid from their own abs-max, activations use
   the calibrated scales.

TPU-first notes: sampling fetches ride the normal jitted executor (one
compile for all batches), the histograms are numpy on host (calibration is
offline), and the emitted program is the same simulated-int8 form the QAT
freeze produces — XLA folds the scale multiplies into the surrounding ops.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ...core.framework import Parameter, Program
from ...core.scope import global_scope, scope_guard
from ..quantize.quantize_transpiler import (
    _QUANTIZABLE_OP_TYPES,
    QuantizeTranspiler,
    _scale_name,
)

__all__ = ["Calibrator"]

_HIST_BINS = 2048


class Calibrator:
    """reference: contrib/int8_inference/utility.py:25."""

    def __init__(self, program: Program, exe, feed_names: Sequence[str] = (),
                 fetch_list=None, scope=None, algo: str = "KL",
                 weight_bits: int = 8, activation_bits: int = 8):
        if algo not in ("KL", "abs_max"):
            raise ValueError("algo must be 'KL' or 'abs_max', got %r" % algo)
        self.program = program
        self.exe = exe
        self.scope = scope or global_scope()
        # constructor feed_names/fetch_list become save_int8_model defaults
        # (reference Calibrator carries them the same way)
        self._default_feed_names = list(feed_names or ())
        self._default_fetch_list = list(fetch_list or ())
        self.algo = algo
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self._act_names = self._quantizable_activations(program)
        self._abs_max: Dict[str, float] = {n: 0.0 for n in self._act_names}
        self._hist: Dict[str, np.ndarray] = {
            n: np.zeros(_HIST_BINS, np.float64) for n in self._act_names}
        self._hist_range: Dict[str, float] = {}
        self._sampled = 0

    @staticmethod
    def _quantizable_activations(program) -> List[str]:
        params = {p.name for b in program.blocks for p in b.vars.values()
                  if isinstance(p, Parameter)}
        acts = []
        for block in program.blocks:
            for op in block.ops:
                if op.type in _QUANTIZABLE_OP_TYPES:
                    for name in op.input_arg_names:
                        if name not in params and name not in acts:
                            acts.append(name)
        return acts

    # -- phase 1: sampling ----------------------------------------------------
    def sample_data(self, feed):
        """One calibration batch: observe every quantizable activation."""
        vals = self.exe.run(self.program, feed=feed,
                            fetch_list=list(self._act_names),
                            scope=self.scope, return_numpy=True)
        for name, v in zip(self._act_names, vals):
            amax = float(np.max(np.abs(v))) if v.size else 0.0
            self._abs_max[name] = max(self._abs_max[name], amax)
            if self.algo == "KL":
                # first batch fixes the histogram range; later batches that
                # overflow it clip into the last bin (same approximation as
                # the reference's fixed sampling range)
                r = self._hist_range.setdefault(name, max(amax, 1e-8))
                # clip so later-batch overflow folds into the edge bin —
                # np.histogram would silently DROP out-of-range values and
                # the KL search would see an artificially light tail
                h, _ = np.histogram(np.minimum(np.abs(v), r),
                                    bins=_HIST_BINS, range=(0, r))
                self._hist[name] += h
        self._sampled += 1

    # -- phase 2: scales + program rewrite ------------------------------------
    def _scales(self) -> Dict[str, float]:
        if self.algo == "abs_max":
            return dict(self._abs_max)
        out = {}
        for n in self._act_names:
            r = self._hist_range.get(n, 1e-8)
            out[n] = _kl_threshold(self._hist[n], r,
                                   bits=self.activation_bits)
            # never clip below what a pure abs-max would within the range
            out[n] = min(max(out[n], 1e-8), max(self._abs_max[n], 1e-8))
        return out

    def calibrate(self, startup_program: Optional[Program] = None) -> Program:
        """Emit the quantized inference program (simulated-int8 form)."""
        if self._sampled == 0:
            raise RuntimeError("Calibrator: call sample_data() on at least "
                               "one batch before calibrate()")
        qprog = self.program.clone()
        startup = startup_program or Program()
        t = QuantizeTranspiler(
            weight_bits=self.weight_bits,
            activation_bits=self.activation_bits,
            activation_quantize_type="range_abs_max",
            weight_quantize_type="abs_max")
        t.training_transpile(qprog, startup)
        # run the quant-state initializers, then overwrite the activation
        # scales with the calibrated values (order matters: startup would
        # reset them to the 0.001 placeholder)
        self.exe.run(startup, scope=self.scope)
        for name, scale in self._scales().items():
            self.scope.set_var(_scale_name(name),
                               np.asarray([scale], np.float32))
        t.freeze_program(qprog, scope=self.scope)
        self._quant_prog = qprog
        return qprog

    def save_int8_model(self, dirname: str, feed_names: Sequence[str] = None,
                        fetch_vars=None) -> None:
        """Calibrate (if needed) and save the deployable int8 model
        (reference: Calibrator.save_int8_model). ``feed_names``/``fetch_vars``
        default to the constructor's feed_names/fetch_list."""
        from ... import io as fluid_io
        from ..quantize.quantize_transpiler import QuantizeTranspiler as _QT

        feed_names = self._default_feed_names if feed_names is None else feed_names
        fetch_vars = self._default_fetch_list if fetch_vars is None else fetch_vars
        if not feed_names or not fetch_vars:
            raise ValueError(
                "save_int8_model needs feed_names and fetch_vars (pass them "
                "here or to the Calibrator constructor)")
        prog = getattr(self, "_quant_prog", None) or self.calibrate()
        t = _QT(weight_bits=self.weight_bits,
                activation_bits=self.activation_bits)
        t.convert_to_int8(prog, scope=self.scope)
        with scope_guard(self.scope):
            fluid_io.save_inference_model(dirname, list(feed_names),
                                          list(fetch_vars), self.exe,
                                          main_program=prog)


def _kl_threshold(hist: np.ndarray, hist_range: float, bits: int = 8) -> float:
    """Entropy-optimal clip threshold over an |x| histogram.

    For each candidate threshold i (from 128 bins up), compare the reference
    distribution P (hist clipped at i, outliers folded into the edge bin)
    with its (2^(bits-1)) -level quantized reconstruction Q; pick the i
    minimizing KL(P||Q). Vectorized numpy — calibration is offline host
    work, no need to jit."""
    nbins = hist.size
    levels = 1 << (bits - 1)  # 128 for int8
    total = hist.sum()
    if total == 0:
        return hist_range
    best_i, best_kl = nbins, np.inf
    for i in range(levels, nbins + 1, 16):
        raw = hist[:i].astype(np.float64)
        p = raw.copy()
        p[i - 1] += hist[i:].sum()  # fold outliers into the clip bin
        if p.sum() == 0:
            continue
        # quantize the RAW clipped histogram (no outlier fold — that's what
        # penalizes aggressive clipping): merge i bins into `levels` groups,
        # redistribute uniformly over the nonzero source bins of each group
        factor = i / float(levels)
        edges = (np.arange(levels + 1) * factor).astype(np.int64)
        q = np.zeros(i, np.float64)
        for g in range(levels):
            lo, hi = edges[g], max(edges[g + 1], edges[g] + 1)
            seg = raw[lo:hi]
            nz = seg > 0
            if nz.any():
                q[lo:hi][nz] = seg.sum() / nz.sum()
        pn = p / p.sum()
        qn = q / max(q.sum(), 1e-12)
        mask = pn > 0
        kl = float(np.sum(pn[mask] * np.log(pn[mask] /
                                            np.maximum(qn[mask], 1e-12))))
        if kl < best_kl:
            best_kl, best_i = kl, i
    return hist_range * best_i / nbins
