"""Post-training int8 calibration (reference:
python/paddle/fluid/contrib/int8_inference/utility.py — Calibrator)."""

from .calibrator import Calibrator  # noqa: F401

__all__ = ["Calibrator"]
