"""Automatic mixed precision.

The reference's half-precision story is the software ``float16`` type
(``platform/float16.h``) + fp16 kernels selected per-op, with contrib loss
scaling. On TPU the native half type is **bfloat16** — same exponent range as
fp32, so no loss scaling is required — and the fp32→bf16 policy is applied at
the executor: forward/backward compute in bf16 against fp32 master weights,
optimizer updates in fp32. fp16 is also accepted (needs loss scaling).

API:
    fluid.amp.enable(program)                    # bf16 forward for program
    opt = fluid.amp.decorate(optimizer, ...)     # + static loss scaling
"""

from __future__ import annotations

from typing import Optional

from .core.framework import Program, default_main_program

__all__ = ["enable", "disable", "decorate", "OptimizerWithMixedPrecision"]


def enable(program: Optional[Program] = None, dtype: str = "bfloat16"):
    """Run this program's forward/backward in ``dtype`` with fp32 master
    weights and fp32 optimizer math."""
    program = program or default_main_program()
    if dtype not in ("bfloat16", "float16"):
        raise ValueError("amp dtype must be bfloat16 or float16, got %r" % dtype)
    program._amp_dtype = dtype
    program._version += 1
    return program


def disable(program: Optional[Program] = None):
    program = program or default_main_program()
    program._amp_dtype = None
    program._version += 1
    return program


class OptimizerWithMixedPrecision:
    """reference: contrib/mixed_precision decorate() — scales the loss before
    backward and unscales gradients before the update. With bf16 the scale
    defaults to 1.0 (not needed); set it for fp16."""

    def __init__(self, optimizer, amp_dtype="bfloat16", init_loss_scaling=1.0,
                 use_dynamic_loss_scaling=False):
        if use_dynamic_loss_scaling:
            raise NotImplementedError(
                "dynamic loss scaling is unnecessary for bf16 (TPU default); "
                "use static init_loss_scaling for fp16")
        self._optimizer = optimizer
        self._amp_dtype = amp_dtype
        self._scale = float(init_loss_scaling)

    def __getattr__(self, name):
        return getattr(self._optimizer, name)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from . import layers
        from .core.framework import program_guard

        program = loss.block.program
        enable(program, self._amp_dtype)
        with program_guard(program, startup_program):
            if self._scale != 1.0:
                scaled = layers.scale(loss, scale=self._scale)
            else:
                scaled = loss
            params_grads = self._optimizer.backward(
                scaled, startup_program, parameter_list, no_grad_set)
            if self._scale != 1.0:
                block = program.global_block
                for _, g in params_grads:
                    block.append_op("scale", inputs={"X": g}, outputs={"Out": g},
                                    attrs={"scale": 1.0 / self._scale})
            optimize_ops = self._optimizer.apply_gradients(params_grads)
        return optimize_ops, params_grads


def decorate(optimizer, amp_dtype="bfloat16", init_loss_scaling=1.0,
             use_dynamic_loss_scaling=False):
    return OptimizerWithMixedPrecision(
        optimizer, amp_dtype, init_loss_scaling, use_dynamic_loss_scaling)
