"""User-extensible Program-pass framework.

Reference: ``framework/ir/pass.h:32`` (Pass base), ``REGISTER_PASS``
(``pass.h:207``), and the PassBuilder exposed at ``pybind/pybind.cc:981-1003``
(``BuildStrategy::CreatePassesFromStrategy`` / append/insert/remove).

The reference's passes rewrite an ``ir::Graph`` lowered from ProgramDesc; the
TPU-native IR *is* the Program (Block/Operator/Variable,
``core/framework.py``), and XLA owns kernel-level fusion — so Program passes
here are for the rewrites XLA cannot do: quantization instrumentation,
inference-time weight folding (conv+bn), pruning, user instrumentation.
Passes run in PassBuilder order inside ``CompiledProgram``'s build step, or
standalone via ``Pass.apply``.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Union

__all__ = ["Pass", "FunctionPass", "PassError", "register_pass", "get_pass",
           "has_pass", "registered_passes", "PassBuilder"]


class PassError(RuntimeError):
    """A pass failed mid-pipeline. Carries ``pass_name`` so the
    transactional-clone error path (``CompiledProgram._apply_build_passes``)
    can say WHICH pass died instead of losing it in the traceback."""

    def __init__(self, pass_name: str, original: BaseException):
        self.pass_name = pass_name
        self.original = original
        super().__init__(
            "pass %r failed: %s: %s"
            % (pass_name, type(original).__name__, original))


class Pass:
    """Base class. Subclasses set ``name`` (or get it from ``register_pass``)
    and implement ``apply_impl(program)``; mutate the program in place and/or
    return it (returning None means "mutated in place").

    Like the reference's ``Pass::Set/Get`` attribute bag (``pass.h:51-99``),
    ``set_attr``/``attr`` carry side inputs such as the Scope holding
    parameter values (weight-folding passes need them).
    """

    name: str = ""

    def __init__(self):
        self._attrs: Dict[str, Any] = {}

    # -- attribute bag --------------------------------------------------------
    def set_attr(self, key: str, value) -> "Pass":
        self._attrs[key] = value
        return self

    def attr(self, key: str, default=None):
        return self._attrs.get(key, default)

    def has_attr(self, key: str) -> bool:
        return key in self._attrs

    # -- application ----------------------------------------------------------
    def apply(self, program):
        from ..monitor import metrics as _mx

        t0 = time.perf_counter() if _mx._enabled else 0.0
        out = self.apply_impl(program)
        program = out if out is not None else program
        program._version += 1  # invalidate executor program caches
        if _mx._enabled:
            _mx.histogram(
                "passes/%s/time_ms" % (self.name or type(self).__name__)
            ).observe((time.perf_counter() - t0) * 1e3)
        return program

    def apply_impl(self, program):
        raise NotImplementedError(
            "Pass %r must implement apply_impl(program)" % type(self).__name__)

    def __repr__(self):
        return "<Pass %s>" % (self.name or type(self).__name__)


class FunctionPass(Pass):
    """Adapter: a plain ``fn(program, pass_) -> Program|None`` as a Pass."""

    def __init__(self, name: str, fn: Callable):
        super().__init__()
        self.name = name
        self._fn = fn

    def apply_impl(self, program):
        return self._fn(program, self)


_PASS_REGISTRY: Dict[str, Callable[[], Pass]] = {}


def register_pass(name: str):
    """Decorator registering a Pass subclass or a function
    (reference: REGISTER_PASS, ir/pass.h:207). Re-registration under the
    same name is an error, matching the reference's static-registrar check."""

    def deco(obj):
        if name in _PASS_REGISTRY:
            raise ValueError("pass %r registered twice" % name)
        if isinstance(obj, type) and issubclass(obj, Pass):
            obj.name = name
            _PASS_REGISTRY[name] = obj
        elif callable(obj):
            _PASS_REGISTRY[name] = lambda: FunctionPass(name, obj)
        else:
            raise TypeError("register_pass: need a Pass subclass or callable")
        return obj

    return deco


def get_pass(name: str) -> Pass:
    try:
        factory = _PASS_REGISTRY[name]
    except KeyError:
        raise KeyError(
            "pass %r is not registered (known: %s)"
            % (name, sorted(_PASS_REGISTRY))) from None
    return factory()


def has_pass(name: str) -> bool:
    return name in _PASS_REGISTRY


def registered_passes() -> List[str]:
    return sorted(_PASS_REGISTRY)


class PassBuilder:
    """Ordered pass pipeline (reference: PassBuilder at pybind.cc:981-1003:
    append_pass/insert_pass/remove_pass over BuildStrategy's pipeline)."""

    def __init__(self, passes: Optional[List[Union[str, Pass]]] = None):
        self._passes: List[Pass] = []
        for p in passes or []:
            self.append_pass(p)

    def _coerce(self, p: Union[str, Pass]) -> Pass:
        return get_pass(p) if isinstance(p, str) else p

    def append_pass(self, p: Union[str, Pass]) -> Pass:
        p = self._coerce(p)
        self._passes.append(p)
        return p

    def insert_pass(self, idx: int, p: Union[str, Pass]) -> Pass:
        p = self._coerce(p)
        self._passes.insert(idx, p)
        return p

    def remove_pass(self, idx: int) -> None:
        del self._passes[idx]

    def all_passes(self) -> List[Pass]:
        return list(self._passes)

    def apply_all(self, program):
        """Apply every pass in order. A failing pass is re-raised as
        :class:`PassError` naming it — callers running the pipeline on a
        transactional clone (``CompiledProgram._apply_build_passes``) keep
        the original program untouched AND know which pass to blame."""
        for p in self._passes:
            try:
                program = p.apply(program)
            except PassError:
                raise  # nested builders: keep the innermost attribution
            except Exception as e:
                raise PassError(p.name or type(p).__name__, e) from e
        return program
