"""Device places.

Fluid's ``Place`` variant (``platform/place.h:26-79``) selects which kernel
library runs each op. Here a Place just picks the JAX backend/device; XLA owns
everything below. ``TPUPlace`` is the headline device — the framework's reason
to exist — with ``CPUPlace`` for tests and host-side work.
"""

from __future__ import annotations

from typing import Optional

import jax

__all__ = ["CPUPlace", "TPUPlace", "CUDAPinnedPlace", "Place", "get_device", "is_compiled_with_tpu"]


class Place:
    device_id = 0

    def jax_device(self) -> Optional[jax.Device]:
        return None

    def __eq__(self, other):
        return type(self) is type(other) and self.device_id == other.device_id

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))

    def __repr__(self):
        return "%s(%d)" % (type(self).__name__, self.device_id)


class CPUPlace(Place):
    def __init__(self):
        self.device_id = 0

    def jax_device(self):
        try:
            return jax.devices("cpu")[0]
        except RuntimeError:
            return None


class TPUPlace(Place):
    """The TPU device (north-star equivalent of CUDAPlace place.h:37)."""

    def __init__(self, device_id: int = 0):
        self.device_id = device_id

    def jax_device(self):
        devs = _accelerator_devices()
        if devs and self.device_id < len(devs):
            return devs[self.device_id]
        return None


class CUDAPinnedPlace(Place):
    """Host staging place; on TPU this is just host memory (API parity only)."""


def _accelerator_devices():
    devs = jax.devices()
    accel = [d for d in devs if d.platform not in ("cpu",)]
    return accel or devs


def get_device(place: Optional[Place]) -> Optional[jax.Device]:
    if place is None:
        return None
    return place.jax_device()


def is_compiled_with_tpu() -> bool:
    return bool([d for d in jax.devices() if d.platform != "cpu"])
