"""Op registry: symbolic op type → pure JAX implementation.

The TPU-native replacement of Fluid's kernel registry
(``framework/op_registry.h:197,237,240`` + ``OperatorWithKernel::RunImpl``
``framework/operator.cc:877``). Fluid keys kernels by (place, dtype, layout,
library) and dispatches per step per op; here each op type has ONE pure
function over jax arrays — XLA owns device/dtype/layout specialization, and
dispatch happens once at trace time inside ``jax.jit``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

__all__ = ["register_op", "get_op_impl", "has_op", "registered_ops", "OpContext"]

_OP_REGISTRY: Dict[str, Callable] = {}


def register_op(*types: str):
    """Decorator registering an impl for one or more op type names.

    Impl signature: ``fn(ctx: OpContext) -> None`` — reads inputs/attrs from
    ctx, writes outputs via ``ctx.set_output``.
    """

    def deco(fn):
        for t in types:
            if t in _OP_REGISTRY:
                raise ValueError("op %r registered twice" % t)
            _OP_REGISTRY[t] = fn
        return fn

    return deco


def get_op_impl(type_: str) -> Callable:
    try:
        return _OP_REGISTRY[type_]
    except KeyError:
        raise NotImplementedError(
            "Op %r has no TPU implementation registered. Registered ops: %d. "
            "(Fluid parity gap — add it in paddle_tpu/ops/.)"
            % (type_, len(_OP_REGISTRY))
        ) from None


def has_op(type_: str) -> bool:
    return type_ in _OP_REGISTRY


def registered_ops() -> List[str]:
    return sorted(_OP_REGISTRY)


class OpContext:
    """Execution context handed to op impls during program tracing.

    The analog of Fluid's ``ExecutionContext`` (``framework/operator.h:203``),
    but functional: values live in a name→array environment dict owned by the
    tracer, and outputs are written back into it.
    """

    def __init__(self, op, env: Dict[str, Any], trace):
        self.op = op
        self.env = env
        self.trace = trace  # TraceContext: rng, mode, program, op index

    # -- inputs ---------------------------------------------------------------
    def input(self, slot: str):
        """Single input value for a slot (None if absent)."""
        names = self.op.inputs.get(slot)
        if not names:
            return None
        return self._lookup(names[0])

    def inputs(self, slot: str) -> List[Any]:
        return [self._lookup(n) for n in self.op.inputs.get(slot, [])]

    def has_input(self, slot: str) -> bool:
        return bool(self.op.inputs.get(slot))

    def _lookup(self, name: str):
        if name not in self.env:
            raise KeyError(
                "Op %r reads var %r which is not materialized. "
                "Feed it, initialize it in the startup program, or check op order."
                % (self.op.type, name)
            )
        return self.env[name]

    # -- outputs --------------------------------------------------------------
    def output_name(self, slot: str) -> Optional[str]:
        names = self.op.outputs.get(slot)
        return names[0] if names else None

    def output_names(self, slot: str) -> List[str]:
        return self.op.outputs.get(slot, [])

    def has_output(self, slot: str) -> bool:
        return bool(self.op.outputs.get(slot))

    def set_output(self, slot: str, value, index: int = 0):
        names = self.op.outputs.get(slot)
        if not names:
            return  # optional output not wired
        self.env[names[index]] = value

    def set_outputs(self, slot: str, values):
        names = self.op.outputs.get(slot, [])
        for n, v in zip(names, values):
            self.env[n] = v

    # -- attrs / metadata -----------------------------------------------------
    def attr(self, name: str, default=None):
        return self.op.attrs.get(name, default)

    def var(self, name: str):
        """Symbolic Variable metadata (shape/dtype) for a var name."""
        return self.op.block.var(name)

    def input_var(self, slot: str):
        names = self.op.inputs.get(slot)
        return self.op.block.var(names[0]) if names else None

    def output_var(self, slot: str):
        names = self.op.outputs.get(slot)
        return self.op.block.var(names[0]) if names else None

    @property
    def is_test(self) -> bool:
        if "is_test" in self.op.attrs:
            return bool(self.op.attrs["is_test"])
        return self.trace.is_test

    def rng(self):
        """Per-op PRNG key, deterministic in (step key, op position, seed attr)."""
        return self.trace.op_rng(self)
