"""Dtype canonicalization.

Fluid uses a ``VarType`` proto enum (reference: framework.proto:105); here
dtypes are canonical numpy/JAX dtype strings. bfloat16 is first-class — it is
the TPU-native half precision (the reference's float16.h software-half role).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

_ALIASES = {
    "float": "float32",
    "double": "float64",
    "half": "float16",
    "bf16": "bfloat16",
    "int": "int32",
    "long": "int64",
    "bool": "bool",
    "uint8": "uint8",
    "int8": "int8",
    "int16": "int16",
    "int32": "int32",
    "int64": "int64",
    "float16": "float16",
    "bfloat16": "bfloat16",
    "float32": "float32",
    "float64": "float64",
}


def convert_dtype(dtype) -> str:
    """Normalize any dtype spec to a canonical string."""
    if dtype is None:
        return "float32"
    if isinstance(dtype, str):
        if dtype in _ALIASES:
            return _ALIASES[dtype]
        return np.dtype(dtype).name
    if dtype in (jnp.bfloat16,) or getattr(dtype, "name", None) == "bfloat16":
        return "bfloat16"
    return np.dtype(dtype).name


def to_jnp_dtype(dtype):
    """Canonicalized for the active JAX config: with x64 disabled (the
    default — TPU-native int32/float32 widths), a declared int64/float64
    maps to int32/float32 HERE, once, instead of every downstream
    astype/arange warning about silent truncation."""
    name = convert_dtype(dtype)
    if name == "bfloat16":
        return jnp.bfloat16
    import jax

    return np.dtype(jax.dtypes.canonicalize_dtype(np.dtype(name)))


def is_float_dtype(dtype) -> bool:
    return convert_dtype(dtype) in ("float16", "bfloat16", "float32", "float64")
