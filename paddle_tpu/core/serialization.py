"""Program serialization.

Fluid serializes the graph as a protobuf ``ProgramDesc``
(``framework/framework.proto:184``). The TPU-native Program is pure-Python;
it serializes to a stable JSON desc (human-readable, versioned). Compiled
inference artifacts can additionally be exported as StableHLO via
``jax.export`` — the XLA-native analog of shipping a ProgramDesc.
"""

from __future__ import annotations

import json
from typing import Any, Dict

from .framework import Block, Operator, Parameter, Program, Variable

FORMAT_VERSION = 1


def program_to_desc(program: Program) -> Dict[str, Any]:
    blocks = []
    for blk in program.blocks:
        vars_ = []
        for v in blk.vars.values():
            vars_.append(
                {
                    "name": v.name,
                    "shape": list(v.shape) if v.shape is not None else None,
                    "dtype": v.dtype,
                    "persistable": v.persistable,
                    "stop_gradient": v.stop_gradient,
                    "is_data": v.is_data,
                    "trainable": v.trainable,
                    "is_parameter": isinstance(v, Parameter),
                }
            )
        ops = []
        for op in blk.ops:
            ops.append(
                {
                    "type": op.type,
                    "inputs": op.inputs,
                    "outputs": op.outputs,
                    "attrs": op.attrs,
                }
            )
        blocks.append({"idx": blk.idx, "parent_idx": blk.parent_idx, "vars": vars_, "ops": ops})
    return {
        "format_version": FORMAT_VERSION,
        "random_seed": program._seed,
        "backward_info": program._backward_info,
        "lr_var_name": program._lr_var_name,
        "blocks": blocks,
    }


def desc_to_program(desc: Dict[str, Any]) -> Program:
    if desc.get("format_version") != FORMAT_VERSION:
        raise ValueError("unsupported program format version: %r" % desc.get("format_version"))
    program = Program()
    program._seed = desc.get("random_seed", 0)
    program._backward_info = desc.get("backward_info")
    program._lr_var_name = desc.get("lr_var_name")
    program.blocks = []
    for bdesc in desc["blocks"]:
        blk = Block(program, bdesc["idx"], bdesc["parent_idx"])
        for vdesc in bdesc["vars"]:
            cls = Parameter if vdesc.get("is_parameter") else Variable
            v = cls(
                blk,
                name=vdesc["name"],
                shape=vdesc["shape"],
                dtype=vdesc["dtype"],
                persistable=vdesc["persistable"],
                stop_gradient=vdesc["stop_gradient"],
            )
            v.is_data = vdesc.get("is_data", False)
            v.trainable = vdesc.get("trainable", True)
            blk.vars[v.name] = v
        for odesc in bdesc["ops"]:
            op = Operator(blk, odesc["type"], attrs=odesc["attrs"])
            op.inputs = {k: list(v) for k, v in odesc["inputs"].items()}
            op.outputs = {k: list(v) for k, v in odesc["outputs"].items()}
            blk.ops.append(op)
        program.blocks.append(blk)
    program._version += 1
    return program


def dumps(program: Program) -> str:
    return json.dumps(program_to_desc(program))


def loads(s: str) -> Program:
    return desc_to_program(json.loads(s))
