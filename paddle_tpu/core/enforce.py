"""Context-rich execution errors (reference: platform/enforce.h
PADDLE_ENFORCE / EnforceNotMet: every kernel failure carries the op, the
call site, and the message).

Here the equivalent moment is program tracing: an op impl that throws gets
wrapped in ``EnforceNotMet`` carrying the op type, its position, its
input/output wiring and the best-available shapes — instead of a bare
KeyError/TypeError from deep inside jnp.
"""

from __future__ import annotations

__all__ = ["EnforceNotMet", "enforce", "wrap_op_error"]


class EnforceNotMet(RuntimeError):
    """reference: platform/enforce.h:EnforceNotMet."""


def enforce(cond: bool, msg: str, *fmt_args):
    if not cond:
        raise EnforceNotMet(msg % fmt_args if fmt_args else msg)


def _var_desc(name, env, block):
    if env is not None and name in env:
        v = env[name]
        shp = getattr(v, "shape", None)
        dt = getattr(v, "dtype", None)
        return "%s[%s,%s]" % (name, list(shp) if shp is not None else "?", dt)
    if block is not None and block.has_var(name):
        v = block.var(name)
        return "%s[%s,%s](sym)" % (name, v.shape, v.dtype)
    return name + "[?]"


def _oom_hint(e: BaseException, op) -> str:
    """Actionable RESOURCE_EXHAUSTED diagnosis: report the bytes the op
    asked for and point at the CTR-scale escape hatches instead of leaving
    a raw XLA allocator traceback (the BENCH_r05 V=1e8 failure mode —
    a [1e8, D] fill_constant/parameter init exhausting one chip's HBM
    at trace time)."""
    txt = "%s: %s" % (type(e).__name__, e)
    if ("RESOURCE_EXHAUSTED" not in txt and "RESOURCE EXHAUSTED" not in txt
            and "out of memory" not in txt.lower()):
        return ""
    detail = ""
    shape = op.attrs.get("shape")
    if shape:
        try:
            import numpy as np

            from .dtypes import to_jnp_dtype

            n = int(np.prod([int(s) for s in shape]))
            itemsize = np.dtype(
                to_jnp_dtype(op.attrs.get("dtype", "float32"))).itemsize
            detail = " (requested %s = %d elements, %.2f GB)" % (
                list(shape), n, n * itemsize / 1e9)
        except Exception:
            pass
    return (
        "\n  hint: device memory exhausted allocating this op's output%s. "
        "For CTR-scale embedding tables: layers.embedding(..., "
        "is_sparse=True) keeps gradients + optimizer updates rows-only, and "
        "parallel.sharded_embedding(..., mesh_axis=...) row-shards the "
        "table AND its Adam moments over a device mesh (V/n rows per "
        "device, initialized shard-by-shard) — see README \"Sparse & CTR\". "
        "Executor.memory_report(program, feed=..., fetch_list=...) gives "
        "the compiled step's authoritative peak-HBM figure WITHOUT running "
        "it — size the fix against that number." % detail)


def wrap_op_error(e: BaseException, op, op_index: int, env=None) -> EnforceNotMet:
    """Build the enriched error for an op impl failure during tracing."""
    block = getattr(op, "block", None)
    ins = {slot: [_var_desc(n, env, block) for n in names]
           for slot, names in op.inputs.items()}
    outs = {slot: list(names) for slot, names in op.outputs.items()}
    msg = (
        "Operator %r (index %d) failed during program tracing:\n"
        "  %s: %s\n"
        "  inputs:  %s\n"
        "  outputs: %s\n"
        "  attrs:   %s%s\n"
        "(reference parity: PADDLE_ENFORCE context, platform/enforce.h)"
        % (op.type, op_index, type(e).__name__, e, ins, outs,
           dict(op.attrs), _oom_hint(e, op))
    )
    return EnforceNotMet(msg)
