"""Unique name generator (reference: python/paddle/fluid/unique_name.py)."""

from __future__ import annotations

import contextlib
from collections import defaultdict

__all__ = ["generate", "guard", "switch"]


class NameGenerator:
    def __init__(self):
        self.ids = defaultdict(int)

    def __call__(self, key: str) -> str:
        name = "%s_%d" % (key, self.ids[key])
        self.ids[key] += 1
        return name


_generator = NameGenerator()


def generate(key: str) -> str:
    return _generator(key)


def switch(new_generator=None):
    global _generator
    prev = _generator
    _generator = new_generator or NameGenerator()
    return prev


@contextlib.contextmanager
def guard(new_generator=None):
    prev = switch(new_generator)
    try:
        yield
    finally:
        switch(prev)
