"""Block interpreter: runs a list of symbolic ops over a name→array env.

This is Fluid's executor hot loop (``framework/executor.cc:433``) — but it
executes exactly once per compilation, inside ``jax.jit`` tracing, so the
per-step cost is zero. Shared by the Executor and by control-flow ops
(while/cond/recurrent), which recursively interpret sub-blocks inside
``lax.while_loop``/``lax.cond``/``lax.scan`` bodies.

Device-side observability rides this loop because it IS the trace:

* each op impl runs under ``jax.named_scope("<slot>:<type>")`` (gated by
  ``PADDLE_TPU_OP_SCOPES``, resolved once per trace on the TraceContext),
  so HLO/xprof/cost_analysis carry Program-op identity at zero step cost;
* with the numerics watchdog armed (``trace.watch`` is the compiled step's
  layout list), every op's floating outputs contribute one ``isfinite``
  bit to ``env[NUMERICS_ENV_KEY]`` — a traced list that legally flows out
  of ``jax.value_and_grad`` as aux, unlike a side list of tracers.

``<slot>`` is ``__op_slot__`` when the trace-time optimizer stamped it
(``passes.analysis.stamp_op_slots`` — original position in the source
program, stable under DCE/CSE renumbering) and the positional index
otherwise.
"""

from __future__ import annotations

from typing import Any, Dict

from .registry import OpContext, get_op_impl

# Ops that are markers/IO and never execute as kernels.
SKIP_OPS = frozenset({"backward_marker", "feed", "fetch"})

# The env key watchdog bits accumulate under inside the traced name->array
# environment (they flow out of jax.value_and_grad as part of the env aux
# legally, unlike a side list, which would leak tracers). THE defining
# copy — the executor and monitor.device import it from here.
NUMERICS_ENV_KEY = "__numerics__"

# sub-blocks interpret at offset 10_000*block_idx (ops/control_flow_ops.py);
# watchdog bits must NOT be collected there — a bit created inside a
# lax.while/scan body cannot be stacked outside it (tracer leak), and the
# sub-block op's own outputs already give per-loop attribution at the top
# level. Named scopes (pure metadata) stay on everywhere.
_SUB_BLOCK_OFFSET = 10_000


def run_block_ops(ops, env: Dict[str, Any], trace, offset: int = 0):
    from .enforce import EnforceNotMet, wrap_op_error

    scopes = getattr(trace, "op_scopes", False)
    watch = getattr(trace, "watch", None) if offset < _SUB_BLOCK_OFFSET \
        else None
    # streaming tensor statistics (monitor.numerics) ride the same gate:
    # stat rows born inside a lax body can't be stacked outside it either
    stats_watch = getattr(trace, "stats_watch", None) \
        if offset < _SUB_BLOCK_OFFSET else None
    if stats_watch is not None:
        from ..monitor.numerics import fold_op_stats
    if scopes:
        import jax

    for i, op in enumerate(ops):
        if op.type in SKIP_OPS:
            continue
        trace.current_op_idx = offset + i
        impl = get_op_impl(op.type)
        try:
            if scopes:
                slot = op.attrs.get("__op_slot__")
                with jax.named_scope(
                        "%d:%s" % (offset + i if slot is None else slot,
                                   op.type)):
                    impl(OpContext(op, env, trace))
            else:
                impl(OpContext(op, env, trace))
        except EnforceNotMet:
            raise  # already enriched (nested blocks)
        except NotImplementedError:
            raise  # registry gap message is already the good pattern
        except Exception as e:
            raise wrap_op_error(e, op, offset + i, env) from e
        if watch is not None:
            _watch_op_outputs(op, env, watch, offset + i)
        if stats_watch is not None:
            fold_op_stats(op, env, stats_watch, offset + i)


def _watch_op_outputs(op, env: Dict[str, Any], layout, pos: int) -> None:
    """Fold each floating output of ``op`` into one isfinite bit appended
    to ``env[NUMERICS_ENV_KEY]``; record (label, outputs) at the same index
    in ``layout`` (index-overwrite, so jit retraces never duplicate)."""
    import jax.numpy as jnp

    bit = None
    outs = []
    for name in op.output_arg_names:
        v = env.get(name)
        dt = getattr(v, "dtype", None)
        if dt is None or not jnp.issubdtype(dt, jnp.floating):
            continue
        ok = jnp.isfinite(v).all()
        bit = ok if bit is None else jnp.logical_and(bit, ok)
        outs.append(name)
    if bit is None:
        return
    bits = env.setdefault(NUMERICS_ENV_KEY, [])
    k = len(bits)
    slot = op.attrs.get("__op_slot__")
    entry = ("%d:%s" % (pos if slot is None else slot, op.type), tuple(outs))
    if k < len(layout):
        layout[k] = entry
    else:
        layout.append(entry)
    bits.append(bit)


class PerStepTrace:
    """Trace proxy for loop bodies (lax.scan/while): folds the (traced) step
    index into every op's PRNG key so stochastic ops (dropout etc.) draw a
    fresh mask per timestep instead of reusing the trace-time constant."""

    # loop bodies never collect watchdog bits or stat rows (they'd leak
    # across the lax boundary); class attrs mask the inner trace's lists
    watch = None
    stats_watch = None

    def __init__(self, inner, step_index):
        self._inner = inner
        self._step_index = step_index

    def __getattr__(self, name):
        return getattr(self._inner, name)

    # current_op_idx is written by run_block_ops — forward to the inner trace
    @property
    def current_op_idx(self):
        return self._inner.current_op_idx

    @current_op_idx.setter
    def current_op_idx(self, v):
        self._inner.current_op_idx = v

    def op_rng(self, ctx):
        import jax

        return jax.random.fold_in(self._inner.op_rng(ctx), self._step_index)
