"""Block interpreter: runs a list of symbolic ops over a name→array env.

This is Fluid's executor hot loop (``framework/executor.cc:433``) — but it
executes exactly once per compilation, inside ``jax.jit`` tracing, so the
per-step cost is zero. Shared by the Executor and by control-flow ops
(while/cond/recurrent), which recursively interpret sub-blocks inside
``lax.while_loop``/``lax.cond``/``lax.scan`` bodies.
"""

from __future__ import annotations

from typing import Any, Dict

from .registry import OpContext, get_op_impl

# Ops that are markers/IO and never execute as kernels.
SKIP_OPS = frozenset({"backward_marker", "feed", "fetch"})


def run_block_ops(ops, env: Dict[str, Any], trace, offset: int = 0):
    for i, op in enumerate(ops):
        if op.type in SKIP_OPS:
            continue
        trace.current_op_idx = offset + i
        impl = get_op_impl(op.type)
        impl(OpContext(op, env, trace))
