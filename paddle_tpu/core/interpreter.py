"""Block interpreter: runs a list of symbolic ops over a name→array env.

This is Fluid's executor hot loop (``framework/executor.cc:433``) — but it
executes exactly once per compilation, inside ``jax.jit`` tracing, so the
per-step cost is zero. Shared by the Executor and by control-flow ops
(while/cond/recurrent), which recursively interpret sub-blocks inside
``lax.while_loop``/``lax.cond``/``lax.scan`` bodies.
"""

from __future__ import annotations

from typing import Any, Dict

from .registry import OpContext, get_op_impl

# Ops that are markers/IO and never execute as kernels.
SKIP_OPS = frozenset({"backward_marker", "feed", "fetch"})


def run_block_ops(ops, env: Dict[str, Any], trace, offset: int = 0):
    from .enforce import EnforceNotMet, wrap_op_error

    for i, op in enumerate(ops):
        if op.type in SKIP_OPS:
            continue
        trace.current_op_idx = offset + i
        impl = get_op_impl(op.type)
        try:
            impl(OpContext(op, env, trace))
        except EnforceNotMet:
            raise  # already enriched (nested blocks)
        except NotImplementedError:
            raise  # registry gap message is already the good pattern
        except Exception as e:
            raise wrap_op_error(e, op, offset + i, env) from e


class PerStepTrace:
    """Trace proxy for loop bodies (lax.scan/while): folds the (traced) step
    index into every op's PRNG key so stochastic ops (dropout etc.) draw a
    fresh mask per timestep instead of reusing the trace-time constant."""

    def __init__(self, inner, step_index):
        self._inner = inner
        self._step_index = step_index

    def __getattr__(self, name):
        return getattr(self._inner, name)

    # current_op_idx is written by run_block_ops — forward to the inner trace
    @property
    def current_op_idx(self):
        return self._inner.current_op_idx

    @current_op_idx.setter
    def current_op_idx(self, v):
        self._inner.current_op_idx = v

    def op_rng(self, ctx):
        import jax

        return jax.random.fold_in(self._inner.op_rng(ctx), self._step_index)
