from . import unique_name  # noqa: F401
from .dtypes import convert_dtype, is_float_dtype, to_jnp_dtype  # noqa: F401
from .framework import (  # noqa: F401
    Block,
    Operator,
    Parameter,
    Program,
    Variable,
    default_main_program,
    default_startup_program,
    grad_var_name,
    name_scope,
    program_guard,
    switch_main_program,
    switch_startup_program,
)
from .place import CPUPlace, CUDAPinnedPlace, Place, TPUPlace, is_compiled_with_tpu  # noqa: F401
from .registry import OpContext, get_op_impl, has_op, register_op, registered_ops  # noqa: F401
from .scope import Scope, global_scope, scope_guard  # noqa: F401
from ..reader.py_reader import EOFException  # noqa: F401  (fluid.core.EOFException parity)
from .enforce import EnforceNotMet, enforce  # noqa: F401
