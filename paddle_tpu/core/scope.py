"""Scope: name → device array state.

Fluid's ``Scope`` (``framework/scope.h:48``) is a hierarchical name→Variable
map mutated in place by C++ kernels. The TPU-native equivalent is a flat
name→jax.Array dict treated functionally: the jitted step consumes the state
and returns the updated state (with buffer donation, so params update in-place
in HBM — the XLA answer to Fluid's in-place optimizer kernels).
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, Iterator, Optional

import numpy as np

__all__ = ["Scope", "global_scope", "scope_guard"]


class Scope:
    def __init__(self, parent: Optional["Scope"] = None):
        self.parent = parent
        self.vars: Dict[str, Any] = {}

    def find_var(self, name: str):
        s: Optional[Scope] = self
        while s is not None:
            if name in s.vars:
                return s.vars[name]
            s = s.parent
        return None

    def has_var(self, name: str) -> bool:
        return self.find_var(name) is not None

    def set_var(self, name: str, value):
        self.vars[name] = value

    def erase(self, name: str):
        self.vars.pop(name, None)

    def new_scope(self) -> "Scope":
        return Scope(parent=self)

    def local_var_names(self):
        return list(self.vars)

    def as_numpy(self, name: str) -> np.ndarray:
        v = self.find_var(name)
        if v is None:
            raise KeyError("Variable %r not found in scope" % name)
        return np.asarray(v)

    def __contains__(self, name: str):
        return self.has_var(name)

    def __len__(self):
        return len(self.vars)


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


@contextlib.contextmanager
def scope_guard(scope: Scope) -> Iterator[None]:
    """Temporarily swap the global scope (reference: executor.py:53)."""
    global _global_scope
    prev, _global_scope = _global_scope, scope
    try:
        yield
    finally:
        _global_scope = prev
