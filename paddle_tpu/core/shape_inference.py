"""Shape/dtype inference by abstract evaluation.

Fluid hand-writes an InferShape function per op (~430 of them, e.g.
``framework/operator.cc:930`` runtime InferShape). Here shapes are derived
from the op implementations themselves: each appended op is abstractly
evaluated with ``jax.eval_shape`` over ShapeDtypeStructs — zero FLOPs, no
duplicate shape rules, and impossible for shape inference to disagree with
the kernel. Dynamic (batch) dims are threaded through as a sentinel value and
mapped back to -1.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from .dtypes import to_jnp_dtype

# Placeholder for dynamic (-1) dims during abstract eval. Prime & unusual to
# make accidental collision with a real static dim unlikely.
DYNAMIC_SENTINEL = 509


def _subst_dynamic(shape):
    return tuple(DYNAMIC_SENTINEL if d == -1 else d for d in shape)


def _restore_dynamic(shape):
    return tuple(-1 if d == DYNAMIC_SENTINEL else d for d in shape)


def infer_op_shapes(op, block) -> None:
    """Best-effort: fills in shape/dtype of output vars with unknown shape.

    Silently skips ops it cannot evaluate (unregistered type, inputs with
    unknown shapes, data-dependent shapes); runtime tracing remains the
    source of truth.
    """
    from .registry import OpContext, has_op, get_op_impl

    if not has_op(op.type):
        return

    env_structs = {}
    for names in op.inputs.values():
        for n in names:
            v = block._find_var_recursive(n)
            if v is None or v.shape is None:
                return  # unknown input — give up
            env_structs[n] = jax.ShapeDtypeStruct(_subst_dynamic(v.shape), to_jnp_dtype(v.dtype))

    out_names = [n for names in op.outputs.values() for n in names]

    class _Trace:
        is_test = False
        current_op_idx = 0

        def __init__(self):
            self.base_rng = None

        def op_rng(self, ctx):
            return self.base_rng

    def _absfn(env, key):
        trace = _Trace()
        trace.base_rng = key
        impl = get_op_impl(op.type)
        ctx = OpContext(op, env, trace)
        impl(ctx)
        return {n: env[n] for n in out_names if n in env}

    try:
        out = jax.eval_shape(
            _absfn, env_structs, jax.ShapeDtypeStruct((2,), np.uint32)
        )
    except Exception:
        return

    for n, s in out.items():
        v = block._find_var_recursive(n)
        if v is None:
            continue
        if v.shape is None:
            v.shape = _restore_dynamic(s.shape)
            v.dtype = np.dtype(s.dtype).name if s.dtype != jax.numpy.bfloat16 else "bfloat16"
