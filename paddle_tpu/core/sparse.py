"""Sparse gradients — the SelectedRows equivalent.

Reference: ``framework/selected_rows.h:32`` (rows + value block) and the
sparse optimizer kernels in ``operators/optimizers/`` (e.g. sgd_op.h's
SelectedRows branch, adam_op.h lazy mode).

XLA has no sparse tensors (SURVEY §7 hard parts): the TPU-native encoding is
an explicit ``(ids, rows)`` pair. For an embedding lookup of N ids into a
[V, D] table, the backward produces ``rows`` of shape [N, D] — O(N·D) HBM
traffic instead of the O(V·D) dense scatter-add, which is the entire point
at CTR-scale vocabularies (V ≥ 1e6, N a few thousand).

``merge_rows`` combines duplicate ids with static shapes (sort + segment
sum); the padded tail gets an out-of-range id, which XLA's scatter semantics
drop — so downstream row-wise optimizer updates are exact without a
dynamic-shape ``unique``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
class SparseGrad:
    """Gradient of a row-gathered parameter: ``rows[i]`` is the gradient
    contribution of table row ``ids[i]``; duplicate ids accumulate."""

    def __init__(self, ids, rows):
        self.ids = ids
        self.rows = rows

    def tree_flatten(self):
        return (self.ids, self.rows), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def __repr__(self):
        return "SparseGrad(ids=%r, rows=%r)" % (self.ids, self.rows)


def merge_rows(ids, rows, invalid_index):
    """Sum rows of duplicate ids. Returns (uniq_ids [N], merged [N, D]) where
    positions past the number of distinct ids carry ``invalid_index`` —
    feed them to ``.at[uniq].set/add`` and XLA drops them (OOB scatter).
    """
    order = jnp.argsort(ids)
    sid = jnp.take(ids, order)
    srows = jnp.take(rows, order, axis=0)
    first = jnp.concatenate(
        [jnp.ones((1,), bool), sid[1:] != sid[:-1]])
    seg = jnp.cumsum(first.astype(jnp.int32)) - 1
    merged = jax.ops.segment_sum(srows, seg, num_segments=ids.shape[0])
    uniq = jnp.full((ids.shape[0],), invalid_index, sid.dtype).at[seg].set(sid)
    return uniq, merged
