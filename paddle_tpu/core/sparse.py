"""Sparse gradients — the SelectedRows equivalent.

Reference: ``framework/selected_rows.h:32`` (rows + value block) and the
sparse optimizer kernels in ``operators/optimizers/`` (e.g. sgd_op.h's
SelectedRows branch, adam_op.h lazy mode).

XLA has no sparse tensors (SURVEY §7 hard parts): the TPU-native encoding is
an explicit ``(ids, rows)`` pair. For an embedding lookup of N ids into a
[V, D] table, the backward produces ``rows`` of shape [N, D] — O(N·D) HBM
traffic instead of the O(V·D) dense scatter-add, which is the entire point
at CTR-scale vocabularies (V ≥ 1e6, N a few thousand).

``merge_rows`` combines duplicate ids with static shapes (sort + segment
sum); the padded tail gets an out-of-range id, which XLA's scatter semantics
drop — so downstream row-wise optimizer updates are exact without a
dynamic-shape ``unique``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
class SparseGrad:
    """Gradient of a row-gathered parameter: ``rows[i]`` is the gradient
    contribution of table row ``ids[i]``; duplicate ids accumulate."""

    def __init__(self, ids, rows):
        self.ids = ids
        self.rows = rows

    def tree_flatten(self):
        return (self.ids, self.rows), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def __repr__(self):
        return "SparseGrad(ids=%r, rows=%r)" % (self.ids, self.rows)


def route_rows_to_shards(ids, rows, n_shards, shard_size, axis_name,
                         invalid_index):
    """PS ``split_ids_op`` parity inside ``shard_map``: bucket this rank's
    (ids, rows) by owning table shard (``id // shard_size``) and exchange
    buckets with ``lax.all_to_all`` so every row lands on the rank that owns
    it. Exact: bucket capacity is the local N (worst case all ids belong to
    one shard), so nothing is ever dropped — the cost model vs the
    replicate-to-all alternative is benchmarks/COLLECTIVES.md §7. Returns
    (ids [n·N], rows [n·N, D]); empty slots carry ``invalid_index``.
    """
    n_loc = ids.shape[0]
    owner = jnp.clip(ids // shard_size, 0, n_shards - 1)
    order = jnp.argsort(owner)
    sid = jnp.take(ids, order)
    srows = jnp.take(rows, order, axis=0)
    sowner = jnp.take(owner, order)
    # position within the (sorted) owner group, then a flat scatter into
    # fixed-capacity buckets — the static-shape sort-based dispatch MoE uses
    pos = (jnp.arange(n_loc, dtype=sowner.dtype)
           - jnp.searchsorted(sowner, sowner, side="left"))
    flat = sowner * n_loc + pos
    bucket_ids = jnp.full((n_shards * n_loc,), invalid_index,
                          sid.dtype).at[flat].set(sid)
    bucket_rows = jnp.zeros((n_shards * n_loc,) + rows.shape[1:],
                            rows.dtype).at[flat].set(srows)
    from ..monitor.device import record_collective

    send_ids = bucket_ids.reshape(n_shards, n_loc)
    send_rows = bucket_rows.reshape((n_shards, n_loc) + rows.shape[1:])
    # trace-time byte accounting: these are the PS-style id/row exchange's
    # per-device per-step volumes (benchmarks/COLLECTIVES.md §7 — measured)
    record_collective("all_to_all", axis_name, send_ids)
    record_collective("all_to_all", axis_name, send_rows)
    recv_ids = jax.lax.all_to_all(send_ids, axis_name, 0, 0)
    recv_rows = jax.lax.all_to_all(send_rows, axis_name, 0, 0)
    return recv_ids.reshape(-1), recv_rows.reshape((-1,) + rows.shape[1:])


def sharded_rows_update(tables, ids, rows, update, mesh, axis,
                        scalars=(), alltoall=False):
    """Rows-only optimizer update on tables row-sharded over a mesh axis —
    the GSPMD-era replacement of the reference parameter server's sparse
    update path (``listen_and_serv`` + ``split_ids``/``send``): each shard
    holds V/n rows (and its own slice of the optimizer moments), receives
    only the gradient rows it owns, and updates them in place. The dense
    [V, D] gradient never exists anywhere.

    ``tables``: tuple of [V, D] arrays annotated/laid out as ``P(axis,
    None)``. ``ids``: [N] globally-merged unique row ids (pads == V).
    ``rows``: [N, D] merged gradient rows. ``update(tabs_loc, lid,
    rows_loc, *scalars)`` maps shard-local tables + local row ids
    (out-of-shard entries set past the shard bound, which XLA's OOB scatter
    semantics drop) to new shard-local tables. ``scalars`` are traced
    scalars the update reads (e.g. the bias-corrected step size) — explicit
    replicated args because shard_map can't close over tracers.

    ``alltoall=False`` replicates (ids, rows) to every shard of ``axis``
    (one all-gather; each shard filters to its own rows). ``alltoall=True``
    instead splits the id list over the shards and routes each row to its
    owner with :func:`route_rows_to_shards` — the explicit PS-style id
    exchange; requires N divisible by the axis size (callers fall back to
    the replicated form otherwise).
    """
    from jax.sharding import PartitionSpec as P

    from ..parallel._compat import shard_map

    vocab = tables[0].shape[0]
    n = mesh.shape[axis]
    shard_size = vocab // n
    t_spec = P(axis, *([None] * (tables[0].ndim - 1)))

    def body(ids_l, rows_l, *rest):
        scal = rest[:len(scalars)]
        tabs = rest[len(scalars):]
        k = jax.lax.axis_index(axis)
        if alltoall:
            ids_l, rows_l = route_rows_to_shards(
                ids_l, rows_l, n, shard_size, axis, vocab)
        lo = k * shard_size
        mine = (ids_l >= lo) & (ids_l < lo + shard_size)
        # out-of-shard rows map just past the shard: reads clamp (harmless,
        # masked by the dropped write), writes drop — same OOB contract
        # merge_rows relies on
        lid = jnp.where(mine, ids_l - lo, shard_size)
        rows_l = jnp.where(mine[:, None], rows_l, jnp.zeros_like(rows_l))
        return update(tabs, lid, rows_l, *scal)

    spec_in = (P(axis) if alltoall else P(),
               P(axis, None) if alltoall else P(None, None))
    fn = shard_map(body, mesh=mesh,
                   in_specs=spec_in + (P(),) * len(scalars)
                   + (t_spec,) * len(tables),
                   out_specs=(t_spec,) * len(tables))
    return fn(ids, rows, *scalars, *tables)


def merge_rows(ids, rows, invalid_index):
    """Sum rows of duplicate ids. Returns (uniq_ids [N], merged [N, D]) where
    positions past the number of distinct ids carry ``invalid_index`` —
    feed them to ``.at[uniq].set/add`` and XLA drops them (OOB scatter).
    """
    order = jnp.argsort(ids)
    sid = jnp.take(ids, order)
    srows = jnp.take(rows, order, axis=0)
    first = jnp.concatenate(
        [jnp.ones((1,), bool), sid[1:] != sid[:-1]])
    seg = jnp.cumsum(first.astype(jnp.int32)) - 1
    merged = jax.ops.segment_sum(srows, seg, num_segments=ids.shape[0])
    uniq = jnp.full((ids.shape[0],), invalid_index, sid.dtype).at[seg].set(sid)
    return uniq, merged
