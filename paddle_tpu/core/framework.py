"""Program/Block/Operator/Variable symbolic graph builder.

TPU-native reimagining of Fluid's ProgramDesc stack (reference:
``python/paddle/fluid/framework.py:242-3152``). Fluid builds a protobuf
``ProgramDesc`` that a C++ executor interprets op-by-op
(``paddle/fluid/framework/executor.cc:433``). Here the Program is a light,
pure-Python symbolic graph; the Executor *traces* it once into a single
``jax.jit``-compiled XLA computation, so the per-op dispatch overhead that
Fluid pays at every step disappears and XLA fuses across the whole step.

The user-facing construction API (``program_guard``, ``Block.append_op``,
``Variable``, two global default programs) mirrors Fluid so that
reference-style training scripts port with minimal changes.
"""

from __future__ import annotations

import contextlib
import copy
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from . import unique_name
from .dtypes import convert_dtype

__all__ = [
    "Variable",
    "Parameter",
    "Operator",
    "Block",
    "Program",
    "default_main_program",
    "default_startup_program",
    "program_guard",
    "switch_main_program",
    "switch_startup_program",
    "name_scope",
    "grad_var_name",
    "in_test_mode",
]

GRAD_VAR_SUFFIX = "@GRAD"


def grad_var_name(name: str) -> str:
    """Gradient variable naming convention (reference: framework.py GRAD_VAR_SUFFIX)."""
    return name + GRAD_VAR_SUFFIX


class Variable:
    """A symbolic tensor in a Block.

    Mirrors Fluid's ``Variable`` (``framework.py:242``): a named node with
    static shape/dtype metadata. ``-1`` in ``shape`` marks a dynamic (batch)
    dimension; the Executor specializes it per feed shape (program-cache
    keyed on actual shapes, like Fluid's executor cache).
    """

    def __init__(
        self,
        block: "Block",
        name: Optional[str] = None,
        shape: Optional[Sequence[int]] = None,
        dtype: Any = "float32",
        persistable: bool = False,
        stop_gradient: bool = False,
        is_data: bool = False,
        initializer: Any = None,
        trainable: bool = True,
    ):
        self.block = block
        if name is None:
            name = unique_name.generate("_generated_var")
        self.name = name
        # None = unknown (filled by abstract-eval shape inference on append_op)
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = convert_dtype(dtype)
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.initializer = initializer
        self.trainable = trainable
        # The op that produced this var during construction (for debugging).
        self.op: Optional[Operator] = None

    # -- ergonomic sugar mirroring fluid's math_op_patch.py ------------------
    def _binary_op(self, other, op_name, reverse=False):
        from ..layers import math_op_patch

        return math_op_patch.binary_op(self, other, op_name, reverse)

    def __add__(self, other):
        return self._binary_op(other, "elementwise_add")

    def __radd__(self, other):
        return self._binary_op(other, "elementwise_add", reverse=True)

    def __sub__(self, other):
        return self._binary_op(other, "elementwise_sub")

    def __rsub__(self, other):
        return self._binary_op(other, "elementwise_sub", reverse=True)

    def __mul__(self, other):
        return self._binary_op(other, "elementwise_mul")

    def __rmul__(self, other):
        return self._binary_op(other, "elementwise_mul", reverse=True)

    def __truediv__(self, other):
        return self._binary_op(other, "elementwise_div")

    def __rtruediv__(self, other):
        return self._binary_op(other, "elementwise_div", reverse=True)

    def __pow__(self, other):
        return self._binary_op(other, "elementwise_pow")

    def __neg__(self):
        from ..layers import tensor as tensor_layers

        return tensor_layers.scale(self, scale=-1.0)

    def __repr__(self):
        return "Variable(name=%s, shape=%s, dtype=%s%s)" % (
            self.name,
            self.shape,
            self.dtype,
            ", persistable" if self.persistable else "",
        )

    __str__ = __repr__


class Parameter(Variable):
    """A trainable persistable Variable (reference: framework.py:2917)."""

    def __init__(self, block, name=None, shape=None, dtype="float32", **kwargs):
        kwargs.setdefault("persistable", True)
        trainable = kwargs.pop("trainable", True)
        self.regularizer = kwargs.pop("regularizer", None)
        self.gradient_clip_attr = kwargs.pop("gradient_clip_attr", None)
        self.do_model_average = kwargs.pop("do_model_average", False)
        super().__init__(block, name=name, shape=shape, dtype=dtype, **kwargs)
        self.trainable = trainable


class Operator:
    """A symbolic op node (reference: framework.py:571).

    ``inputs``/``outputs`` map slot names to lists of variable names; ``attrs``
    is a plain dict. The actual computation lives in the op registry
    (``paddle_tpu/core/registry.py``) as a pure JAX function — the Fluid
    equivalent of the ``OpKernelType``-keyed kernel map
    (``framework/op_registry.h:197``), except there is exactly one impl per
    op because XLA owns device/dtype/layout specialization.
    """

    def __init__(
        self,
        block: "Block",
        type: str,
        inputs: Optional[Dict[str, Any]] = None,
        outputs: Optional[Dict[str, Any]] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ):
        self.block = block
        self.type = type
        self.inputs: Dict[str, List[str]] = {}
        self.outputs: Dict[str, List[str]] = {}
        self.attrs: Dict[str, Any] = dict(attrs or {})

        def _canon(slot_map, store):
            for slot, vars_ in (slot_map or {}).items():
                if vars_ is None:
                    continue
                if isinstance(vars_, (Variable, str)):
                    vars_ = [vars_]
                names = []
                for v in vars_:
                    names.append(v.name if isinstance(v, Variable) else str(v))
                store[slot] = names

        _canon(inputs, self.inputs)
        _canon(outputs, self.outputs)

    def input(self, slot: str) -> List[str]:
        return self.inputs.get(slot, [])

    def output(self, slot: str) -> List[str]:
        return self.outputs.get(slot, [])

    @property
    def input_arg_names(self):
        return [n for ns in self.inputs.values() for n in ns]

    @property
    def output_arg_names(self):
        return [n for ns in self.outputs.values() for n in ns]

    def attr(self, name: str, default=None):
        return self.attrs.get(name, default)

    def _rename_input(self, old: str, new: str):
        """Replace every occurrence of input var ``old`` with ``new``
        (reference: framework.py Operator._rename_input; used by Program
        rewrite passes like the quantize transpiler)."""
        for slot, names in self.inputs.items():
            self.inputs[slot] = [new if n == old else n for n in names]
        self.block.program._version += 1

    def _rename_output(self, old: str, new: str):
        for slot, names in self.outputs.items():
            self.outputs[slot] = [new if n == old else n for n in names]
        self.block.program._version += 1

    def __repr__(self):
        ins = {k: v for k, v in self.inputs.items()}
        outs = {k: v for k, v in self.outputs.items()}
        return "{%s} = %s(%s) attrs=%s" % (outs, self.type, ins, self.attrs)


class Block:
    """An ordered list of ops plus a var symbol table (reference: framework.py:1020)."""

    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = {}
        self.ops: List[Operator] = []

    @property
    def parent_block(self) -> Optional["Block"]:
        if self.parent_idx < 0:
            return None
        return self.program.blocks[self.parent_idx]

    def create_var(self, **kwargs) -> Variable:
        var = Variable(self, **kwargs)
        self.vars[var.name] = var
        return var

    def create_parameter(self, **kwargs) -> Parameter:
        param = Parameter(self, **kwargs)
        self.vars[param.name] = param
        return param

    def var(self, name: str) -> Variable:
        v = self._find_var_recursive(name)
        if v is None:
            raise ValueError("Variable %r not found in block %d" % (name, self.idx))
        return v

    def has_var(self, name: str) -> bool:
        return self._find_var_recursive(name) is not None

    def _find_var_recursive(self, name: str) -> Optional[Variable]:
        blk: Optional[Block] = self
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = blk.parent_block
        return None

    def append_op(self, type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type, inputs=inputs, outputs=outputs, attrs=attrs)
        self.ops.append(op)
        for slot in op.outputs.values():
            for name in slot:
                if name in self.vars:
                    self.vars[name].op = op
        self.program._version += 1
        # calc_gradient sets its output shapes itself; abstractly executing it
        # would eval_shape-retrace the whole forward prefix per call.
        if type not in ("backward_marker", "calc_gradient"):
            from .shape_inference import infer_op_shapes

            infer_op_shapes(op, self)
        return op

    def prepend_op(self, type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type, inputs=inputs, outputs=outputs, attrs=attrs)
        self.ops.insert(0, op)
        self.program._version += 1
        return op

    def insert_op(self, index: int, type: str, inputs=None, outputs=None,
                  attrs=None) -> Operator:
        """Insert an op at position ``index`` (reference: block._insert_op —
        the primitive Program-rewrite passes build on)."""
        op = Operator(self, type, inputs=inputs, outputs=outputs, attrs=attrs)
        self.ops.insert(index, op)
        for slot in op.outputs.values():
            for name in slot:
                if name in self.vars:
                    self.vars[name].op = op
        self.program._version += 1
        from .shape_inference import infer_op_shapes

        infer_op_shapes(op, self)
        return op

    def remove_op(self, index: int):
        del self.ops[index]
        self.program._version += 1

    def all_parameters(self) -> List[Parameter]:
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    def __repr__(self):
        lines = ["Block(idx=%d, parent=%d)" % (self.idx, self.parent_idx)]
        for v in self.vars.values():
            lines.append("  " + repr(v))
        for op in self.ops:
            lines.append("  " + repr(op))
        return "\n".join(lines)


class Program:
    """The model+training-loop graph (reference: framework.py:2284).

    Unlike Fluid there is no protobuf serialization of the graph itself —
    persistence parity is provided at the *state* level (paddle_tpu/io.py)
    and at the *compiled artifact* level (jax.export / StableHLO), which is
    the XLA-native equivalent of saving a ProgramDesc.
    """

    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0)]
        self.current_block_idx = 0
        self._version = 0  # bumped on mutation; executor cache key component
        self._seed = 0
        # Filled by append_backward: {'loss': name, 'param_to_grad': {p: g}}
        self._backward_info: Optional[Dict[str, Any]] = None
        # Optimization metadata (lr scheduler var names etc.)
        self._lr_var_name: Optional[str] = None
        # PyReaders bound to this program's data vars (layers.io.py_reader);
        # the Executor drains one batch per run. Not carried by clone().
        self._py_readers: List[Any] = []

    # -- block management -----------------------------------------------------
    @property
    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[self.current_block_idx]

    def _create_block(self, parent_idx: Optional[int] = None) -> Block:
        parent = self.current_block_idx if parent_idx is None else parent_idx
        blk = Block(self, len(self.blocks), parent)
        self.blocks.append(blk)
        self.current_block_idx = blk.idx
        return blk

    def _rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    @property
    def random_seed(self):
        return self._seed

    @random_seed.setter
    def random_seed(self, seed):
        self._seed = int(seed)
        # seed is baked into compiled steps — invalidate cached specializations
        self._version += 1

    def all_parameters(self) -> List[Parameter]:
        params = []
        for blk in self.blocks:
            params.extend(blk.all_parameters())
        return params

    def list_vars(self):
        for blk in self.blocks:
            for v in blk.vars.values():
                yield v

    def clone(self, for_test: bool = False) -> "Program":
        """Structural copy (reference: Program.clone framework.py:2669).

        ``for_test=True`` flips ``is_test`` attrs (dropout/batch_norm switch to
        inference behavior) and prunes backward/optimize ops.
        """
        p = Program()
        p._seed = self._seed
        p.blocks = []
        for blk in self.blocks:
            nb = Block(p, blk.idx, blk.parent_idx)
            for name, v in blk.vars.items():
                nv = copy.copy(v)
                nv.block = nb
                nb.vars[name] = nv
            for op in blk.ops:
                if for_test and op.type == "backward_marker":
                    # Everything from the marker on (grad clip, regularizer,
                    # optimizer ops) reads @GRAD vars — drop it all.
                    break
                no = Operator(nb, op.type, attrs=copy.deepcopy(op.attrs))
                no.inputs = copy.deepcopy(op.inputs)
                no.outputs = copy.deepcopy(op.outputs)
                if for_test and "is_test" in no.attrs:
                    no.attrs["is_test"] = True
                nb.ops.append(no)
            p.blocks.append(nb)
        if not for_test:
            p._backward_info = copy.deepcopy(self._backward_info)
            p._lr_var_name = self._lr_var_name
        # AMP mode survives cloning — an inference clone of an amp-decorated
        # program must still run its forward in the low-precision dtype.
        p._amp_dtype = getattr(self, "_amp_dtype", None)
        p._version = self._version
        return p

    def to_string(self) -> str:
        return "\n".join(repr(b) for b in self.blocks)

    __str__ = to_string
    __repr__ = to_string


# Op types considered "optimize ops" for clone(for_test=True) pruning.
OPTIMIZER_OP_TYPES = (
    "sgd",
    "momentum",
    "adam",
    "adamw",
    "adamax",
    "adagrad",
    "adadelta",
    "decayed_adagrad",
    "rmsprop",
    "ftrl",
    "lars_momentum",
    "lamb",
)


# -- global default programs (reference: framework.py:3001,3019) --------------
_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


def switch_main_program(program: Program) -> Program:
    global _main_program
    prev, _main_program = _main_program, program
    return prev


def switch_startup_program(program: Program) -> Program:
    global _startup_program
    prev, _startup_program = _startup_program, program
    return prev


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    """Scoped redirection of the default programs (reference: framework.py:3069)."""
    prev_main = switch_main_program(main_program)
    prev_startup = None
    if startup_program is not None:
        prev_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(prev_main)
        if prev_startup is not None:
            switch_startup_program(prev_startup)


_name_scope_stack: List[str] = []


@contextlib.contextmanager
def name_scope(prefix: str):
    """Cosmetic op-name scoping; maps onto jax.named_scope at trace time."""
    _name_scope_stack.append(prefix)
    try:
        yield
    finally:
        _name_scope_stack.pop()


def current_name_scope() -> str:
    return "/".join(_name_scope_stack)


_test_mode = False


@contextlib.contextmanager
def test_mode():
    global _test_mode
    prev, _test_mode = _test_mode, True
    try:
        yield
    finally:
        _test_mode = prev


def in_test_mode() -> bool:
    return _test_mode
