"""RecordIO Python API over the native C++ library
(reference: recordio/ + python recordio_writer.py + reader ops'
create_recordio_file_reader).
"""

from __future__ import annotations

import ctypes
import pickle
from typing import Any, Iterator

from .native import build_and_load

__all__ = ["Writer", "Scanner", "write_records", "read_records",
           "recordio_reader", "RecordIOCorruptError"]


class RecordIOCorruptError(RuntimeError):
    pass


def _lib():
    lib = build_and_load("recordio")
    lib.ptrio_writer_open.restype = ctypes.c_void_p
    lib.ptrio_writer_open.argtypes = [ctypes.c_char_p]
    lib.ptrio_writer_write.restype = ctypes.c_int
    lib.ptrio_writer_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
    lib.ptrio_writer_close.restype = ctypes.c_int
    lib.ptrio_writer_close.argtypes = [ctypes.c_void_p]
    lib.ptrio_scanner_open.restype = ctypes.c_void_p
    lib.ptrio_scanner_open.argtypes = [ctypes.c_char_p]
    lib.ptrio_scanner_next.restype = ctypes.c_void_p
    lib.ptrio_scanner_next.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)]
    lib.ptrio_scanner_close.argtypes = [ctypes.c_void_p]
    return lib


class Writer:
    def __init__(self, path: str):
        self._lib = _lib()
        self._h = self._lib.ptrio_writer_open(path.encode())
        if not self._h:
            raise IOError("cannot open %r for writing" % path)

    def write(self, data: bytes):
        if self._lib.ptrio_writer_write(self._h, data, len(data)) != 0:
            raise IOError("recordio write failed")

    def close(self):
        if self._h:
            if self._lib.ptrio_writer_close(self._h) != 0:
                raise IOError("recordio close/flush failed")
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


class Scanner:
    def __init__(self, path: str):
        self._lib = _lib()
        self._h = self._lib.ptrio_scanner_open(path.encode())
        if not self._h:
            raise IOError("cannot open %r for reading" % path)

    def __iter__(self) -> Iterator[bytes]:
        length = ctypes.c_uint64()
        while True:
            p = self._lib.ptrio_scanner_next(self._h, ctypes.byref(length))
            if not p:
                if length.value == 0xFFFFFFFFFFFFFFFF:
                    raise RecordIOCorruptError("recordio chunk CRC/framing error")
                return
            yield ctypes.string_at(p, length.value)

    def close(self):
        if self._h:
            self._lib.ptrio_scanner_close(self._h)
            self._h = None


def write_records(path: str, examples, serializer=None):
    """Write records. By default records must already be ``bytes`` (the
    reference recordio stores raw byte records); pass
    ``serializer=pickle.dumps`` to store arbitrary objects."""
    if serializer is None:
        serializer = _require_bytes
    with Writer(path) as w:
        n = 0
        for e in examples:
            w.write(serializer(e))
            n += 1
    return n


def _require_bytes(e):
    if not isinstance(e, (bytes, bytearray)):
        raise TypeError(
            "recordio stores bytes; got %s — pass serializer=pickle.dumps "
            "to store arbitrary objects" % type(e).__name__)
    return bytes(e)


def read_records(path: str, deserializer=None):
    """Yield records as raw ``bytes`` by default. Deserializing with pickle
    executes arbitrary code from the file, so it is strictly opt-in
    (``deserializer=pickle.loads``) for files you trust."""
    s = Scanner(path)
    try:
        for rec in s:
            yield deserializer(rec) if deserializer is not None else rec
    finally:
        s.close()


def recordio_reader(path: str, deserializer=None):
    """A reader() factory over a recordio file — plugs into the decorator
    pipeline (batch/shuffle/...) like the reference's recordio reader op.
    Yields raw bytes unless an explicit ``deserializer`` is given (see
    ``read_records`` for the pickle trust caveat)."""

    def reader():
        return read_records(path, deserializer)

    return reader
