"""``fluid.param_attr`` module alias (reference:
python/paddle/fluid/param_attr.py) — the classes live with LayerHelper."""

from .layers.layer_helper import ParamAttr, WeightNormParamAttr  # noqa: F401

__all__ = ["ParamAttr", "WeightNormParamAttr"]
