"""Gradient clipping (reference: python/paddle/fluid/clip.py).

GradientClipByValue (clip.py:120), GradientClipByNorm (:166),
GradientClipByGlobalNorm (:212) — appended as ops on the grad vars after the
backward marker, before optimize ops, exactly like Fluid's
append_gradient_clip_ops (clip.py:336).
"""

from __future__ import annotations

from typing import List, Tuple

__all__ = [
    "ErrorClipByValue",
    "GradientClipByValue",
    "GradientClipByNorm",
    "GradientClipByGlobalNorm",
    "set_gradient_clip",
    "append_gradient_clip_ops",
    "error_clip_callback",
]


class BaseErrorClipAttr:
    pass


class ErrorClipByValue(BaseErrorClipAttr):
    def __init__(self, max, min=None):
        self.max = max
        self.min = min if min is not None else -max


def error_clip_callback(*args, **kwargs):
    pass


class BaseGradientClipAttr:
    def _process_context(self, context, param, grad):
        pass

    def _create_operators(self, param, grad):
        raise NotImplementedError


class NullGradientClipAttr(BaseGradientClipAttr):
    def _create_operators(self, param, grad):
        return param, grad


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def _create_operators(self, param, grad):
        grad.block.append_op("clip", inputs={"X": grad}, outputs={"Out": grad},
                             attrs={"min": self.min, "max": self.max})
        return param, grad


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _create_operators(self, param, grad):
        grad.block.append_op("clip_by_norm", inputs={"X": grad}, outputs={"Out": grad},
                             attrs={"max_norm": self.clip_norm})
        return param, grad


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    """Global-norm clip: scale = clip_norm / max(global_norm, clip_norm)."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _process_context(self, context, param, grad):
        if self.group_name not in context:
            context[self.group_name] = []
            context[self.group_name + "_clip_value"] = self.clip_norm
        elif context[self.group_name + "_clip_value"] != self.clip_norm:
            raise ValueError("All parameters' clip_norm in a group should be equal")
        from .layers.layer_helper import LayerHelper

        helper = LayerHelper("global_norm")
        sq = helper.create_variable_for_type_inference(grad.dtype)
        grad.block.append_op("squared_l2_norm", inputs={"X": grad}, outputs={"Out": sq})
        context[self.group_name].append((param, grad, sq))

    def _create_operators(self, param, grad):
        # handled at group level in append_gradient_clip_ops
        return param, grad


def set_gradient_clip(clip, param_list=None, program=None):
    from .core.framework import default_main_program

    program = program or default_main_program()
    if param_list is None:
        param_list = program.all_parameters()
    param_list = [program.global_block.var(p) if isinstance(p, str) else p for p in param_list]
    for param in param_list:
        param.gradient_clip_attr = clip


def append_gradient_clip_ops(param_grads: List[Tuple]) -> List[Tuple]:
    """reference: clip.py:336."""
    context = {}
    clips = []
    for p, g in param_grads:
        if g is None:
            continue
        clip_attr = getattr(p, "gradient_clip_attr", None) or NullGradientClipAttr()
        clip_attr._process_context(context, p, g)
        clips.append((p, g, clip_attr))

    res = []
    handled_groups = set()
    for p, g, clip_attr in clips:
        if isinstance(clip_attr, GradientClipByGlobalNorm):
            if clip_attr.group_name not in handled_groups:
                _append_global_norm_clip(context, clip_attr.group_name)
                handled_groups.add(clip_attr.group_name)
            res.append((p, g))
        else:
            res.append(clip_attr._create_operators(p, g))
    return res


def _append_global_norm_clip(context, group_name):
    from .layers.layer_helper import LayerHelper

    helper = LayerHelper("global_norm_clip")
    group = context[group_name]
    clip_value = context[group_name + "_clip_value"]
    block = group[0][1].block
    gsum = helper.create_variable_for_type_inference("float32")
    block.append_op("sum", inputs={"X": [sq for _, _, sq in group]}, outputs={"Out": gsum})
    gnorm = helper.create_variable_for_type_inference("float32")
    block.append_op("sqrt", inputs={"X": gsum}, outputs={"Out": gnorm})
    clip_const = helper.create_variable_for_type_inference("float32")
    block.append_op("fill_constant", outputs={"Out": clip_const},
                    attrs={"shape": [1], "dtype": "float32", "value": clip_value})
    denom = helper.create_variable_for_type_inference("float32")
    block.append_op("elementwise_max", inputs={"X": gnorm, "Y": clip_const}, outputs={"Out": denom})
    scale_var = helper.create_variable_for_type_inference("float32")
    block.append_op("elementwise_div", inputs={"X": clip_const, "Y": denom}, outputs={"Out": scale_var})
    for p, g, _ in group:
        block.append_op("elementwise_mul", inputs={"X": g, "Y": scale_var}, outputs={"Out": g})
