"""MultiSlot streaming text readers — AsyncExecutor parity, checkpointable.

The reference framework's production CTR ingestion is AsyncExecutor +
MultiSlotDataFeed (SURVEY L4): text lines of ``<count> <values...>`` per
slot, parsed by trainer threads into padded batches. This module feeds the
SAME on-disk format through :class:`~.reader.CheckpointableReader`, so the
streaming path gains what AsyncExecutor never had: an exactly-once
checkpointable position, typed corrupt-record quarantine, and a bounded
prefetch that composes with ``DevicePrefetcher``.

Two readers:

* :class:`MultiSlotTextReader` — generic slots (``DataFeedDesc`` objects
  or :func:`slot` specs), batching to the framework's padded+``_length``
  convention for sparse slots (byte-identical feeds to
  ``AsyncExecutor.run`` over the same files — tested).
* :class:`CTRMultiSlotReader` — the DeepFM/CTR shape: ``label`` slot +
  one dense slot + ``num_fields`` single-id sparse slots per line,
  yielding ``{"ids": [B, F] int64, "dense": [B, D] float32,
  "label": [B, 1] int64}`` — exactly ``bench.py``'s DeepFM feed, schema
  validated per record (a field slot with 0 or 2 ids is a corrupt record,
  not a crash).

:func:`write_ctr_shards` generates synthetic shards in this format for
benches and drills.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..async_executor import _parse_multislot_line
from .reader import CheckpointableReader, FieldSpec

__all__ = [
    "slot", "MultiSlotTextReader", "CTRMultiSlotReader",
    "ctr_slots", "write_ctr_shards",
]


class _Slot:
    __slots__ = ("name", "type", "is_dense", "is_used", "dense_dim")

    def __init__(self, name, type="uint64", is_dense=False, is_used=True,
                 dense_dim=1):
        self.name = name
        self.type = type
        self.is_dense = is_dense
        self.is_used = is_used
        self.dense_dim = dense_dim


def slot(name: str, type: str = "uint64", is_dense: bool = False,
         is_used: bool = True, dense_dim: int = 1) -> _Slot:
    """A slot spec compatible with ``DataFeedDesc`` slots (same attrs)."""
    return _Slot(name, type, is_dense, is_used, dense_dim)


def _multislot_parse_fn(slots):
    """parse_fn: one MultiSlot line -> {slot name: per-record array} via
    the SAME parser AsyncExecutor uses (byte-format parity by
    construction). Dense slots additionally validate their declared dim."""

    def parse(line: str) -> Dict[str, np.ndarray]:
        vals = _parse_multislot_line(line, slots)
        rec = {}
        for s, v in zip(slots, vals):
            if not s.is_used:
                continue
            if s.is_dense and len(v) != s.dense_dim:
                raise ValueError("dense slot %r has %d values, declared %d"
                                 % (s.name, len(v), s.dense_dim))
            rec[s.name] = (v.astype(np.float32)
                           if s.type.startswith("float") else v)
        return rec

    return parse


def _multislot_collate(slots):
    """AsyncExecutor's batch convention: dense -> [B, dim]; sparse
    (variable length) -> ``<name>`` [B, Lmax] padded with 0 +
    ``<name>_length`` [B] int64."""
    used = [s for s in slots if s.is_used]

    def collate(records: List[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
        feed = {}
        for s in used:
            col = [r[s.name] for r in records]
            if s.is_dense:
                feed[s.name] = np.stack(col).astype(
                    np.float32 if s.type.startswith("float") else np.int64)
            else:
                lens = np.asarray([len(c) for c in col], np.int64)
                lmax = max(1, int(lens.max()))
                padded = np.zeros((len(col), lmax), col[0].dtype)
                for r, c in enumerate(col):
                    padded[r, :len(c)] = c
                feed[s.name] = padded
                feed[s.name + "_length"] = lens
        return feed

    return collate


class MultiSlotTextReader(CheckpointableReader):
    """Checkpointable MultiSlot reader over sharded text files.

    ``slots`` accepts :func:`slot` specs or a ``DataFeedDesc``'s slots
    (same attribute shape). Feeds are batch-identical to
    ``AsyncExecutor.run`` over the same files, plus the exactly-once /
    quarantine machinery of :class:`CheckpointableReader`."""

    def __init__(self, shards: Sequence[str], slots, batch_size: int, **kw):
        slots = list(slots)
        super().__init__(
            shards, _multislot_parse_fn(slots), batch_size,
            collate_fn=_multislot_collate(slots), **kw)
        self.slots = slots


def ctr_slots(num_fields: int = 26, dense_dim: int = 13):
    """The dist_ctr line layout: label, dense, then one sparse slot per
    hashed feature field (each carrying exactly one id)."""
    out = [slot("label", type="uint64"),
           slot("dense", type="float32", is_dense=True, dense_dim=dense_dim)]
    out += [slot("field_%d" % i) for i in range(num_fields)]
    return out


class CTRMultiSlotReader(CheckpointableReader):
    """MultiSlot CTR shards -> the DeepFM bench feed, schema-validated.

    Each line must carry the :func:`ctr_slots` layout; the per-field
    single ids are packed into one ``ids [B, num_fields] int64`` tensor
    (the ``models.deepfm`` contract). A line whose field slot carries 0 or
    >1 ids, a dense slot of the wrong width, an id >= ``vocab`` — all are
    corrupt RECORDS: quarantined and skipped, never a crash."""

    def __init__(self, shards: Sequence[str], batch_size: int,
                 num_fields: int = 26, dense_dim: int = 13,
                 vocab: Optional[int] = None, **kw):
        slots = ctr_slots(num_fields, dense_dim)
        self.num_fields = int(num_fields)
        self.dense_dim = int(dense_dim)
        self.vocab = vocab
        base = _multislot_parse_fn(slots)

        def parse(line: str) -> Dict[str, np.ndarray]:
            rec = base(line)
            ids = np.empty((num_fields,), np.int64)
            for i in range(num_fields):
                v = rec["field_%d" % i]
                if len(v) != 1:
                    raise ValueError("field_%d carries %d ids, expected 1"
                                     % (i, len(v)))
                ids[i] = v[0]
            if vocab is not None and ((ids < 0).any() or
                                      (ids >= vocab).any()):
                raise ValueError("id out of range [0, %d)" % vocab)
            return {"ids": ids,
                    "dense": rec["dense"].astype(np.float32),
                    "label": rec["label"].astype(np.int64)}

        schema = [FieldSpec("ids", (num_fields,), np.int64),
                  FieldSpec("dense", (dense_dim,), np.float32),
                  FieldSpec("label", (1,), np.int64)]
        super().__init__(shards, parse, batch_size, schema=schema, **kw)


def write_ctr_shards(dirname: str, n_records: int, n_shards: int = 2,
                     num_fields: int = 26, dense_dim: int = 13,
                     vocab: int = 1000, seed: int = 0,
                     prefix: str = "ctr") -> List[str]:
    """Synthetic CTR MultiSlot shards for benches/tests/drills; returns
    the shard paths. Deterministic per (seed, geometry)."""
    os.makedirs(dirname, exist_ok=True)
    rng = np.random.RandomState(seed)
    per = (n_records + n_shards - 1) // n_shards
    paths = []
    written = 0
    for si in range(n_shards):
        path = os.path.join(dirname, "%s_%05d.txt" % (prefix, si))
        with open(path, "w") as f:
            for _ in range(min(per, n_records - written)):
                parts = ["1 %d" % rng.randint(0, 2)]
                parts.append("%d %s" % (dense_dim, " ".join(
                    "%.6f" % v for v in rng.rand(dense_dim))))
                for _f in range(num_fields):
                    parts.append("1 %d" % rng.randint(0, vocab))
                f.write(" ".join(parts) + "\n")
                written += 1
        paths.append(path)
    return paths
