"""data/* instruments: the monitor-registry face of the ingestion pipeline.

One module owns every ``data/*`` name so the reader, the prefetch wrapper
and the MultiSlot parser never race a get-or-create, and tools
(``tools/dump_metrics --selftest``) can assert the full set exists by
importing this module alone. Same hot-path contract as the serving
instruments: module-level handles, a single disabled-branch per call.
"""

from __future__ import annotations

from ..monitor import metrics as _mx

__all__ = [
    "RECORDS_READ", "RECORDS_CORRUPT", "RECORDS_SKIPPED",
    "RECORDS_QUARANTINED", "BATCHES", "BYTES_READ", "EPOCHS_COMPLETED",
    "PREFETCH_DEPTH", "PREFETCH_WAIT_MS",
]

RECORDS_READ = _mx.counter(
    "data/records_read", help="records parsed, validated and batched")
RECORDS_CORRUPT = _mx.counter(
    "data/records_corrupt",
    help="records that failed parse/shape/dtype validation (skipped and "
         "quarantined, never trained on)")
RECORDS_SKIPPED = _mx.counter(
    "data/records_skipped",
    help="records skipped because a previous quarantine listed their id "
         "(corrupt records on a later epoch, sentinel-poisoned windows)")
RECORDS_QUARANTINED = _mx.counter(
    "data/records_quarantined",
    help="record ids appended to the quarantine JSONL (validation "
         "failures + divergence-sentinel data windows)")
BATCHES = _mx.counter(
    "data/batches", help="batches yielded by CheckpointableReader")
BYTES_READ = _mx.counter(
    "data/bytes_read", help="raw shard bytes consumed (pre-parse)")
EPOCHS_COMPLETED = _mx.counter(
    "data/epochs_completed", help="full passes over the shard set")
PREFETCH_DEPTH = _mx.gauge(
    "data/prefetch_depth", help="parsed batches buffered ahead of training")
PREFETCH_WAIT_MS = _mx.histogram(
    "data/prefetch_wait_ms",
    help="consumer wait for the next prefetched batch")
