"""CheckpointableReader — deterministic, exactly-once, corruption-tolerant
ingestion over sharded record streams.

The host-side half of self-healing training (reference: the AsyncExecutor
MultiSlot readers of the source framework, SURVEY L4 — re-shaped so the
stream position is *state*, not a side effect):

* **Exactly-once across kill/resume.** The reader's full position —
  epoch, shard index, record index, lifetime counters, quarantined ids —
  is a JSON-serializable :meth:`~CheckpointableReader.state_dict`.
  ``run_supervised`` persists it inside every rotating checkpoint and
  restores it on resume, so the data stream rewinds WITH the model and the
  RNG counter; no caller implements ``feed_source(start_step)`` anymore
  (the legacy contract still works for plain callables).
* **Corrupt records are data, not crashes.** Every record passes typed
  parse/shape/dtype validation; a failure is skipped, appended to a
  quarantine JSONL (record id + reason) and counted (``data/*``). A
  corrupt *rate* above ``max_corrupt_rate`` raises the typed
  :class:`DataCorruptionError` instead of silently starving the trainer.
* **Quarantine is addressable.** Record ids are stable
  (``<shard-basename>#<line>``), so the divergence sentinel can quarantine
  the exact data window that preceded a loss blow-up and the reader will
  skip those records on every subsequent pass.
* **Backpressured prefetch.** :meth:`~CheckpointableReader.prefetch`
  parses ahead on a bounded queue without giving up checkpointability;
  its output composes with :class:`~paddle_tpu.reader.DevicePrefetcher`
  for the host→HBM overlap.

Restore cost note: positions are record-indexed (not byte offsets), so
``load_state_dict`` re-reads and discards ``record`` lines of the current
shard — O(position within one shard), never O(stream).
"""

from __future__ import annotations

import json
import os
from collections import deque
from typing import (Any, Callable, Dict, Iterator, List, Optional, Sequence,
                    Tuple)

import numpy as np

from . import metrics as _dm

__all__ = [
    "FieldSpec", "RecordError", "DataCorruptionError",
    "CheckpointableReader", "PrefetchReader",
]

STATE_VERSION = 1


class RecordError(ValueError):
    """One record failed parse/shape/dtype validation. Carries the stable
    record id and the reason that lands in the quarantine JSONL."""

    def __init__(self, record_id: str, reason: str):
        super().__init__("record %s: %s" % (record_id, reason))
        self.record_id = record_id
        self.reason = reason


class DataCorruptionError(RuntimeError):
    """The stream's corrupt-record rate exceeded the configured bound —
    the data source itself is broken (truncated upload, format drift),
    and training on the survivors would be silent garbage. Typed so the
    supervisor's classify() treats it as fatal, never retried."""


class FieldSpec:
    """Declarative per-record validation for one feed field: ``shape`` is
    the PER-RECORD shape (batching adds the leading axis); ``None`` dims
    are wildcards (variable-length slots)."""

    __slots__ = ("name", "shape", "dtype")

    def __init__(self, name: str, shape: Sequence[Optional[int]], dtype):
        self.name = name
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)

    def validate(self, record_id: str, value) -> np.ndarray:
        arr = np.asarray(value)
        if arr.dtype != self.dtype:
            raise RecordError(record_id, "field %r dtype %s != declared %s"
                              % (self.name, arr.dtype, self.dtype))
        if len(arr.shape) != len(self.shape) or any(
                d is not None and d != a
                for d, a in zip(self.shape, arr.shape)):
            raise RecordError(record_id, "field %r shape %s != declared %s"
                              % (self.name, arr.shape, self.shape))
        return arr

    def __repr__(self):
        return "FieldSpec(%r, %r, %s)" % (self.name, self.shape, self.dtype)


def _stack_collate(records: List[Dict[str, np.ndarray]]
                   ) -> Dict[str, np.ndarray]:
    """Default collation: stack each field on a new leading batch axis
    (fixed per-record shapes; MultiSlot's padded+length collation handles
    the variable-length case)."""
    return {name: np.stack([r[name] for r in records])
            for name in records[0]}


class CheckpointableReader:
    """Iterate batches (feed dicts) over sharded line-record files with a
    fully serializable position.

    ``shards``: ordered file paths (one record per line; blank lines are
    skipped). ``parse_fn(line) -> dict[str, array-like]`` produces one
    record; any exception it raises marks the record corrupt. ``schema``
    (a list of :class:`FieldSpec`) adds typed shape/dtype validation.
    ``epochs=None`` cycles forever. A yielded batch is ``batch_size``
    records collated by ``collate_fn`` (default: ``np.stack`` per field);
    a final partial batch is dropped unless ``drop_remainder=False``.
    """

    def __init__(self, shards: Sequence[str],
                 parse_fn: Callable[[str], Dict[str, Any]],
                 batch_size: int,
                 schema: Optional[Sequence[FieldSpec]] = None,
                 epochs: Optional[int] = 1,
                 collate_fn: Optional[Callable[[List[Dict[str, np.ndarray]]],
                                               Dict[str, np.ndarray]]] = None,
                 quarantine_path: Optional[str] = None,
                 max_corrupt_rate: float = 0.01,
                 corrupt_check_min: int = 100,
                 drop_remainder: bool = True,
                 id_history: int = 64):
        if not shards:
            raise ValueError("CheckpointableReader needs at least one shard")
        if batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        self.shards = [str(s) for s in shards]
        names = [os.path.basename(s) for s in self.shards]
        if len(set(names)) != len(names):
            # record ids are <basename>#<line>; colliding basenames would
            # alias quarantine entries across shards
            raise ValueError("shard basenames must be unique: %r" % names)
        self.parse_fn = parse_fn
        self.batch_size = int(batch_size)
        self.schema = list(schema) if schema else None
        self.epochs = epochs if epochs is None else int(epochs)
        self.collate_fn = collate_fn if collate_fn is not None \
            else _stack_collate
        self.quarantine_path = quarantine_path
        self.max_corrupt_rate = float(max_corrupt_rate)
        self.corrupt_check_min = int(corrupt_check_min)
        self.drop_remainder = bool(drop_remainder)
        # -- position (everything state_dict carries) --
        self._epoch = 0
        self._shard = 0          # index into self.shards
        self._record = 0         # next line index within the current shard
        self._records_read = 0
        self._records_corrupt = 0
        self._records_skipped = 0
        self._batches = 0
        self._skip_ids: set = set()
        # -- transient --
        self._fh = None
        self._exhausted = False
        self._ids_history: deque = deque(maxlen=max(1, int(id_history)))

    # -- record ids -----------------------------------------------------------
    def _rid(self, shard_idx: int, line_idx: int) -> str:
        return "%s#%d" % (os.path.basename(self.shards[shard_idx]), line_idx)

    # -- quarantine -----------------------------------------------------------
    def quarantine(self, ids: Sequence[str], reason: str) -> None:
        """Append ``ids`` to the quarantine JSONL (one ``{"id", "reason"}``
        row each) and add them to the skip set, so every later pass —
        including a sentinel rollback replay — drops them. Public: the
        divergence sentinel quarantines whole data windows through this."""
        ids = list(ids)
        if not ids:
            return
        self._skip_ids.update(ids)
        _dm.RECORDS_QUARANTINED.inc(len(ids))
        if self.quarantine_path:
            with open(self.quarantine_path, "a") as f:
                for rid in ids:
                    f.write(json.dumps({"id": rid, "reason": reason}) + "\n")

    def quarantined_ids(self) -> List[str]:
        return sorted(self._skip_ids)

    def _quarantine_corrupt(self, err: RecordError) -> None:
        self._records_corrupt += 1
        _dm.RECORDS_CORRUPT.inc()
        self.quarantine([err.record_id], err.reason)
        seen = self._records_read + self._records_corrupt
        if seen >= self.corrupt_check_min and \
                self._records_corrupt > self.max_corrupt_rate * seen:
            raise DataCorruptionError(
                "corrupt-record rate %.4f (%d of %d) exceeds the %.4f "
                "bound — refusing to train on the survivors (last: %s)"
                % (self._records_corrupt / seen, self._records_corrupt,
                   seen, self.max_corrupt_rate, err)) from err

    # -- raw line stream ------------------------------------------------------
    def _open_current(self):
        if self._fh is None:
            self._fh = open(self.shards[self._shard], "r")
            for _ in range(self._record):  # record-indexed restore
                self._fh.readline()
        return self._fh

    def _next_line(self) -> Optional[Tuple[str, str]]:
        """(record_id, line) of the next non-blank line, advancing the
        position; None when the configured epochs are exhausted."""
        while not self._exhausted:
            fh = self._open_current()
            line = fh.readline()
            if line:
                rid = self._rid(self._shard, self._record)
                self._record += 1
                _dm.BYTES_READ.inc(len(line))
                if not line.strip():
                    continue
                return rid, line.rstrip("\n")
            # shard exhausted
            fh.close()
            self._fh = None
            self._record = 0
            self._shard += 1
            if self._shard >= len(self.shards):
                self._shard = 0
                self._epoch += 1
                _dm.EPOCHS_COMPLETED.inc()
                if self.epochs is not None and self._epoch >= self.epochs:
                    self._exhausted = True
        return None

    # -- records --------------------------------------------------------------
    def _parse_validate(self, rid: str, line: str) -> Dict[str, np.ndarray]:
        try:
            rec = self.parse_fn(line)
        except Exception as e:
            raise RecordError(rid, "parse: %s: %s" % (type(e).__name__, e))
        if not isinstance(rec, dict) or not rec:
            raise RecordError(rid, "parse_fn returned %r, not a non-empty "
                                   "field dict" % type(rec).__name__)
        if self.schema is not None:
            out = {}
            for spec in self.schema:
                if spec.name not in rec:
                    raise RecordError(rid, "missing field %r" % spec.name)
                out[spec.name] = spec.validate(rid, rec[spec.name])
            return out
        return {k: np.asarray(v) for k, v in rec.items()}

    def _next_record(self) -> Optional[Tuple[str, Dict[str, np.ndarray]]]:
        while True:
            nxt = self._next_line()
            if nxt is None:
                return None
            rid, line = nxt
            if rid in self._skip_ids:
                self._records_skipped += 1
                _dm.RECORDS_SKIPPED.inc()
                continue
            try:
                rec = self._parse_validate(rid, line)
            except RecordError as e:
                self._quarantine_corrupt(e)  # may raise DataCorruptionError
                continue
            self._records_read += 1
            _dm.RECORDS_READ.inc()
            return rid, rec

    # -- iteration ------------------------------------------------------------
    def __iter__(self) -> "CheckpointableReader":
        return self

    def __next__(self) -> Dict[str, np.ndarray]:
        records: List[Dict[str, np.ndarray]] = []
        ids: List[str] = []
        while len(records) < self.batch_size:
            nxt = self._next_record()
            if nxt is None:
                if records and not self.drop_remainder:
                    break
                raise StopIteration
            rid, rec = nxt
            ids.append(rid)
            records.append(rec)
        self._batches += 1
        _dm.BATCHES.inc()
        self._ids_history.append(ids)
        return self.collate_fn(records)

    def last_batch_ids(self, n: int = 1) -> List[List[str]]:
        """Record ids of the last ``n`` yielded batches, oldest first —
        the sentinel's handle on "the data window that preceded the trip"
        (bounded by ``id_history``)."""
        hist = list(self._ids_history)
        return hist[-n:] if n > 0 else []

    # -- checkpointable position ----------------------------------------------
    def state_dict(self) -> dict:
        """The FULL position after the last yielded batch, JSON-ready —
        what ``run_supervised`` folds into every rotating checkpoint."""
        return {
            "version": STATE_VERSION,
            "shards": [os.path.basename(s) for s in self.shards],
            "epoch": self._epoch,
            "shard": self._shard,
            "record": self._record,
            "records_read": self._records_read,
            "records_corrupt": self._records_corrupt,
            "records_skipped": self._records_skipped,
            "batches": self._batches,
            "skip_ids": sorted(self._skip_ids),
        }

    def load_state_dict(self, state: dict) -> None:
        """Restore an exact stream position (same shard set). The open file
        handle, lookahead and id history are reset; reading resumes at the
        recorded record index."""
        if state.get("version") != STATE_VERSION:
            raise ValueError("reader state version %r != %d"
                             % (state.get("version"), STATE_VERSION))
        names = [os.path.basename(s) for s in self.shards]
        if state.get("shards") != names:
            raise ValueError(
                "reader state was taken over shards %r, this reader has %r "
                "— resuming would consume different records"
                % (state.get("shards"), names))
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        self._epoch = int(state["epoch"])
        self._shard = int(state["shard"])
        self._record = int(state["record"])
        self._records_read = int(state["records_read"])
        self._records_corrupt = int(state["records_corrupt"])
        self._records_skipped = int(state["records_skipped"])
        self._batches = int(state["batches"])
        self._skip_ids = set(state.get("skip_ids", ()))
        self._exhausted = (self.epochs is not None
                           and self._epoch >= self.epochs)
        self._ids_history.clear()

    # -- stats / prefetch -----------------------------------------------------
    @property
    def records_read(self) -> int:
        return self._records_read

    @property
    def records_corrupt(self) -> int:
        return self._records_corrupt

    def prefetch(self, capacity: int = 4) -> "PrefetchReader":
        """Parse ahead on a bounded background queue (backpressure: the
        worker blocks when ``capacity`` batches are ready). The wrapper
        stays checkpointable — its ``state_dict`` is the position of the
        last batch the CONSUMER saw, not whatever the worker read ahead —
        and composes with ``DevicePrefetcher`` for the H2D overlap::

            feed = DevicePrefetcher(reader.prefetch(4), capacity=2)
        """
        return PrefetchReader(self, capacity)


class PrefetchReader:
    """Bounded parse-ahead over a :class:`CheckpointableReader` that
    PRESERVES the checkpoint contract: every queued batch rides with the
    reader state *after* it was produced, so ``state_dict()`` reflects
    exactly the batches the consumer has been handed. ``quarantine`` and
    ``load_state_dict`` rewind the overread (worker stopped, queue dropped,
    inner reader restored) before acting, so sentinel rollback works the
    same with or without prefetch."""

    _END = object()

    def __init__(self, reader: CheckpointableReader, capacity: int = 4):
        import queue as _q
        import threading as _t

        self.reader = reader
        self._capacity = max(1, int(capacity))
        self._queue_mod = _q
        self._thread_mod = _t
        self._q = _q.Queue(maxsize=self._capacity)
        self._thread = None
        self._stop = _t.Event()
        self._err: Optional[BaseException] = None
        self._last_state = reader.state_dict()
        self._ids_history: deque = deque(maxlen=reader._ids_history.maxlen)

    # -- worker ---------------------------------------------------------------
    def _worker(self):
        try:
            while not self._stop.is_set():
                try:
                    batch = next(self.reader)
                except StopIteration:
                    break
                item = (batch, self.reader.state_dict(),
                        self.reader.last_batch_ids(1)[0])
                while not self._stop.is_set():
                    try:
                        self._q.put(item, timeout=0.05)
                        break
                    except self._queue_mod.Full:
                        continue
        except BaseException as e:  # DataCorruptionError et al: re-raised
            self._err = e           # in the consumer with its traceback
        finally:
            while not self._stop.is_set():
                try:
                    self._q.put(self._END, timeout=0.05)
                    break
                except self._queue_mod.Full:
                    continue

    def _ensure_started(self):
        if self._thread is None:
            self._stop.clear()
            self._thread = self._thread_mod.Thread(
                target=self._worker, daemon=True)
            self._thread.start()

    def _halt(self):
        """Stop the worker and drop its read-ahead (consumer-side state is
        authoritative; the dropped batches are re-read after restore)."""
        if self._thread is None:
            return
        self._stop.set()
        while True:
            try:
                self._q.get_nowait()
            except self._queue_mod.Empty:
                break
        self._thread.join(timeout=30.0)
        if self._thread.is_alive():
            # refuse to touch the inner reader under a live worker: a
            # restore racing a stuck parse would corrupt the position
            raise RuntimeError(
                "PrefetchReader: worker did not stop within 30s (parse_fn "
                "or shard read stuck?) — cannot safely restore/quarantine")
        self._thread = None
        self._q = self._queue_mod.Queue(maxsize=self._capacity)

    # -- iteration ------------------------------------------------------------
    def __iter__(self) -> "PrefetchReader":
        return self

    def __next__(self) -> Dict[str, np.ndarray]:
        import time as _time

        self._ensure_started()
        from ..monitor import metrics as _mx

        if _mx.enabled():
            _dm.PREFETCH_DEPTH.set(self._q.qsize())
            t0 = _time.perf_counter()
            item = self._q.get()
            _dm.PREFETCH_WAIT_MS.observe((_time.perf_counter() - t0) * 1e3)
        else:
            item = self._q.get()
        if item is self._END:
            self._thread = None
            if self._err is not None:
                err, self._err = self._err, None
                raise err
            raise StopIteration
        batch, state, ids = item
        self._last_state = state
        self._ids_history.append(ids)
        return batch

    # -- checkpointable contract ----------------------------------------------
    def state_dict(self) -> dict:
        return self._last_state

    def load_state_dict(self, state: dict) -> None:
        self._halt()
        self.reader.load_state_dict(state)
        self._last_state = self.reader.state_dict()
        self._ids_history.clear()

    def last_batch_ids(self, n: int = 1) -> List[List[str]]:
        hist = list(self._ids_history)
        return hist[-n:] if n > 0 else []

    def quarantine(self, ids: Sequence[str], reason: str) -> None:
        # rewind the overread first: quarantined records the worker already
        # parsed past must be re-read (and now skipped) after restore
        self._halt()
        self.reader.load_state_dict(self._last_state)
        self.reader.quarantine(ids, reason)
        self._last_state = self.reader.state_dict()

    def quarantined_ids(self) -> List[str]:
        return self.reader.quarantined_ids()

    def stop(self) -> None:
        """Release the worker thread (idempotent; context-manager exit)."""
        self._halt()

    def __enter__(self) -> "PrefetchReader":
        self._ensure_started()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
