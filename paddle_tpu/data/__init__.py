"""paddle_tpu.data — exactly-once, corruption-tolerant ingestion.

The host-side half of self-healing training (ROADMAP item 5's streaming
ingestion, re-grounded in the reference's AsyncExecutor MultiSlot readers):

* :class:`~.reader.CheckpointableReader` — deterministic batches over
  sharded line-record files whose FULL position (epoch/shard/record,
  counters, quarantined ids) is a JSON ``state_dict``. ``run_supervised``
  persists it in every rotating checkpoint and restores it on resume:
  exactly-once consumption across kill/resume with zero caller-side
  bookkeeping (the legacy ``feed_source(start_step)`` callable contract
  still works).
* Corrupt records (typed parse/shape/dtype validation) are skipped and
  appended to a quarantine JSONL with id + reason; a corrupt rate above
  the bound raises :class:`~.reader.DataCorruptionError` instead of
  silently starving training. The divergence sentinel
  (:mod:`paddle_tpu.reliability.sentinel`) quarantines whole data windows
  through the same :meth:`~.reader.CheckpointableReader.quarantine`.
* :class:`~.multislot.MultiSlotTextReader` /
  :class:`~.multislot.CTRMultiSlotReader` — the AsyncExecutor MultiSlot
  text format, streamed checkpointably into the DeepFM/CTR bench feed.
* :meth:`~.reader.CheckpointableReader.prefetch` — bounded parse-ahead
  that keeps the checkpoint contract and composes with
  :class:`~paddle_tpu.reader.DevicePrefetcher` for the H2D overlap.

Counters: ``data/*`` (:mod:`~.metrics`), exported continuously by the
telemetry layer like every other registry family.
"""

from . import metrics  # noqa: F401  (registers the data/* instruments)
from .multislot import (  # noqa: F401
    CTRMultiSlotReader, MultiSlotTextReader, ctr_slots, slot,
    write_ctr_shards,
)
from .reader import (  # noqa: F401
    CheckpointableReader, DataCorruptionError, FieldSpec, PrefetchReader,
    RecordError,
)

__all__ = [
    "CheckpointableReader", "PrefetchReader", "FieldSpec",
    "RecordError", "DataCorruptionError",
    "MultiSlotTextReader", "CTRMultiSlotReader", "ctr_slots", "slot",
    "write_ctr_shards", "metrics",
]
