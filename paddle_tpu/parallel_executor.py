"""Legacy ParallelExecutor API (reference:
python/paddle/fluid/parallel_executor.py — a deprecated wrapper the
reference itself routes to CompiledProgram + Executor; scripts that
instantiate it directly must keep running).

The TPU mapping is the same one CompiledProgram makes: a data-axis mesh over
the local devices with GSPMD inserting the gradient psum (the role NCCL
AllReduce op handles played, ``details/all_reduce_op_handle.cc:55``).
"""

from __future__ import annotations

import warnings
from typing import Optional

from .compiler import BuildStrategy, CompiledProgram, ExecutionStrategy
from .core.framework import default_main_program
from .core.place import CPUPlace, TPUPlace
from .core.scope import global_scope
from .executor import Executor
from .monitor import metrics as _mx, tracer as _tr

__all__ = ["ParallelExecutor"]

_m_runs = _mx.counter("parallel_executor/runs",
                      help="ParallelExecutor.run invocations (legacy wrapper)")


class ParallelExecutor:
    def __init__(self, use_cuda, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None, build_strategy=None,
                 num_trainers=1, trainer_id=0, scope=None):
        warnings.warn(
            "ParallelExecutor is deprecated. Please use CompiledProgram and "
            "Executor (compiler.py).", DeprecationWarning, stacklevel=2)
        build_strategy = build_strategy or BuildStrategy()
        build_strategy.num_trainers = num_trainers
        build_strategy.trainer_id = trainer_id
        self._program = main_program or default_main_program()
        self._scope = scope or global_scope()
        self._places = [TPUPlace(0)] if use_cuda else [CPUPlace()]
        self._compiled = CompiledProgram(self._program).with_data_parallel(
            loss_name=loss_name,
            build_strategy=build_strategy,
            exec_strategy=exec_strategy or ExecutionStrategy(),
            share_vars_from=getattr(share_vars_from, "_compiled", share_vars_from),
        )
        self._exe = Executor(self._places[0])

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        """reference: parallel_executor.py:123 (feed_dict is the deprecated
        alias feed wins over)."""
        if feed is None:
            feed = feed_dict
        _m_runs.inc()
        if _tr._active:
            with _tr.span("parallel_executor/run", cat="executor"):
                return self._exe.run(self._compiled, feed=feed,
                                     fetch_list=fetch_list, scope=self._scope,
                                     return_numpy=return_numpy)
        return self._exe.run(self._compiled, feed=feed, fetch_list=fetch_list,
                             scope=self._scope, return_numpy=return_numpy)

    @property
    def device_count(self) -> int:
        import jax

        return len(jax.devices())
