"""Parameter initializers (reference: python/paddle/fluid/initializer.py).

Each initializer appends an init op to the *startup program* block holding the
parameter — same two-program structure as Fluid (default_startup_program runs
once, default_main_program runs per step).
"""

from __future__ import annotations

import contextlib
import math

import numpy as np

from .core import framework

__all__ = [
    "Constant",
    "Uniform",
    "Normal",
    "TruncatedNormal",
    "Xavier",
    "MSRA",
    "Bilinear",
    "NumpyArrayInitializer",
    "ConstantInitializer",
    "UniformInitializer",
    "NormalInitializer",
    "XavierInitializer",
    "MSRAInitializer",
]


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError

    @staticmethod
    def _fan_in_fan_out(var):
        shape = var.shape
        if len(shape) < 2:
            return (shape[0] if shape else 1,) * 2
        receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
        fan_in = shape[1] * receptive if len(shape) > 2 else shape[0]
        fan_out = shape[0] * receptive if len(shape) > 2 else shape[1]
        return fan_in, fan_out


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, var, block):
        return block.append_op(
            "fill_constant",
            outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": var.dtype, "value": float(self.value)},
        )


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        return block.append_op(
            "uniform_random",
            outputs={"Out": var},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "min": float(self.low),
                "max": float(self.high),
                "seed": self.seed,
            },
        )


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            "gaussian_random",
            outputs={"Out": var},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "mean": float(self.loc),
                "std": float(self.scale),
                "seed": self.seed,
            },
        )


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            "truncated_gaussian_random",
            outputs={"Out": var},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "mean": float(self.loc),
                "std": float(self.scale),
                "seed": self.seed,
            },
        )


class XavierInitializer(Initializer):
    """Glorot init (reference: initializer.py XavierInitializer)."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out, self.seed = uniform, fan_in, fan_out, seed

    def __call__(self, var, block):
        fi, fo = self._fan_in_fan_out(var)
        fan_in = self.fan_in if self.fan_in is not None else fi
        fan_out = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fan_in + fan_out))
            return UniformInitializer(-limit, limit, self.seed)(var, block)
        std = math.sqrt(2.0 / (fan_in + fan_out))
        return NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    """He/Kaiming init (reference: initializer.py MSRAInitializer)."""

    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fi, _ = self._fan_in_fan_out(var)
        fan_in = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = math.sqrt(6.0 / fan_in)
            return UniformInitializer(-limit, limit, self.seed)(var, block)
        std = math.sqrt(2.0 / fan_in)
        return NormalInitializer(0.0, std, self.seed)(var, block)


class BilinearInitializer(Initializer):
    """For conv_transpose upsampling weights."""

    def __call__(self, var, block):
        shape = var.shape
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        weight = np.zeros(shape, dtype="float32")
        size = int(np.prod(shape))
        vals = np.zeros(size)
        for i in range(size):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            vals[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        weight = vals.reshape(shape)
        return NumpyArrayInitializer(weight)(var, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        return block.append_op(
            "assign_value",
            outputs={"Out": var},
            attrs={
                "shape": list(self.value.shape),
                "dtype": var.dtype,
                "values": self.value.reshape(-1).tolist(),
            },
        )


# Fluid-style aliases
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer


def _global_weight_initializer():
    return XavierInitializer()


def _global_bias_initializer():
    return ConstantInitializer(0.0)


_force_init_on_cpu_flag = False


def force_init_on_cpu():
    """reference: initializer.py force_init_on_cpu — query the init-on-cpu
    flag. Under XLA, initializers run inside the compiled startup program on
    the device; the flag is tracked for API parity only."""
    return _force_init_on_cpu_flag


@contextlib.contextmanager
def init_on_cpu():
    """reference: initializer.py init_on_cpu context. No device switch is
    needed on TPU (XLA places initialization), so this only toggles the
    queryable flag."""
    global _force_init_on_cpu_flag
    prev, _force_init_on_cpu_flag = _force_init_on_cpu_flag, True
    try:
        yield
    finally:
        _force_init_on_cpu_flag = prev


__all__ += ["force_init_on_cpu", "init_on_cpu"]
