"""Executor: traces a Program into one jit-compiled XLA step.

Fluid's ``Executor::Run`` (reference: ``framework/executor.cc:186,398``)
interprets ops one by one against a Scope, paying per-op dispatch +
InferShape + kernel-lookup every step. Here the op loop runs ONCE, at trace
time, inside ``jax.jit``: every op impl is a pure JAX function over a
name→array environment, so the whole step — forward, jax.grad backward,
optimizer updates — compiles to a single fused XLA executable. State
(persistable vars) is threaded functionally with buffer donation, giving
in-place param updates in HBM.

Feed/fetch semantics, the program cache (keyed like Fluid's
``executor.py:224,310`` cache plus feed shapes for XLA's static-shape
requirement), and scope handling mirror ``python/paddle/fluid/executor.py``.
"""

from __future__ import annotations

import contextlib
import functools
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import ops as _ops  # noqa: F401 — registers all op impls
from .core.dtypes import to_jnp_dtype
from .core.framework import (Program, Variable, default_main_program,
                             grad_var_name, in_test_mode)
from .flags import flags as _flags
from .core.interpreter import run_block_ops
from .core.place import Place, get_device
from .core.registry import OpContext, get_op_impl
from .core.scope import Scope, global_scope
from .monitor import GRAD_NORM_VAR, metrics as _mx, tracer as _tr

__all__ = ["Executor", "TraceContext"]

# Instruments are module-level handles: looked up once, so the per-run cost
# with metrics ON is a few lock+add ops, and with metrics OFF a single
# branch inside each instrument call (no lock, no allocation) — the
# acceptance bar for the hot path.
_m_runs = _mx.counter("executor/runs", help="Executor.run invocations")
_m_cache_hit = _mx.counter("executor/cache_hit",
                           help="program-cache hits (reused _CompiledStep)")
_m_cache_miss = _mx.counter("executor/cache_miss",
                            help="program-cache misses (new specialization)")
_m_step_ms = _mx.histogram("executor/step_time_ms",
                           help="wall time of one cached step dispatch")
_m_compile_ms = _mx.histogram(
    "executor/compile_time_ms",
    help="trace+XLA-compile wall time of a cache-miss first step")
_m_trace_ms = _mx.histogram(
    "executor/trace_setup_ms",
    help="host time to build a _CompiledStep specialization")
_m_feed_bytes = _mx.counter("executor/feed_bytes",
                            help="bytes handed to the step as feeds")
_m_fetch_bytes = _mx.counter("executor/fetch_bytes",
                             help="bytes fetched back to host")
_m_hbm_used = _mx.gauge("device/hbm_bytes_in_use",
                        help="memory_stats bytes_in_use, summed over devices")
_m_hbm_limit = _mx.gauge("device/hbm_bytes_limit",
                         help="memory_stats bytes_limit, summed over devices")
_m_grad_norm = _mx.gauge("optimizer/grad_global_norm",
                         help="pre-clip global grad norm (PADDLE_TPU_GRAD_NORM=1)")

_mem_stats_ok: Optional[bool] = None  # None = not probed yet
_HBM_SAMPLE_EVERY = 32  # sample memory_stats on miss + every Nth run


_mem_devices = None  # cached jax.local_devices() once the probe succeeds


def _update_hbm_gauges() -> None:
    """Refresh HBM gauges from device memory_stats(); probes capability once
    (CPU backends may not implement it) and then never raises per step."""
    global _mem_stats_ok, _mem_devices
    if _mem_stats_ok is False:
        return
    try:
        if _mem_devices is None:
            _mem_devices = jax.local_devices()
        used = limit = 0
        got = False
        for d in _mem_devices:
            stats = d.memory_stats()
            if not stats:
                continue
            got = True
            used += stats.get("bytes_in_use", 0)
            limit += stats.get("bytes_limit", 0)
        if not got:
            _mem_stats_ok = False
            return
        _mem_stats_ok = True
        _m_hbm_used.set(used)
        if limit:
            _m_hbm_limit.set(limit)
    except Exception:
        _mem_stats_ok = False


_NULL_CTX = contextlib.nullcontext()


def _nbytes(arrays) -> int:
    total = 0
    for a in arrays:
        nb = getattr(a, "nbytes", None)
        if nb is None:
            nb = np.asarray(a).nbytes
        total += nb
    return total

_UserCompiledProgram = None  # lazily bound CompiledProgram class (import cycle)


class TraceContext:
    """Per-trace state: RNG derivation, test mode, mesh, current op position."""

    def __init__(self, program: Program, is_test: bool, base_rng, mesh=None):
        self.program = program
        self.is_test = is_test
        self.base_rng = base_rng
        self.mesh = mesh
        self.current_op_idx = 0
        self._key_table = None
        self._n_ops = 0

    def op_rng(self, ctx: OpContext):
        seed = ctx.attr("seed", 0) or self.program.random_seed
        if seed:
            # explicit per-op seed: a constant key XLA constant-folds
            return jax.random.fold_in(jax.random.PRNGKey(seed),
                                      self.current_op_idx)
        # Derive the main-block per-op keys with one batched split instead of
        # a scalar fold_in per RNG-consuming op: each scalar fold_in is ~113
        # unfusable scalar u32 entry instructions (a full threefry chain),
        # and a BERT step with ~50 dropout sites carried ~5,700 of them —
        # the batched table is one vectorized threefry plus slices that fuse
        # into the consumers (benchmarks/diag_bert_kernels.py).
        # Sub-block ops (while/cond bodies) run at offset 10_000*block_idx
        # (ops/control_flow_ops.py) — far past the table, where JAX's static
        # indexing would silently CLAMP to the last row and hand every such
        # op the same key — so anything past the table keeps the scalar
        # fold_in (distinct key per index; those ops trace once inside the
        # loop body, so the scalar chains stay rare).
        idx = self.current_op_idx
        if self._key_table is None:
            self._n_ops = len(self.program.global_block.ops) + 8
            self._key_table = jax.random.split(self.base_rng, self._n_ops)
        if idx < self._n_ops:
            return self._key_table[idx]
        return jax.random.fold_in(self.base_rng, idx)


def _canon(value, dtype_name: str):
    target = to_jnp_dtype(dtype_name)
    canonical = jax.dtypes.canonicalize_dtype(target)
    if isinstance(value, jax.Array):
        # already on device (e.g. via DevicePrefetcher) — never round-trip to host
        return value if value.dtype == canonical else value.astype(canonical)
    arr = np.asarray(value)
    if arr.dtype != canonical:
        arr = arr.astype(canonical)
    return arr


class _CompiledStep:
    """A specialization of (program, feed sig, fetch list, state names).

    With a mesh: state replicated, feeds sharded on the ``data`` axis —
    XLA/GSPMD inserts the gradient psum over ICI (the TPU-native
    ParallelExecutor+NCCL path, SURVEY.md §7).
    """

    def __init__(self, program: Program, feed_names: Tuple[str, ...],
                 fetch_names: Tuple[str, ...], state_names: Tuple[str, ...],
                 is_test: bool, jit: bool = True, mesh=None,
                 accumulation_steps: int = 1):
        self.program = program
        self.feed_names = feed_names
        self.fetch_names = fetch_names
        self.state_names = state_names
        self.is_test = is_test
        self.mesh = mesh

        bw = program._backward_info
        block = program.global_block
        ops = block.ops
        marker_idx = None
        if bw is not None:
            for i, op in enumerate(ops):
                if op.type == "backward_marker":
                    marker_idx = i
                    break
        accum = max(1, int(accumulation_steps)) if marker_idx is not None else 1

        # AMP: run the forward in bf16/fp16 against fp32 master weights
        # (the TPU-native float16.h story; enabled via paddle_tpu.amp).
        amp_dtype = getattr(program, "_amp_dtype", None)
        if amp_dtype is not None:
            amp_dtype = to_jnp_dtype(amp_dtype)

        def _amp_cast_tree(d):
            if amp_dtype is None:
                return d
            return {
                k: (v.astype(amp_dtype)
                    if hasattr(v, "dtype") and v.dtype == jnp.float32 else v)
                for k, v in d.items()
            }

        seed_const = program.random_seed or 0
        self._out_state_sh = None  # set below when jit+mesh; guards jit=False

        def step(state, feeds, step_idx):
            # key derivation is part of the compiled step (fused, zero host
            # cost per run); step_idx is the only changing input
            rng_key = jax.random.fold_in(jax.random.PRNGKey(seed_const), step_idx)
            trace = TraceContext(program, is_test, rng_key, mesh=mesh)
            if bw is None or marker_idx is None:
                env = dict(state)
                env.update(feeds)
                if amp_dtype is not None:
                    # Cast a COPY of the env for the forward; the fp32 master
                    # state must survive an eval/fetch run un-degraded. Only
                    # vars an op actually rewrote (tracer identity changed)
                    # flow back, cast to their original dtype.
                    env = _amp_cast_tree(env)
                    before = dict(env)  # hold refs so identity compare is sound
                    run_block_ops(ops, env, trace)
                    for k in list(env):
                        if k not in state:
                            continue
                        v = env[k]
                        if before.get(k) is v:
                            env[k] = state[k]
                        elif (hasattr(v, "dtype") and hasattr(state[k], "dtype")
                              and v.dtype != state[k].dtype):
                            env[k] = v.astype(state[k].dtype)
                else:
                    run_block_ops(ops, env, trace)
            else:
                loss_name = bw["loss"]
                param_to_grad = bw["param_to_grad"]
                all_param_names = [p for p in param_to_grad if p in state]
                block0 = program.global_block
                sparse_names = [
                    p for p in all_param_names
                    if getattr(block0._find_var_recursive(p), "is_sparse_param", False)
                ]
                param_names = [p for p in all_param_names if p not in sparse_names]
                params = {n: state[n] for n in param_names}
                rest = {n: v for n, v in state.items() if n not in params}
                fwd_ops = ops[:marker_idx]
                post_ops = ops[marker_idx + 1 :]

                def fwd(params_in, virtuals_in, feeds_in):
                    env = dict(rest)
                    env.update(_amp_cast_tree(params_in))
                    env.update(_amp_cast_tree(feeds_in))
                    if virtuals_in:
                        env["__sparse_virtual__"] = virtuals_in
                    run_block_ops(fwd_ops, env, trace)
                    loss = jnp.sum(env[loss_name].astype(jnp.float32))
                    return loss, env

                virtuals = {}
                if sparse_names:
                    # Sparse path (SelectedRows equivalent, core/sparse.py):
                    # an abstract probe discovers each table's per-step row
                    # count; zero "virtual rows" become extra grad leaves so
                    # the table itself is never densely differentiated.
                    if accum != 1:
                        raise NotImplementedError(
                            "is_sparse embeddings + gradient accumulation is "
                            "not supported yet (per-microbatch row shapes)")
                    collect = {}

                    def probe(params_in, feeds_in):
                        env = dict(rest)
                        env.update(params_in)
                        env.update(feeds_in)
                        env["__sparse_collect__"] = collect
                        run_block_ops(fwd_ops, env, trace)
                        return 0

                    jax.eval_shape(probe, params, feeds)
                    missing = [p for p in sparse_names if p not in collect]
                    if missing:
                        raise ValueError(
                            "params marked is_sparse but never looked up "
                            "sparsely: %s" % missing)
                    vd = amp_dtype
                    virtuals = {
                        w: jnp.zeros(shape, vd if (vd is not None and
                                                   dt == jnp.float32) else dt)
                        for w, (shape, dt) in collect.items()
                    }

                if accum == 1:
                    if virtuals:
                        (loss_val, env), (grads, vgrads) = jax.value_and_grad(
                            fwd, argnums=(0, 1), has_aux=True)(
                                params, virtuals, feeds)
                    else:
                        (loss_val, env), grads = jax.value_and_grad(
                            fwd, has_aux=True)(params, {}, feeds)
                else:
                    # Gradient accumulation (the reference's multi_batch_merge
                    # pass, ir/multi_batch_merge_pass.cc): split the feed batch
                    # into microbatches, average grads before the optimizer.
                    # lax.scan keeps trace size and compile time CONSTANT in
                    # accumulation_steps (one traced microbatch, not N); the
                    # first microbatch runs outside the scan to seed the
                    # carry structure (grads + the activation env post_ops
                    # read from).
                    mb = {
                        n: v.reshape((accum, v.shape[0] // accum) + v.shape[1:])
                        for n, v in feeds.items()
                    }
                    sub0 = {n: v[0] for n, v in mb.items()}
                    (loss_sum, env), grads = jax.value_and_grad(
                        fwd, has_aux=True)(params, {}, sub0)

                    def _mb_step(carry, sub):
                        g_acc, l_acc, _ = carry
                        (li, env_i), gi = jax.value_and_grad(
                            fwd, has_aux=True)(params, {}, sub)
                        g_acc = jax.tree_util.tree_map(jnp.add, g_acc, gi)
                        return (g_acc, l_acc + li, env_i), None

                    (grads, loss_sum, env), _ = jax.lax.scan(
                        _mb_step, (grads, loss_sum, env),
                        {n: v[1:] for n, v in mb.items()})
                    grads = jax.tree_util.tree_map(lambda g: g / accum, grads)
                    env[loss_name] = loss_sum / accum
                # restore fp32 master params for the optimizer ops (the env
                # holds their amp-cast forward copies)
                env.update(params)
                for p in param_names:
                    env[param_to_grad[p]] = grads[p]
                for p in sparse_names:
                    from .core.sparse import SparseGrad

                    env[param_to_grad[p]] = SparseGrad(
                        env["__sparse_ids__" + p], vgrads[p])
                env[bw.get("loss_grad") or grad_var_name(loss_name)] = jnp.ones_like(
                    jnp.sum(env[loss_name]))
                run_block_ops(post_ops, env, trace, offset=marker_idx + 1)

            new_state = {}
            for n in self.state_names:
                val = env.get(n, state.get(n))
                if (self._out_state_sh is not None and val is not None
                        and hasattr(val, "dtype")):
                    # pin output layout: params replicated, annotated vars (TP
                    # params, ZeRO-1 optimizer shards) sharded — donation holds
                    # and ZeRO-1 accumulators never silently gather
                    val = jax.lax.with_sharding_constraint(
                        val, self._out_state_sh[n])
                new_state[n] = val
            fetches = [env[f] for f in self.fetch_names]
            return new_state, fetches

        if jit and mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            repl = NamedSharding(mesh, P())
            batch_spec = P("data") if "data" in mesh.axis_names else P()
            feed_sh = {n: NamedSharding(mesh, batch_spec) for n in feed_names}
            # State shardings come from the arrays themselves (the executor
            # device_puts them per Variable.sharding annotations). Output state
            # is pinned to the same layout — params replicated, annotated vars
            # (TP params, ZeRO-1 optimizer shards) sharded — so buffer
            # donation holds and ZeRO-1 accumulators never silently gather.
            out_state_sh = {}
            for n in state_names:
                v = program.global_block._find_var_recursive(n)
                spec = getattr(v, "sharding", None) if v is not None else None
                if spec is not None and all(
                        a is None or a in mesh.axis_names for a in spec):
                    out_state_sh[n] = NamedSharding(mesh, P(*spec))
                else:
                    out_state_sh[n] = repl
            self._out_state_sh = out_state_sh
            self.fn = jax.jit(
                step,
                in_shardings=(None, feed_sh, repl),
                donate_argnums=(0,),
            )
        elif jit:
            self.fn = jax.jit(step, donate_argnums=(0,))
        else:
            self.fn = step

    def __call__(self, state, feeds, step_idx):
        return self.fn(state, feeds, step_idx)


class Executor:
    """reference: python/paddle/fluid/executor.py:262."""

    def __init__(self, place: Optional[Place] = None):
        self.place = place
        self._cache: Dict[tuple, _CompiledStep] = {}
        self._step_counters: Dict[int, int] = {}
        # persistable-name tuples are cached on each Program (see run()):
        # recomputed only on version bump, freed with the Program. Walking
        # every program var per run() was the single largest host cost.

    def close(self):
        """Parity with executor.py:388 (pserver notify) — nothing to release."""
        self._cache.clear()

    # -- helpers --------------------------------------------------------------
    @staticmethod
    def _fetch_names(fetch_list) -> Tuple[str, ...]:
        names = []
        for f in fetch_list or []:
            names.append(f.name if isinstance(f, Variable) else str(f))
        return tuple(names)

    @staticmethod
    def _persistable_names(program: Program, scope: Scope) -> Tuple[str, ...]:
        names = set()
        for v in program.list_vars():
            if v.persistable:
                names.add(v.name)
        # vars already in scope that program ops read (e.g. created by startup)
        return tuple(sorted(names))

    def _gather_state(self, program: Program, scope: Scope, names) -> Dict[str, Any]:
        state = {}
        for n in names:
            val = scope.find_var(n)
            if val is not None:
                state[n] = val
        return state

    def _rng_key(self, program: Program):
        """Per-step PRNG: only a uint32 step index crosses the host/device
        boundary; the fold_in runs inside the compiled step (this eager key
        construction used to cost ~70% of per-step host overhead)."""
        pid = id(program)
        step = self._step_counters.get(pid, 0)
        self._step_counters[pid] = step + 1
        return np.uint32(step)

    # -- the public API -------------------------------------------------------
    def run(
        self,
        program: Optional[Program] = None,
        feed: Optional[Dict[str, Any]] = None,
        fetch_list: Optional[Sequence] = None,
        feed_var_name: str = "feed",
        fetch_var_name: str = "fetch",
        scope: Optional[Scope] = None,
        return_numpy: bool = True,
        use_program_cache: bool = True,
    ):
        global _UserCompiledProgram
        if _UserCompiledProgram is None:
            from .compiler import CompiledProgram as _cp

            _UserCompiledProgram = _cp
        if isinstance(program, _UserCompiledProgram):
            return program._run(self, feed, fetch_list, scope, return_numpy)

        return self._run_impl(
            program, feed, fetch_list, scope, return_numpy, use_program_cache
        )

    def _run_impl(
        self,
        program: Optional[Program] = None,
        feed: Optional[Dict[str, Any]] = None,
        fetch_list: Optional[Sequence] = None,
        scope: Optional[Scope] = None,
        return_numpy: bool = True,
        use_program_cache: bool = True,
        mesh=None,
        accumulation_steps: int = 1,
    ):
        if program is None:
            program = default_main_program()
        if scope is None:
            scope = global_scope()
        feed = dict(feed or {})
        # py_reader-fed programs: drain one batch per run for each started
        # reader whose vars aren't explicitly fed (reference: the in-graph
        # `read` op popping the blocking queue; raises EOFException at end).
        for reader in getattr(program, "_py_readers", ()):
            if not reader._started:
                continue
            fed = [n for n in reader.var_names if n in feed]
            if not fed:
                for n, v in reader.next_feed().items():
                    feed[n] = v
            elif len(fed) != len(reader.var_names):
                # Mixing an explicit partial feed with queue data would
                # silently consume a queued batch and pair unrelated rows.
                raise ValueError(
                    "run(): feed covers only %s of started py_reader vars %s; "
                    "feed all of them or none" % (fed, list(reader.var_names)))
        fetch_names = self._fetch_names(fetch_list)

        block = program.global_block
        # hot-path guards read the module flags directly: with metrics and
        # tracing both off, the whole observability layer costs these two
        # attribute loads + branches per run — no lock, no allocation
        mx_on = _mx._enabled
        tr_on = _tr._active
        # Opt-in grad-norm gauge: the probe var is non-persistable (kept out
        # of checkpoints and the state signature), so it reaches the host as
        # a hidden extra fetch appended to the user's fetch list.
        grad_norm_fetch = (mx_on and GRAD_NORM_VAR in block.vars
                           and GRAD_NORM_VAR not in fetch_names)
        run_fetch_names = (fetch_names + (GRAD_NORM_VAR,)
                           if grad_norm_fetch else fetch_names)
        feeds = {}
        feed_sig = []
        for name in sorted(feed):
            var = block.var(name) if block.has_var(name) else None
            dtype = var.dtype if var is not None else np.asarray(feed[name]).dtype.name
            arr = _canon(feed[name], dtype)
            feeds[name] = arr
            feed_sig.append((name, arr.shape, str(arr.dtype)))

        # cache lives ON the Program (keyed by version) so it dies with it —
        # an executor-held dict keyed by id(program) leaks entries per
        # mutation and can silently serve a stale tuple after id() reuse
        cached = getattr(program, "_pnames_cache_entry", None)
        if cached is not None and cached[0] == program._version:
            state_names = cached[1]
        else:
            state_names = self._persistable_names(program, scope)
            program._pnames_cache_entry = (program._version, state_names)
        # state vars that actually exist (startup creates them on first run);
        # iteration follows the pre-sorted state_names so no per-step re-sort
        state = {}
        svars = scope.vars
        for n in state_names:
            v = svars.get(n)
            if v is None and scope.parent is not None:
                v = scope.find_var(n)
            if v is not None:
                state[n] = v
        avail_state_names = tuple(state)

        is_test = in_test_mode()
        is_training_or_has_feed = bool(feeds) or bool(fetch_names)
        key = (
            id(program),
            program._version,
            tuple(feed_sig),
            run_fetch_names,
            avail_state_names,
            is_test,
            id(mesh) if mesh is not None else None,
            accumulation_steps,
        )
        compiled = self._cache.get(key) if use_program_cache else None
        was_miss = compiled is None
        if compiled is None:
            from .log import vlog

            vlog(1, "Executor: compiling new step specialization "
                    "(program v%s, %d feeds, fetch=%s, test=%s)",
                 program._version, len(feed_sig), list(fetch_names), is_test)
            if mx_on:
                _m_cache_miss.inc()
            t_build = time.perf_counter() if mx_on else 0.0
            with _tr.span("executor/trace_setup", cat="executor",
                          args={"program_version": program._version,
                                "n_feeds": len(feed_sig)}) if tr_on \
                    else _NULL_CTX:
                compiled = _CompiledStep(
                    program,
                    tuple(sorted(feeds)),
                    run_fetch_names,
                    state_names,
                    is_test=is_test,
                    jit=is_training_or_has_feed,
                    mesh=mesh,
                    accumulation_steps=accumulation_steps,
                )
            if mx_on:
                _m_trace_ms.observe((time.perf_counter() - t_build) * 1e3)
            if use_program_cache:
                self._cache[key] = compiled
        elif mx_on:
            _m_cache_hit.inc()

        rng_key = self._rng_key(program)
        if mesh is not None:
            # Lay out state across the mesh: replicated by default (the Fluid
            # BCastParamsToDevices moment, parallel_executor.cc:340), or per
            # Variable.sharding annotation (model-parallel params, sharded
            # embeddings). Feeds shard on the data axis. No-op when already
            # laid out correctly.
            from jax.sharding import NamedSharding, PartitionSpec as P

            repl = NamedSharding(mesh, P())
            specs = {}
            for v in program.list_vars():
                spec = getattr(v, "sharding", None)
                if spec is not None and all(a is None or a in mesh.axis_names for a in spec):
                    specs[v.name] = NamedSharding(mesh, P(*spec))
            batch_sh = NamedSharding(mesh, P("data") if "data" in mesh.axis_names else P())
            state = {k: jax.device_put(v, specs.get(k, repl)) for k, v in state.items()}
            feeds = {k: jax.device_put(v, batch_sh) for k, v in feeds.items()}
        else:
            dev = get_device(self.place)
            if dev is not None and feeds:
                # jax.Arrays already on the right device skip the device_put —
                # re-placing them every step costs real host time. Arrays
                # committed elsewhere (e.g. fetched from a CPU executor) still
                # get moved like before.
                feeds = {k: v if isinstance(v, jax.Array) and dev in v.devices()
                         else jax.device_put(v, dev)
                         for k, v in feeds.items()}
        t_step = time.perf_counter() if mx_on else 0.0
        if tr_on:
            with _tr.span("executor/compile_and_step" if was_miss
                          else "executor/step", cat="executor"):
                new_state, fetches = compiled(state, feeds, rng_key)
        else:
            new_state, fetches = compiled(state, feeds, rng_key)
        if mx_on:
            # A cache-miss first call pays jit trace + XLA compile; report it
            # separately so the steady-state step histogram stays clean. On
            # async backends the hit-path number is dispatch wall time (add
            # FLAGS_benchmark for a per-step device sync).
            dt_ms = (time.perf_counter() - t_step) * 1e3
            (_m_compile_ms if was_miss else _m_step_ms).observe(dt_ms)
            _m_runs.inc()
            if feeds:
                _m_feed_bytes.inc(_nbytes(feeds.values()))
            # HBM gauges are a coarse signal; sampling on miss + every Nth
            # run keeps the per-device memory_stats() calls off the
            # steady-state dispatch path
            if was_miss or int(_m_runs.value) % _HBM_SAMPLE_EVERY == 0:
                _update_hbm_gauges()
        if grad_norm_fetch:
            # opt-in (PADDLE_TPU_GRAD_NORM=1 at graph-build time): one
            # scalar device sync per step
            try:
                _m_grad_norm.set(float(np.asarray(fetches[-1])))
            except (TypeError, ValueError):
                pass
            fetches = fetches[:-1]

        if _flags.benchmark:
            # per-step device sync (reference: FLAGS_benchmark operator.cc:942)
            jax.block_until_ready((new_state, fetches))
        if _flags.check_nan_inf:
            # post-step NaN/Inf scan (reference: FLAGS_check_nan_inf
            # operator.cc:947) over fetches + updated state
            for label, val in list(zip(fetch_names, fetches)) + list(new_state.items()):
                arr = np.asarray(val)
                if np.issubdtype(arr.dtype, np.floating) and not np.isfinite(arr).all():
                    raise RuntimeError(
                        "FLAGS_check_nan_inf: non-finite values in %r after op "
                        "execution" % label)

        for n, v in new_state.items():
            if v is not None:
                scope.set_var(n, v)

        if not fetch_names:
            return []
        if return_numpy:
            out = [np.asarray(f) for f in fetches]
        else:
            out = list(fetches)
        if mx_on and out:
            _m_fetch_bytes.inc(_nbytes(out))
        return out

    # Fluid parity alias
    def infer_from_program(self, *a, **kw):
        return self.run(*a, **kw)
