"""Executor: traces a Program into one jit-compiled XLA step.

Fluid's ``Executor::Run`` (reference: ``framework/executor.cc:186,398``)
interprets ops one by one against a Scope, paying per-op dispatch +
InferShape + kernel-lookup every step. Here the op loop runs ONCE, at trace
time, inside ``jax.jit``: every op impl is a pure JAX function over a
name→array environment, so the whole step — forward, jax.grad backward,
optimizer updates — compiles to a single fused XLA executable. State
(persistable vars) is threaded functionally with buffer donation, giving
in-place param updates in HBM.

Feed/fetch semantics, the program cache (keyed like Fluid's
``executor.py:224,310`` cache plus feed shapes for XLA's static-shape
requirement), and scope handling mirror ``python/paddle/fluid/executor.py``.
"""

from __future__ import annotations

import contextlib
import functools
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import ops as _ops  # noqa: F401 — registers all op impls
from .core.dtypes import to_jnp_dtype
from .core.framework import (Program, Variable, default_main_program,
                             grad_var_name, in_test_mode)
from .flags import flags as _flags
from .core.interpreter import NUMERICS_ENV_KEY as _NUMERICS_ENV_KEY, run_block_ops
from .core.place import Place, get_device
from .core.registry import OpContext, get_op_impl
from .core.scope import Scope, global_scope
from .monitor import GRAD_NORM_VAR, device as _dev, metrics as _mx, tracer as _tr
from .monitor import numerics as _num
from .monitor.numerics import NUM_STATS as _NUM_STATS, \
    STATS_ENV_KEY as _STATS_ENV_KEY
from .reliability import faults as _faults

__all__ = ["Executor", "FeedError", "FetchHandle", "TraceContext",
           "aot_compile"]


class FeedError(RuntimeError):
    """The feed source raised while ``run_steps`` assembled a fused chunk.

    Typed (and flight-recorded by the run_steps crash path) so a data-side
    failure names the global step and the position inside the chunk instead
    of surfacing as a bare stack from ``lax.scan`` input prep."""

# Instruments are module-level handles: looked up once, so the per-run cost
# with metrics ON is a few lock+add ops, and with metrics OFF a single
# branch inside each instrument call (no lock, no allocation) — the
# acceptance bar for the hot path.
_m_runs = _mx.counter("executor/runs", help="Executor.run invocations")
_m_cache_hit = _mx.counter("executor/cache_hit",
                           help="program-cache hits (reused _CompiledStep)")
_m_cache_miss = _mx.counter("executor/cache_miss",
                            help="program-cache misses (new specialization)")
_m_step_ms = _mx.histogram("executor/step_time_ms",
                           help="wall time of one cached step dispatch")
_m_compile_ms = _mx.histogram(
    "executor/compile_time_ms",
    help="trace+XLA-compile wall time of a cache-miss first step")
_m_trace_ms = _mx.histogram(
    "executor/trace_setup_ms",
    help="host time to build a _CompiledStep specialization")
_m_feed_bytes = _mx.counter("executor/feed_bytes",
                            help="bytes handed to the step as feeds")
_m_fetch_bytes = _mx.counter("executor/fetch_bytes",
                             help="bytes fetched back to host")
_m_plan_hit = _mx.counter("executor/plan_hit",
                          help="dispatch-plan cache hits (near-zero Python "
                               "bookkeeping per step)")
_m_plan_miss = _mx.counter("executor/plan_miss",
                           help="dispatch-plan cache misses (full per-run "
                                "bookkeeping)")
_m_chain_dispatches = _mx.counter(
    "executor/run_steps_dispatches",
    help="fused multi-step dispatches issued by Executor.run_steps")
_m_chain_steps = _mx.counter(
    "executor/run_steps_steps",
    help="train steps rolled into run_steps dispatches")
_m_chain_ms = _mx.histogram(
    "executor/run_steps_chunk_ms",
    help="host dispatch wall time of one fused run_steps chunk")
_m_hbm_used = _mx.gauge("device/hbm_bytes_in_use",
                        help="memory_stats bytes_in_use, summed over devices")
_m_hbm_limit = _mx.gauge("device/hbm_bytes_limit",
                         help="memory_stats bytes_limit, summed over devices")
_m_grad_norm = _mx.gauge("optimizer/grad_global_norm",
                         help="pre-clip global grad norm (PADDLE_TPU_GRAD_NORM=1)")

_mem_stats_ok: Optional[bool] = None  # None = not probed yet
_HBM_SAMPLE_EVERY = 32  # sample memory_stats on miss + every Nth run


_mem_devices = None  # cached jax.local_devices() once the probe succeeds


def _update_hbm_gauges() -> None:
    """Refresh HBM gauges from device memory_stats(); probes capability once
    (CPU backends may not implement it) and then never raises per step."""
    global _mem_stats_ok, _mem_devices
    if _mem_stats_ok is False:
        return
    try:
        if _mem_devices is None:
            _mem_devices = jax.local_devices()
        used = limit = 0
        got = False
        for d in _mem_devices:
            stats = d.memory_stats()
            if not stats:
                continue
            got = True
            used += stats.get("bytes_in_use", 0)
            limit += stats.get("bytes_limit", 0)
        if not got:
            _mem_stats_ok = False
            return
        _mem_stats_ok = True
        _m_hbm_used.set(used)
        if limit:
            _m_hbm_limit.set(limit)
    except Exception:
        _mem_stats_ok = False


_NULL_CTX = contextlib.nullcontext()


def _nbytes(arrays) -> int:
    total = 0
    for a in arrays:
        nb = getattr(a, "nbytes", None)
        if nb is None:
            nb = np.asarray(a).nbytes
        total += nb
    return total

class FetchHandle:
    """Deferred fetch result: ``run(..., return_numpy=False)`` returns one.

    Holds the step's fetched ``jax.Array``\\ s, which may still be computing
    on an async backend — so steady-state training can dispatch step N+1
    while step N's device work is in flight. All host-side resolve work (the
    numpy conversion, the opt-in ``PADDLE_TPU_GRAD_NORM`` gauge read and the
    ``executor/fetch_bytes`` accounting) is deferred to :meth:`numpy`, which
    is the only method that forces a device→host transfer.

    The sequence protocol (``len``/index/unpack) hands back the raw device
    arrays WITHOUT a sync, so existing ``loss, = exe.run(...,
    return_numpy=False)`` call sites keep their non-blocking behavior.
    """

    __slots__ = ("_values", "_names", "_aux", "_np", "_aux_done")

    def __init__(self, values, names, aux=None):
        self._values = list(values)
        self._names = tuple(names)
        self._aux = aux  # hidden grad-norm fetch (device scalar) or None
        self._np = None
        self._aux_done = aux is None

    @property
    def names(self):
        return self._names

    @property
    def raw(self):
        """The fetched device arrays, no sync."""
        return list(self._values)

    def __len__(self):
        return len(self._values)

    def __getitem__(self, i):
        return self._values[i]

    def __iter__(self):
        return iter(self._values)

    def _consume_aux(self):
        """Mirror the hidden grad-norm fetch into its gauge (one scalar
        device sync; only on the resolve path, never at dispatch)."""
        if self._aux_done:
            return
        self._aux_done = True
        if not _mx._enabled:
            return
        try:
            _m_grad_norm.set(float(np.asarray(self._aux).ravel()[-1]))
        except (TypeError, ValueError):
            pass

    def done(self) -> bool:
        """True once every fetched array's device computation finished
        (non-blocking; conservatively True on backends without is_ready)."""
        for v in self._values:
            ready = getattr(v, "is_ready", None)
            if ready is not None and not ready():
                return False
        return True

    def block(self):
        """Wait for the device work behind the fetches; returns self."""
        jax.block_until_ready(self._values)
        self._consume_aux()
        return self

    def numpy(self):
        """Resolve to host numpy arrays (syncs; cached after first call)."""
        if self._np is None:
            out = [np.asarray(v) for v in self._values]
            self._consume_aux()
            if _mx._enabled and out:
                _m_fetch_bytes.inc(_nbytes(out))
            self._np = out
        return list(self._np)

    # the "resolve path" name used in docs; same operation
    resolve = numpy

    def __del__(self):
        # A dropped handle must not silently lose the grad-norm sample the
        # user opted into; this is a scalar sync at GC time, best-effort.
        try:
            self._consume_aux()
        except Exception:
            pass


@jax.jit
def _finite_all(vals):
    """ONE fused device-side isfinite reduction over a list of float
    arrays → a scalar bool. The whole NaN check is then a single
    scalar device sync instead of the legacy full-model host copy
    (every fetch AND state entry through np.asarray, per step)."""
    ok = jnp.bool_(True)
    for v in vals:
        ok = jnp.logical_and(ok, jnp.isfinite(v).all())
    return ok


def _enforce_step_flags(fetch_names, fetches, state):
    """FLAGS_benchmark device sync (reference: operator.cc:942) and the
    FLAGS_check_nan_inf post-step check (operator.cc:947) — the one epilogue
    both drivers (run() and run_steps) must apply identically.

    The NaN check is a fused device-side reduction (see ``_finite_all``);
    its scalar fetch is the only sync, and after FLAGS_benchmark's
    block_until_ready it is free — the two flags compose without a second
    sync or any host copy. Only the (rare) failure path walks the values on
    host to recover the legacy error message's offending label.
    ``PADDLE_TPU_CHECK_NUMERICS>=1`` arms the same check without the legacy
    flag; level 2's per-op mask (checked before this) already attributed
    the op, so this stays the fetch/state-level backstop."""
    if _flags.benchmark:
        jax.block_until_ready((state, fetches))
    if _flags.check_nan_inf or _dev.numerics_level() >= 1:
        labeled = list(zip(fetch_names, fetches)) + list(state.items())
        vals = [v for _, v in labeled
                if getattr(v, "dtype", None) is not None
                and jnp.issubdtype(v.dtype, jnp.floating)]
        if not vals or bool(_finite_all(vals)):  # one scalar device sync
            return
        for label, val in labeled:
            arr = np.asarray(val)
            if np.issubdtype(arr.dtype, np.floating) and not np.isfinite(arr).all():
                raise RuntimeError(
                    "FLAGS_check_nan_inf: non-finite values in %r after op "
                    "execution" % label)
        raise RuntimeError(
            "FLAGS_check_nan_inf: non-finite values after op execution")


def _safe_flight_dump(fr, reason, exc):
    """Crash-path flight-recorder dump: an unwritable PADDLE_TPU_FLIGHT_DIR
    (or a serialization hiccup) must never REPLACE the step error the dump
    exists to explain."""
    if fr is None:
        return
    try:
        fr.dump(reason, exc)
    except Exception as dump_err:
        from .log import vlog

        vlog(0, "flight-recorder dump failed (%r); original error preserved",
             dump_err)


def _mesh_repl(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def _mesh_batch_spec(mesh, leading_step_axis=False):
    """PartitionSpec for feed batches: the batch axis shards over ``data``;
    ``leading_step_axis`` prepends a replicated axis for run_steps' stacked
    (step, batch, ...) chain feeds. One definition so the single-step and
    chain drivers can never lay feeds out differently."""
    from jax.sharding import PartitionSpec as P

    if "data" not in mesh.axis_names:
        return P()
    return P(None, "data") if leading_step_axis else P("data")


def _valid_sharding(spec, mesh):
    """A Variable.sharding annotation applies iff every named axis exists on
    this mesh — the one predicate all sharding consumers share."""
    return spec is not None and all(
        a is None or a in mesh.axis_names for a in spec)


def _abstractify(tree):
    """Pytree → ShapeDtypeStructs (ShapeDtypeStructs pass through)."""
    return jax.tree_util.tree_map(
        lambda v: v if isinstance(v, jax.ShapeDtypeStruct)
        else jax.ShapeDtypeStruct(tuple(getattr(v, "shape", ())),
                                  getattr(v, "dtype", np.float32)),
        tree)


def _timed_lower_compile(jitted_fn, args):
    """(lowered, executable) with the compile wall time routed to the
    executor/compile_time_ms histogram — the one AOT timing convention
    shared by Executor.prepare and aot_compile."""
    _faults.fire("executor.compile")  # chaos drills: injected compile failure
    t0 = time.perf_counter()
    lowered = jitted_fn.lower(*args)
    aot = lowered.compile()
    if _mx._enabled:
        _m_compile_ms.observe((time.perf_counter() - t0) * 1e3)
    return lowered, aot


def aot_compile(fn, abstract_args, donate_argnums=(), static_argnums=()):
    """AOT lower + XLA-compile ``fn`` at abstract shapes WITHOUT running it
    — ``Executor.prepare``'s artifact path exposed for non-Program drivers
    (the serving decode engine compiles its per-bucket prefill fns and the
    fused decode step through here).

    ``abstract_args`` is a tuple of pytrees of arrays or
    ``ShapeDtypeStruct``\\ s (only shapes/dtypes are read). Compile time
    lands in ``executor/compile_time_ms``; with ``PADDLE_TPU_COMPILE_CACHE``
    set the executable persists across processes, so a serving restart
    skips every prefill/decode compile. Returns the compiled executable
    (call it with concrete arrays; ``donate_argnums`` buffers are consumed).
    """
    jitted = jax.jit(fn, donate_argnums=donate_argnums,
                     static_argnums=static_argnums)
    static = set(static_argnums if isinstance(static_argnums, (tuple, list))
                 else (static_argnums,))
    # static args must reach the trace as their CONCRETE values, not shape
    # structs — only the traced (dynamic) positions are abstractified
    args = tuple(a if i in static else _abstractify(a)
                 for i, a in enumerate(abstract_args))
    _, aot = _timed_lower_compile(jitted, args)
    return aot


_UserCompiledProgram = None  # lazily bound CompiledProgram class (import cycle)


class TraceContext:
    """Per-trace state: RNG derivation, test mode, mesh, current op position."""

    def __init__(self, program: Program, is_test: bool, base_rng, mesh=None):
        self.program = program
        self.is_test = is_test
        self.base_rng = base_rng
        self.mesh = mesh
        self.current_op_idx = 0
        self._key_table = None
        self._n_ops = 0
        # device-side observability (monitor/device.py): op-identity named
        # scopes (trace-time-only cost, resolved once per trace) and the
        # numerics-watchdog layout list the owning _CompiledStep arms
        self.op_scopes = _dev.op_scopes_enabled()
        self.watch = None

    def op_rng(self, ctx: OpContext):
        # RNG-stability contract (passes/analysis.py): an optimizer pass may
        # delete or move ops, which would shift every later op's positional
        # key. The pipeline stamps each stochastic op's ORIGINAL position
        # into __rng_slot__ before mutating; honoring it here keeps the
        # optimized program's RNG stream bit-identical to OPT_LEVEL=0.
        idx = ctx.attr("__rng_slot__")
        if idx is None:
            idx = self.current_op_idx
        seed = ctx.attr("seed", 0) or self.program.random_seed
        if seed:
            # explicit per-op seed: a constant key XLA constant-folds
            return jax.random.fold_in(jax.random.PRNGKey(seed), idx)
        # Derive the main-block per-op keys with one batched split instead of
        # a scalar fold_in per RNG-consuming op: each scalar fold_in is ~113
        # unfusable scalar u32 entry instructions (a full threefry chain),
        # and a BERT step with ~50 dropout sites carried ~5,700 of them —
        # the batched table is one vectorized threefry plus slices that fuse
        # into the consumers (benchmarks/diag_bert_kernels.py).
        # Sub-block ops (while/cond bodies) run at offset 10_000*block_idx
        # (ops/control_flow_ops.py) — far past the table, where JAX's static
        # indexing would silently CLAMP to the last row and hand every such
        # op the same key — so anything past the table keeps the scalar
        # fold_in (distinct key per index; those ops trace once inside the
        # loop body, so the scalar chains stay rare).
        if self._key_table is None:
            # jax.random.split(key, n) keys depend on n, so an optimized
            # program must build the table at the SOURCE program's size
            # (_rng_table_n, stamped by the pipeline) for stamped slots to
            # resolve to the same keys as the unoptimized program.
            self._n_ops = getattr(self.program, "_rng_table_n",
                                  len(self.program.global_block.ops) + 8)
            self._key_table = jax.random.split(self.base_rng, self._n_ops)
        if idx < self._n_ops:
            return self._key_table[idx]
        return jax.random.fold_in(self.base_rng, idx)


def _canon(value, dtype_name: str):
    target = to_jnp_dtype(dtype_name)
    canonical = jax.dtypes.canonicalize_dtype(target)
    if isinstance(value, jax.ShapeDtypeStruct):
        # abstract feed (Executor.prepare): only shape/dtype matter
        return (value if value.dtype == canonical
                else jax.ShapeDtypeStruct(value.shape, canonical))
    if isinstance(value, jax.Array):
        # already on device (e.g. via DevicePrefetcher) — never round-trip to host
        return value if value.dtype == canonical else value.astype(canonical)
    arr = np.asarray(value)
    if arr.dtype != canonical:
        arr = arr.astype(canonical)
    return arr


class _CompiledStep:
    """A specialization of (program, feed sig, fetch list, state names).

    With a mesh: state replicated, feeds sharded on the ``data`` axis —
    XLA/GSPMD inserts the gradient psum over ICI (the TPU-native
    ParallelExecutor+NCCL path, SURVEY.md §7).
    """

    def __init__(self, program: Program, feed_names: Tuple[str, ...],
                 fetch_names: Tuple[str, ...], state_names: Tuple[str, ...],
                 is_test: bool, jit: bool = True, mesh=None,
                 accumulation_steps: int = 1, numerics: bool = False,
                 stats: bool = False):
        self.program = program
        self.feed_names = feed_names
        self.fetch_names = fetch_names
        self.state_names = state_names
        self.is_test = is_test
        self.mesh = mesh
        # PADDLE_TPU_CHECK_NUMERICS=2: this specialization is the GUARDED
        # variant — every op's floating outputs feed an isfinite bit into a
        # packed mask appended as a hidden trailing fetch; watch_layout maps
        # mask bit k -> (op label, output names), written at trace time
        # (index-overwrite, so jit retraces never desync it).
        self.numerics = bool(numerics)
        self.watch_layout: list = []
        # PADDLE_TPU_NUMERICS>=1: streaming tensor statistics — every op's
        # floating outputs fold one packed stat row into a [K, NUM_STATS]
        # hidden trailing fetch (monitor.numerics); stats_layout maps row
        # k -> (op label, output names, dtype max), same index-overwrite
        # discipline as watch_layout.
        self.stats = bool(stats)
        self.stats_layout: list = []

        bw = program._backward_info
        block = program.global_block
        ops = block.ops
        marker_idx = None
        if bw is not None:
            for i, op in enumerate(ops):
                if op.type == "backward_marker":
                    marker_idx = i
                    break
        accum = max(1, int(accumulation_steps)) if marker_idx is not None else 1

        # AMP: run the forward in bf16/fp16 against fp32 master weights
        # (the TPU-native float16.h story; enabled via paddle_tpu.amp).
        amp_dtype = getattr(program, "_amp_dtype", None)
        if amp_dtype is not None:
            amp_dtype = to_jnp_dtype(amp_dtype)

        def _amp_cast_tree(d):
            if amp_dtype is None:
                return d
            return {
                k: (v.astype(amp_dtype)
                    if hasattr(v, "dtype") and v.dtype == jnp.float32 else v)
                for k, v in d.items()
            }

        seed_const = program.random_seed or 0
        self._out_state_sh = None  # set below when jit+mesh; guards jit=False

        def step(state, feeds, step_idx):
            # key derivation is part of the compiled step (fused, zero host
            # cost per run); step_idx is the only changing input
            rng_key = jax.random.fold_in(jax.random.PRNGKey(seed_const), step_idx)
            trace = TraceContext(program, is_test, rng_key, mesh=mesh)
            if self.numerics:
                trace.watch = self.watch_layout
            if self.stats:
                trace.stats_watch = self.stats_layout
            if bw is None or marker_idx is None:
                env = dict(state)
                env.update(feeds)
                if amp_dtype is not None:
                    # Cast a COPY of the env for the forward; the fp32 master
                    # state must survive an eval/fetch run un-degraded. Only
                    # vars an op actually rewrote (tracer identity changed)
                    # flow back, cast to their original dtype.
                    env = _amp_cast_tree(env)
                    before = dict(env)  # hold refs so identity compare is sound
                    run_block_ops(ops, env, trace)
                    for k in list(env):
                        if k not in state:
                            continue
                        v = env[k]
                        if before.get(k) is v:
                            env[k] = state[k]
                        elif (hasattr(v, "dtype") and hasattr(state[k], "dtype")
                              and v.dtype != state[k].dtype):
                            env[k] = v.astype(state[k].dtype)
                else:
                    run_block_ops(ops, env, trace)
            else:
                loss_name = bw["loss"]
                param_to_grad = bw["param_to_grad"]
                all_param_names = [p for p in param_to_grad if p in state]
                block0 = program.global_block
                sparse_names = [
                    p for p in all_param_names
                    if getattr(block0._find_var_recursive(p), "is_sparse_param", False)
                ]
                param_names = [p for p in all_param_names if p not in sparse_names]
                params = {n: state[n] for n in param_names}
                rest = {n: v for n, v in state.items() if n not in params}
                fwd_ops = ops[:marker_idx]
                post_ops = ops[marker_idx + 1 :]

                def fwd(params_in, virtuals_in, feeds_in):
                    env = dict(rest)
                    env.update(_amp_cast_tree(params_in))
                    env.update(_amp_cast_tree(feeds_in))
                    if virtuals_in:
                        env["__sparse_virtual__"] = virtuals_in
                    run_block_ops(fwd_ops, env, trace)
                    loss = jnp.sum(env[loss_name].astype(jnp.float32))
                    return loss, env

                virtuals = {}
                if sparse_names:
                    # Sparse path (SelectedRows equivalent, core/sparse.py):
                    # an abstract probe discovers each table's per-step row
                    # count; zero "virtual rows" become extra grad leaves so
                    # the table itself is never densely differentiated.
                    if accum != 1:
                        raise NotImplementedError(
                            "is_sparse embeddings + gradient accumulation is "
                            "not supported yet (per-microbatch row shapes)")
                    collect = {}

                    def probe(params_in, feeds_in):
                        env = dict(rest)
                        env.update(params_in)
                        env.update(feeds_in)
                        env["__sparse_collect__"] = collect
                        run_block_ops(fwd_ops, env, trace)
                        return 0

                    jax.eval_shape(probe, params, feeds)
                    missing = [p for p in sparse_names if p not in collect]
                    if missing:
                        raise ValueError(
                            "params marked is_sparse but never looked up "
                            "sparsely: %s" % missing)
                    vd = amp_dtype
                    virtuals = {
                        w: jnp.zeros(shape, vd if (vd is not None and
                                                   dt == jnp.float32) else dt)
                        for w, (shape, dt) in collect.items()
                    }

                if accum == 1:
                    if virtuals:
                        (loss_val, env), (grads, vgrads) = jax.value_and_grad(
                            fwd, argnums=(0, 1), has_aux=True)(
                                params, virtuals, feeds)
                    else:
                        (loss_val, env), grads = jax.value_and_grad(
                            fwd, has_aux=True)(params, {}, feeds)
                else:
                    # Gradient accumulation (the reference's multi_batch_merge
                    # pass, ir/multi_batch_merge_pass.cc): split the feed batch
                    # into microbatches, average grads before the optimizer.
                    # lax.scan keeps trace size and compile time CONSTANT in
                    # accumulation_steps (one traced microbatch, not N); the
                    # first microbatch runs outside the scan to seed the
                    # carry structure (grads + the activation env post_ops
                    # read from).
                    mb = {
                        n: v.reshape((accum, v.shape[0] // accum) + v.shape[1:])
                        for n, v in feeds.items()
                    }
                    sub0 = {n: v[0] for n, v in mb.items()}
                    (loss_sum, env), grads = jax.value_and_grad(
                        fwd, has_aux=True)(params, {}, sub0)

                    def _mb_step(carry, sub):
                        g_acc, l_acc, env_prev = carry
                        (li, env_i), gi = jax.value_and_grad(
                            fwd, has_aux=True)(params, {}, sub)
                        if self.numerics:
                            # AND the watchdog bits across microbatches —
                            # carrying only env_i would drop every earlier
                            # microbatch's forward bits and misattribute a
                            # mid-accumulation NaN to the optimizer ops
                            prev = env_prev.get(_NUMERICS_ENV_KEY)
                            cur = env_i.get(_NUMERICS_ENV_KEY)
                            if prev and cur:
                                env_i[_NUMERICS_ENV_KEY] = [
                                    jnp.logical_and(a, b)
                                    for a, b in zip(prev, cur)]
                        if self.stats:
                            # merge stat rows across microbatches the same
                            # way (absmax by max, sums add) so a chunk's
                            # stats cover every microbatch, not just the
                            # last one
                            prev = env_prev.get(_STATS_ENV_KEY)
                            cur = env_i.get(_STATS_ENV_KEY)
                            if prev and cur:
                                env_i[_STATS_ENV_KEY] = [
                                    _num.merge_stat_rows(a, b)
                                    for a, b in zip(prev, cur)]
                        g_acc = jax.tree_util.tree_map(jnp.add, g_acc, gi)
                        return (g_acc, l_acc + li, env_i), None

                    (grads, loss_sum, env), _ = jax.lax.scan(
                        _mb_step, (grads, loss_sum, env),
                        {n: v[1:] for n, v in mb.items()})
                    grads = jax.tree_util.tree_map(lambda g: g / accum, grads)
                    env[loss_name] = loss_sum / accum
                # restore fp32 master params for the optimizer ops (the env
                # holds their amp-cast forward copies)
                env.update(params)
                for p in param_names:
                    env[param_to_grad[p]] = grads[p]
                for p in sparse_names:
                    from .core.sparse import SparseGrad

                    env[param_to_grad[p]] = SparseGrad(
                        env["__sparse_ids__" + p], vgrads[p])
                env[bw.get("loss_grad") or grad_var_name(loss_name)] = jnp.ones_like(
                    jnp.sum(env[loss_name]))
                run_block_ops(post_ops, env, trace, offset=marker_idx + 1)

            new_state = {}
            for n in self.state_names:
                val = env.get(n, state.get(n))
                if (self._out_state_sh is not None and val is not None
                        and hasattr(val, "dtype")):
                    # pin output layout: params replicated, annotated vars (TP
                    # params, ZeRO-1 optimizer shards) sharded — donation holds
                    # and ZeRO-1 accumulators never silently gather
                    val = jax.lax.with_sharding_constraint(
                        val, self._out_state_sh[n])
                new_state[n] = val
            fetches = [env[f] for f in self.fetch_names]
            if self.numerics:
                # the packed watchdog mask rides as the LAST hidden fetch
                # (after the grad-norm probe, which is part of fetch_names);
                # run()/run_steps pop it first and attribute failures via
                # watch_layout
                bits = env.get(_NUMERICS_ENV_KEY)
                fetches.append(jnp.stack(bits) if bits
                               else jnp.ones((1,), jnp.bool_))
            if self.stats:
                # the packed stat rows ride as the VERY last hidden fetch
                # (after the watchdog mask when both are armed); run()/
                # run_steps pop in reverse append order
                rows = env.get(_STATS_ENV_KEY)
                fetches.append(jnp.stack(rows) if rows
                               else jnp.zeros((1, _NUM_STATS), jnp.float32))
            return new_state, fetches

        # the raw (unjitted) step closure: _CompiledStepChain scans over it
        # to fuse k steps into one dispatch (Executor.run_steps)
        self._step_fn = step
        self.jitted = bool(jit)

        if jit and mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            repl = _mesh_repl(mesh)
            batch_spec = _mesh_batch_spec(mesh)
            feed_sh = {n: NamedSharding(mesh, batch_spec) for n in feed_names}
            # State shardings come from the arrays themselves (the executor
            # device_puts them per Variable.sharding annotations). Output state
            # is pinned to the same layout — params replicated, annotated vars
            # (TP params, ZeRO-1 optimizer shards) sharded — so buffer
            # donation holds and ZeRO-1 accumulators never silently gather.
            out_state_sh = {}
            for n in state_names:
                v = program.global_block._find_var_recursive(n)
                spec = getattr(v, "sharding", None) if v is not None else None
                if _valid_sharding(spec, mesh):
                    out_state_sh[n] = NamedSharding(mesh, P(*spec))
                else:
                    out_state_sh[n] = repl
            self._out_state_sh = out_state_sh
            self.fn = jax.jit(
                step,
                in_shardings=(None, feed_sh, repl),
                donate_argnums=(0,),
            )
        elif jit:
            self.fn = jax.jit(step, donate_argnums=(0,))
        else:
            self.fn = step

    def __call__(self, state, feeds, step_idx):
        return self.fn(state, feeds, step_idx)


class _CompiledStepChain:
    """``length`` consecutive steps of a ``_CompiledStep`` fused into ONE
    dispatched call.

    ``lax.scan`` rolls the base step over feed batches stacked on a new
    leading axis — the same stack-and-scan shape plumbing the gradient
    accumulation path uses for microbatches, except here each scan iteration
    is a FULL step (forward, backward, optimizer update) threading the state
    carry, so host dispatch cost drops to 1/length while the traced program
    (and its RNG stream: ``fold_in(key, step_idx)`` with the step index
    carried through the scan) stays identical to ``length`` separate runs.
    Per-step fetches come back stacked on the leading axis.
    """

    def __init__(self, base: _CompiledStep, length: int):
        self.base = base
        self.length = int(length)
        step_fn = base._step_fn

        def chain(state, stacked_feeds, step_idx0):
            def body(carry, feeds):
                st, idx = carry
                new_st, fetches = step_fn(st, feeds, idx)
                return (new_st, idx + jnp.uint32(1)), fetches

            # explicit length: a feedless (state-only) program hands scan an
            # empty xs pytree, which otherwise cannot infer the step count
            (state, _), fetches = jax.lax.scan(
                body, (state, jnp.uint32(step_idx0)), stacked_feeds,
                length=self.length)
            return state, fetches

        if base.jitted and base.mesh is not None:
            from jax.sharding import NamedSharding

            mesh = base.mesh
            repl = _mesh_repl(mesh)
            # axis 0 is the step axis; the per-step batch axis (1) shards
            # over ``data`` exactly like the single-step driver
            spec = _mesh_batch_spec(mesh, leading_step_axis=True)
            feed_sh = {n: NamedSharding(mesh, spec) for n in base.feed_names}
            self.fn = jax.jit(chain, in_shardings=(None, feed_sh, repl),
                              donate_argnums=(0,))
        elif base.jitted:
            self.fn = jax.jit(chain, donate_argnums=(0,))
        else:
            self.fn = chain

    def __call__(self, state, stacked_feeds, step_idx0):
        return self.fn(state, stacked_feeds, step_idx0)


class _DispatchPlan:
    """Memoized per-run Python bookkeeping for one (program version, feed
    names/dtypes, fetch list) shape of ``Executor.run``.

    A cache-hit step skips the per-feed ``block.var`` + dtype
    canonicalization machinery, the feed-signature build, the persistable
    walk and the specialization-key construction — the bookkeeping that
    dominated host dispatch time — and goes straight to the cached
    ``_CompiledStep``. Plans live on the Program (keyed by version, see
    ``Executor._resolve_plan``), so a version bump invalidates them and they
    die with the Program.
    """

    __slots__ = ("feed_specs", "fetch_names", "run_fetch_names",
                 "grad_norm_fetch", "numerics", "stats", "state_names",
                 "avail_names", "compiled", "key", "put_specs", "batch_sh",
                 "mesh_repl")

    def __init__(self, feed_specs, fetch_names, run_fetch_names,
                 grad_norm_fetch, numerics, stats, state_names, avail_names,
                 compiled, key, put_specs=None, batch_sh=None, mesh_repl=None):
        self.feed_specs = feed_specs  # tuple of (name, np.dtype, shape)
        self.fetch_names = fetch_names
        self.run_fetch_names = run_fetch_names
        self.grad_norm_fetch = grad_norm_fetch
        self.numerics = numerics  # guarded variant: watchdog mask fetch last
        self.stats = stats  # stats variant: packed stat rows fetch after it
        self.state_names = state_names
        self.avail_names = avail_names  # state vars present at plan build
        self.compiled = compiled
        self.key = key  # the _CompiledStep cache key (chain keys derive from it)
        self.put_specs = put_specs  # mesh only: {name: NamedSharding}
        self.batch_sh = batch_sh
        self.mesh_repl = mesh_repl


class Executor:
    """reference: python/paddle/fluid/executor.py:262."""

    def __init__(self, place: Optional[Place] = None):
        self.place = place
        self._cache: Dict[tuple, Any] = {}
        self._dev = None  # get_device(place), resolved lazily once
        self._dev_resolved = False
        # Per-program state (persistable-name tuples, dispatch plans, the
        # step counter feeding the per-step RNG) is cached ON each Program:
        # recomputed only on version bump, freed with the Program. An
        # executor-held dict keyed by id(program) would grow one entry per
        # program forever and could silently serve stale state after id()
        # reuse — the bug close() used to leave behind in _step_counters.

    def close(self):
        """Parity with executor.py:388 (pserver notify): drop every cached
        specialization. Per-program bookkeeping (dispatch plans, step
        counters) lives on the Program objects and dies with them."""
        self._cache.clear()

    # -- helpers --------------------------------------------------------------
    @staticmethod
    def _fetch_names(fetch_list) -> Tuple[str, ...]:
        names = []
        for f in fetch_list or []:
            names.append(f.name if isinstance(f, Variable) else str(f))
        return tuple(names)

    @staticmethod
    def _persistable_names(program: Program, scope: Scope) -> Tuple[str, ...]:
        names = set()
        for v in program.list_vars():
            if v.persistable:
                names.add(v.name)
        # vars already in scope that program ops read (e.g. created by startup)
        return tuple(sorted(names))

    def _gather_state(self, program: Program, scope: Scope, names) -> Dict[str, Any]:
        state = {}
        for n in names:
            val = scope.find_var(n)
            if val is not None:
                state[n] = val
        return state

    @staticmethod
    def _unwrap_program(program, scope):
        """(plain program, mesh, accumulation_steps) from a possibly-wrapped
        CompiledProgram — the shared front door of run_steps and prepare
        (run() instead routes through CompiledProgram._run)."""
        global _UserCompiledProgram
        if _UserCompiledProgram is None:
            from .compiler import CompiledProgram as _cp

            _UserCompiledProgram = _cp
        mesh = None
        accumulation_steps = 1
        if isinstance(program, _UserCompiledProgram):
            cp = program
            cp._apply_build_passes(scope)
            mesh = cp._mesh()
            cp._apply_reduce_strategy(mesh)
            if cp._build_strategy is not None:
                accumulation_steps = getattr(
                    cp._build_strategy, "gradient_accumulation_steps", 1)
            program = cp._program
        if program is None:
            program = default_main_program()
        return program, mesh, accumulation_steps

    @staticmethod
    def _next_step_index(program: Program, n: int = 1):
        """Per-step PRNG: only a uint32 step index crosses the host/device
        boundary; the fold_in runs inside the compiled step (this eager key
        construction used to cost ~70% of per-step host overhead). The
        counter lives on the Program so it dies with it and a fused
        ``run_steps`` chunk advances it by the number of steps it rolled."""
        step = getattr(program, "_tpu_step_counter", 0)
        program._tpu_step_counter = step + n
        return np.uint32(step)

    def _device(self):
        if not self._dev_resolved:
            self._dev = get_device(self.place)
            self._dev_resolved = True
        return self._dev

    @staticmethod
    def _maybe_optimize(program: Program, fetch_names, scope):
        """Default trace-time optimizer (passes/, PADDLE_TPU_OPT_LEVEL,
        default 1): returns the memoized optimized clone for this (program
        version, fetch set) — the clone is what plan resolution and tracing
        see, so the optimized program participates in the dispatch-plan and
        compile-cache keys and a cache-hit run never re-enters a pass. The
        per-step RNG counter stays on the SOURCE program (callers pass the
        source to _next_step_index), keeping the RNG stream shared across
        fetch-set variants exactly as at opt level 0."""
        from .passes.pipeline import maybe_optimize

        return maybe_optimize(program, fetch_names, scope)

    # -- the public API -------------------------------------------------------
    def run(
        self,
        program: Optional[Program] = None,
        feed: Optional[Dict[str, Any]] = None,
        fetch_list: Optional[Sequence] = None,
        feed_var_name: str = "feed",
        fetch_var_name: str = "fetch",
        scope: Optional[Scope] = None,
        return_numpy: bool = True,
        use_program_cache: bool = True,
    ):
        global _UserCompiledProgram
        if _UserCompiledProgram is None:
            from .compiler import CompiledProgram as _cp

            _UserCompiledProgram = _cp
        if isinstance(program, _UserCompiledProgram):
            return program._run(self, feed, fetch_list, scope, return_numpy)

        return self._run_impl(
            program, feed, fetch_list, scope, return_numpy, use_program_cache
        )

    def _run_impl(
        self,
        program: Optional[Program] = None,
        feed: Optional[Dict[str, Any]] = None,
        fetch_list: Optional[Sequence] = None,
        scope: Optional[Scope] = None,
        return_numpy: bool = True,
        use_program_cache: bool = True,
        mesh=None,
        accumulation_steps: int = 1,
    ):
        if program is None:
            program = default_main_program()
        if scope is None:
            scope = global_scope()
        feed = dict(feed or {})
        # py_reader-fed programs: drain one batch per run for each started
        # reader whose vars aren't explicitly fed (reference: the in-graph
        # `read` op popping the blocking queue; raises EOFException at end).
        for reader in getattr(program, "_py_readers", ()):
            if not reader._started:
                continue
            fed = [n for n in reader.var_names if n in feed]
            if not fed:
                for n, v in reader.next_feed().items():
                    feed[n] = v
            elif len(fed) != len(reader.var_names):
                # Mixing an explicit partial feed with queue data would
                # silently consume a queued batch and pair unrelated rows.
                raise ValueError(
                    "run(): feed covers only %s of started py_reader vars %s; "
                    "feed all of them or none" % (fed, list(reader.var_names)))
        fetch_names = self._fetch_names(fetch_list)

        # default trace-time optimizer: all bookkeeping below (plans, the
        # specialization cache, tracing) keys on the optimized clone; only
        # the step counter stays on the source program
        src_program = program
        program = self._maybe_optimize(program, fetch_names, scope)

        # hot-path guards read the module flags directly: with metrics and
        # tracing both off, the whole observability layer costs these two
        # attribute loads + branches per run — no lock, no allocation
        mx_on = _mx._enabled
        tr_on = _tr._active

        plan, feeds, state, was_miss = self._resolve_plan(
            program, feed, fetch_names, scope, mesh, accumulation_steps,
            mx_on, tr_on, use_program_cache)
        compiled = plan.compiled

        rng_key = self._next_step_index(src_program)
        state, feeds = self._place(plan, state, feeds, mesh)
        fr = _dev.flight_recorder()  # None unless PADDLE_TPU_FLIGHT_DIR set
        if fr is not None:
            # fingerprint the SOURCE program (the one the user can inspect;
            # watchdog slots are source-relative); the optimized clone's
            # fingerprint rides along for compile-cache correlation
            fr.record_step(
                "run", src_program, plan.feed_specs, fetch_names,
                extra={"optimized": _dev.program_fingerprint(program)})
        t_step = time.perf_counter() if mx_on else 0.0
        try:
            spec = _faults.fire("executor.dispatch")
            if spec is not None and spec.kind == "nan":
                feeds = _faults.poison_feeds(feeds)
            if tr_on:
                with _tr.span("executor/compile_and_step" if was_miss
                              else "executor/step", cat="executor"):
                    new_state, fetches = compiled(state, feeds, rng_key)
            else:
                new_state, fetches = compiled(state, feeds, rng_key)
            if mx_on:
                # A cache-miss first call pays jit trace + XLA compile;
                # report it separately so the steady-state step histogram
                # stays clean. On async backends the hit-path number is
                # dispatch wall time (add FLAGS_benchmark for a per-step
                # device sync).
                dt_ms = (time.perf_counter() - t_step) * 1e3
                (_m_compile_ms if was_miss else _m_step_ms).observe(dt_ms)
                _m_runs.inc()
                if feeds:
                    _m_feed_bytes.inc(_nbytes(feeds.values()))
                # HBM gauges are a coarse signal; sampling on miss + every
                # Nth run keeps the per-device memory_stats() calls off the
                # steady-state dispatch path
                if was_miss or int(_m_runs.value) % _HBM_SAMPLE_EVERY == 0:
                    _update_hbm_gauges()
            if was_miss and compiled.jitted and _dev.profile_enabled():
                self._publish_device_profile(compiled, new_state, feeds)
            if plan.stats:
                # stat rows ride after the watchdog mask, so they pop first;
                # accumulate BEFORE check_numerics_mask so the trip chunk's
                # range history still lands in the registries/flight dump
                _num.accumulate(fetches[-1], compiled.stats_layout,
                                fingerprint=_dev.program_fingerprint(
                                    src_program),
                                driver="run")
                fetches = fetches[:-1]
            mask = None
            if plan.numerics:
                # the packed per-op isfinite mask is the LAST hidden fetch
                mask = fetches[-1]
                fetches = fetches[:-1]
            aux = None
            if plan.grad_norm_fetch:
                # opt-in (PADDLE_TPU_GRAD_NORM=1 at graph-build time): the
                # gauge read is a scalar device sync, so it rides the
                # FetchHandle's resolve path instead of blocking the
                # dispatch loop here
                aux = fetches[-1]
                fetches = fetches[:-1]
            # write the new state back BEFORE the numerics checks: donation
            # consumed the scope's old buffers at dispatch, so raising first
            # would leave the scope pointing at deleted arrays — writing the
            # (possibly non-finite) state keeps a watchdog failure
            # recoverable/inspectable, mirroring run_steps' finally-flush
            for n, v in new_state.items():
                if v is not None:
                    scope.set_var(n, v)
            if mask is not None:
                _dev.check_numerics_mask(mask, compiled.watch_layout)
            _enforce_step_flags(fetch_names, fetches, new_state)
        except Exception as e:
            if fr is None:
                fr = _dev.flight_recorder()
            _safe_flight_dump(fr, "executor.run", e)
            raise

        if not fetch_names:
            if aux is not None:
                # no user fetches to hang a handle on — keep the old eager
                # gauge behavior instead of dropping the sample
                FetchHandle((), (), aux)._consume_aux()
            return []
        handle = FetchHandle(fetches, fetch_names, aux)
        if return_numpy:
            return handle.numpy()
        return handle

    # -- dispatch-plan machinery ----------------------------------------------
    def _resolve_plan(self, program, feed, fetch_names, scope, mesh,
                      accumulation_steps, mx_on, tr_on, use_program_cache,
                      sample_stats=True):
        """(plan, canonical feeds, state, was_compile_miss) for this run.

        The hit path does near-zero bookkeeping: one dict lookup on the
        Program-resident plan table plus a cheap per-feed shape/dtype check;
        anything that doesn't match falls through to the full (slow) path,
        which rebuilds the plan in place.
        """
        block = program.global_block
        is_test = in_test_mode()
        # Opt-in grad-norm gauge: the probe var is non-persistable (kept out
        # of checkpoints and the state signature), so it reaches the host as
        # a hidden extra fetch appended to the user's fetch list.
        grad_norm_fetch = bool(mx_on and GRAD_NORM_VAR in block.vars
                               and GRAD_NORM_VAR not in fetch_names)
        # PADDLE_TPU_CHECK_NUMERICS=2 compiles a GUARDED step variant (per-op
        # isfinite mask, _CompiledStep numerics=True) — part of both cache
        # keys so flipping the env var mid-process re-specializes instead of
        # silently reusing the unguarded step
        numerics = _dev.numerics_level() >= 2
        # PADDLE_TPU_NUMERICS>=1 compiles the STATS variant (packed per-op
        # stat rows, _CompiledStep stats=True) — this read is the entire
        # level-0 cost, and it joins both cache keys for the same
        # no-silent-reuse reason as the watchdog flag. Armed, only every
        # Nth chunk runs the stats variant (PADDLE_TPU_NUMERICS_EVERY,
        # chunk 0 always sampled): both variants sit side by side in the
        # plan/compile caches, so steady state alternates between two
        # cache hits and the per-op reduction cost is paid 1/N of the time
        stats = _num.stats_level() >= 1
        if stats and sample_stats:
            every = _num.stats_every()
            if every > 1:
                k = getattr(program, "_numerics_chunk", 0)
                program._numerics_chunk = k + 1
                stats = (k % every) == 0
        feed_names = tuple(sorted(feed))
        mesh_id = id(mesh) if mesh is not None else None
        # shapes are part of the key so alternating batch shapes (the last
        # partial batch of every epoch, train/eval interleave) each keep
        # their own plan instead of thrashing one slot; non-array feeds
        # (shape None) fall through to the per-feed spec check on hit
        feed_shapes = tuple(getattr(feed[n], "shape", None)
                            for n in feed_names)
        plan_key = (feed_names, feed_shapes, fetch_names, is_test, mesh_id,
                    accumulation_steps, grad_norm_fetch, numerics, stats)

        plans = None
        if use_program_cache:
            # plans live ON the Program (keyed by version) so they die with
            # it — an executor-held dict keyed by id(program) leaks entries
            # per mutation and can serve stale state after id() reuse
            entry = getattr(program, "_dispatch_plans", None)
            if entry is None or entry[0] != program._version:
                entry = (program._version, {})
                program._dispatch_plans = entry
            plans = entry[1]
            plan = plans.get(plan_key)
            if plan is not None:
                feeds = self._feeds_from_plan(plan, feed)
                if feeds is not None:
                    state = self._gather_plan_state(plan, scope)
                    if state is not None:
                        if mx_on:
                            _m_plan_hit.inc()
                            _m_cache_hit.inc()
                        return plan, feeds, state, False

        # ---- slow path: full per-run bookkeeping ----
        if mx_on:
            _m_plan_miss.inc()
        feeds = {}
        feed_sig = []
        feed_specs = []
        for name in feed_names:
            var = block.var(name) if block.has_var(name) else None
            if var is not None:
                dtype = var.dtype
            else:
                v0 = feed[name]
                dt0 = getattr(v0, "dtype", None)
                dtype = str(dt0) if dt0 is not None else np.asarray(v0).dtype.name
            arr = _canon(feed[name], dtype)
            feeds[name] = arr
            feed_sig.append((name, arr.shape, str(arr.dtype)))
            feed_specs.append((name, np.dtype(arr.dtype), arr.shape))

        cached = getattr(program, "_pnames_cache_entry", None)
        if cached is not None and cached[0] == program._version:
            state_names = cached[1]
        else:
            state_names = self._persistable_names(program, scope)
            program._pnames_cache_entry = (program._version, state_names)
        # state vars that actually exist (startup creates them on first run);
        # iteration follows the pre-sorted state_names so no per-step re-sort
        state = {}
        svars = scope.vars
        for n in state_names:
            v = svars.get(n)
            if v is None and scope.parent is not None:
                v = scope.find_var(n)
            if v is not None:
                state[n] = v
        avail_state_names = tuple(state)

        run_fetch_names = (fetch_names + (GRAD_NORM_VAR,)
                           if grad_norm_fetch else fetch_names)
        is_training_or_has_feed = bool(feeds) or bool(fetch_names)
        key = (
            id(program),
            program._version,
            tuple(feed_sig),
            run_fetch_names,
            avail_state_names,
            is_test,
            mesh_id,
            accumulation_steps,
            numerics,
            stats,
        )
        compiled = self._cache.get(key) if use_program_cache else None
        was_miss = compiled is None
        if compiled is None:
            from .log import vlog

            vlog(1, "Executor: compiling new step specialization "
                    "(program v%s, %d feeds, fetch=%s, test=%s)",
                 program._version, len(feed_sig), list(fetch_names), is_test)
            if mx_on:
                _m_cache_miss.inc()
            t_build = time.perf_counter() if mx_on else 0.0
            with _tr.span("executor/trace_setup", cat="executor",
                          args={"program_version": program._version,
                                "n_feeds": len(feed_sig)}) if tr_on \
                    else _NULL_CTX:
                compiled = _CompiledStep(
                    program,
                    feed_names,
                    run_fetch_names,
                    state_names,
                    is_test=is_test,
                    jit=is_training_or_has_feed,
                    mesh=mesh,
                    accumulation_steps=accumulation_steps,
                    numerics=numerics,
                    stats=stats,
                )
            if mx_on:
                _m_trace_ms.observe((time.perf_counter() - t_build) * 1e3)
            if use_program_cache:
                self._cache[key] = compiled
        elif mx_on:
            _m_cache_hit.inc()

        put_specs = batch_sh = mesh_repl = None
        if mesh is not None:
            # Mesh layout is a function of (program version, mesh) — memoize
            # the annotation walk on the plan instead of re-walking every
            # program var per run. Placement itself stays per-run (values
            # change); see _place.
            from jax.sharding import NamedSharding, PartitionSpec as P

            mesh_repl = _mesh_repl(mesh)
            put_specs = {}
            for v in program.list_vars():
                spec = getattr(v, "sharding", None)
                if _valid_sharding(spec, mesh):
                    put_specs[v.name] = NamedSharding(mesh, P(*spec))
            batch_sh = NamedSharding(mesh, _mesh_batch_spec(mesh))

        plan = _DispatchPlan(tuple(feed_specs), fetch_names, run_fetch_names,
                             grad_norm_fetch, numerics, stats, state_names,
                             avail_state_names, compiled, key, put_specs,
                             batch_sh, mesh_repl)
        if plans is not None:
            plans[plan_key] = plan
        return plan, feeds, state, was_miss

    @staticmethod
    def _feeds_from_plan(plan, feed):
        """Canonicalize ``feed`` against the plan's recorded dtypes; None on
        any shape mismatch (caller falls back to the slow path)."""
        feeds = {}
        for name, dt, shp in plan.feed_specs:
            v = feed[name]
            if isinstance(v, jax.ShapeDtypeStruct):
                if v.dtype != dt:
                    v = jax.ShapeDtypeStruct(v.shape, dt)
            else:
                if not isinstance(v, jax.Array):
                    v = np.asarray(v)
                if v.dtype != dt:
                    v = v.astype(dt)
            if v.shape != shp:
                return None
            feeds[name] = v
        return feeds

    @staticmethod
    def _gather_plan_state(plan, scope):
        state = {}
        svars = scope.vars
        parent = scope.parent
        for n in plan.state_names:
            v = svars.get(n)
            if v is None and parent is not None:
                v = scope.find_var(n)
            if v is not None:
                state[n] = v
        if tuple(state) != plan.avail_names:
            # scope membership changed since the plan was built (a var
            # loaded/erased — including same-COUNT swaps from partial
            # checkpoint loads) — rebuild so the specialization key, which
            # is keyed on the exact available-state tuple, stays honest
            return None
        return state

    def _place(self, plan, state, feeds, mesh):
        if mesh is not None:
            # Lay out state across the mesh: replicated by default (the Fluid
            # BCastParamsToDevices moment, parallel_executor.cc:340), or per
            # Variable.sharding annotation (model-parallel params, sharded
            # embeddings). Feeds shard on the data axis. No-op when already
            # laid out correctly.
            repl = plan.mesh_repl
            specs = plan.put_specs
            state = {k: jax.device_put(v, specs.get(k, repl))
                     for k, v in state.items()}
            feeds = {k: jax.device_put(v, plan.batch_sh)
                     for k, v in feeds.items()}
        else:
            if state:
                # State rides a donate_argnums=(0,) jit. Host (numpy)
                # entries — the scope right after a checkpoint load — MUST
                # become jax-OWNED copies first: on the CPU backend a
                # zero-copy device_put would alias the numpy buffer, and
                # donating an aliased buffer lets the async execution keep
                # using memory Python frees the moment the scope swaps in
                # the step's outputs (observed as rare corrupted/NaN state
                # in the first chunk after a restore). jax.Arrays pass
                # through untouched — the steady-state carry costs nothing.
                state = {k: v if isinstance(v, jax.Array) else jnp.array(v)
                         for k, v in state.items()}
            dev = self._device()
            if dev is not None and feeds:
                # jax.Arrays already on the right device skip the device_put —
                # re-placing them every step costs real host time. Arrays
                # committed elsewhere (e.g. fetched from a CPU executor) still
                # get moved like before.
                feeds = {k: v if isinstance(v, jax.Array) and dev in v.devices()
                         else jax.device_put(v, dev)
                         for k, v in feeds.items()}
        return state, feeds

    # -- fused multi-step driver ----------------------------------------------
    def run_steps(
        self,
        program: Optional[Program] = None,
        feed_iter=None,
        steps: Optional[int] = None,
        fetch_list: Optional[Sequence] = None,
        fetch_every: int = 1,
        scope: Optional[Scope] = None,
        return_numpy: bool = True,
    ):
        """Drive up to ``steps`` training steps, fusing ``fetch_every``
        consecutive steps into ONE dispatched call (a ``lax.scan`` over feed
        batches stacked on a new leading axis), so host dispatch cost per
        step drops to 1/``fetch_every`` and state never round-trips through
        the scope between fused steps.

        ``feed_iter`` yields one feed dict per step — a plain iterator, a
        generator, or a :class:`~paddle_tpu.reader.DevicePrefetcher` (its
        batches are drained directly; if run_steps is what starts it, it
        also stops it on return, so an early exit at ``steps`` never leaves
        the worker thread pinning device buffers — pre-start it or use its
        context manager to keep ownership). When omitted, started
        ``py_reader``\\ s bound to the program are drained instead, stopping
        cleanly at EOF. ``steps=None`` runs until the feed source is
        exhausted. A feed-shape change between chunks (the final partial
        batch of an epoch) transparently re-resolves the dispatch plan,
        like ``run()``'s per-shape plans.

        Returns per-step fetch rows (``return_numpy=True``: a list of
        ``[np.ndarray, ...]`` rows, bit-identical to ``steps`` individual
        ``run()`` calls) or one :class:`FetchHandle` per fused dispatch
        (``return_numpy=False``; a multi-step chunk's handle resolves to
        arrays whose leading axis is that chunk's step count, a
        single-step chunk's to plain per-fetch arrays like ``run()``).
        """
        program, mesh, accumulation_steps = self._unwrap_program(program, scope)
        if scope is None:
            scope = global_scope()
        fetch_names = self._fetch_names(fetch_list)
        k = max(1, int(fetch_every))
        # readers and the step counter stay bound to the source program; the
        # optimized clone owns plans/specializations (same split as run())
        src_program = program
        program = self._maybe_optimize(program, fetch_names, scope)

        owned_prefetcher = None
        if feed_iter is None:
            readers = [r for r in getattr(src_program, "_py_readers", ())
                       if r._started]
            if not readers:
                raise ValueError(
                    "run_steps() needs a feed_iter or a started py_reader "
                    "bound to the program")
            from .reader.py_reader import EOFException

            def _drain_readers():
                while True:
                    f = {}
                    try:
                        for r in readers:
                            f.update(r.next_feed())
                    except EOFException:
                        return
                    yield f

            feed_iter = _drain_readers()
        else:
            from .reader.prefetcher import DevicePrefetcher

            if (isinstance(feed_iter, DevicePrefetcher)
                    and feed_iter._thread is None):
                # we start it (via iter below), so we own its lifecycle:
                # stop it on exit so an early return at ``steps`` doesn't
                # leave the worker blocked holding device buffers. A
                # caller-started prefetcher (start() / context manager) is
                # the caller's to stop.
                owned_prefetcher = feed_iter
            feed_iter = iter(feed_iter)

        def _shape_sig(f):
            """(signature, feed) — list/scalar feed values (run() accepts
            them too) are converted to numpy ONCE here; the returned feed
            carries the converted arrays so canon never re-converts."""
            sig = []
            conv = None
            for n in sorted(f):
                v = f[n]
                shp = getattr(v, "shape", None)
                if shp is None:
                    v = np.asarray(v)
                    if conv is None:
                        conv = dict(f)
                    conv[n] = v
                    shp = v.shape
                sig.append((n, tuple(shp)))
            return tuple(sig), (conv if conv is not None else f)

        mx_on = _mx._enabled
        tr_on = _tr._active
        fr = _dev.flight_recorder()  # None unless PADDLE_TPU_FLIGHT_DIR set
        rows: List[Any] = []      # return_numpy=True: one row per step
        handles: List[FetchHandle] = []  # else: one handle per fused chunk
        state = None
        plan = None
        consumed = 0
        pending = None  # lookahead feed cut from the previous chunk
        try:
            while steps is None or consumed < steps:
                want = k if steps is None else min(k, steps - consumed)
                chunk = []
                sig0 = None
                while len(chunk) < want:
                    if pending is not None:
                        f, pending = pending, None
                    else:
                        try:
                            f = next(feed_iter)
                        except StopIteration:
                            break
                        except Exception as e:
                            # typed data-side error: names the step index
                            # within the chunk (and the global step), and
                            # rides the outer except into the flight dump
                            _faults.record_feed_error()
                            raise FeedError(
                                "run_steps(): feed source raised at global "
                                "step %d (position %d of the current "
                                "%d-step chunk): %s: %s"
                                % (consumed + len(chunk), len(chunk), want,
                                   type(e).__name__, e)) from e
                    try:
                        sig, f = _shape_sig(f)
                    except Exception as e:
                        _faults.record_feed_error()
                        raise FeedError(
                            "run_steps(): feed for global step %d (position "
                            "%d of the current %d-step chunk) could not be "
                            "converted to arrays: %s: %s"
                            % (consumed + len(chunk), len(chunk), want,
                               type(e).__name__, e)) from e
                    if chunk and sig != sig0:
                        # shape boundary (the epoch's final partial batch):
                        # cut the chunk here — stacking needs uniform
                        # shapes — and carry the odd feed into the next
                        # chunk, where the plan re-resolves for it
                        pending = f
                        break
                    sig0 = sig
                    chunk.append(f)
                if not chunk:
                    break

                chunk_was_miss = False
                if plan is not None:
                    try:
                        chunk_feeds = [self._canon_chunk_feed(plan, f)
                                       for f in chunk]
                    except ValueError:
                        # the feed shape changed mid-stream (the final
                        # partial batch of a real epoch): flush the live
                        # carry to the scope and re-resolve a plan for the
                        # new shape — mirrors run()'s per-shape plans. A
                        # shape mix WITHIN one chunk still raises below
                        # (it cannot be stacked).
                        for name, v in state.items():
                            if v is not None:
                                scope.set_var(name, v)
                        plan = None
                if plan is None:
                    # sample_stats=False: the resolved plan persists across
                    # the whole stream, so a sampled decision would freeze
                    # arbitrarily — armed run_steps chunks are always
                    # observed (one fused chunk is one EMA tick already)
                    plan, feeds0, state, chunk_was_miss = self._resolve_plan(
                        program, chunk[0], fetch_names, scope, mesh,
                        accumulation_steps, mx_on, tr_on, True,
                        sample_stats=False)
                    chunk_feeds = [feeds0]
                    chunk_feeds += [self._canon_chunk_feed(plan, f)
                                    for f in chunk[1:]]
                    state, _ = self._place(plan, state, {}, mesh)

                n = len(chunk_feeds)
                step_idx0 = self._next_step_index(src_program, n)
                if n == 1:
                    _, stacked = self._place(plan, {}, chunk_feeds[0], mesh)
                    compiled = plan.compiled
                else:
                    stacked = {name: jnp.stack([f[name] for f in chunk_feeds])
                               for name, _, _ in plan.feed_specs}
                    if mesh is None:
                        _, stacked = self._place(plan, {}, stacked, mesh)
                    # with a mesh, the chain's in_shardings (step axis
                    # replicated, batch axis over ``data``) lay the stack out
                    compiled, chain_miss = self._chain_for(plan, n)
                    chunk_was_miss = chunk_was_miss or chain_miss

                if fr is not None:
                    fr.record_step(
                        "run_steps", src_program, plan.feed_specs,
                        fetch_names,
                        extra={"chunk_steps": n,
                               "optimized": _dev.program_fingerprint(program)})
                spec = _faults.fire("executor.dispatch")
                if spec is not None and spec.kind == "nan":
                    stacked = _faults.poison_feeds(stacked)
                t0 = time.perf_counter() if mx_on else 0.0
                if tr_on:
                    with _tr.span("executor/run_steps_chunk", cat="executor",
                                  args={"steps": n}):
                        state, fetches = compiled(state, stacked, step_idx0)
                else:
                    state, fetches = compiled(state, stacked, step_idx0)
                if mx_on:
                    # a fresh specialization/chain pays its jit trace + XLA
                    # compile on this first call — route that to the compile
                    # histogram so the steady-state chunk histogram stays
                    # clean, mirroring run()'s miss/hit split
                    (_m_compile_ms if chunk_was_miss else _m_chain_ms).observe(
                        (time.perf_counter() - t0) * 1e3)
                    _m_chain_dispatches.inc()
                    _m_chain_steps.inc(n)
                    _m_feed_bytes.inc(_nbytes(stacked.values()))
                    # keep the HBM signal alive for pipeline-driven jobs,
                    # same sampling policy as run()
                    if int(_m_chain_dispatches.value) % _HBM_SAMPLE_EVERY \
                            in (1, 0):
                        _update_hbm_gauges()
                consumed += n

                if plan.stats:
                    # stat rows pop first (stacked [n, K, S] for a fused
                    # chunk); accumulated before the watchdog check so the
                    # trip chunk's range history still lands host-side
                    _num.accumulate(
                        fetches[-1], plan.compiled.stats_layout,
                        fingerprint=_dev.program_fingerprint(src_program),
                        driver="run_steps")
                    fetches = fetches[:-1]
                mask = None
                if plan.numerics:
                    # the per-op isfinite mask rides last; a fused chunk's is
                    # stacked [n, K], so a NaN is attributed to BOTH the
                    # originating op and the step inside the chunk — the old
                    # post-step scan saw only the k-th step's fetches
                    mask = fetches[-1]
                    fetches = fetches[:-1]
                aux = None
                if plan.grad_norm_fetch:
                    aux = fetches[-1]
                    fetches = fetches[:-1]
                if mask is not None:
                    _dev.check_numerics_mask(mask, plan.compiled.watch_layout,
                                             driver="run_steps")
                _enforce_step_flags(plan.fetch_names, fetches, state)
                if not fetch_names:
                    if aux is not None:
                        FetchHandle((), (), aux)._consume_aux()
                    continue
                handle = FetchHandle(fetches, fetch_names, aux)
                if not return_numpy:
                    handles.append(handle)
                elif n == 1:
                    rows.append(handle.numpy())
                else:
                    arrs = handle.numpy()
                    rows.extend([a[i] for a in arrs] for i in range(n))
        except Exception as e:
            if fr is None:
                fr = _dev.flight_recorder()
            _safe_flight_dump(fr, "executor.run_steps", e)
            raise
        finally:
            # Donation consumed the scope's old state buffers at the first
            # dispatch — write the live carry back even on an error mid-loop.
            # Best-effort: if the FAILING dispatch itself already consumed
            # the carry via donation, those arrays are deleted and writing
            # them would poison the scope — skip them (recoverability after
            # a post-donation failure is inherently limited, same as run()).
            if state is not None:
                for name, v in state.items():
                    if v is None:
                        continue
                    if isinstance(v, jax.Array):
                        deleted = getattr(v, "is_deleted", None)
                        if deleted is not None and deleted():
                            continue
                    scope.set_var(name, v)
            if owned_prefetcher is not None:
                # we started it; stopping releases the worker thread and its
                # buffered device batches when we return before exhaustion
                owned_prefetcher.stop()

        if not fetch_names:
            return []
        return rows if return_numpy else handles

    def _canon_chunk_feed(self, plan, feed):
        try:
            feeds = self._feeds_from_plan(plan, feed)
        except KeyError:  # a feed name vanished mid-stream
            feeds = None
        if feeds is None or len(feed) != len(plan.feed_specs):
            raise ValueError(
                "run_steps(): feed dict changed shape/dtype/names mid-stream; "
                "expected %s" % [(n, str(d), s) for n, d, s in plan.feed_specs])
        return feeds

    def _chain_for(self, plan, length: int):
        """(chain, was_miss) — the fused-chain specialization for ``plan``."""
        key = plan.key + ("chain", length)
        chain = self._cache.get(key)
        was_miss = chain is None
        if chain is None:
            from .log import vlog

            vlog(1, "Executor: building fused %d-step chain", length)
            if _mx._enabled:
                _m_cache_miss.inc()
            chain = _CompiledStepChain(plan.compiled, length)
            self._cache[key] = chain
        elif _mx._enabled:
            _m_cache_hit.inc()
        return chain, was_miss

    # -- AOT warmup -----------------------------------------------------------
    def prepare(
        self,
        program: Optional[Program] = None,
        feed: Optional[Dict[str, Any]] = None,
        fetch_list: Optional[Sequence] = None,
        scope: Optional[Scope] = None,
    ):
        """Ahead-of-time build + XLA-compile the step specialization for
        ``feed`` WITHOUT executing it (the TVM-style AOT artifact path).

        ``feed`` values may be real arrays, ``jax.ShapeDtypeStruct``\\ s, or
        ``(shape, dtype)`` tuples — only shapes/dtypes matter. With
        ``PADDLE_TPU_COMPILE_CACHE`` set, the XLA executable lands in the
        persistent cache, so a later process (``tools/warmup.py`` then the
        real job) skips the compile entirely. Accepts a ``CompiledProgram``
        like ``run()`` (its mesh specialization is what gets AOT-compiled).
        Returns the cached ``_CompiledStep``.
        """
        program, mesh, accumulation_steps = self._unwrap_program(program, scope)
        if scope is None:
            scope = global_scope()
        feed = dict(feed or {})
        fetch_names = self._fetch_names(fetch_list)
        # AOT-compile the OPTIMIZED program — the same object run() resolves,
        # so the warmed specialization (and persistent-cache entry) is the
        # one the real job hits
        program = self._maybe_optimize(program, fetch_names, scope)
        block = program.global_block
        abstract = {}
        for name in sorted(feed):
            v = feed[name]
            if isinstance(v, jax.ShapeDtypeStruct):
                abstract[name] = v
                continue
            if isinstance(v, tuple) and len(v) == 2 and not hasattr(v, "dtype"):
                shape, dtype = v
            else:
                arr = v if hasattr(v, "shape") else np.asarray(v)
                shape, dtype = arr.shape, arr.dtype
            var = block.var(name) if block.has_var(name) else None
            target = to_jnp_dtype(var.dtype) if var is not None else dtype
            canonical = jax.dtypes.canonicalize_dtype(target)
            abstract[name] = jax.ShapeDtypeStruct(tuple(shape), canonical)

        # the plan machinery accepts abstract feeds, so prepare() and a later
        # run() at the same shapes share one plan + specialization entry
        plan, _, state, _ = self._resolve_plan(
            program, abstract, fetch_names, scope, mesh, accumulation_steps,
            _mx._enabled, _tr._active, True)
        compiled = plan.compiled
        if not compiled.jitted:
            return compiled
        abstract_state = _abstractify(state)
        lowered, aot = _timed_lower_compile(
            compiled.fn, (abstract_state, abstract,
                          jax.ShapeDtypeStruct((), np.dtype("uint32"))))
        # the AOT artifacts are the attribution surface: the executable's
        # cost_analysis/memory_analysis feed the device_profile/* gauges
        # (memory_report, tools/profile_report read them), and the lowered
        # module keeps the FULL per-op named-scope coverage that XLA's
        # fusion passes strip from the compiled text
        # (monitor.device.lowered_scope_text) — free here, prepare() paid
        # the lower+compile anyway
        compiled._lowered = lowered
        compiled._aot = aot
        _dev.publish_compiled_analysis(aot)
        return compiled

    @staticmethod
    def _publish_device_profile(compiled, state, feeds):
        """``PADDLE_TPU_DEVICE_PROFILE=1`` compile-miss hook: AOT-lower this
        specialization at abstract shapes and publish the device_profile/*
        gauges. Costs an extra trace (+ an XLA compile served from the
        persistent cache when ``PADDLE_TPU_COMPILE_CACHE`` is set) — a
        debug opt-in, never on the default path, never raising into the
        step."""
        try:
            abstract_state, abstract_feeds = jax.tree_util.tree_map(
                lambda v: jax.ShapeDtypeStruct(
                    tuple(getattr(v, "shape", ())),
                    getattr(v, "dtype", np.float32)),
                (state, feeds))
            aot = compiled.fn.lower(
                abstract_state, abstract_feeds,
                jax.ShapeDtypeStruct((), np.dtype("uint32"))).compile()
            compiled._aot = aot
            _dev.publish_compiled_analysis(aot)
        except Exception as e:
            from .log import vlog

            vlog(1, "device-profile analysis failed: %r", e)

    def memory_report(self, program=None, feed=None, fetch_list=None,
                      scope=None):
        """The authoritative pre-run memory figure for a compiled step:
        AOT-compile the (program, feed-spec) specialization WITHOUT running
        it and return ``compiled.memory_analysis()`` as a dict
        (``argument_bytes`` / ``output_bytes`` / ``temp_bytes`` /
        ``peak_hbm_bytes`` ...). ``feed`` takes the same abstract specs as
        :meth:`prepare` (``(shape, dtype)`` tuples suffice). Run the startup
        program first so parameters are part of the figure. This is the
        number ``contrib.utils.memory_usage``'s pre-trace estimate defers
        to, and the first thing to check after a RESOURCE_EXHAUSTED."""
        compiled = self.prepare(program, feed, fetch_list, scope)
        return _dev.memory_report_from(getattr(compiled, "_aot", None))

    # Fluid parity alias
    def infer_from_program(self, *a, **kw):
        return self.run(*a, **kw)
