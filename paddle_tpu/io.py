"""Checkpoint / model IO (reference: python/paddle/fluid/io.py:92-1015).

Fluid builds tiny save/load programs of ``save``/``load_combine`` ops
(``operators/save_op.cc``, ``load_combine_op.cc:143``) that serialize
LoDTensors. The TPU-native equivalent serializes the scope's pytree state
directly (numpy .npz — host-side, no device round trip besides D2H), and the
inference artifact is the pruned Program's JSON desc plus its params —
the role ``save_inference_model`` plays in Fluid.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import List, Optional, Sequence

import numpy as np

from .core import serialization
from .core.framework import Parameter, Program, Variable, default_main_program
from .core.scope import Scope, global_scope

__all__ = [
    "save_vars",
    "save_params",
    "save_persistables",
    "load_vars",
    "load_params",
    "load_persistables",
    "save_inference_model",
    "load_inference_model",
]

_COMBINED_DEFAULT = "__params__"
_MODEL_FILENAME = "__model__"


def _target_vars(main_program: Optional[Program], predicate) -> List[Variable]:
    program = main_program or default_main_program()
    out = []
    seen = set()
    for v in program.list_vars():
        if v.name in seen:
            continue
        if predicate(v):
            out.append(v)
            seen.add(v.name)
    return out


def _is_persistable(v: Variable) -> bool:
    return v.persistable and not v.is_data


def _is_parameter(v: Variable) -> bool:
    return isinstance(v, Parameter)


def save_vars(executor, dirname, main_program=None, vars=None, predicate=None, filename=None):
    """reference: io.py:92 — saves to one .npy per var, or a combined .npz."""
    scope = global_scope()
    if vars is None:
        vars = _target_vars(main_program, predicate or _is_persistable)
    os.makedirs(dirname, exist_ok=True)
    arrays = {}
    for v in vars:
        name = v.name if isinstance(v, Variable) else str(v)
        val = scope.find_var(name)
        if val is None:
            raise RuntimeError("save_vars: %r not found in scope (run startup first)" % name)
        # copy=True, not a view: np.asarray of a CPU-backend jax array can
        # be ZERO-COPY, and the very next fused chunk DONATES these state
        # buffers — a view captured here would then alias memory XLA is
        # about to scribble outputs into (observed as rare non-determinism
        # in the rollback drill's post-checkpoint chunk)
        arrays[name] = np.array(val, copy=True)
    if filename is None:
        for name, arr in arrays.items():
            np.save(os.path.join(dirname, name.replace("/", "__") + ".npy"), arr)
        index = {"vars": sorted(arrays), "combined": None}
    else:
        np.savez(os.path.join(dirname, filename + ".npz"), **arrays)
        index = {"vars": sorted(arrays), "combined": filename}
    with open(os.path.join(dirname, "__index__.json"), "w") as f:
        json.dump(index, f)


def save_params(executor, dirname, main_program=None, filename=None):
    """reference: io.py save_params — trainable Parameters only."""
    save_vars(executor, dirname, main_program, predicate=_is_parameter, filename=filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    """reference: io.py:441 — all persistables (params + optimizer state +
    BN stats + counters), sufficient for exact training resume."""
    save_vars(executor, dirname, main_program, predicate=_is_persistable, filename=filename)


def load_vars(executor, dirname, main_program=None, vars=None, predicate=None, filename=None):
    """reference: io.py load_vars."""
    scope = global_scope()
    with open(os.path.join(dirname, "__index__.json")) as f:
        index = json.load(f)
    if vars is not None:
        wanted = [v.name if isinstance(v, Variable) else str(v) for v in vars]
    elif predicate is not None or main_program is not None:
        wanted = [v.name for v in _target_vars(main_program, predicate or _is_persistable)]
    else:
        wanted = index["vars"]
    if index.get("combined"):
        data = np.load(os.path.join(dirname, index["combined"] + ".npz"))
        store = {n: data[n] for n in data.files}
    else:
        store = None
    def _lookup(name):
        if store is not None:
            return store.get(name)
        path = os.path.join(dirname, name.replace("/", "__") + ".npy")
        return np.load(path) if os.path.exists(path) else None

    missing = []
    for name in wanted:
        arr = _lookup(name)
        if arr is None and "_qkv" in name:
            # r5 migration: attention stores ONE merged qkv projection (the
            # split form's concat backward blocked optimizer fusion, see
            # layers/attention.py); checkpoints from earlier builds hold
            # three separate q/k/v weights (and adam moments) — concat them
            # on load. Shapes: [d_in, d'] x3 -> [d_in, 3d'].
            parts = [_lookup(name.replace("_qkv", s, 1))
                     for s in ("_q", "_k", "_v")]
            if all(p is not None for p in parts):
                arr = np.concatenate(parts, axis=1)
        if arr is None:
            # r4 layout change: Adam keeps ONE shared beta-pow pair (vs the
            # earlier per-param scalars). Either direction, every pow var of
            # the same beta is numerically identical — fill a missing one
            # from any checkpointed sibling.
            m = re.search(r"_(adam\w*)_(beta[12]_pow_acc)", name)
            if m is not None:
                pat = "_%s_%s" % (m.group(1), m.group(2))
                if store is not None:
                    cands = [store[k] for k in store if pat in k]
                else:
                    cands = [np.load(h) for h in glob.glob(os.path.join(
                        dirname, "*%s*.npy" % pat.replace("/", "__")))]
                if cands:
                    # refuse ambiguity: with several Adam instances at
                    # different step counts the siblings differ — silently
                    # picking one would skew bias correction on resume
                    if any(not np.array_equal(c, cands[0]) for c in cands[1:]):
                        raise RuntimeError(
                            "load_vars: cannot migrate %r — checkpoint holds "
                            "multiple distinct %s values (several Adam "
                            "instances?); rename or load explicitly"
                            % (name, pat))
                    arr = cands[0]
        if arr is None:
            missing.append(name)
            continue
        scope.set_var(name, arr)
    if missing:
        raise RuntimeError("load_vars: missing from checkpoint: %s" % missing)
    # A load swaps state under cached optimizations: passes that folded
    # VALUES (conv+bn weight folding) baked the pre-load params into derived
    # scope vars. Bumping the version invalidates the program's optimization
    # + dispatch-plan caches so the next run re-derives from the fresh state.
    # load_params/load_persistables default main_program=None but still load
    # into default_main_program()'s vars — bump that one then. (Programs the
    # bump can't reach — e.g. eval clones — are protected value-wise: the
    # conv+bn fold records the scope objects it read and the optimizer memo
    # re-validates them by identity, passes/pipeline._fold_sources_fresh.)
    (main_program if main_program is not None
     else default_main_program())._version += 1


def load_params(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program, predicate=_is_parameter, filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    """reference: io.py:658."""
    load_vars(executor, dirname, main_program, predicate=_is_persistable, filename=filename)


# -- program pruning ----------------------------------------------------------


def prune_program(program: Program, feed_names: Sequence[str], target_names: Sequence[str]) -> Program:
    """Reverse-reachability prune of block 0 to the feed→target subgraph
    (reference: framework/prune.cc via Program._prune)."""
    pruned = program.clone(for_test=True)
    block = pruned.global_block
    needed = set(target_names)
    kept = []
    for op in reversed(block.ops):
        if any(o in needed for o in op.output_arg_names):
            kept.append(op)
            needed.update(op.input_arg_names)
    kept.reverse()
    block.ops = kept
    referenced = set(feed_names) | set(target_names)
    for op in kept:
        referenced.update(op.input_arg_names)
        referenced.update(op.output_arg_names)
    block.vars = {n: v for n, v in block.vars.items() if n in referenced}
    pruned._version += 1
    return pruned


def save_inference_model(
    dirname,
    feeded_var_names: Sequence[str],
    target_vars: Sequence[Variable],
    executor,
    main_program: Optional[Program] = None,
    model_filename: Optional[str] = None,
    params_filename: Optional[str] = None,
    export_for_deployment: bool = True,
):
    """reference: io.py:863 — prunes to the inference subgraph, embeds
    feed/fetch names, and saves the params the subgraph needs."""
    program = main_program or default_main_program()
    target_names = [v.name if isinstance(v, Variable) else str(v) for v in target_vars]
    pruned = prune_program(program, feeded_var_names, target_names)
    desc = serialization.program_to_desc(pruned)
    desc["feed_names"] = list(feeded_var_names)
    desc["fetch_names"] = target_names
    os.makedirs(dirname, exist_ok=True)
    with open(os.path.join(dirname, model_filename or _MODEL_FILENAME), "w") as f:
        json.dump(desc, f)
    needed_params = [
        v for v in pruned.global_block.vars.values() if v.persistable and not v.is_data
    ]
    save_vars(executor, dirname, vars=needed_params, filename=params_filename or _COMBINED_DEFAULT)
    return target_names


def load_inference_model(dirname, executor, model_filename=None, params_filename=None):
    """reference: io.py:1015 — returns (program, feed_names, fetch_names)."""
    with open(os.path.join(dirname, model_filename or _MODEL_FILENAME)) as f:
        desc = json.load(f)
    program = serialization.desc_to_program(desc)
    load_vars(executor, dirname, vars=None, filename=params_filename or _COMBINED_DEFAULT)
    return program, desc.get("feed_names", []), desc.get("fetch_names", [])


# -- rotating checkpoints + preemption resume ---------------------------------
# (reference: contrib/trainer.py CheckpointConfig:100 + the Trainer's
# _save_checkpoint/_load_checkpoint; SURVEY §5.3/5.4 elastic resume)

_CKPT_PREFIX = "checkpoint_"
_SUCCESS_MARK = "_SUCCESS"


class CheckpointConfig:
    """reference: contrib/trainer.py:100."""

    def __init__(self, checkpoint_dir=None, max_num_checkpoints=3,
                 epoch_interval=1, step_interval=10):
        self.checkpoint_dir = checkpoint_dir or os.getcwd()
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = epoch_interval
        self.step_interval = step_interval


def _fsync_path(path):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_tree(dirpath):
    """fsync every file under ``dirpath`` and the directories themselves —
    the durability barrier a host crash between write and the
    ``os.replace`` publish requires: without it a _SUCCESS-marked
    checkpoint can survive the rename while its tensor payloads are still
    unflushed page cache (a torn checkpoint that LOOKS complete).
    Best-effort on filesystems without fsync semantics."""
    try:
        for root, dirs, files in os.walk(dirpath):
            for name in files:
                _fsync_path(os.path.join(root, name))
            for name in dirs:
                _fsync_path(os.path.join(root, name))
        _fsync_path(dirpath)
    except OSError:
        pass


def _checkpoint_serials(checkpoint_dir):
    if not os.path.isdir(checkpoint_dir):
        return []
    serials = []
    for name in os.listdir(checkpoint_dir):
        if name.startswith(_CKPT_PREFIX):
            try:
                serials.append(int(name[len(_CKPT_PREFIX):]))
            except ValueError:
                continue
    return sorted(serials)


def save_checkpoint(executor, checkpoint_dir, main_program=None,
                    trainer_id=0, trainer_args=None, max_num_checkpoints=3):
    """Write a new serial-numbered checkpoint of all persistables, durably
    and atomically: param files + trainer_args + _SUCCESS marker land in a
    tmp dir, everything is fsync'd (files AND directory — a host crash
    between write and publish must not leave a _SUCCESS-marked torn
    checkpoint), then one ``os.replace`` publishes the serial. Rotation is
    performed ONLY by ``trainer_id == 0`` so concurrent multi-trainer
    savers can't race-delete each other's serials. ``trainer_args``
    (e.g. {'step': 123, 'epoch': 4}) are stored for resume bookkeeping."""
    serials = _checkpoint_serials(checkpoint_dir)
    serial = (serials[-1] + 1) if serials else 0
    final = os.path.join(checkpoint_dir, _CKPT_PREFIX + str(serial))
    # the staging dir is per-trainer (and per-process): two trainers that
    # race to the same serial stage into DIFFERENT dirs, so neither can
    # rmtree the other's half-written payload or publish a mixed dir
    tmp = "%s.tmp.%d.%d" % (final, trainer_id, os.getpid())
    if os.path.isdir(tmp):
        import shutil

        shutil.rmtree(tmp)
    save_persistables(executor, tmp, main_program)
    # chaos drills: an injected fault HERE leaves an unpublished .tmp dir —
    # exactly the torn-write state load_checkpoint must skip
    from .reliability import faults as _faults

    _faults.fire("io.save_checkpoint")
    with open(os.path.join(tmp, "trainer_args.json"), "w") as f:
        json.dump({"trainer_id": trainer_id, **(trainer_args or {})}, f)
    with open(os.path.join(tmp, _SUCCESS_MARK), "w") as f:
        f.write("ok")
        f.flush()
        os.fsync(f.fileno())
    _fsync_tree(tmp)
    try:
        os.replace(tmp, final)
    except OSError:
        # lost the publish race: a concurrent trainer already published
        # this serial (same persistable state — both savers hold replicas).
        # Drop our staging copy; the peer's checkpoint serves the resume.
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
        if not os.path.isfile(os.path.join(final, _SUCCESS_MARK)):
            raise
        return serial
    # publish barrier: the rename itself must survive the crash
    try:
        _fsync_path(checkpoint_dir)
    except OSError:
        pass
    if trainer_id == 0:
        serials.append(serial)
        import shutil

        for old in (serials[:-max_num_checkpoints]
                    if max_num_checkpoints > 0 else []):
            shutil.rmtree(
                os.path.join(checkpoint_dir, _CKPT_PREFIX + str(old)),
                ignore_errors=True)
    return serial


def load_checkpoint(executor, checkpoint_dir, main_program=None, serial=None):
    """Restore the latest complete checkpoint (or ``serial``); returns the
    stored trainer_args dict, or None if no valid checkpoint exists — the
    auto-resume contract: call at startup, train from scratch on None.

    Torn-restore fallback: a _SUCCESS-marked checkpoint whose payload is
    unreadable (truncated tensor file, disk corruption) is logged and
    SKIPPED in favour of the previous serial instead of raising
    mid-restore with the scope half-loaded — the fallback serial's full
    ``load_persistables`` overwrites any partially-set vars."""
    from .log import vlog

    serials = _checkpoint_serials(checkpoint_dir)
    candidates = [serial] if serial is not None else list(reversed(serials))
    last_exc = None
    for s in candidates:
        d = os.path.join(checkpoint_dir, _CKPT_PREFIX + str(s))
        if not os.path.isfile(os.path.join(d, _SUCCESS_MARK)):
            continue  # partial write (preempted mid-save) — skip
        try:
            load_persistables(executor, d, main_program)
        except Exception as e:
            last_exc = e
            vlog(0, "load_checkpoint: serial %d is _SUCCESS-marked but "
                    "unreadable (%s: %s); falling back to the previous "
                    "serial", s, type(e).__name__, e)
            continue
        try:
            with open(os.path.join(d, "trainer_args.json")) as f:
                return json.load(f)
        except FileNotFoundError:
            return {}
    if last_exc is not None:
        # every _SUCCESS candidate was torn: surface the corruption rather
        # than silently training from scratch over a half-loaded scope
        raise RuntimeError(
            "load_checkpoint: no readable checkpoint in %r (all "
            "_SUCCESS-marked serials failed to restore; last error: %s: %s)"
            % (checkpoint_dir, type(last_exc).__name__, last_exc)
        ) from last_exc
    return None


def clean_checkpoint(checkpoint_dir, delete_dir=False):
    """reference: io.py clean_checkpoint."""
    import shutil

    for s in _checkpoint_serials(checkpoint_dir):
        shutil.rmtree(os.path.join(checkpoint_dir, _CKPT_PREFIX + str(s)),
                      ignore_errors=True)
    if delete_dir and os.path.isdir(checkpoint_dir):
        try:
            os.rmdir(checkpoint_dir)
        except OSError:
            pass


__all__ += ["CheckpointConfig", "save_checkpoint", "load_checkpoint",
            "clean_checkpoint"]
