"""Python-side metric accumulators (reference: python/paddle/fluid/metrics.py)."""

from __future__ import annotations

import numpy as np

__all__ = ["MetricBase", "Accuracy", "Precision", "Recall", "Auc",
           "EditDistance", "CompositeMetric", "ChunkEvaluator",
           "DetectionMAP"]


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        for k, v in self.__dict__.items():
            if k.startswith("_"):
                continue
            if isinstance(v, (int, float)):
                setattr(self, k, 0)
            elif isinstance(v, np.ndarray):
                setattr(self, k, np.zeros_like(v))

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(value) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("Accuracy: no updates yet")
        return self.value / self.weight


class Precision(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(int).reshape(-1)
        labels = np.asarray(labels).astype(int).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(int).reshape(-1)
        labels = np.asarray(labels).astype(int).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class Auc(MetricBase):
    """Histogram AUC accumulator matching the in-graph auc op."""

    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._num = num_thresholds + 1
        self.stat_pos = np.zeros(self._num, dtype=np.float64)
        self.stat_neg = np.zeros(self._num, dtype=np.float64)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        pos_prob = preds[:, 1] if preds.ndim == 2 and preds.shape[1] == 2 else preds.reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        bucket = np.clip((pos_prob * self._num).astype(int), 0, self._num - 1)
        for b, l in zip(bucket, labels):
            if l > 0:
                self.stat_pos[b] += 1
            else:
                self.stat_neg[b] += 1

    def eval(self):
        tot_pos = self.stat_pos.sum()
        tot_neg = self.stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        pos_above = tot_pos - np.cumsum(self.stat_pos)
        auc_sum = np.sum(self.stat_neg * (pos_above + self.stat_pos * 0.5))
        return float(auc_sum / (tot_pos * tot_neg))


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num=None):
        distances = np.asarray(distances).reshape(-1)
        self.total_distance += float(distances.sum())
        self.seq_num += seq_num if seq_num is not None else len(distances)
        self.instance_error += int(np.sum(distances > 0))

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("EditDistance: no updates yet")
        return (self.total_distance / self.seq_num,
                self.instance_error / self.seq_num)


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class DetectionMAP(MetricBase):
    """Mean average precision over padded detection outputs (reference:
    python/paddle/fluid/metrics.py DetectionMAP + detection_map_op.cc).

    ``update(det, det_len, gt)``:
    - det: [B, K, 6] rows (label, score, x1, y1, x2, y2), -1-padded
      (multiclass_nms output convention)
    - det_len: [B] valid counts (the Length output)
    - gt: [B, Ng, 5] rows (label, x1, y1, x2, y2); zero-area rows pad

    ``eval()`` → mAP with ``ap_version`` 'integral' or '11point'.
    """

    def __init__(self, name=None, overlap_threshold=0.5, evaluate_difficult=True,
                 ap_version="integral"):
        super().__init__(name)
        if ap_version not in ("integral", "11point"):
            raise ValueError("ap_version must be 'integral' or '11point'")
        self.overlap_threshold = overlap_threshold
        self.ap_version = ap_version
        self.reset()

    def reset(self, executor=None, reset_program=None):
        self._gt_count = {}        # class -> total gt
        self._records = {}         # class -> list of (score, tp)

    @staticmethod
    def _iou(a, b):
        ix = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
        iy = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
        inter = ix * iy
        ua = ((a[2] - a[0]) * (a[3] - a[1]) + (b[2] - b[0]) * (b[3] - b[1])
              - inter)
        return inter / ua if ua > 0 else 0.0

    def update(self, det, det_len, gt):
        import numpy as np

        det = np.asarray(det)
        det_len = np.asarray(det_len).astype(int)
        gt = np.asarray(gt)
        for b in range(det.shape[0]):
            gts = [g for g in gt[b] if g[3] > g[1] and g[4] > g[2]]
            for g in gts:
                c = int(g[0])
                self._gt_count[c] = self._gt_count.get(c, 0) + 1
            used = [False] * len(gts)
            rows = det[b, :det_len[b]]
            for lab, score, *box in sorted(rows.tolist(), key=lambda r: -r[1]):
                c = int(lab)
                best, best_j = 0.0, -1
                for j, g in enumerate(gts):
                    if int(g[0]) != c or used[j]:
                        continue
                    ov = self._iou(box, g[1:5])
                    if ov > best:
                        best, best_j = ov, j
                tp = best >= self.overlap_threshold and best_j >= 0
                if tp:
                    used[best_j] = True
                self._records.setdefault(c, []).append((float(score), bool(tp)))

    def eval(self, executor=None):
        import numpy as np

        aps = []
        for c, total in self._gt_count.items():
            recs = sorted(self._records.get(c, []), key=lambda r: -r[0])
            if total == 0:
                continue
            tp_cum = fp_cum = 0
            precisions, recalls = [], []
            for _, tp in recs:
                tp_cum += tp
                fp_cum += not tp
                precisions.append(tp_cum / (tp_cum + fp_cum))
                recalls.append(tp_cum / total)
            if not recs:
                aps.append(0.0)
                continue
            if self.ap_version == "integral":
                ap, prev_r = 0.0, 0.0
                for p, r in zip(precisions, recalls):
                    ap += p * (r - prev_r)
                    prev_r = r
            else:  # 11point
                ap = 0.0
                for t in np.linspace(0, 1, 11):
                    ps = [p for p, r in zip(precisions, recalls) if r >= t]
                    ap += (max(ps) if ps else 0.0) / 11.0
            aps.append(ap)
        return float(np.mean(aps)) if aps else 0.0


class ChunkEvaluator(MetricBase):
    """Accumulate chunk_eval op counters across mini-batches and derive
    precision/recall/F1 (reference: python/paddle/fluid/metrics.py:359)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self, executor=None, reset_program=None):
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        import numpy as np

        self.num_infer_chunks += int(np.asarray(num_infer_chunks).sum())
        self.num_label_chunks += int(np.asarray(num_label_chunks).sum())
        self.num_correct_chunks += int(np.asarray(num_correct_chunks).sum())

    def eval(self, executor=None):
        precision = (self.num_correct_chunks / self.num_infer_chunks
                     if self.num_infer_chunks else 0.0)
        recall = (self.num_correct_chunks / self.num_label_chunks
                  if self.num_label_chunks else 0.0)
        f1 = (2 * precision * recall / (precision + recall)
              if self.num_correct_chunks else 0.0)
        return precision, recall, f1
