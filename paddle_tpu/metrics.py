"""Python-side metric accumulators (reference: python/paddle/fluid/metrics.py)."""

from __future__ import annotations

import numpy as np

__all__ = ["MetricBase", "Accuracy", "Precision", "Recall", "Auc",
           "EditDistance", "CompositeMetric"]


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        for k, v in self.__dict__.items():
            if k.startswith("_"):
                continue
            if isinstance(v, (int, float)):
                setattr(self, k, 0)
            elif isinstance(v, np.ndarray):
                setattr(self, k, np.zeros_like(v))

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(value) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("Accuracy: no updates yet")
        return self.value / self.weight


class Precision(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(int).reshape(-1)
        labels = np.asarray(labels).astype(int).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(int).reshape(-1)
        labels = np.asarray(labels).astype(int).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class Auc(MetricBase):
    """Histogram AUC accumulator matching the in-graph auc op."""

    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._num = num_thresholds + 1
        self.stat_pos = np.zeros(self._num, dtype=np.float64)
        self.stat_neg = np.zeros(self._num, dtype=np.float64)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        pos_prob = preds[:, 1] if preds.ndim == 2 and preds.shape[1] == 2 else preds.reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        bucket = np.clip((pos_prob * self._num).astype(int), 0, self._num - 1)
        for b, l in zip(bucket, labels):
            if l > 0:
                self.stat_pos[b] += 1
            else:
                self.stat_neg[b] += 1

    def eval(self):
        tot_pos = self.stat_pos.sum()
        tot_neg = self.stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        pos_above = tot_pos - np.cumsum(self.stat_pos)
        auc_sum = np.sum(self.stat_neg * (pos_above + self.stat_pos * 0.5))
        return float(auc_sum / (tot_pos * tot_neg))


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num=None):
        distances = np.asarray(distances).reshape(-1)
        self.total_distance += float(distances.sum())
        self.seq_num += seq_num if seq_num is not None else len(distances)
        self.instance_error += int(np.sum(distances > 0))

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("EditDistance: no updates yet")
        return (self.total_distance / self.seq_num,
                self.instance_error / self.seq_num)


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]
