"""Memory-optimization transpiler shims (reference:
transpiler/memory_optimization_transpiler.py — liveness-based var reuse).

XLA's buffer assignment performs this optimization (and more: liveness,
aliasing, donation) on every compile, so these are accepted no-ops kept for
script compatibility.
"""

from __future__ import annotations

__all__ = ["memory_optimize", "release_memory"]


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=False):
    return input_program


def release_memory(input_program, skip_opt_set=None):
    return input_program
