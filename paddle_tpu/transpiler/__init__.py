from .distribute_transpiler import DistributeTranspiler, DistributeTranspilerConfig  # noqa: F401
from .fuse_passes import ConvBNFusePass  # noqa: F401
from .memory_optimization import memory_optimize, release_memory  # noqa: F401


class InferenceTranspiler:
    """reference: transpiler/inference_transpiler.py — pre-deploy program
    rewrites. Elementwise/act fusion is XLA's job for every jitted program;
    the cross-op WEIGHT folds are not, so transpile runs the conv+bn fold
    pass (transpiler/fuse_passes.py) when a scope with parameter values is
    available, and is the identity otherwise."""

    def transpile(self, program, place=None, scope=None):
        if scope is None:
            from ..core.scope import global_scope

            scope = global_scope()
        from ..core.pass_framework import get_pass

        return get_pass("conv_bn_fuse_pass").set_attr("scope", scope).apply(program)


__all__ = [
    "DistributeTranspiler",
    "DistributeTranspilerConfig",
    "InferenceTranspiler",
    "memory_optimize",
    "release_memory",
]
