from .distribute_transpiler import DistributeTranspiler, DistributeTranspilerConfig  # noqa: F401
from .memory_optimization import memory_optimize, release_memory  # noqa: F401


class InferenceTranspiler:
    """Compat shim (reference: transpiler/inference_transpiler.py — BN fold,
    conv+BN fuse, relu fuse for CPU/MKLDNN inference). Under XLA these
    algebraic fusions happen in the compiler for every jitted program, so
    transpile is the identity; kept so reference inference scripts run
    unchanged."""

    def transpile(self, program, place=None, scope=None):
        return program


__all__ = [
    "DistributeTranspiler",
    "DistributeTranspilerConfig",
    "InferenceTranspiler",
    "memory_optimize",
    "release_memory",
]
