from .distribute_transpiler import DistributeTranspiler, DistributeTranspilerConfig  # noqa: F401
from .memory_optimization import memory_optimize, release_memory  # noqa: F401
