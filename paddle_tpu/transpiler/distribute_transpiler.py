"""DistributeTranspiler compatibility shim.

Reference: ``python/paddle/fluid/transpiler/distribute_transpiler.py:161``
(2078 lines rewriting programs into trainer/pserver pairs with send/recv ops,
sliced param blocks, and barriers) plus its NCCL2 mode (``:226``).

On TPU none of that program surgery exists: collectives are inserted by
XLA/GSPMD from sharding annotations, multi-host bootstrap is
``parallel.init_distributed`` (replacing gen_nccl_id), and the parameter
server's sharded tables are row-sharded Parameters
(``parallel.sharded_embedding``/``annotate_sharding``). This class keeps the
reference's launch-script surface working:

- NCCL2 mode → no-op transpile (the program is already collective-ready);
  ``get_trainer_program`` returns it unchanged.
- pserver mode → ``transpile`` succeeds (trainer side unchanged);
  ``get_pserver_program`` raises with migration guidance, since there is no
  pserver process in the TPU architecture.
"""

from __future__ import annotations

from typing import Optional

from ..core.framework import Program, default_main_program, default_startup_program

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig"]


class DistributeTranspilerConfig:
    """reference: distribute_transpiler.py:130 — accepted for compatibility."""

    slice_var_up = True
    split_method = None
    min_block_size = 8192
    enable_dc_asgd = False
    sync_mode = True
    runtime_split_send_recv = False
    mode = "pserver"


class DistributeTranspiler:
    def __init__(self, config: Optional[DistributeTranspilerConfig] = None):
        self.config = config or DistributeTranspilerConfig()
        self._program: Optional[Program] = None
        self._startup: Optional[Program] = None
        self._trainer_id = 0
        self._trainers = 1
        self._sync_mode = True
        self._mode = "pserver"

    def transpile(
        self,
        trainer_id: int,
        program: Optional[Program] = None,
        pservers: str = "",
        trainers=1,
        sync_mode: bool = True,
        startup_program: Optional[Program] = None,
        current_endpoint: str = "",
    ):
        """reference: distribute_transpiler.py:280. ``trainers`` may be an
        int (pserver mode) or an endpoint list string (NCCL2 mode)."""
        self._program = program or default_main_program()
        self._startup = startup_program or default_startup_program()
        self._trainer_id = trainer_id
        self._sync_mode = sync_mode
        if isinstance(trainers, str) or self.config.mode == "nccl2":
            self._mode = "collective"
            eps = trainers.split(",") if isinstance(trainers, str) else []
            self._trainers = len(eps) or int(trainers or 1)
        else:
            self._mode = "pserver"
            self._trainers = int(trainers)
        # No program rewriting: gradient synchronization is inserted by
        # XLA/GSPMD when the program runs on a multi-process mesh after
        # parallel.init_distributed().
        return self._program

    def get_trainer_program(self, wait_port: bool = True) -> Program:
        """reference: :554 — the trainer program is the original program."""
        if self._program is None:
            raise RuntimeError("call transpile() first")
        return self._program

    def get_pserver_program(self, endpoint: str) -> Program:
        """reference: :674 — intentionally unsupported."""
        raise NotImplementedError(
            "There is no parameter-server process in the TPU architecture: "
            "dense state is replicated or sharded over the device mesh "
            "(CompiledProgram.with_mesh + parallel.annotate_sharding) and "
            "sparse tables are row-sharded embeddings "
            "(parallel.sharded_embedding). Launch every host as a trainer "
            "with parallel.init_distributed()."
        )

    def get_pserver_programs(self, endpoint: str):
        return self.get_pserver_program(endpoint)

    def get_startup_program(self, endpoint: str = "", pserver_program=None,
                            startup_program=None) -> Program:
        """reference: :927 — the shared startup program works for every host
        (param init is deterministic and replicated)."""
        if self._startup is None:
            raise RuntimeError("call transpile() first")
        return self._startup
