"""Inference-time fusion passes on the Program-pass framework.

Reference: ``framework/ir/conv_bn_fuse_pass.cc`` (+ its tester pattern,
``ir/fc_fuse_pass_tester.cc``: build a tiny program, apply, assert fused
node counts). XLA already fuses elementwise chains into convs at compile
time — what it cannot do is *fold weights across ops*, because the conv
filter and the BN statistics are separate runtime inputs to the compiled
step. Folding W' = W·γ/√(σ²+ε), b' = β + (b−μ)·γ/√(σ²+ε) removes the BN op
and its four parameter reads entirely, which is the reference pass's win and
is equally real on TPU (fewer HBM reads, one less kernel input).

Only valid for inference programs (BN in global-stats mode): the pass
requires the op to run with ``is_test``/``use_global_stats`` semantics.

Folded values land in FRESH scope vars (``<w>.bn_fused``) and the conv is
re-pointed at them; the original parameters are never overwritten. That
makes the pass safe to re-apply from a clone of the original program over
the same scope (the default optimizer pipeline does exactly that per fetch
set) — a re-application recomputes the same fold from the same untouched
inputs instead of compounding it. A second application to an already-fused
program is a structural no-op (no ``batch_norm`` ops remain).
"""

from __future__ import annotations

import numpy as np

from ..core.pass_framework import Pass, register_pass

__all__ = ["ConvBNFusePass"]


@register_pass("conv_bn_fuse_pass")
class ConvBNFusePass(Pass):
    """Fold batch_norm into the preceding conv2d's weights.

    Requires attrs:
      - ``scope``: the Scope holding parameter values (weights are folded
        numerically, like the reference InferenceTranspiler).
    Matches: conv2d → [elementwise_add bias] → batch_norm, where each
    intermediate is consumed only by the next op in the chain.
    """

    def apply_impl(self, program):
        scope = self.attr("scope")
        if scope is None:
            raise ValueError(
                "conv_bn_fuse_pass needs set_attr('scope', scope) — weight "
                "folding reads/writes parameter values")
        # shared graph maps (passes/analysis.py): one linear scan each
        # instead of the old per-candidate O(n) rescan (O(n^2) over a deep
        # resnet), rebuilt after each (rare) fuse — and use_counts also sees
        # sub-block/attr readers, so a var a while-body consumes is never
        # mistaken for single-consumer
        from ..passes import analysis as A

        block = program.global_block
        ops = block.ops
        uses = A.use_counts(program)
        prod = A.producer_map(block)

        def _materialize_param(name, value):
            """Materialize a folded value under a NEW deterministic name;
            the original param is left untouched (re-apply safety)."""
            if not block.has_var(name):
                block.create_parameter(
                    name=name, shape=[int(s) for s in value.shape],
                    dtype=str(value.dtype), trainable=False, persistable=True)
            scope.set_var(name, value)
            return name

        fused = 0
        replaced = []  # original param names the fuse may have orphaned
        # scope objects the fold derived values from: maybe_optimize checks
        # these by identity on every memo hit, so a checkpoint load or a
        # train-step weight update (new array objects) forces a re-fold
        # instead of silently serving stale fused weights
        fold_sources = getattr(program, "_fold_sources", None) or {}
        i = 0
        while i < len(ops):
            bn = ops[i]
            if bn.type != "batch_norm":
                i += 1
                continue
            if not (bn.attrs.get("is_test") or bn.attrs.get("use_global_stats")):
                i += 1
                continue
            x_name = bn.inputs["X"][0]
            producer = prod.get(x_name)
            if producer is None or uses.get(x_name, 0) != 1:
                i += 1
                continue
            bias_op = None
            if producer.type == "elementwise_add":
                bias_op = producer
                conv_out = bias_op.inputs["X"][0]
                conv = prod.get(conv_out)
                if (conv is None or conv.type != "conv2d"
                        or conv_out not in conv.outputs.get("Output", ())
                        or uses.get(conv_out, 0) != 1):
                    i += 1
                    continue
                # the add must be a per-channel BIAS, not a residual/shortcut
                # or spatial-broadcast add: Y is a 1-D scope-resident var of
                # length C, broadcast on the channel axis (axis=1 for NCHW)
                y_var = block._find_var_recursive(bias_op.inputs["Y"][0])
                w_var = block._find_var_recursive(conv.inputs["Filter"][0])
                out_c = (w_var.shape[0] if w_var is not None
                         and w_var.shape else None)
                if (y_var is None or y_var.shape is None
                        or len(y_var.shape) != 1
                        or y_var.shape[0] != out_c
                        or bias_op.attrs.get("axis", -1) != 1
                        or scope.find_var(bias_op.inputs["Y"][0]) is None):
                    i += 1
                    continue
            elif producer.type == "conv2d":
                conv = producer
            else:
                i += 1
                continue

            w_name = conv.inputs["Filter"][0]
            src_names = (bn.inputs["Scale"][0], bn.inputs["Bias"][0],
                         bn.inputs["Mean"][0], bn.inputs["Variance"][0],
                         w_name)
            vals = [scope.find_var(n) for n in src_names]
            if any(v is None for v in vals):
                # parameters not materialized (e.g. transpile before startup
                # ran) — leave this candidate alone rather than crash
                i += 1
                continue
            fold_sources.update(zip(src_names, vals))
            gamma, beta, mu, var, w = (np.asarray(v) for v in vals)
            eps = float(bn.attrs.get("epsilon", 1e-5))
            inv_std = gamma / np.sqrt(var + eps)

            w_fused = _materialize_param(
                w_name + ".bn_fused",
                (w * inv_std.reshape(-1, 1, 1, 1)).astype(w.dtype))
            conv.inputs["Filter"] = [w_fused]
            replaced.append(w_name)
            replaced.extend(bn.inputs[s][0]
                            for s in ("Scale", "Bias", "Mean", "Variance"))
            bn_y = bn.outputs["Y"][0]
            if bias_op is not None:
                b_name = bias_op.inputs["Y"][0]
                b_obj = scope.find_var(b_name)
                fold_sources[b_name] = b_obj
                b = np.asarray(b_obj)
                b_fused = _materialize_param(
                    b_name + ".bn_fused",
                    (beta + (b - mu) * inv_std).astype(b.dtype))
                bias_op.inputs["Y"] = [b_fused]
                bias_op.outputs["Out"] = [bn_y]
                replaced.append(b_name)
            else:
                # conv had no bias: the folded β − μ·γ/√(σ²+ε) becomes one,
                # written straight into the scope (inference programs don't
                # re-run startup).
                b_name = _materialize_param(
                    w_name + ".bn_fold_bias",
                    (beta - mu * inv_std).astype(beta.dtype))
                bias_var = block.var(b_name)
                idx = ops.index(bn)
                block.insert_op(
                    idx, "elementwise_add",
                    inputs={"X": conv.outputs["Output"][0], "Y": bias_var},
                    outputs={"Out": bn_y}, attrs={"axis": 1})
            block.remove_op(ops.index(bn))
            fused += 1
            uses = A.use_counts(program)
            prod = A.producer_map(block)

        if fused:
            program._fold_sources = fold_sources
            # demote originals nothing reads anymore: they leave the
            # persistable state set (no doubled conv weights in HBM) and
            # dead-var elimination may then drop them from the symbol table.
            # Scope values are untouched — a re-apply from a fresh clone of
            # the ORIGINAL program still folds from pristine inputs.
            all_uses = A.use_counts(program)
            for name in replaced:
                if all_uses.get(name, 0) == 0:
                    v = block._find_var_recursive(name)
                    if v is not None:
                        v.persistable = False
        self.set_attr("fused_count", fused)
        return program
