"""Inference-time fusion passes on the Program-pass framework.

Reference: ``framework/ir/conv_bn_fuse_pass.cc`` (+ its tester pattern,
``ir/fc_fuse_pass_tester.cc``: build a tiny program, apply, assert fused
node counts). XLA already fuses elementwise chains into convs at compile
time — what it cannot do is *fold weights across ops*, because the conv
filter and the BN statistics are separate runtime inputs to the compiled
step. Folding W' = W·γ/√(σ²+ε), b' = β + (b−μ)·γ/√(σ²+ε) removes the BN op
and its four parameter reads entirely, which is the reference pass's win and
is equally real on TPU (fewer HBM reads, one less kernel input).

Only valid for inference programs (BN in global-stats mode): the pass
requires the op to run with ``is_test``/``use_global_stats`` semantics.
"""

from __future__ import annotations

import numpy as np

from ..core.pass_framework import Pass, register_pass

__all__ = ["ConvBNFusePass"]


@register_pass("conv_bn_fuse_pass")
class ConvBNFusePass(Pass):
    """Fold batch_norm into the preceding conv2d's weights.

    Requires attrs:
      - ``scope``: the Scope holding parameter values (weights are folded
        numerically, like the reference InferenceTranspiler).
    Matches: conv2d → [elementwise_add bias] → batch_norm, where each
    intermediate is consumed only by the next op in the chain.
    """

    def apply_impl(self, program):
        scope = self.attr("scope")
        if scope is None:
            raise ValueError(
                "conv_bn_fuse_pass needs set_attr('scope', scope) — weight "
                "folding reads/writes parameter values")
        block = program.global_block
        ops = block.ops

        def consumers(name, upto=None):
            return [o for o in ops if any(
                name in ns for ns in o.inputs.values())]

        fused = 0
        i = 0
        while i < len(ops):
            bn = ops[i]
            if bn.type != "batch_norm":
                i += 1
                continue
            if not (bn.attrs.get("is_test") or bn.attrs.get("use_global_stats")):
                i += 1
                continue
            x_name = bn.inputs["X"][0]
            producer = next((o for o in ops if any(
                x_name in ns for ns in o.outputs.values())), None)
            if producer is None or len(consumers(x_name)) != 1:
                i += 1
                continue
            bias_op = None
            if producer.type == "elementwise_add":
                bias_op = producer
                conv_out = bias_op.inputs["X"][0]
                conv = next((o for o in ops if o.type == "conv2d" and
                             conv_out in o.outputs.get("Output", ())), None)
                if conv is None or len(consumers(conv_out)) != 1:
                    i += 1
                    continue
                # the add must be a per-channel BIAS, not a residual/shortcut
                # or spatial-broadcast add: Y is a 1-D scope-resident var of
                # length C, broadcast on the channel axis (axis=1 for NCHW)
                y_var = block._find_var_recursive(bias_op.inputs["Y"][0])
                w_var = block._find_var_recursive(conv.inputs["Filter"][0])
                out_c = (w_var.shape[0] if w_var is not None
                         and w_var.shape else None)
                if (y_var is None or y_var.shape is None
                        or len(y_var.shape) != 1
                        or y_var.shape[0] != out_c
                        or bias_op.attrs.get("axis", -1) != 1
                        or scope.find_var(bias_op.inputs["Y"][0]) is None):
                    i += 1
                    continue
            elif producer.type == "conv2d":
                conv = producer
            else:
                i += 1
                continue

            w_name = conv.inputs["Filter"][0]
            vals = [scope.find_var(n) for n in (
                bn.inputs["Scale"][0], bn.inputs["Bias"][0],
                bn.inputs["Mean"][0], bn.inputs["Variance"][0], w_name)]
            if any(v is None for v in vals):
                # parameters not materialized (e.g. transpile before startup
                # ran) — leave this candidate alone rather than crash
                i += 1
                continue
            gamma, beta, mu, var, w = (np.asarray(v) for v in vals)
            eps = float(bn.attrs.get("epsilon", 1e-5))
            inv_std = gamma / np.sqrt(var + eps)

            scope.set_var(w_name, (w * inv_std.reshape(-1, 1, 1, 1)).astype(w.dtype))
            bn_y = bn.outputs["Y"][0]
            if bias_op is not None:
                b_name = bias_op.inputs["Y"][0]
                b = np.asarray(scope.find_var(b_name))
                scope.set_var(b_name,
                              (beta + (b - mu) * inv_std).astype(b.dtype))
                bias_op.outputs["Out"] = [bn_y]
            else:
                # conv had no bias: the folded β − μ·γ/√(σ²+ε) becomes one,
                # written straight into the scope (inference programs don't
                # re-run startup).
                b_name = w_name + ".bn_fold_bias"
                block.create_parameter(
                    name=b_name, shape=[int(beta.shape[0])],
                    dtype=str(beta.dtype), trainable=False, persistable=True)
                scope.set_var(b_name, (beta - mu * inv_std).astype(beta.dtype))
                bias_var = block.var(b_name)
                idx = ops.index(bn)
                block.insert_op(
                    idx, "elementwise_add",
                    inputs={"X": conv.outputs["Output"][0], "Y": bias_var},
                    outputs={"Out": bn_y}, attrs={"axis": 1})
            block.remove_op(ops.index(bn))
            fused += 1
        self.set_attr("fused_count", fused)
        return program
