"""Production telemetry: Prometheus export, the continuous JSONL exporter,
SLO monitoring, per-request serving traces, and collective-traffic budgets.

The PR-8 surface: ``monitor.to_prometheus()`` round-trips under a
promtool-style parser; ``monitor.telemetry.TelemetryExporter`` writes a
bounded crash-safe JSONL ring wired into the serving-engine and supervisor
lifecycles; ``monitor.slo`` evaluates declarative specs per tick (an
injected decode-latency fault must trip the p99 SLO, hit the flight
recorder, and flip ``engine.health()`` to degraded); the serving request
tracer reconstructs the continuous-batching schedule; and the checked-in
collective budgets reject traffic regressions.
"""

import json
import logging
import os
import re
import threading
import time

import numpy as np
import pytest

from paddle_tpu.monitor import budgets, metrics, slo, telemetry, tracer
from paddle_tpu.monitor.telemetry import TelemetryExporter, TelemetrySample


@pytest.fixture(autouse=True)
def _clean_metrics():
    metrics.enable()
    metrics.reset()
    yield
    metrics.reset()


def _tiny_engine(slots=3, **cfg_kw):
    from paddle_tpu import serving
    from paddle_tpu.models import decoder_lm

    cfg = decoder_lm.DecoderConfig(vocab_size=64, n_layer=2, d_model=32,
                                   n_head=2, max_seq=64)
    model = decoder_lm.DecoderLM(cfg, seed=0)
    return serving.ServingEngine(model, serving.ServingConfig(
        slots=slots, page_size=8, max_seq=64, **cfg_kw))


# -- Prometheus text exposition ----------------------------------------------

_PROM_SAMPLE = re.compile(
    r'^([a-zA-Z_][a-zA-Z0-9_]*)(\{le="([^"]+)"\})? ([0-9eE.+-]+|\+Inf|NaN)$')


def _parse_prometheus(text):
    """Minimal promtool-style validation: TYPE lines, legal names, legal
    sample lines, cumulative monotone histogram buckets ending in +Inf.
    Returns {name: value} for scalars and {name: {...}} for histograms."""
    types, scalars, hists = {}, {}, {}
    for line in text.splitlines():
        if not line or line.startswith("# HELP"):
            continue
        if line.startswith("# TYPE"):
            _, _, name, kind = line.split()
            assert kind in ("counter", "gauge", "histogram"), line
            types[name] = kind
            continue
        m = _PROM_SAMPLE.match(line)
        assert m, "unparseable exposition line: %r" % line
        name, _, le, val = m.groups()
        val = float(val) if val != "+Inf" else float("inf")
        if le is not None:
            assert name.endswith("_bucket"), line
            base = name[:-len("_bucket")]
            assert types.get(base) == "histogram", "untyped bucket %r" % line
            hists.setdefault(base, {"buckets": []})["buckets"].append(
                (float("inf") if le == "+Inf" else float(le), val))
        elif name.endswith("_sum") and types.get(name[:-4]) == "histogram":
            hists.setdefault(name[:-4], {"buckets": []})["sum"] = val
        elif name.endswith("_count") and types.get(name[:-6]) == "histogram":
            hists.setdefault(name[:-6], {"buckets": []})["count"] = val
        else:
            assert name in types, "sample before TYPE: %r" % line
            scalars[name] = val
    for name, h in hists.items():
        bounds = [b for b, _ in h["buckets"]]
        counts = [c for _, c in h["buckets"]]
        assert bounds == sorted(bounds) and bounds[-1] == float("inf"), name
        assert counts == sorted(counts), "non-cumulative buckets: %s" % name
        assert counts[-1] == h["count"], name
    return scalars, hists


def test_to_prometheus_roundtrip():
    c = metrics.counter("promtest/reqs", help="help text with \\ and\nnewline")
    c.inc(7)
    metrics.gauge("promtest/depth:q").set(3.5)
    h = metrics.histogram("promtest/lat_ms", buckets=(1.0, 10.0, 100.0))
    for v in (0.5, 5.0, 50.0, 500.0):
        h.observe(v)
    scalars, hists = _parse_prometheus(metrics.to_prometheus())
    # names sanitized: '/' and ':' -> '_'
    assert scalars["promtest_reqs"] == 7
    assert scalars["promtest_depth_q"] == 3.5
    hh = hists["promtest_lat_ms"]
    assert hh["count"] == 4 and abs(hh["sum"] - 555.5) < 1e-9
    # cumulative: 1 obs <=1, 2 <=10, 3 <=100, 4 <=+Inf
    assert [c for _, c in hh["buckets"]] == [1, 2, 3, 4]


def test_prometheus_name_sanitization():
    assert metrics.prometheus_name("serving/ttft_ms") == "serving_ttft_ms"
    assert metrics.prometheus_name("a:b/c-d.e") == "a_b_c_d_e"
    assert metrics.prometheus_name("9lives") == "_9lives"


# -- telemetry exporter -------------------------------------------------------

def test_exporter_ring_write_rotate_readback(tmp_path):
    exp = TelemetryExporter(str(tmp_path), interval_s=999.0,
                            rotate_samples=3, keep_files=2)
    c = metrics.counter("texp/ticks")
    for _ in range(8):
        c.inc()
        exp.tick()
    exp.stop()  # + final flush sample
    series = telemetry.read_series(str(tmp_path), pid=os.getpid())
    seqs = [s["seq"] for s in series]
    assert seqs == sorted(seqs) and seqs[-1] == 9
    files = [f for f in os.listdir(str(tmp_path)) if f.endswith(".jsonl")]
    assert len(files) <= 2
    # interval deltas: each live tick saw exactly +1
    live = [s for s in series if s["seq"] <= 8]
    assert all(s["deltas"]["counters"].get("texp/ticks") == 1 for s in live)
    # the prometheus textfile rides along
    assert (tmp_path / "metrics.prom").exists()


def test_exporter_thread_final_partial_interval_flush(tmp_path):
    exp = TelemetryExporter(str(tmp_path), interval_s=60.0)  # never ticks
    exp.start()
    c = metrics.counter("texp/final")
    c.inc(5)
    exp.stop()  # must flush the partial interval
    series = telemetry.read_series(str(tmp_path), pid=os.getpid())
    assert series, "final partial interval lost"
    assert series[-1]["deltas"]["counters"].get("texp/final") == 5
    assert exp.closed


def test_exporter_unwritable_dir_logs_once_and_disables(tmp_path, caplog):
    blocker = tmp_path / "blocker"
    blocker.write_text("a file where a dir must go")
    bad = str(blocker / "sub")  # makedirs under a file -> OSError
    exp = TelemetryExporter(bad, interval_s=999.0)
    hits = []
    mon = slo.SLOMonitor([slo.SLO("texp/g", max_value=1.0)])
    exp.add_listener(lambda s: hits.append(s))
    exp.add_listener(mon.on_sample)
    metrics.gauge("texp/g").set(5.0)
    with caplog.at_level(logging.ERROR, logger="paddle_tpu"):
        exp.tick()
        exp.tick()
        exp.tick()
    errors = [r for r in caplog.records
              if "PADDLE_TPU_TELEMETRY_DIR" in r.getMessage()]
    assert len(errors) == 1, "must log exactly once, got %d" % len(errors)
    assert exp.disabled
    # the run is not masked and LISTENERS kept working disk-free
    assert len(hits) == 3
    assert mon.breaches_total == 3  # gauge ceiling kept evaluating
    exp.stop()


def test_two_engines_share_one_exporter_thread(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_TELEMETRY_DIR", str(tmp_path))
    monkeypatch.setenv("PADDLE_TPU_TELEMETRY_INTERVAL_S", "60")

    def _threads():
        return [t for t in threading.enumerate()
                if t.name == "tpu-telemetry" and t.is_alive()]

    assert not _threads()
    eng1 = _tiny_engine(slots=2)
    eng2 = _tiny_engine(slots=2)
    try:
        assert len(_threads()) == 1, "exporter thread double-started"
        assert eng1._telemetry is eng2._telemetry
        eng1.close()
        assert len(_threads()) == 1, "refcounted exporter died early"
    finally:
        eng2.close()
        eng1.close()
    time.sleep(0.05)
    assert not _threads(), "last release did not stop the exporter"
    # the shutdown flushed a final sample
    assert telemetry.read_series(str(tmp_path), pid=os.getpid())


def test_engine_without_env_has_no_exporter(monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_TELEMETRY_DIR", raising=False)
    eng = _tiny_engine(slots=2)
    try:
        assert eng._telemetry is None
    finally:
        eng.close()


def test_supervisor_telemetry_lifecycle(tmp_path, monkeypatch):
    import paddle_tpu as fluid
    from paddle_tpu.reliability import run_supervised

    monkeypatch.setenv("PADDLE_TPU_TELEMETRY_DIR", str(tmp_path / "tele"))
    monkeypatch.setenv("PADDLE_TPU_TELEMETRY_INTERVAL_S", "60")
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        x = fluid.layers.data("x", shape=[4])
        loss = fluid.layers.mean(fluid.layers.fc(x, size=2))
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)

    def feed_source(start):
        def gen():
            for _ in range(start, 4):
                yield {"x": rng.randn(2, 4).astype("float32")}
        return gen()

    res = run_supervised(exe, main_prog, feed_source, total_steps=4,
                         fetch_list=[loss],
                         checkpoint_dir=str(tmp_path / "ckpt"),
                         exit_on_preempt=False)
    assert res.steps_done == 4
    series = telemetry.read_series(str(tmp_path / "tele"), pid=os.getpid())
    assert series, "supervisor did not flush the final partial interval"
    last = series[-1]
    assert last["deltas"]["counters"].get(
        "executor/run_steps_steps", 0) >= 4
    # the supervised run released its reference: no thread left behind
    assert not [t for t in threading.enumerate()
                if t.name == "tpu-telemetry" and t.is_alive()]


def test_tick_counter_reset_never_emits_negative_deltas(tmp_path):
    exp = TelemetryExporter(str(tmp_path), interval_s=999.0)
    c = metrics.counter("texp/reset")
    h = metrics.histogram("texp/reset_h")
    c.inc(5)
    h.observe(1.0)
    exp.tick()
    metrics.reset()  # mid-run reset (bench/selftest code does this)
    c.inc(2)
    h.observe(3.0)
    sample = exp.tick()
    # Prometheus rate() semantics: the post-reset value IS the increment
    assert sample.counter_delta("texp/reset") == 2
    hd = sample.histogram_delta("texp/reset_h")
    assert hd["count"] == 1 and hd["sum"] == 3.0
    assert all(v >= 0 for v in sample.deltas["counters"].values())
    exp.stop()


def test_interval_percentile_overflow_bucket_reports_largest_bound():
    """Observations past the last finite bound must NOT be understated:
    an SLO ceiling below that bound has to breach (the slow-death case)."""
    exp = TelemetryExporter("", interval_s=999.0)
    exp.disabled = True
    h = metrics.histogram("texp/slow_ms", buckets=(1.0, 10.0, 100.0))
    h.observe(0.5)            # one fast request
    for _ in range(5):
        h.observe(30000.0)    # five stalled past every bound
    sample = exp.tick()
    p99 = sample.histogram_interval_percentile("texp/slow_ms", 99)
    assert p99 == 100.0, p99  # the largest finite bound, not ~0.5
    assert slo.SLO("texp/slow_ms", p=99, max_ms=50.0).evaluate(sample)
    exp.stop()


def test_watch_ring_tail_survives_rotation(tmp_path, capsys):
    """The tail keys on per-writer seq, not list index: rotation prunes
    shrink the doc list mid-watch, and an index cursor would go blind for
    a whole rotation's worth of samples."""
    from tools.dump_metrics import watch

    exp = TelemetryExporter(str(tmp_path), interval_s=999.0,
                            rotate_samples=2, keep_files=2)
    c = metrics.counter("watchtest/rot")
    for _ in range(3):
        c.inc()
        exp.tick()
    done = threading.Event()

    def feeder():
        for _ in range(6):  # drives several prunes under the live tail
            c.inc()
            exp.tick()
            time.sleep(0.02)
        done.set()

    t = threading.Thread(target=feeder)
    t.start()
    watch(0.01, telemetry_dir=str(tmp_path), max_ticks=40)
    t.join()
    exp.stop()
    out = capsys.readouterr().out
    assert done.is_set()
    # the final sample (seq 9) printed even though the pruned ring holds
    # fewer docs than the tail had already consumed
    assert "-- seq 9" in out, out[-600:]
    assert "watchtest/rot" in out


def test_track_labels_survive_cross_process_conversion(tmp_path, monkeypatch):
    tracer.clear_spans()
    tracer.start_tracing()
    tracer.record_span("work", 100, 50, cat="serving", track="serving slot 1")
    spans = tracer.stop_tracing()
    raw = tmp_path / "spans.json"
    tracer.save_spans(str(raw), spans)
    # simulate the converter running in a fresh process: no in-memory
    # virtual-track registry
    monkeypatch.setattr(tracer, "_track_names", {})
    monkeypatch.setattr(tracer, "_track_ids", {})
    loaded = tracer.load_spans(str(raw))
    doc = tracer.to_chrome_trace(loaded)
    labels = [e["args"]["name"] for e in doc["traceEvents"]
              if e.get("ph") == "M" and e["name"] == "thread_name"]
    assert "serving slot 1" in labels, labels
    # chrome -> spans -> chrome keeps the label too (second generation)
    chrome2 = tmp_path / "trace2.json"
    tracer.save_chrome_trace(str(chrome2), loaded)
    again = tracer.load_spans(str(chrome2))
    doc2 = tracer.to_chrome_trace(again)
    labels2 = [e["args"]["name"] for e in doc2["traceEvents"]
               if e.get("ph") == "M" and e["name"] == "thread_name"]
    assert "serving slot 1" in labels2, labels2


def test_validate_digest_reports_real_slot_index(rng):
    from paddle_tpu.serving import trace as strace

    tracer.clear_spans()
    tracer.start_tracing()
    eng = _tiny_engine(slots=2)
    try:
        req = eng.submit(list(rng.randint(0, 64, 4)), 3)
        eng.run()
    finally:
        eng.close()
        spans = tracer.stop_tracing()
    digest = strace.validate_request_spans(spans, [req])[req.trace_id]
    assert digest["slot"] in (0, 1), digest  # a real slot, not a track tid


# -- SLO specs ----------------------------------------------------------------

def _sample(seq=1, dt=1.0, counters=None, hists=None, gauges=None):
    snap = {}
    for n, v in (gauges or {}).items():
        snap[n] = {"type": "gauge", "value": v}
    deltas = {"counters": counters or {}, "histograms": hists or {}}
    return TelemetrySample(seq, time.time(), dt, snap, deltas)


def test_slo_modes():
    lat = slo.SLO("m/lat_ms", p=99, max_ms=100.0)
    hit = _sample(hists={"m/lat_ms": {
        "count": 10, "sum": 2500.0,
        "buckets": {"le_50": 1, "le_500": 9}}})
    b = lat.evaluate(hit)
    assert b is not None and b.value > 100.0
    ok = _sample(hists={"m/lat_ms": {
        "count": 10, "sum": 100.0, "buckets": {"le_50": 10}}})
    assert lat.evaluate(ok) is None
    assert lat.evaluate(_sample()) is None  # no observations -> no verdict

    depth = slo.SLO("m/depth", max_value=8)
    assert depth.evaluate(_sample(gauges={"m/depth": 9})) is not None
    assert depth.evaluate(_sample(gauges={"m/depth": 8})) is None

    qps = slo.SLO("m/done", min_rate=10.0)
    assert qps.evaluate(_sample(counters={"m/done": 5}, dt=1.0)) is not None
    assert qps.evaluate(_sample(counters={"m/done": 20}, dt=1.0)) is None
    assert qps.evaluate(_sample(counters={}, dt=1.0)) is None  # idle != slow

    err = slo.SLO("m/fail", max_ratio=0.01, over="m/done")
    assert err.evaluate(_sample(
        counters={"m/fail": 2, "m/done": 100})) is not None
    assert err.evaluate(_sample(
        counters={"m/fail": 0, "m/done": 100})) is None
    assert err.evaluate(_sample(counters={"m/fail": 2})) is None  # den 0


def test_slo_constructor_validation():
    with pytest.raises(ValueError):
        slo.SLO("m/x")  # no mode
    with pytest.raises(ValueError):
        slo.SLO("m/x", max_ms=5, max_value=5)  # two modes
    with pytest.raises(ValueError):
        slo.SLO("m/x", max_ms=5)  # percentile without p
    with pytest.raises(ValueError):
        slo.SLO("m/x", max_ratio=0.1)  # error rate without denominator


def test_parse_slos_env_grammar():
    specs = slo.parse_slos(
        "serving/request_latency_ms:p99<=250; serving/queue_depth<=512;"
        "serving/requests_retired>=10/s;"
        "serving/requests_failed<=0.01 over serving/requests_retired")
    kinds = [s.kind for s in specs]
    assert kinds == ["percentile", "ceiling", "rate_floor", "error_rate"]
    assert specs[0].p == 99 and specs[0].threshold == 250
    assert specs[3].over == "serving/requests_retired"
    with pytest.raises(ValueError):
        slo.parse_slos("serving/queue_depth=512")
    with pytest.raises(ValueError):
        # 'over' + rate floor is a malformed error-rate spec, not a
        # silently-different rate-floor SLO
        slo.parse_slos("serving/requests_failed>=0.01/s "
                       "over serving/requests_retired")


def test_slo_monitor_counters_and_flight_recorder(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
    from paddle_tpu.monitor import device as dev

    mon = slo.SLOMonitor([slo.SLO("m/depth", max_value=1.0, name="depthcap")])
    mon.on_sample(_sample(gauges={"m/depth": 5}))
    snap = metrics.snapshot()
    assert snap["slo/breaches"]["value"] == 1
    assert snap["slo/depthcap/breaches"]["value"] == 1
    fr = dev.flight_recorder()
    assert any(e.get("event") == "slo_breach" and e.get("slo") == "depthcap"
               for e in fr._entries)
    # a healthy tick clears
    cleared = []
    mon.on_clear = lambda: cleared.append(1)
    mon.on_sample(_sample(gauges={"m/depth": 0}))
    assert cleared


def test_observational_breach_does_not_block_recovery():
    """A breaching degrade=False spec must not pin health 'degraded'."""
    state = {"degraded": False}
    mon = slo.SLOMonitor(
        [slo.SLO("m/lat", p=99, max_ms=10.0, name="lat"),
         slo.SLO("m/watch_only", max_value=1.0, degrade=False, name="obs")],
        on_breach=lambda b: state.update(degraded=True),
        on_clear=lambda: state.update(degraded=False))
    slow = {"m/lat": {"count": 5, "sum": 500.0, "buckets": {"le_500": 5}}}
    mon.on_sample(_sample(hists=slow, gauges={"m/watch_only": 9}))
    assert state["degraded"]
    # latency healthy again, observational spec still breaching
    mon.on_sample(_sample(gauges={"m/watch_only": 9}))
    assert not state["degraded"], \
        "observational breach blocked health recovery"
    assert mon.breaches_total == 3  # both ticks still counted obs breaches


def test_ceiling_slo_on_counter_is_inert_and_warns_once(caplog):
    metrics.counter("sloct/c").inc(100)
    spec = slo.SLO("sloct/c", max_value=10.0)
    exp = TelemetryExporter("", interval_s=999.0)
    exp.disabled = True
    sample = exp.tick()
    with caplog.at_level(logging.WARNING, logger="paddle_tpu"):
        assert spec.evaluate(sample) is None  # lifetime total != gauge
        assert spec.evaluate(sample) is None
    warns = [r for r in caplog.records if "gauge ceiling" in r.getMessage()]
    assert len(warns) == 1
    exp.stop()


def test_gauge_changes_ride_sample_deltas(tmp_path):
    exp = TelemetryExporter(str(tmp_path), interval_s=999.0)
    g = metrics.gauge("texp/depth")
    g.set(3.0)
    s1 = exp.tick()
    assert s1.deltas["gauges"].get("texp/depth") == 3.0
    s2 = exp.tick()  # unchanged -> not flagged
    assert "texp/depth" not in s2.deltas["gauges"]
    g.set(7.0)
    s3 = exp.tick()
    assert s3.deltas["gauges"].get("texp/depth") == 7.0
    exp.stop()


def test_dir_change_keeps_old_exporter_alive_for_holders(
        tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_TELEMETRY_DIR", str(tmp_path / "a"))
    h1 = telemetry.acquire()
    h2 = telemetry.acquire()
    assert h1 is h2 and h1._refs == 2
    monkeypatch.setenv("PADDLE_TPU_TELEMETRY_DIR", str(tmp_path / "b"))
    h3 = telemetry.acquire()
    assert h3 is not h1
    telemetry.release(h1)
    assert not h1.closed, "dir change + one release killed a held exporter"
    telemetry.release(h2)
    assert h1.closed  # last holder released the superseded exporter
    telemetry.release(h3)
    assert h3.closed


# -- serving traces -----------------------------------------------------------

def test_serving_trace_reconstructs_schedule(rng):
    from paddle_tpu.serving import trace as strace

    tracer.clear_spans()
    tracer.start_tracing()
    eng = _tiny_engine(slots=3)
    base = metrics.snapshot()
    reqs = []
    try:
        for _ in range(8):
            p = list(rng.randint(0, 64, int(rng.randint(3, 20))))
            reqs.append(eng.submit(p, int(rng.randint(2, 8))))
        done = eng.run()
    finally:
        eng.close()
        spans = tracer.stop_tracing()
    assert len(done) == 8
    digests = strace.validate_request_spans(spans, reqs)
    assert len(digests) == 8

    def delta(name):
        return (metrics.snapshot()[name]["value"]
                - base.get(name, {}).get("value", 0))

    # slot occupancy from spans == the serving/* counters
    by_slot = strace.slot_assignments_from_spans(spans)
    assert sum(len(v) for v in by_slot.values()) == delta(
        "serving/requests_admitted") == 8
    assert len(by_slot) <= 3  # never more tracks than slots
    prefills = [s for s in spans if s["name"].startswith("prefill(")]
    assert len(prefills) == delta("serving/prefills")
    decode_windows = {s["ts_us"] for s in spans if s["name"] == "decode"}
    assert len(decode_windows) == delta("serving/decode_dispatches")
    # every request's span chain is causally ordered
    for req in reqs:
        mine = sorted((s for s in spans
                       if (s.get("args") or {}).get("trace_id") == req.trace_id
                       and s["name"] != "queued"),
                      key=lambda s: s["ts_us"])
        assert mine[0]["name"] == "submitted"
        assert mine[-1]["name"] == "retired"
    # no ghost slots: at no time do lifetime spans on one track overlap
    for tid, ids in by_slot.items():
        assert len(ids) == len(set(ids))


def test_trace_ids_link_flight_recorder_to_spans(tmp_path, monkeypatch, rng):
    monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
    from paddle_tpu.monitor import device as dev
    from paddle_tpu.reliability import FaultPlan

    tracer.clear_spans()
    tracer.start_tracing()
    eng = _tiny_engine(slots=2)
    try:
        req = eng.submit(list(rng.randint(0, 64, 6)), 8)
        with FaultPlan.parse("serving.decode@1=fatal"):
            eng.run(max_steps=10)
    finally:
        eng.close()
        spans = tracer.stop_tracing()
    assert req.state == "failed"
    fr = dev.flight_recorder()
    batch_events = [e for e in fr._entries
                    if e.get("event") == "serving_inflight_batch"]
    assert batch_events, "no in-flight batch captured"
    traced_ids = {(s.get("args") or {}).get("trace_id") for s in spans}
    for ev in batch_events:
        for row in ev["slots"]:
            assert row["trace_id"] in traced_ids, \
                "flight recorder row not linkable to the trace: %r" % row


def test_untraced_engine_emits_no_serving_spans(rng):
    tracer.clear_spans()
    assert not tracer.active()
    eng = _tiny_engine(slots=2)
    try:
        eng.submit(list(rng.randint(0, 64, 4)), 3)
        eng.run()
    finally:
        eng.close()
    assert not [s for s in tracer.get_spans() if s.get("cat") == "serving"]


# -- the acceptance drill: latency fault -> SLO -> degraded health ------------

def test_latency_fault_trips_p99_slo_and_degrades_health(
        tmp_path, monkeypatch, rng):
    monkeypatch.setenv("PADDLE_TPU_TELEMETRY_DIR", str(tmp_path / "tele"))
    monkeypatch.setenv("PADDLE_TPU_TELEMETRY_INTERVAL_S", "60")  # manual ticks
    monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path / "flight"))
    from paddle_tpu.monitor import device as dev
    from paddle_tpu.reliability import FaultPlan

    eng = _tiny_engine(slots=2, slos=[
        slo.SLO("serving/decode_step_ms", p=99, max_ms=20.0)])
    try:
        # healthy traffic, healthy tick
        eng.submit(list(rng.randint(0, 64, 4)), 3)
        eng.run()
        telemetry.force_tick()
        assert eng.health()["status"] == "ok"
        breaches0 = metrics.snapshot()["slo/breaches"]["value"]
        # inject a 60ms decode latency fault: dispatches stay successful
        # but slow — the crash-free degradation SLOs exist to catch
        with FaultPlan.parse("serving.decode@1=latency:3:60"):
            eng.submit(list(rng.randint(0, 64, 4)), 4)
            eng.run()
        sample = telemetry.force_tick()
        assert sample.histogram_interval_percentile(
            "serving/decode_step_ms", 99) > 20.0
        snap = metrics.snapshot()
        assert snap["slo/breaches"]["value"] > breaches0
        health = eng.health()
        assert health["status"] == "degraded", health
        assert health["slo_breach"]["metric"] == "serving/decode_step_ms"
        fr = dev.flight_recorder()
        assert any(e.get("event") == "slo_breach" for e in fr._entries)
        # healthy tick (no new observations) clears the degradation
        telemetry.force_tick()
        assert eng.health()["status"] == "ok"
    finally:
        eng.close()
    # the JSONL series caught all of it: >= 3 manual ticks + final flush
    series = telemetry.read_series(str(tmp_path / "tele"), pid=os.getpid())
    assert len(series) >= 4
    assert any(s["deltas"]["histograms"].get("serving/decode_step_ms")
               for s in series)


def test_env_declared_slos_apply(monkeypatch, rng, tmp_path):
    monkeypatch.setenv("PADDLE_TPU_TELEMETRY_DIR", str(tmp_path))
    monkeypatch.setenv("PADDLE_TPU_TELEMETRY_INTERVAL_S", "60")
    monkeypatch.setenv("PADDLE_TPU_SLO", "serving/queue_depth<=0.5")
    eng = _tiny_engine(slots=2)
    try:
        assert eng._slo_monitor is not None
        eng.submit(list(rng.randint(0, 64, 4)), 3)  # queue_depth -> 1
        telemetry.force_tick()
        assert eng.health()["status"] == "degraded"
        eng.run()
    finally:
        eng.close()


# -- collective budgets -------------------------------------------------------

def test_budget_formulas_closed_forms():
    # gpipe: M=4 over S=4, A bytes -> 2*(4-1) + 4+4-2 = 12 hops
    assert budgets.budget_bytes("gpipe.fwd", microbatches=4, stages=4,
                                activation_bytes=128) == 12 * 128
    # ragged M pads up to a stage multiple first
    assert budgets.budget_bytes("gpipe.fwd", microbatches=3, stages=4,
                                activation_bytes=10) == \
        budgets.budget_bytes("gpipe.fwd", microbatches=4, stages=4,
                             activation_bytes=10)
    assert budgets.budget_bytes("ring_attention.fwd", n_devices=4,
                                block_bytes=1024) == 8192
    assert budgets.budget_bytes("ring_attention.bwd", n_devices=4,
                                block_bytes=1024, block_elems=256) == \
        2 * 4 * 1024 + 2 * 4 * 256 * 4
    assert budgets.budget_bytes("ctr.row_routing", n_shards=8, n_local=16,
                                dim=8, id_itemsize=4, row_itemsize=4) == \
        8 * 16 * (4 + 8 * 4)


def test_check_budget_pass_and_tightened_failure():
    rec = budgets.check_budget("ring_attention.fwd", 8192, n_devices=4,
                               block_bytes=1024)
    assert rec["utilization"] == 1.0
    with pytest.raises(budgets.CollectiveBudgetExceeded) as ei:
        budgets.check_budget("ring_attention.fwd", 8192, budget=8191)
    assert "ring_attention.fwd" in str(ei.value)
    with pytest.raises(KeyError):
        budgets.budget_bytes("no.such.leg")


def test_measured_ring_bytes_within_budget(rng):
    """The in-process twin of tools/check_budgets --selftest's ring leg
    (the full three-leg sweep including gpipe + CTR routing runs there)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from paddle_tpu.parallel import ring_attention

    sp = 4
    mesh = Mesh(np.array(jax.devices()[:sp]), ("sp",))
    q = jnp.asarray(rng.randn(2, 2, 8 * sp, 8).astype("float32"))
    before = metrics.snapshot().get(
        "collectives/ppermute/bytes", {}).get("value", 0)
    with mesh:
        ring_attention(q, q + .1, q + .2, mesh=mesh, axis_name="sp")
    measured = metrics.snapshot()["collectives/ppermute/bytes"]["value"] \
        - before
    rec = budgets.check_budget("ring_attention.fwd", measured,
                               n_devices=sp, block_bytes=q.size // sp * 4)
    assert rec["measured_bytes"] == rec["budget_bytes"]


# -- watch formatter ----------------------------------------------------------

def test_dump_metrics_watch_formatter_and_ring_tail(tmp_path, capsys):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    from tools.dump_metrics import watch

    exp = TelemetryExporter(str(tmp_path), interval_s=999.0)
    metrics.counter("watchtest/c").inc(3)
    metrics.histogram("watchtest/h").observe(2.0)
    exp.tick()
    exp.stop()
    rc = watch(0.01, telemetry_dir=str(tmp_path), max_ticks=1)
    assert rc == 0
    out = capsys.readouterr().out
    assert "watchtest/c" in out and "+3" in out
    assert "watchtest/h" in out
