"""AsyncExecutor / CTR ingestion tests: MultiSlot text parsing, DataFeedDesc
proto-text parsing, multi-threaded file training end to end (a DeepFM-style
sparse+dense CTR model reaches decreasing loss), dataset family smoke, and
strategy/enforce UX contracts."""

import os
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid


PROTO = """
name: "MultiSlotDataFeed"
batch_size: 8
multi_slot_desc {
  slots {
    name: "ids"
    type: "uint64"
    is_dense: false
    is_used: true
  }
  slots {
    name: "dense"
    type: "float"
    is_dense: true
    is_used: true
  }
  slots {
    name: "label"
    type: "float"
    is_dense: true
    is_used: true
  }
}
"""


def _write_ctr_files(tmp_path, rng, n_files=3, lines_per_file=64, vocab=100, dense_dim=4):
    """CTR rule: label = sigmoid-ish of whether any id < vocab/4 plus dense[0]."""
    files = []
    for fi in range(n_files):
        fn = str(tmp_path / ("part-%d.txt" % fi))
        with open(fn, "w") as f:
            for _ in range(lines_per_file):
                k = rng.randint(1, 6)
                ids = rng.randint(0, vocab, size=k)
                dense = rng.randn(dense_dim).astype("float32")
                y = 1.0 if (ids < vocab // 4).any() or dense[0] > 0.5 else 0.0
                line = "%d %s %d %s 1 %.1f" % (
                    k, " ".join(map(str, ids)),
                    dense_dim, " ".join("%.4f" % v for v in dense), y)
                f.write(line + "\n")
        files.append(fn)
    return files


def test_data_feed_desc_parses_proto_text(tmp_path):
    p = tmp_path / "feed.proto"
    p.write_text(PROTO)
    desc = fluid.DataFeedDesc(str(p))
    assert desc.name == "MultiSlotDataFeed"
    assert desc.batch_size == 8
    assert [s.name for s in desc.slots] == ["ids", "dense", "label"]
    assert [s.is_dense for s in desc.slots] == [False, True, True]
    desc.set_batch_size(16)
    assert desc.batch_size == 16
    desc.set_use_slots(["ids", "label"])
    assert [s.is_used for s in desc.slots] == [True, False, True]
    assert "MultiSlotDataFeed" in desc.desc()


def test_async_executor_trains_ctr(tmp_path, rng):
    vocab, dense_dim = 100, 4
    files = _write_ctr_files(tmp_path, rng)
    p = tmp_path / "feed.proto"
    p.write_text(PROTO)
    desc = fluid.DataFeedDesc(str(p))

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data("ids", shape=[-1], dtype="int64")   # [B, L]
        ids_len = fluid.layers.data("ids_length", shape=[], dtype="int64")
        dense = fluid.layers.data("dense", shape=[dense_dim])
        label = fluid.layers.data("label", shape=[1])
        emb = fluid.layers.embedding(ids, size=[vocab, 8])
        pooled = fluid.layers.sequence.sequence_pool(emb, "average", length=ids_len)
        h = fluid.layers.concat([pooled, dense], axis=1)
        h = fluid.layers.fc(h, size=16, act="relu")
        pred = fluid.layers.fc(h, size=1)
        loss = fluid.layers.mean(
            fluid.layers.sigmoid_cross_entropy_with_logits(pred, label))
        fluid.optimizer.Adam(5e-3).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    async_exe = fluid.AsyncExecutor(fluid.CPUPlace())
    r1 = async_exe.run(main, desc, files, thread_num=3, fetch=[loss.name])
    assert len(r1) == 3 * 64 // 8
    first_epoch = np.mean([float(v[0]) for v in r1])
    for _ in range(4):
        rl = async_exe.run(main, desc, files, thread_num=2, fetch=[loss.name])
    last_epoch = np.mean([float(v[0]) for v in rl])
    assert np.isfinite(last_epoch)
    assert last_epoch < first_epoch, (first_epoch, last_epoch)


def test_async_executor_propagates_parse_errors(tmp_path, rng):
    bad = tmp_path / "bad.txt"
    bad.write_text("3 1 2\n")  # declares 3 values, provides 2
    p = tmp_path / "feed.proto"
    p.write_text(PROTO)
    desc = fluid.DataFeedDesc(str(p))
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data("ids", shape=[-1], dtype="int64",
                                append_batch_size=False)
        out = fluid.layers.cast(ids, "float32")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    with pytest.raises(ValueError, match="declares 3 values"):
        fluid.AsyncExecutor().run(main, desc, [str(bad)], thread_num=1,
                                  fetch=[out.name])


def test_dataset_family_smoke():
    from paddle_tpu.dataset import conll05, imdb, imikolov, movielens, wmt16

    seq, y = next(imdb.train()())
    assert isinstance(seq, list) and y in (0, 1)
    gram = next(imikolov.train(n=5)())
    assert len(gram) == 5
    rec = next(conll05.train()())
    words = rec[0]
    assert len(rec) == 9 and len(rec[8]) == len(words)
    src, trg, trg_next = next(wmt16.train()())
    assert trg[0] == wmt16.BOS and trg_next[-1] == wmt16.EOS
    assert len(trg) == len(trg_next)
    row = next(movielens.train()())
    assert len(row) == 8 and 1.0 <= row[-1][0] <= 5.0
    assert len(imdb.word_dict()) == imdb.VOCAB


def test_strategy_knobs_warn_when_inert():
    es = fluid.ExecutionStrategy()
    with pytest.warns(UserWarning, match="no effect"):
        es.num_threads = 8
    bs = fluid.BuildStrategy()
    with pytest.warns(UserWarning, match="XLA"):
        bs.fuse_all_reduce_ops = False
    # honored knobs must NOT warn (reduce_strategy drives ZeRO-1 since r3)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        bs.gradient_accumulation_steps = 4
        bs.reduce_strategy = fluid.BuildStrategy.ReduceStrategy.Reduce


def test_enforce_error_carries_op_context(rng):
    """A failing op impl surfaces as EnforceNotMet naming op/inputs/attrs."""
    from paddle_tpu.core import EnforceNotMet

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        y = fluid.layers.data("y", shape=[6])
        # elementwise on incompatible shapes → impl-level failure at trace
        bad = fluid.layers.elementwise_add(x, y)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    with pytest.raises(EnforceNotMet, match="elementwise_add"):
        exe.run(main, feed={"x": rng.randn(2, 4).astype("float32"),
                            "y": rng.randn(2, 6).astype("float32")},
                fetch_list=[bad])
