"""ZeRO-1 (BuildStrategy.ReduceStrategy.Reduce) on the 8-device CPU mesh.

The TPU-idiomatic reading of the reference's Reduce mode
(details/build_strategy.h:35 + details/reduce_op_handle.cc): optimizer
accumulators shard over the data axis, GSPMD partitions the update math and
all_gathers fresh params. Must match AllReduce-mode losses exactly and cut
per-device optimizer-state memory by ~the data-axis size.
"""

import jax
import numpy as np

import paddle_tpu as fluid


def _build(seed=1234):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[16])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=64, act="relu")
        logits = fluid.layers.fc(h, size=4)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
    return main, startup, loss


def _run(rng_seed, reduce_mode, steps=8, batch=16):
    rng = np.random.RandomState(rng_seed)
    xs = rng.randn(steps * batch, 16).astype("float32")
    ys = rng.randint(0, 4, (steps * batch, 1)).astype("int64")
    with fluid.unique_name.guard():
        with fluid.scope_guard(fluid.Scope()):
            main, startup, loss = _build()
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            bs = fluid.BuildStrategy()
            if reduce_mode:
                bs.reduce_strategy = fluid.BuildStrategy.ReduceStrategy.Reduce
            prog = fluid.CompiledProgram(main).with_data_parallel(
                loss_name=loss.name, build_strategy=bs)
            losses = []
            for i in range(0, len(xs), batch):
                l, = exe.run(prog, feed={"x": xs[i:i + batch], "y": ys[i:i + batch]},
                             fetch_list=[loss])
                losses.append(float(l))
            scope = fluid.global_scope()
            moments = {n: scope.find_var(n) for n in scope.local_var_names()
                       if "_adam_moment" in n}
            return losses, moments


def test_zero1_loss_parity():
    assert len(jax.devices()) == 8
    base, _ = _run(7, reduce_mode=False)
    zero1, moments = _run(7, reduce_mode=True)
    np.testing.assert_allclose(base, zero1, rtol=1e-4, atol=1e-5)
    assert zero1[-1] < zero1[0]


def test_zero1_optimizer_state_actually_sharded():
    _, moments = _run(7, reduce_mode=True)
    # fc weights are [16,64]/[64,4]: dim0 divides 8 -> moments shard 8-way
    sharded = {n: v for n, v in moments.items()
               if np.asarray(v).ndim == 2}
    assert sharded, "expected 2-D adam moments in scope"
    for n, v in sharded.items():
        assert len(v.sharding.device_set) == 8, n
        shard = v.addressable_shards[0].data
        assert shard.shape[0] * 8 == v.shape[0], (n, shard.shape, v.shape)


def test_allreduce_mode_keeps_state_replicated():
    _, moments = _run(7, reduce_mode=False)
    for n, v in moments.items():
        if np.asarray(v).ndim != 2:
            continue
        # replicated: every device holds the full array
        assert v.addressable_shards[0].data.shape == v.shape, n
